"""Incremental ALS fold-in: re-solve ONLY the entities with new evidence.

The ALX alternating-solve structure (PAPERS.md: "Large Scale Matrix
Factorization on TPUs") makes per-entity refresh cheap: each half-step's
normal equations are independent per row, so a user (or item) whose
evidence changed can be re-solved exactly against FROZEN opposite-side
factors without touching the rest of the catalog. A fold-in generation is
one restricted ALS iteration over the touched rows:

  1. user half — every user with delta events is re-solved against the
     parent instance's item factors;
  2. item half — every item with delta events is re-solved against the
     UPDATED user factors (the same ordering a full ``_iteration_dense``
     runs, so the restricted step is a faithful slice of a full one);
  3. untouched rows are byte-identical copies of the parent factors
     (pinned exactly in tests/test_foldin.py).

The device math reuses the dense solver's own pieces (models/als_dense.py):
the cell sort + duplicate/zero-cell correction collapse
(``_sorted_main_and_corrections``), the compact-COO pack + on-device
densify (``_pack_block``/``_scatter_block``) streamed through the
``io.transfer.ChunkStager`` (pack+upload of block k+1 overlaps the densify
of block k, exactly like ``acquire_device_inputs``' staging path), and the
payload-matmul half solve (``_dense_half_solve`` → ``_normal_eq_solve``).
The sub-matrix is [touched, n_other] instead of [catalog, n_other], so a
generation costs O(touched x catalog) cells instead of a full iteration
sweep — the events-to-servable headline this subsystem exists for.

Brand-new users/items append zero-initialized rows and get their first
solve as a pure least-squares against the frozen opposite side (their
rated counterparts that are themselves new contribute nothing this
generation and refine on the next — the ALX fold-in convention).

When the delta touches more than ``PIO_FOLDIN_MAX_FRACTION`` of either
catalog the incremental step declines (``fold_in_ready`` → False) and the
trainer falls back to the exact-parity full retrain path.

:func:`run_foldin` is the engine-instance lifecycle around the solve — the
fold-in twin of ``workflow.core_workflow.run_train``: INIT → fold_in per
algorithm → persist → refreshed quality baseline → COMPLETED, under a
``runlog.run_scope`` so ``pio runs``/``pio watch``/STALLED-RUN cover the
generation like any other training run. The produced instance records its
lineage in ``env``: ``foldin_of`` (parent id), ``foldin_generation``, and
the new ``train_watermark_seq`` the continuous trainer resumes from.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from functools import partial

import numpy as np

logger = logging.getLogger(__name__)


def max_fraction() -> float:
    """``PIO_FOLDIN_MAX_FRACTION`` (default 0.2): the catalog fraction
    past which a delta stops being "incremental" and the exact full
    retrain is the better (and drift-free) deal."""
    from predictionio_tpu.utils.env import env_float

    return env_float("PIO_FOLDIN_MAX_FRACTION", 0.2)


@dataclass
class FoldinData:
    """The trainer's full interaction snapshot with the delta appended at
    the tail: rows ``[delta_start:]`` are the events newer than the
    parent instance's train watermark. The full snapshot rides along
    because a touched entity's re-solve needs ALL its evidence (old and
    new rows alike), not just the delta.

    The optional ENCODED view (``uidx``/``iidx`` int32 COO +
    ``user_ids``/``item_ids`` BiMaps) is the O(delta) snapshot the
    ``ContinuousTrainer`` maintains persistently — only delta rows get
    string→int encoded per cycle, instead of the whole history. An
    algorithm's ``fold_in`` uses it when the maps verifiably EXTEND the
    model's own (same index for every model entity — checked, because
    the trainer is model-agnostic) and falls back to re-encoding the
    string lists otherwise."""

    users: list
    items: list
    ratings: np.ndarray
    delta_start: int
    uidx: np.ndarray | None = None
    iidx: np.ndarray | None = None
    user_ids: object = None  # BiMap over users, delta entities included
    item_ids: object = None  # BiMap over items

    @property
    def delta_users(self) -> list:
        return self.users[self.delta_start:]

    @property
    def delta_items(self) -> list:
        return self.items[self.delta_start:]

    def encoded(self) -> bool:
        """True when the encoded COO + maps ride along (and cover every
        row — a partial view would silently drop evidence)."""
        return (self.uidx is not None and self.iidx is not None
                and self.user_ids is not None
                and self.item_ids is not None
                and len(self.uidx) == len(self.users)
                and len(self.iidx) == len(self.items))


def extended_ids(ids, delta):
    """A BiMap grown by the delta's unseen entities in first-appearance
    order — existing indices preserved (untouched rows keep their
    position, so a parent's factor/embedding rows copy over
    byte-identical). ONE definition shared by every template's fold-in
    AND mirrored by ``EncodedSnapshot.append`` in train/continuous.py:
    the trainer's O(delta) encoded maps verifiably extend the model's
    (:func:`maps_extend`) only because both apply this exact rule."""
    from predictionio_tpu.data.bimap import BiMap

    fwd = dict(ids.to_dict())
    for key in delta:
        if key not in fwd:
            fwd[key] = len(fwd)
    return BiMap(fwd)


def maps_extend(base, ext) -> bool:
    """True when BiMap ``ext`` is ``base`` plus appended entities: every
    base entity keeps its index. O(base entities) — constant per cycle
    regardless of event history, which is the point."""
    if ext is None or len(ext) < len(base):
        return False
    ed = ext.to_dict()
    return all(ed.get(k) == v for k, v in base.to_dict().items())


def _pow2(n: int, floor: int = 8) -> int:
    """Next power of two ≥ max(n, floor): the touched-row count varies
    per cycle, and padding it onto a pow2 ladder bounds the fold-in
    program's compile count the same way the serving tick ladder does."""
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


def _foldin_half_program():
    """The jitted restricted half-step, built lazily so importing this
    module costs no jax work. One program per (shape-bucket x static
    config); cached on the module."""
    global _FOLDIN_HALF
    if _FOLDIN_HALF is not None:
        return _FOLDIN_HALF
    import jax

    from predictionio_tpu.models import als_dense
    from predictionio_tpu.obs import device as device_obs

    @device_obs.profiled_program(
        lambda *a, **kw: f"als_foldin_rank{kw['rank']}",
        bucket=als_dense._dense_bucket,
        sync=True,  # the rows are read back immediately; a synced
        # histogram keeps the recorded time device-true
    )
    @partial(
        jax.jit,
        static_argnames=("implicit", "rank", "scale", "ub", "exact"),
    )
    def foldin_half(prev, fixed, blocks, dup, lambda_, alpha, *,
                    implicit: bool, rank: int, scale: int, ub: int,
                    exact: bool = False):
        return als_dense._dense_half_solve(
            prev, fixed, blocks, None, dup, lambda_, alpha, implicit,
            rank, scale, ub, exact, False)

    _FOLDIN_HALF = foldin_half
    return foldin_half


_FOLDIN_HALF = None


#: Compiled sharded fold-in programs keyed by layout statics — module-
#: level so steady-state continuous-training cycles re-dispatch warm.
_FOLDIN_SPMD_PROGRAMS: dict = {}


def _foldin_spmd_program(mesh, ndev: int, us: int, S: int, rank: int,
                         implicit: bool, scale: int, exact: bool,
                         has_dup: bool):
    """The sharded restricted half-step: a vmap over per-shard
    ``[us, S]`` sub-blocks, jitted over data-sharded stacked inputs.
    The fixed side is FROZEN for the whole generation, so each shard's
    referenced rows are host-gathered into its ``[S, rank]`` slice at
    pack time — no collectives, and the fixed matrix is never
    materialized whole on any device (the same never-whole contract as
    ``train_dense_sharded``). Implicit mode's shared XtX Gram term rides
    in as a precomputed ``[rank, rank]`` operand for the same reason."""
    key = (mesh, ndev, us, S, rank, implicit, scale, exact, has_dup)
    prog = _FOLDIN_SPMD_PROGRAMS.get(key)
    if prog is not None:
        return prog
    import jax

    from predictionio_tpu.models import als_dense
    from predictionio_tpu.obs import device as device_obs

    dots = als_dense._make_dots(implicit, exact, rank=rank)

    def one(items, vals, row_starts, k, fixed_sl, prev, dup):
        a = als_dense._scatter_block(items, vals, row_starts, k,
                                     ub=us, n_items=S)
        ip, vp = als_dense._local_half_inputs(fixed_sl, rank, implicit)
        gi, gv = dots(a, ip, vp, ((1,), (0,)))
        corr = (als_dense._dup_correction(dup, fixed_sl, rank, us,
                                          one.alpha, implicit)
                if has_dup else None)
        return als_dense._normal_eq_solve(
            prev, gi, gv, corr, fixed_sl, one.lambda_, one.alpha,
            implicit, rank, scale, xtx=one.xtx)

    def foldin_spmd(items, vals, row_starts, k, fixed_sl, prev, dup,
                    xtx, lambda_, alpha):
        # scalars + the shared xtx ride as closure attributes so the
        # vmap axes stay purely the per-shard stacks
        one.xtx, one.lambda_, one.alpha = xtx, lambda_, alpha
        axes = (0, 0, 0, 0, 0, 0, 0 if has_dup else None)
        return jax.vmap(one, in_axes=axes)(
            items, vals, row_starts, k, fixed_sl, prev, dup)

    prog = device_obs.profiled_program(
        f"als_foldin_spmd_rank{rank}",
        # shard count rides the bucket key (the train-program contract)
        bucket=lambda *a, **kw: (ndev, rank,
                                 device_obs.shape_bucket(*a)),
        sync=True,
    )(jax.jit(foldin_spmd))
    if len(_FOLDIN_SPMD_PROGRAMS) >= 8:
        _FOLDIN_SPMD_PROGRAMS.pop(next(iter(_FOLDIN_SPMD_PROGRAMS)))
    _FOLDIN_SPMD_PROGRAMS[key] = prog
    return prog


def _solve_entities_sharded(params, entities, e_idx, o_idx, vals, fixed,
                            prev_rows, n_entities: int, n_other: int,
                            mesh, ndev: int) -> np.ndarray | None:
    """Sharded restricted half-step: touched entities split into one
    contiguous row chunk per ``data`` shard, each solved against a
    host-gathered slice of the frozen fixed side. Same restricted math
    as the single-device path — untouched rows never enter, so the
    byte-exactness contract is unchanged."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from predictionio_tpu.models import als_dense

    p = params
    m = int(len(entities))
    local = np.full(n_entities, -1, np.int32)
    local[entities] = np.arange(m, dtype=np.int32)
    le_all = local[np.asarray(e_idx, np.int32)]
    sel = le_all >= 0
    le = le_all[sel]
    lo = np.asarray(o_idx, np.int32)[sel]
    lv = np.asarray(vals, np.float32)[sel]
    scale = als_dense._int8_scale(lv)
    if scale == 0:
        return None
    mu, mi, mv, dup_u, _dup_i = als_dense._sorted_main_and_corrections(
        le, lo, lv, m, n_other, scale)
    us0 = -(-m // ndev)  # real rows per shard (last may be short)
    us = _pow2(us0)
    starts = np.searchsorted(mu, np.arange(ndev + 1) * us0)
    dstarts = (np.searchsorted(dup_u.seg, np.arange(ndev + 1) * us0)
               if dup_u is not None else None)
    m_pad = _pow2(int(np.diff(starts).max()) if m else 1, floor=4096)
    nd = 0
    if dup_u is not None:
        nd = _pow2(int(np.diff(dstarts).max()), floor=4096)
    # per-shard dedup'd slice of the frozen fixed side
    slice_rows = []
    for d in range(ndev):
        ref = mi[starts[d]:starts[d + 1]]
        if dup_u is not None:
            ref = np.concatenate(
                [ref, dup_u.nbr[dstarts[d]:dstarts[d + 1]]])
        slice_rows.append(np.unique(ref).astype(np.int32))
    S = _pow2(max((len(r) for r in slice_rows), default=1), floor=8)
    rank = p.rank
    fx = np.asarray(fixed, np.float32)
    items_h = np.zeros((ndev, m_pad), np.int32)
    vals_h = np.zeros((ndev, m_pad), np.int8)
    rs_h = np.zeros((ndev, us + 1), np.int32)
    k_h = np.zeros(ndev, np.int32)
    fixed_h = np.zeros((ndev, S, rank), np.float32)
    prev_h = np.zeros((ndev, us, rank), np.float32)
    dup_h = (np.zeros((ndev, nd), np.int32), np.zeros((ndev, nd), np.int32),
             np.zeros((ndev, nd), np.float32),
             np.zeros((ndev, nd), np.float32)) if nd else None
    for d in range(ndev):
        lookup = np.zeros(n_other, np.int32)
        rows = slice_rows[d]
        lookup[rows] = np.arange(len(rows), dtype=np.int32)
        lo_, hi_ = starts[d], starts[d + 1]
        k = int(hi_ - lo_)
        items_h[d, :k] = lookup[mi[lo_:hi_]]
        vals_h[d, :k] = mv[lo_:hi_]
        rs_h[d] = np.searchsorted(mu[lo_:hi_],
                                  d * us0 + np.arange(us + 1))
        k_h[d] = k
        fixed_h[d, :len(rows)] = fx[rows]
        r0, r1 = d * us0, min((d + 1) * us0, m)
        if r1 > r0:
            prev_h[d, :r1 - r0] = np.asarray(prev_rows,
                                             np.float32)[r0:r1]
        if nd:
            dl, dh = dstarts[d], dstarts[d + 1]
            kd = int(dh - dl)
            dup_h[0][d, :kd] = dup_u.seg[dl:dh] - d * us0
            dup_h[1][d, :kd] = lookup[dup_u.nbr[dl:dh]]
            dup_h[2][d, :kd] = dup_u.cnt[dl:dh]
            dup_h[3][d, :kd] = dup_u.val[dl:dh]
            if kd:  # keep segment ids sorted through the padding
                dup_h[0][d, kd:] = dup_h[0][d, kd - 1]
    xtx = None
    if p.implicit_prefs:
        # the shared Gram term needs the FULL frozen fixed matrix; a
        # per-shard slice gram would double-count rows referenced by
        # several shards, so it is computed once on host (f64 accumulate
        # ≈ the device's HIGHEST-precision f32 dot)
        xtx = (fx.astype(np.float64).T @ fx.astype(np.float64)) \
            .astype(np.float32)

    def put(a, *trail):
        return jax.device_put(
            a, NamedSharding(mesh, P("data", *trail)))

    dup_dev = (tuple(put(x, None) for x in dup_h) if nd else None)
    prog = _foldin_spmd_program(
        mesh, ndev, us, S, rank, p.implicit_prefs, scale,
        p.gather_dtype == "float32", nd > 0)
    # shard observatory (obs/shards.py): per-shard fold-in cell loads.
    # This path moves NO collectives (each shard solves against its own
    # host-gathered fixed slice), so the ledger shows skew and dispatch
    # time with a zero exchange fraction — which is the point.
    from predictionio_tpu.obs import shards as shard_obs

    shard_obs.OBSERVATORY.program_meta(
        f"als_foldin_spmd_rank{rank}", shards=ndev,
        steps_per_dispatch=1)
    shard_obs.OBSERVATORY.record_shard_load(
        f"als_foldin_spmd_rank{rank}",
        [int(c) for c in np.diff(starts)], kind="foldin cells")
    out = prog(put(items_h, None), put(vals_h, None), put(rs_h, None),
               put(k_h), put(fixed_h, None, None),
               put(prev_h, None, None), dup_dev,
               None if xtx is None else jnp.asarray(xtx),
               float(p.lambda_), float(p.alpha))
    out = np.asarray(out)
    return np.concatenate(
        [out[d, :min(us0, m - d * us0)] for d in range(ndev)
         if d * us0 < m])


def solve_entities(params, entities: np.ndarray, e_idx: np.ndarray,
                   o_idx: np.ndarray, vals: np.ndarray, fixed,
                   prev_rows: np.ndarray, n_entities: int,
                   n_other: int, ctx=None) -> np.ndarray | None:
    """Re-solved factor rows ``[m, rank]`` for ``entities`` (sorted
    unique int32 ids of one side) against frozen ``fixed`` opposite-side
    factors, from the FULL COO ``(e_idx, o_idx, vals)``. The math is the
    dense solver's half-step restricted to the touched rows: the
    sub-matrix of their cells is densified on device (streamed through
    the ChunkStager in row blocks) and one payload-matmul + Cholesky
    dispatch re-solves all of them. None when the values are not
    int8-encodable (the dense formulation does not apply — callers fall
    back to a full retrain).

    With a multi-device ``ctx``, the touched rows and the referenced
    fixed slices shard across the ``data`` axis instead
    (:func:`_solve_entities_sharded`) — continuous training survives a
    model whose factor matrices outgrow one device."""
    import jax.numpy as jnp

    from predictionio_tpu.io import transfer
    from predictionio_tpu.models import als_dense

    p = params
    m = int(len(entities))
    if m == 0:
        return prev_rows
    if ctx is not None:
        import jax

        ndev = ctx.mesh.shape.get("data", 1)
        if ndev > 1 and jax.process_count() == 1:
            return _solve_entities_sharded(
                params, entities, e_idx, o_idx, vals, fixed, prev_rows,
                n_entities, n_other, ctx.mesh, ndev)
    # select the touched entities' edges and remap to local row ids
    local = np.full(n_entities, -1, np.int32)
    local[entities] = np.arange(m, dtype=np.int32)
    le_all = local[np.asarray(e_idx, np.int32)]
    sel = le_all >= 0
    le = le_all[sel]
    lo = np.asarray(o_idx, np.int32)[sel]
    lv = np.asarray(vals, np.float32)[sel]
    scale = als_dense._int8_scale(lv)
    if scale == 0:
        return None
    mu, mi, mv, dup_u, _dup_i = als_dense._sorted_main_and_corrections(
        le, lo, lv, m, n_other, scale)
    # pow2-pad the row axis (bounds the program's retrace ladder as the
    # touched count varies cycle to cycle), then block the padded rows
    # the same way acquire_device_inputs' streamed path does
    m_pad = _pow2(m)
    nb, ub, starts, item_dtype = als_dense._block_split(
        mu, m_pad, n_other, None,
        max_block_bytes=min(als_dense._BLOCK_BYTES,
                            transfer.transfer_chunk_bytes()))
    # the packed cell count varies with the delta's evidence mass; force
    # it onto the same pow2 ladder as the row axis so a steady-state
    # cycle re-dispatches warm programs instead of recompiling
    # (_pack_block's padding cells are dropped by the device scatter)
    pack_m = _pow2(int(np.diff(starts).max()) if nb else 1, floor=4096)

    def pack(b: int):
        return als_dense._pack_block(b, mu, mi, mv, starts, ub, pack_m,
                                     item_dtype)

    def upload(packed):
        import jax

        f, v, rs, k = packed
        return (jax.device_put(f), jax.device_put(v),
                jax.device_put(rs), jnp.int32(k))

    stager = transfer.ChunkStager(name="als_foldin")
    blocks = []
    for _idx, (fd, vd, rsd, kd) in stager.stream(
            range(nb), pack, upload=upload):
        blocks.append(als_dense._scatter_block(
            fd, vd, rsd, kd, ub=ub, n_items=n_other))
    blocks = tuple(blocks)
    dup_dev = None
    if dup_u is not None:
        import jax

        # pow2-pad the correction arrays too — their length is the
        # delta's duplicate/zero-cell count, different every cycle, and
        # each new length would recompile the half program. Pad rows are
        # exact no-ops: cnt=0/val=0 zero both the pair and rhs weights
        # in _dup_correction, and repeating the last seg id keeps the
        # segment-sum's indices_are_sorted contract
        nd = len(dup_u.seg)
        nd_pad = _pow2(nd, floor=4096)
        seg_fill = int(dup_u.seg[-1]) if nd else 0
        dup_dev = tuple(jax.device_put(x) for x in (
            np.pad(dup_u.seg, (0, nd_pad - nd),
                   constant_values=seg_fill),
            np.pad(dup_u.nbr, (0, nd_pad - nd)),
            np.pad(dup_u.cnt, (0, nd_pad - nd)),
            np.pad(dup_u.val, (0, nd_pad - nd)),
        ))
    prev_pad = np.zeros((nb * ub, p.rank), np.float32)
    prev_pad[:m] = np.asarray(prev_rows, np.float32)
    half = _foldin_half_program()
    out = half(
        jnp.asarray(prev_pad), jnp.asarray(np.asarray(fixed, np.float32)),
        blocks, dup_dev, jnp.float32(p.lambda_), jnp.float32(p.alpha),
        implicit=p.implicit_prefs, rank=p.rank, scale=scale, ub=ub,
        exact=p.gather_dtype == "float32")
    return np.asarray(out)[:m]


class _FoldinDeclined(Exception):
    """An algorithm declined the incremental path mid-run (e.g. the delta
    values stopped being int8-encodable): the caller falls back to the
    full retrain."""


def run_foldin(engine, engine_params, parent, models, data: FoldinData,
               generation: int, watermark: dict
               ) -> tuple[str, list] | None:
    """The fold-in generation's engine-instance lifecycle (the
    ``run_train`` twin): run every algorithm's ``fold_in`` under a run
    ledger, persist the models, refresh the quality baseline, and mark
    the instance COMPLETED with its lineage env. Returns ``(instance_id,
    new_models)``, or None when any algorithm lacks the protocol or its
    ``fold_in_ready`` pre-check declines (callers run the exact full
    retrain instead). A mid-run failure marks the instance ABORTED and
    re-raises — the trainer counts it and re-queues the delta."""
    import hashlib

    from predictionio_tpu.core.persistent_model import (
        PersistentModel,
        PersistentModelManifest,
        class_path,
        serialize_models,
    )
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import EngineInstance, Model
    from predictionio_tpu.obs import quality, runlog, trace
    from predictionio_tpu.utils.time import now
    from predictionio_tpu.workflow.context import workflow_context

    algorithms = engine._algorithms(engine_params)
    for algo, model in zip(algorithms, models):
        if getattr(algo, "fold_in", None) is None:
            logger.info("fold-in unsupported by %s; full retrain",
                        type(algo).__name__)
            return None
        ready = getattr(algo, "fold_in_ready", None)
        if ready is not None and not ready(model, data):
            return None

    ctx = workflow_context(batch=parent.batch, mode="FoldIn")
    instances = Storage.get_meta_data_engine_instances()
    instance_id = instances.insert(EngineInstance(**{
        **parent.__dict__,
        "id": "",
        "status": "INIT",
        "start_time": now(),  # a generation reads as a FRESH model:
        # model age / staleness derive from start_time, and inheriting
        # the parent's would leave the swap invisible to the SLO
        "end_time": now(),
        "env": {},
    }))
    params_hash = hashlib.sha1(
        parent.algorithms_params.encode()).hexdigest()[:12]
    try:
        with runlog.run_scope(run_id=instance_id,
                              engine=parent.engine_factory,
                              params_hash=params_hash), \
                trace.span("run_foldin", instance=instance_id):
            t0 = time.perf_counter()
            new_models = []
            for algo, model in zip(algorithms, models):
                refreshed = algo.fold_in(ctx, model, data)
                if refreshed is None:
                    raise _FoldinDeclined(type(algo).__name__)
                new_models.append(refreshed)
            runlog.phase("foldin_solve", time.perf_counter() - t0)
            t0 = time.perf_counter()
            persisted = []
            for algo, model in zip(algorithms, new_models):
                p = algo.make_persistent_model(ctx, instance_id, model)
                if isinstance(p, PersistentModel):
                    saved = p.save(instance_id, None)
                    p = (PersistentModelManifest(class_path(type(p)))
                         if saved else model)
                persisted.append(p)
            blob = serialize_models(persisted)
            Storage.get_model_data_models().insert(
                Model(instance_id, blob))
            runlog.phase("persist", time.perf_counter() - t0)
            # refreshed quality baseline: the shadow gate and live drift
            # must judge THIS generation's score distribution, not the
            # parent's
            from predictionio_tpu.parallel import placement

            t0 = time.perf_counter()
            with placement.serving_cache_bypass():
                baseline = quality.baseline_env(
                    engine, engine_params, new_models)
            runlog.phase("baseline", time.perf_counter() - t0)
    except _FoldinDeclined as e:
        instances.delete(instance_id)
        logger.info("fold-in declined by %s; full retrain", e)
        return None
    except Exception:
        aborted = EngineInstance(**{
            **instances.get(instance_id).__dict__,
            "status": "ABORTED",
            "end_time": now(),
        })
        instances.update(aborted)
        raise
    env = {
        "foldin_of": parent.id,
        "foldin_generation": str(int(generation)),
        "train_watermark_seq": str(watermark.get("seq", "")),
        "train_watermark_time_ms": str(watermark.get("timeMs", "")),
        **baseline,
    }
    done = EngineInstance(**{
        **instances.get(instance_id).__dict__,
        "status": "COMPLETED",
        "end_time": now(),
        "env": env,
    })
    instances.update(done)
    logger.info(
        "fold-in generation %d: instance %s (parent %s, %d delta rows)",
        generation, instance_id, parent.id,
        len(data.users) - data.delta_start)
    return instance_id, new_models
