"""Continuous training daemon: tail the event store → fold in → hot-swap.

The actuator the fleet layer has watched for since PR 10: the
``model_staleness`` SLO and the shadow-gated ``/reload`` fan-out existed,
but the only refresh path was a manual full retrain + redeploy. The
:class:`ContinuousTrainer` closes the event→model→serving loop (the
production norm in the Google ads-infra paper, PAPERS.md):

  * **tail** — the persisted watermark cursor rides the event store's
    ingestion-order seq (``PEventStore.events_since``: the SQLite rowid /
    memory-insertion-order cursor from data/storage), so polling reads
    only what arrived since — never a log rescan. Backends without a
    stable cursor degrade to full retrains per cycle, detected via a
    time-bounded scan.
  * **batch** — deltas accumulate until ``PIO_FOLDIN_MIN_EVENTS`` or
    ``PIO_FOLDIN_INTERVAL_S`` (whichever trips first) and fold in as one
    generation via :func:`train.foldin.run_foldin` (a real engine
    instance under a run ledger — ``pio runs``/``pio watch``/STALLED-RUN
    all apply).
  * **swap** — the generation hot-swaps through the existing ``/reload``
    fan-out behind the PR-13 shadow gate. A 409-blocked candidate is
    QUARANTINED: the parent keeps serving, the trainer keeps folding new
    deltas into the blocked candidate's factors, and the swap retries
    with the next generation (counted in
    ``pio_foldin_quarantined_total`` and surfaced in ``pio status``).
  * **bound drift** — every ``PIO_FOLDIN_FULL_EVERY`` generations (and
    whenever the delta exceeds ``PIO_FOLDIN_MAX_FRACTION`` of the
    catalog, or fold-in fails) the cycle runs the exact full retrain
    through ``run_train`` instead, re-anchoring the factor state.

Watermark discipline (the crash-recovery contract): the watermark of
record is the ``train_watermark_seq`` env of the newest COMPLETED
instance — persisted atomically WITH the model it describes. The trainer's
own state file under ``<runs dir>/continuous/`` is a status surface
(``pio status`` / ``pio doctor`` STALLED-LOOP), not the source of truth. A
daemon killed mid-cycle restarts from the last persisted instance's
watermark: events past it re-read into the pending delta and fold into a
model that never saw them — nothing double-applied, nothing dropped
(pinned in tests/test_foldin.py).

Events-to-servable is the subsystem's first-class measured quantity: the
wall from the oldest delta event's ingest to the gated swap landing, as
``pio_foldin_events_to_servable_seconds`` (plus per-cycle size/duration
histograms, the generation gauge, and history series for the dashboards).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from predictionio_tpu.obs import REGISTRY
from predictionio_tpu.obs.metrics import DEFAULT_SIZE_BUCKETS
from predictionio_tpu.utils.env import (
    env_float as _env_float,
    env_int as _env_int,
)

logger = logging.getLogger(__name__)

#: state-file heartbeat period while the daemon runs (a side thread, so
#: a minutes-long cycle cannot starve the doctor's liveness judgment)
_KEEPALIVE_S = 2.0

# -- telemetry (documented in docs/operations.md § Monitoring) ---------------

_GENERATIONS = REGISTRY.counter(
    "pio_foldin_generations_total",
    "Continuous-training generations by path (foldin|full) and outcome "
    "(swapped|blocked|swap_error|no_target|failed)",
    labels=("path", "result"),
)
_EVENTS_PER_CYCLE = REGISTRY.histogram(
    "pio_foldin_events_per_cycle",
    "Delta events consumed per continuous-training cycle",
    buckets=DEFAULT_SIZE_BUCKETS,
)
_CYCLE_SECONDS = REGISTRY.histogram(
    "pio_foldin_cycle_seconds",
    "Wall seconds per continuous-training cycle (solve + persist + swap)",
)
_EVENTS_TO_SERVABLE = REGISTRY.histogram(
    "pio_foldin_events_to_servable_seconds",
    "Oldest delta event's ingest-to-hot-swap wall per swapped generation",
)
_WATERMARK_LAG = REGISTRY.gauge(
    "pio_foldin_watermark_lag_seconds",
    "Age of the oldest event not yet folded into a servable model "
    "(0 when the loop is caught up)",
)
_GENERATION_GAUGE = REGISTRY.gauge(
    "pio_foldin_generation",
    "Current continuous-training generation counter",
)
_QUARANTINED = REGISTRY.counter(
    "pio_foldin_quarantined_total",
    "Fold-in candidates refused by the reload shadow gate (409) and "
    "held for retry after the next delta",
)
_ENCODED_ROWS = REGISTRY.histogram(
    "pio_foldin_encoded_rows",
    "Interaction rows string->int encoded per continuous-training "
    "cycle — the O(delta) snapshot contract: equals the delta size, "
    "never the full history",
    buckets=DEFAULT_SIZE_BUCKETS,
)


class _GrowArray:
    """Amortized-O(append) numpy buffer (capacity doubling) — the
    encoded snapshot must not pay an O(history) copy per cycle."""

    def __init__(self, dtype):
        self._buf = np.empty(1024, dtype)
        self._n = 0

    def append(self, values) -> None:
        values = np.asarray(values, self._buf.dtype)
        need = self._n + len(values)
        if need > len(self._buf):
            cap = len(self._buf)
            while cap < need:
                cap *= 2
            grown = np.empty(cap, self._buf.dtype)
            grown[: self._n] = self._buf[: self._n]
            self._buf = grown
        self._buf[self._n: need] = values
        self._n = need

    def view(self) -> np.ndarray:
        return self._buf[: self._n]

    def truncate(self, n: int) -> None:
        self._n = min(self._n, int(n))

    def __len__(self) -> int:
        return self._n


class EncodedSnapshot:
    """Persistent encoded interaction snapshot: int32 COO + entity maps,
    appended per delta — never re-encoded from the string lists (the
    O(delta) trainer-cycle contract, ROADMAP item 2). Entity ids extend
    in first-appearance order, the same rule the algorithms'
    ``_extended_ids`` applies, so the maps verifiably extend the served
    model's (``foldin.maps_extend``) as long as both read the same
    stream order."""

    def __init__(self):
        self.user_map: dict = {}
        self.item_map: dict = {}
        self.u = _GrowArray(np.int32)
        self.i = _GrowArray(np.int32)
        self.r = _GrowArray(np.float32)
        self._user_bimap = None  # cache, dropped when the map grows
        self._item_bimap = None

    def append(self, users, items, ratings) -> int:
        """Encode + append delta rows; returns the rows encoded (the
        per-cycle work measure the O(delta) test pins)."""
        un = np.empty(len(users), np.int32)
        inn = np.empty(len(items), np.int32)
        umap, imap = self.user_map, self.item_map
        grew = (len(umap), len(imap))
        for k, (u, i) in enumerate(zip(users, items)):
            idx = umap.get(u)
            if idx is None:
                idx = umap[u] = len(umap)
            un[k] = idx
            idx = imap.get(i)
            if idx is None:
                idx = imap[i] = len(imap)
            inn[k] = idx
        if (len(umap), len(imap)) != grew:
            self._user_bimap = self._item_bimap = None
        self.u.append(un)
        self.i.append(inn)
        self.r.append(ratings)
        return len(users)

    def bimaps(self):
        """(user BiMap, item BiMap) — rebuilt only when the maps grew
        (steady-state cycles with no new entities reuse the cache)."""
        from predictionio_tpu.data.bimap import BiMap

        if self._user_bimap is None or len(self._user_bimap) \
                != len(self.user_map):
            self._user_bimap = BiMap(self.user_map)
        if self._item_bimap is None or len(self._item_bimap) \
                != len(self.item_map):
            self._item_bimap = BiMap(self.item_map)
        return self._user_bimap, self._item_bimap

    def mark(self) -> tuple:
        return (len(self.u), len(self.user_map), len(self.item_map))

    def rollback(self, mark: tuple) -> None:
        """Undo appends past ``mark`` (a failed cycle re-queues its
        rows): truncate the arrays and pop the entities the delta
        minted (dicts preserve insertion order)."""
        rows, n_users, n_items = mark
        for arr in (self.u, self.i, self.r):
            arr.truncate(rows)
        for m, keep in ((self.user_map, n_users),
                        (self.item_map, n_items)):
            for key in list(m)[keep:]:
                del m[key]
        self._user_bimap = self._item_bimap = None


@dataclass(frozen=True)
class DeltaSpec:
    """What the trainer tails and how interaction events become
    ``(user, item, rating)`` rows — the return value of a datasource's
    ``delta_source()`` continuous-training protocol method. The
    conversion mirrors ``eventlog.intern_interactions`` exactly (same
    rating-property coercion rules), so a row folded in incrementally is
    the row a full retrain's scan would produce."""

    app_name: str
    event_names: tuple
    rating_property: str | None = "rating"
    default_rating: float = 1.0
    channel_name: str | None = None

    def event_row(self, event) -> tuple[str, str, float] | None:
        """``(user, item, rating)`` for an interaction event, None for
        anything else (non-interaction events advance the cursor but
        contribute no rows)."""
        if event.event not in self.event_names \
                or event.target_entity_id is None:
            return None
        from predictionio_tpu.data.storage.eventlog import coerce_rating

        return (event.entity_id, event.target_entity_id,
                coerce_rating(event.properties, self.rating_property,
                              self.default_rating))


@dataclass
class ContinuousConfig:
    """Trainer tunables; None fields resolve from the environment at
    trainer construction (``PIO_FOLDIN_INTERVAL_S`` /
    ``PIO_FOLDIN_MIN_EVENTS`` / ``PIO_FOLDIN_FULL_EVERY``)."""

    interval_s: float | None = None  # delta batching window (default 10)
    min_events: int | None = None    # early-trigger threshold (default 32)
    full_every: int | None = None    # full retrain cadence (default 16)
    reload_url: str | None = None    # /reload target (gateway or replica)
    poll_s: float = 1.0              # cursor poll period
    page_limit: int = 10_000         # events per cursor page
    name: str = "default"            # state-file name (one per variant)


def state_dir(directory: Path | str | None = None) -> Path:
    """Where trainer state files live: ``<runs dir>/continuous/`` — the
    same ``PIO_RUNS_DIR`` filesystem surface the run ledger uses, so
    ``pio status``/``pio doctor`` judge the loop without reaching the
    trainer process."""
    if directory is not None:
        return Path(directory)
    from predictionio_tpu.obs import runlog

    return runlog.runs_dir() / "continuous"


def train_watermark_env(engine, engine_params) -> dict[str, str]:
    """The ``train_watermark_seq`` env fragment ``run_train`` merges into
    every completed instance: the event-store cursor tail snapshotted
    BEFORE the training read, so the instance records which events it
    could have seen. Events landing during the read land at seqs past
    the snapshot and simply re-fold later — a re-solve against data the
    model already saw is idempotent, while a dropped event never would
    be. ``{}`` when the datasource has no ``delta_source()`` protocol or
    the backend no stable cursor."""
    try:
        from predictionio_tpu.core.engine import _instantiate
        from predictionio_tpu.data.store import PEventStore

        ds = _instantiate(engine.data_source_class,
                          engine_params.data_source_params)
        src = getattr(ds, "delta_source", None)
        if src is None:
            return {}
        spec = src()
        tail = PEventStore.tail_seq(spec.app_name, spec.channel_name)
        if tail is None:
            return {}
        return {
            "train_watermark_seq": str(int(tail)),
            "train_watermark_time_ms": str(int(time.time() * 1000)),
        }
    except Exception:  # noqa: BLE001 — a watermark must never sink a train
        logger.debug("train watermark snapshot failed", exc_info=True)
        return {}


class ContinuousTrainer:
    """The ingest-driven trainer daemon. Construct with the engine (and
    the variant identity its instances are filed under), then either
    ``start()`` the background thread, or drive ``bootstrap()`` +
    ``poll_once()`` manually (the test/bench path — deterministic, no
    thread)."""

    def __init__(self, engine, engine_params, *,
                 engine_id: str = "default", engine_version: str = "1",
                 engine_variant: str = "default",
                 engine_factory: str = "", batch: str = "",
                 config: ContinuousConfig | None = None):
        from predictionio_tpu.core.engine import _instantiate

        cfg = config or ContinuousConfig()
        self.engine = engine
        self.engine_params = engine_params
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self.engine_factory = engine_factory
        self.batch = batch
        self.interval_s = (cfg.interval_s if cfg.interval_s is not None
                           else _env_float("PIO_FOLDIN_INTERVAL_S", 10.0))
        self.min_events = (cfg.min_events if cfg.min_events is not None
                           else _env_int("PIO_FOLDIN_MIN_EVENTS", 32))
        self.full_every = (cfg.full_every if cfg.full_every is not None
                           else _env_int("PIO_FOLDIN_FULL_EVERY", 16))
        self.reload_url = (cfg.reload_url or "").rstrip("/") or None
        self.poll_s = cfg.poll_s
        self.page_limit = cfg.page_limit
        self.name = cfg.name

        ds = _instantiate(engine.data_source_class,
                          engine_params.data_source_params)
        src = getattr(ds, "delta_source", None)
        if src is None:
            raise RuntimeError(
                "the engine's datasource does not implement the "
                "delta_source() continuous-training protocol "
                "(see docs/operations.md § Continuous training)")
        self.spec: DeltaSpec = src()

        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.RLock()
        # model + data state (all owned by the trainer thread)
        self._instance = None
        self._models = None
        self._users: list = []
        self._items: list = []
        self._ratings: list = []
        #: O(delta) snapshot: persistent int32 COO + entity maps — only
        #: delta rows get string->int encoded per cycle (rebuilt, at
        #: O(history), only at bootstrap and after full retrains)
        self._enc: EncodedSnapshot | None = None
        self._last_encoded_rows: int | None = None
        #: (seq, wall_ts, user, item, rating) rows read but not folded
        self._pending: list = []
        self._read_seq = 0
        self._watermark_seq = 0
        self._watermark_time_ms = 0
        self._generation = 0
        self._quarantined = 0
        self._last_swap: str | None = None
        self._last_swap_detail: str | None = None
        self._last_error: str | None = None
        self._last_advance = time.time()
        self._last_cycle_s: float | None = None
        self._last_events_to_servable_s: float | None = None
        self._first_pending_t: float | None = None
        self._force_full: str | None = None
        #: events-to-servable measures THIS loop's responsiveness — a
        #: bootstrap backfill of a weeks-old log must not feed the
        #: headline histogram week-long "latencies"
        self._start_wall = time.time()
        #: consecutive failed cycles → exponential retry backoff (a
        #: persistent failure must not mint an ABORTED instance per
        #: poll tick)
        self._fail_streak = 0
        self._backoff_until = 0.0
        #: cursor reads supported? (False → every cycle is a full
        #: retrain and delta detection is a time-bounded scan)
        self._incremental = True
        self._fallback_last_ms = 0
        self._fallback_seen: set = set()
        self._bootstrapped = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Run the daemon on a background thread (the ``pio deploy
        --auto-train`` shape)."""
        self._thread = threading.Thread(
            target=self._run, name=f"continuous-train-{self.name}",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> bool:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._write_state(running=False)
        return t is None or not t.is_alive()

    def run_forever(self) -> None:
        """Foreground loop (the ``pio train --continuous`` shape):
        bootstrap, then poll until stopped."""
        hb = self._start_keepalive()
        try:
            self.bootstrap()
            while not self._stop.wait(self.poll_s):
                self._safe_poll()
        except KeyboardInterrupt:
            pass
        finally:
            self._stop.set()
            hb.join(2 * _KEEPALIVE_S)
            self._write_state(running=False)

    def request_stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        hb = self._start_keepalive()
        try:
            self.bootstrap()
        except Exception as e:  # noqa: BLE001
            logger.exception("continuous trainer bootstrap failed")
            self._last_error = repr(e)
            self._stop.set()
            hb.join(2 * _KEEPALIVE_S)
            self._write_state(running=False)
            return
        while not self._stop.wait(self.poll_s):
            self._safe_poll()
        hb.join(2 * _KEEPALIVE_S)
        self._write_state(running=False)

    def _start_keepalive(self) -> threading.Thread:
        """Heartbeat the state file every ~2s on a side thread for as
        long as the daemon lives: ``_write_state`` otherwise runs only
        BETWEEN poll ticks, and any cycle longer than the doctor's
        60s dead-daemon bound (a cadence full retrain on a real
        dataset, a long bootstrap rebuild) would read as a false
        critical STALLED-LOOP — the same starvation the run ledger's
        keepalive solves for ``pio watch``."""

        def beat():
            while not self._stop.wait(_KEEPALIVE_S):
                self._write_state()

        t = threading.Thread(
            target=beat, name=f"continuous-train-hb-{self.name}",
            daemon=True)
        t.start()
        return t

    def _safe_poll(self) -> None:
        try:
            self.poll_once()
        except Exception as e:  # noqa: BLE001 — the loop must survive a
            logger.exception("continuous trainer poll failed")  # bad cycle
            self._last_error = repr(e)
            self._write_state()

    # -- bootstrap -----------------------------------------------------------

    def bootstrap(self) -> None:
        """Adopt the newest COMPLETED instance and rebuild the trainer's
        interaction snapshot from the cursor log up to its watermark;
        events past it become the first pending delta. With no completed
        instance (or no recorded watermark) the first cycle runs a full
        retrain to establish a clean (model, watermark) pair."""
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.data.store import PEventStore

        instances = Storage.get_meta_data_engine_instances()
        latest = instances.get_latest_completed(
            self.engine_id, self.engine_version, self.engine_variant)
        tail = PEventStore.tail_seq(self.spec.app_name,
                                    self.spec.channel_name)
        self._incremental = tail is not None
        if latest is None:
            self._force_full = "no completed engine instance"
        else:
            self._instance = latest
            self._generation = int(
                (latest.env or {}).get("foldin_generation", 0) or 0)
            wm = (latest.env or {}).get("train_watermark_seq", "")
            if self._incremental and wm not in ("", None):
                self._watermark_seq = int(wm)
                self._watermark_time_ms = int(
                    (latest.env or {}).get("train_watermark_time_ms", 0)
                    or 0)
            elif self._incremental:
                # instance predates the watermark discipline: one full
                # retrain re-anchors rather than guessing what it saw
                self._force_full = (
                    f"instance {latest.id} has no train watermark")
        if self._incremental:
            self._load_snapshot()
        if self._instance is not None and self._models is None \
                and self._force_full is None:
            self._models = self._prepare_models(self._instance)
        self._bootstrapped = True
        self._write_state()
        logger.info(
            "continuous trainer up: instance %s, watermark seq %d, "
            "%d pending event(s)%s",
            getattr(self._instance, "id", None), self._watermark_seq,
            len(self._pending),
            f" (full retrain forced: {self._force_full})"
            if self._force_full else "")

    def _load_snapshot(self) -> None:
        """Rebuild the interaction COO from the cursor log: rows at seq
        <= watermark form the base snapshot (what the current model
        saw), later rows queue as pending delta."""
        from predictionio_tpu.data.store import PEventStore

        self._users, self._items, self._ratings = [], [], []
        self._pending = []
        self._read_seq = 0
        while True:
            page = PEventStore.events_since(
                self.spec.app_name, self._read_seq,
                channel_name=self.spec.channel_name,
                limit=self.page_limit)
            if page is None:
                self._incremental = False
                return
            if not page:
                break
            for seq, ev in page:
                self._read_seq = max(self._read_seq, seq)
                row = self.spec.event_row(ev)
                if row is None:
                    continue
                if seq <= self._watermark_seq:
                    self._users.append(row[0])
                    self._items.append(row[1])
                    self._ratings.append(row[2])
                else:
                    self._note_pending(seq, ev, row)
            if len(page) < self.page_limit:
                break
        self._rebuild_encoded()

    def _rebuild_encoded(self) -> None:
        """Rebuild the encoded snapshot from the string lists — an
        O(history) pass paid only at bootstrap and after a full retrain
        (each itself already O(history)); every fold-in cycle appends
        O(delta) through :meth:`EncodedSnapshot.append`."""
        enc = EncodedSnapshot()
        enc.append(self._users, self._items, self._ratings)
        self._enc = enc

    def _prepare_models(self, instance) -> list:
        """Load an instance's trained models (the serving loader's
        prepare path, minus serving)."""
        from predictionio_tpu.core.engine import WorkflowParams
        from predictionio_tpu.core.persistent_model import (
            deserialize_models,
        )
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.workflow.context import workflow_context

        blob = Storage.get_model_data_models().get(instance.id)
        if blob is None:
            raise RuntimeError(f"no model data for instance {instance.id}")
        persisted = deserialize_models(blob.models)
        ctx = workflow_context(batch=instance.batch, mode="Training")
        return self.engine.prepare_deploy(
            ctx, self.engine_params, instance.id, persisted,
            WorkflowParams())

    # -- the poll tick -------------------------------------------------------

    def _note_pending(self, seq: int, ev, row) -> None:
        wall = time.time()
        ct = getattr(ev, "creation_time", None)
        if ct is not None:
            try:
                wall = ct.timestamp()
            except (OSError, OverflowError, ValueError):
                pass
        self._pending.append((seq, wall, row[0], row[1], row[2]))
        if self._first_pending_t is None:
            self._first_pending_t = time.time()

    def _read_pages(self) -> None:
        from predictionio_tpu.data.store import PEventStore

        while True:
            page = PEventStore.events_since(
                self.spec.app_name, self._read_seq,
                channel_name=self.spec.channel_name,
                limit=self.page_limit)
            if page is None:
                self._incremental = False
                return
            if not page:
                return
            for seq, ev in page:
                self._read_seq = max(self._read_seq, seq)
                row = self.spec.event_row(ev)
                if row is not None:
                    self._note_pending(seq, ev, row)
            if len(page) < self.page_limit:
                return

    def _read_fallback(self) -> None:
        """Delta detection without a cursor (server databases): a
        time-bounded scan with an id-dedup set at the boundary. Rows
        still queue as pending, but cycles run full retrains — the
        trainer cannot prove its snapshot complete."""
        import datetime as dt

        from predictionio_tpu.data.store import PEventStore

        start = None
        if self._fallback_last_ms:
            start = dt.datetime.fromtimestamp(
                self._fallback_last_ms / 1e3, tz=dt.timezone.utc)
        seen_now = set()
        for ev in PEventStore.find(
                self.spec.app_name, channel_name=self.spec.channel_name,
                start_time=start):
            if ev.event_id in self._fallback_seen:
                continue
            seen_now.add(ev.event_id)
            ms = int(ev.event_time.timestamp() * 1e3)
            self._fallback_last_ms = max(self._fallback_last_ms, ms)
            row = self.spec.event_row(ev)
            if row is not None:
                self._note_pending(0, ev, row)
        if seen_now:
            self._fallback_seen |= seen_now
            if len(self._fallback_seen) > 100_000:
                self._fallback_seen = seen_now

    def poll_once(self, now: float | None = None) -> bool:
        """One poll tick: advance the cursor, refresh the lag gauge and
        heartbeat, and run a cycle when the delta triggers. Returns True
        when a cycle ran."""
        now = time.time() if now is None else now
        if self._incremental:
            self._read_pages()
        else:
            self._read_fallback()
        lag = 0.0
        if self._pending:
            lag = max(now - self._pending[0][1], 0.0)
        _WATERMARK_LAG.set(lag)
        ran = False
        if self._should_cycle(now):
            self._cycle()
            ran = True
        self._write_state()
        return ran

    def _should_cycle(self, now: float) -> bool:
        if now < self._backoff_until:
            return False
        if self._force_full and (self._pending or self._instance is None):
            return True
        if not self._pending:
            return False
        if len(self._pending) >= max(self.min_events, 1):
            return True
        first = self._first_pending_t or now
        return (now - first) >= self.interval_s

    # -- the cycle -----------------------------------------------------------

    def _cycle(self) -> None:
        from predictionio_tpu.train import foldin

        t0 = time.time()
        rows = self._pending
        self._pending = []
        self._first_pending_t = None
        new_seq = self._read_seq
        new_time_ms = int(max((r[1] for r in rows), default=t0) * 1000)
        oldest_wall = max(min((r[1] for r in rows), default=t0),
                          self._start_wall)
        generation = self._generation + 1
        watermark = {"seq": new_seq, "timeMs": new_time_ms}
        want_full = bool(
            self._force_full
            or not self._incremental
            or self._models is None
            or self._instance is None
            or (self.full_every > 0 and generation % self.full_every == 0)
        )
        path = "full" if want_full else "foldin"
        instance_id = None
        base_rows = len(self._users)
        enc_mark = None
        committed = False
        try:
            if not want_full:
                if self._enc is None:
                    self._rebuild_encoded()
                # O(delta) snapshot append: ONLY the delta rows get
                # string->int encoded (pio_foldin_encoded_rows pins the
                # per-cycle work; a failed cycle rolls the appends back)
                enc_mark = self._enc.mark()
                encoded = self._enc.append(
                    [r[2] for r in rows], [r[3] for r in rows],
                    [r[4] for r in rows])
                self._last_encoded_rows = encoded
                _ENCODED_ROWS.observe(float(encoded))
                self._users += [r[2] for r in rows]
                self._items += [r[3] for r in rows]
                self._ratings += [r[4] for r in rows]
                committed = True
                u_ids, i_ids = self._enc.bimaps()
                data = foldin.FoldinData(
                    users=self._users, items=self._items,
                    ratings=self._enc.r.view(),
                    delta_start=base_rows,
                    uidx=self._enc.u.view(), iidx=self._enc.i.view(),
                    user_ids=u_ids, item_ids=i_ids,
                )
                got = foldin.run_foldin(
                    self.engine, self.engine_params, self._instance,
                    self._models, data, generation, watermark)
                if got is not None:
                    instance_id, new_models = got
                    self._models = new_models
            if instance_id is None:
                path = "full"
                instance_id = self._full_retrain(generation, watermark)
                # the retrained model's read covers at least the consumed
                # rows; commit them to the snapshot like a fold-in would
                if not committed:
                    self._users += [r[2] for r in rows]
                    self._items += [r[3] for r in rows]
                    self._ratings += [r[4] for r in rows]
                    committed = True
                # the fresh model's entity maps were rebuilt by its own
                # scan — re-anchor the encoded snapshot to the committed
                # string lists (O(history), like the retrain itself)
                self._rebuild_encoded()
        except Exception as e:  # noqa: BLE001
            # the rows are real events the model does not have yet:
            # re-queue them at the front so the next cycle retries —
            # rolling back this cycle's snapshot appends
            if committed:
                del self._users[base_rows:]
                del self._items[base_rows:]
                del self._ratings[base_rows:]
            if enc_mark is not None and self._enc is not None:
                self._enc.rollback(enc_mark)
            self._pending = rows + self._pending
            self._first_pending_t = time.time()
            self._last_error = repr(e)
            self._fail_streak += 1
            if not want_full:
                # the documented fallback covers FAILED fold-ins, not
                # just declined ones: a deterministic fold-in fault
                # (solve bug, persistent device error on this delta)
                # must not loop the incremental path forever — the
                # retry runs the exact full retrain instead
                self._force_full = f"fold-in cycle failed: {e!r}"
            self._backoff_until = time.time() + min(
                60.0, max(self.poll_s, 1.0) * 2 ** min(self._fail_streak, 6))
            _GENERATIONS.inc(path=path, result="failed")
            logger.exception("continuous-training cycle failed "
                             "(generation %d re-queued, retry in %.0fs)",
                             generation, self._backoff_until - time.time())
            return
        # generation committed: advance the watermark of record (a full
        # retrain may have bumped watermark["seq"] to its own fresher
        # pre-read snapshot — commit THAT, matching the instance env)
        self._generation = generation
        self._watermark_seq = int(watermark["seq"])
        self._watermark_time_ms = new_time_ms
        self._last_advance = time.time()
        self._force_full = None
        self._last_error = None
        self._fail_streak = 0
        self._backoff_until = 0.0
        _GENERATION_GAUGE.set(generation)
        _EVENTS_PER_CYCLE.observe(float(len(rows)))
        self._swap(instance_id, path, oldest_wall, had_rows=bool(rows))
        self._last_cycle_s = round(time.time() - t0, 3)
        _CYCLE_SECONDS.observe(self._last_cycle_s)

    def _full_retrain(self, generation: int, watermark: dict) -> str:
        """The exact path: a normal ``run_train`` (which snapshots its
        own fresh watermark env), annotated with the generation
        counter."""
        from predictionio_tpu.core.engine import WorkflowParams
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.data.storage.base import EngineInstance
        from predictionio_tpu.workflow.core_workflow import (
            new_engine_instance,
            run_train,
        )

        instance = new_engine_instance(
            self.engine_id, self.engine_version, self.engine_variant,
            self.engine_factory, self.engine_params, batch=self.batch)
        iid = run_train(self.engine, self.engine_params, instance,
                        WorkflowParams(batch=self.batch))
        instances = Storage.get_meta_data_engine_instances()
        done = instances.get(iid)
        env = dict(done.env or {})
        env["foldin_generation"] = str(int(generation))
        wm = env.get("train_watermark_seq", "")
        instances.update(EngineInstance(**{**done.__dict__, "env": env}))
        self._instance = instances.get(iid)
        self._models = self._prepare_models(self._instance)
        if wm not in ("", None):
            # run_train's snapshot is at least as fresh as ours
            watermark["seq"] = max(int(wm), int(watermark["seq"]))
        return iid

    def _swap(self, instance_id: str, path: str, oldest_wall: float,
              had_rows: bool) -> None:
        from predictionio_tpu.data.storage import Storage

        self._instance = Storage.get_meta_data_engine_instances().get(
            instance_id)
        if self.reload_url is None:
            self._last_swap = "no_target"
            self._last_swap_detail = "no reload url configured"
            _GENERATIONS.inc(path=path, result="no_target")
            return
        url = f"{self.reload_url}/reload"
        try:
            req = urllib.request.Request(url, method="GET")
            with urllib.request.urlopen(req, timeout=120.0) as resp:
                body = json.loads(resp.read() or b"{}")
            self._last_swap = "swapped"
            self._last_swap_detail = f"instance {instance_id}"
            if had_rows:
                e2s = max(time.time() - oldest_wall, 0.0)
                self._last_events_to_servable_s = round(e2s, 3)
                _EVENTS_TO_SERVABLE.observe(e2s)
            _GENERATIONS.inc(path=path, result="swapped")
            logger.info("generation %d swapped in via %s (%s)",
                        self._generation, url,
                        json.dumps(body.get("shadow")) if isinstance(
                            body, dict) else "")
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = json.loads(e.read() or b"{}").get(
                    "shadow") or {}
            except ValueError:
                detail = {}
            if e.code == 409:
                # shadow-gate refusal: the candidate is quarantined —
                # the parent keeps serving, the next delta folds into
                # the candidate's factors and the swap retries then
                self._quarantined += 1
                _QUARANTINED.inc()
                self._last_swap = "blocked"
                self._last_swap_detail = (
                    f"shadow gate 409 (overlap "
                    f"{(detail or {}).get('overlapAtK')})")
                _GENERATIONS.inc(path=path, result="blocked")
                logger.warning(
                    "generation %d BLOCKED by the shadow gate; parent "
                    "keeps serving, retrying after the next delta",
                    self._generation)
            else:
                self._last_swap = "swap_error"
                self._last_swap_detail = f"HTTP {e.code}"
                _GENERATIONS.inc(path=path, result="swap_error")
                logger.warning("reload %s answered HTTP %s", url, e.code)
        except Exception as e:  # noqa: BLE001
            self._last_swap = "swap_error"
            self._last_swap_detail = repr(e)
            _GENERATIONS.inc(path=path, result="swap_error")
            logger.warning("reload %s failed: %s", url, e)

    # -- state surface -------------------------------------------------------

    def state(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "engineId": self.engine_id,
                "engineVariant": self.engine_variant,
                "instanceId": getattr(self._instance, "id", None),
                "generation": self._generation,
                "watermarkSeq": self._watermark_seq,
                "watermarkTimeMs": self._watermark_time_ms,
                "readSeq": self._read_seq,
                "pendingEvents": len(self._pending),
                "quarantined": self._quarantined,
                "lastSwap": self._last_swap,
                "lastSwapDetail": self._last_swap_detail,
                "lastError": self._last_error,
                "lastAdvance": self._last_advance,
                "lastCycleSeconds": self._last_cycle_s,
                "lastCycleEncodedRows": self._last_encoded_rows,
                "snapshotRows": len(self._users),
                "lastEventsToServableSeconds":
                    self._last_events_to_servable_s,
                "intervalS": self.interval_s,
                "minEvents": self.min_events,
                "fullEvery": self.full_every,
                "incremental": self._incremental,
                "reloadUrl": self.reload_url,
            }

    def _write_state(self, running: bool = True) -> None:
        """Atomically persist the status surface (NOT the watermark of
        record — that lives in the instance env). ``updated`` doubles as
        the heartbeat ``pio doctor`` judges daemon liveness from."""
        try:
            d = state_dir()
            d.mkdir(parents=True, exist_ok=True)
            doc = self.state()
            doc["running"] = bool(running and not self._stop.is_set())
            doc["updated"] = time.time()
            tmp = d / f".{self.name}.json.tmp"
            # stop() (caller thread) and the trainer thread both write
            # here; the lock keeps the shared tmp path from interleaving
            with self._lock:
                tmp.write_text(json.dumps(doc))
                os.replace(tmp, d / f"{self.name}.json")
        except OSError:
            logger.debug("trainer state write failed", exc_info=True)


# -- external status/diagnosis (pio status / pio doctor) ---------------------


def trainer_states(directory: Path | str | None = None,
                   now: float | None = None) -> list[dict]:
    """Every persisted trainer state doc, newest first, each with a
    computed ``heartbeatAgeSeconds``. Torn writes are skipped (writes
    are atomic; a torn file means a dead writer mid-rename race)."""
    d = state_dir(directory)
    now = time.time() if now is None else now
    out = []
    if not d.is_dir():
        return out
    for path in sorted(d.glob("*.json")):
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        doc["heartbeatAgeSeconds"] = round(
            max(now - float(doc.get("updated", 0) or 0), 0.0), 1)
        out.append(doc)
    out.sort(key=lambda s: s.get("updated", 0), reverse=True)
    return out


def _stall_after(interval_s: float) -> float:
    """Seconds without watermark advance (while events are pending)
    before the loop reads as stalled: generous multiples of the batching
    window, floored by ``PIO_FOLDIN_STALL_GRACE``."""
    return max(_env_float("PIO_FOLDIN_STALL_GRACE", 30.0),
               4.0 * max(float(interval_s), 1.0))


def diagnose_trainers(slo_state: dict | None = None,
                      directory: Path | str | None = None,
                      now: float | None = None) -> list[dict]:
    """Doctor findings for the continuous-training loop. STALLED-LOOP is
    the headline: the ``model_staleness`` SLO burns while a trainer IS
    registered but its watermark is not advancing — a different problem
    from plain staleness with no trainer (no actuator at all), so it
    gets its own named finding with a runbook (docs/operations.md)."""
    now = time.time() if now is None else now
    staleness_burning = False
    for slo in (slo_state or {}).get("slos", []):
        if slo.get("name") != "model_staleness":
            continue
        fast = (slo.get("burnRates") or {}).get("fast")
        staleness_burning = bool(
            slo.get("breached")
            or (fast is not None
                and fast > slo.get("burnThreshold", 14.4)))
    findings: list[dict] = []
    for st in trainer_states(directory, now=now):
        name = st.get("name", "?")
        hb_age = st.get("heartbeatAgeSeconds", 0.0)
        interval = float(st.get("intervalS", 10.0) or 10.0)
        stall_after = _stall_after(interval)
        if not st.get("running"):
            continue  # cleanly stopped: nothing to watch
        if hb_age > max(stall_after, 60.0):
            findings.append({
                "severity": "critical",
                "subject": f"STALLED-LOOP trainer {name}",
                "detail": (
                    f"continuous trainer heartbeat is {hb_age:.0f}s old "
                    "(daemon dead or wedged) — the event→model→serving "
                    "loop has no actuator; restart `pio train "
                    "--continuous` / the --auto-train deploy"),
            })
            continue
        pending = int(st.get("pendingEvents", 0) or 0)
        adv_age = now - float(st.get("lastAdvance", now) or now)
        stalled = pending > 0 and adv_age > stall_after
        if stalled and staleness_burning:
            findings.append({
                "severity": "critical",
                "subject": f"STALLED-LOOP trainer {name}",
                "detail": (
                    f"model_staleness is burning while {pending} "
                    f"event(s) wait and the watermark has not advanced "
                    f"in {adv_age:.0f}s (generation "
                    f"{st.get('generation')}, last swap "
                    f"{st.get('lastSwap')}"
                    + (f", last error {st.get('lastError')}"
                       if st.get("lastError") else "") + ")"),
            })
        elif stalled:
            findings.append({
                "severity": "warn",
                "subject": f"STALLED-LOOP trainer {name}",
                "detail": (
                    f"{pending} pending event(s) but no watermark "
                    f"advance in {adv_age:.0f}s"
                    + (f"; last error {st.get('lastError')}"
                       if st.get("lastError") else "")),
            })
        elif st.get("lastSwap") == "blocked":
            findings.append({
                "severity": "warn",
                "subject": f"trainer {name}",
                "detail": (
                    f"latest generation {st.get('generation')} is "
                    "QUARANTINED by the reload shadow gate "
                    f"({st.get('quarantined')} total); the parent keeps "
                    "serving and the swap retries after the next delta"),
            })
    return findings


def render_status_lines(states: list[dict] | None = None) -> list[str]:
    """``pio status`` lines for the continuous-training loop: watermark
    lag, generation, last swap outcome."""
    if states is None:
        states = trainer_states()
    lines = []
    for st in states:
        run = "running" if st.get("running") else "stopped"
        lag = ""
        if st.get("pendingEvents"):
            lag = f", {st['pendingEvents']} event(s) pending"
        e2s = st.get("lastEventsToServableSeconds")
        e2s_txt = f", events→servable {e2s:.1f}s" if e2s else ""
        lines.append(
            f"[INFO]   trainer {st.get('name')}: {run}, generation "
            f"{st.get('generation')}, watermark seq "
            f"{st.get('watermarkSeq')}{lag}, last swap "
            f"{st.get('lastSwap') or 'n/a'}"
            f"{e2s_txt}, heartbeat {st.get('heartbeatAgeSeconds')}s ago")
        if st.get("quarantined"):
            lines.append(
                f"[WARN]   trainer {st.get('name')}: "
                f"{st['quarantined']} generation(s) quarantined by the "
                "shadow gate")
    return lines
