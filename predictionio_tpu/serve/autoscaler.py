"""SLO-driven autoscaler: the closed loop over senses PRs 9-10 built.

The gateway already *observes* overload (admission 429s, queue-wait
tails, SLO burn rates) and *locally* absorbs failure (breakers,
failover, shedding) — but replica count was fixed at deploy time, so a
sustained load spike was a page, not a scaling event. This module turns
the observability into actuation, the capacity-management stance of the
ads-infra production line and the latency-SLO-driven elasticity of the
serverless-dataflow prediction-serving work (PAPERS.md):

  * **scale up** when a serving SLO's fast-window burn rate crosses its
    threshold, when admission keeps shedding (429s over
    ``pressure_ticks`` consecutive history ticks), when the queue-wait
    p99 or micro-batch queue depth climbs past its bound, or when fewer
    routable replicas remain than ``min_replicas``;
  * **scale down** one replica at a time after ``idle_ticks``
    consecutive quiet ticks (no shedding, no burn, per-replica qps under
    ``idle_qps_per_replica``), draining the victim through the
    registry's graceful path before stopping it;
  * **cooldowns + flap damping** bound the loop: a scale-up starts both
    cooldown clocks, so a spike can't saw the fleet up and down — the
    idle streak must *outlast* ``scale_down_cooldown_s`` measured from
    the last action in either direction.

The decision inputs come from the process surfaces that already exist:
the SLO engine's last judgment (obs/slo.py) and the history rings
(obs/history.py) — the autoscaler ticks on its own thread but reads the
same clock the operator's dashboard reads, so every decision is
explainable from ``/debug/history`` + ``/debug/slo`` after the fact.
Every decision (including holds) lands in
``pio_autoscaler_decisions_total{action,reason}``; the current replica
count and last-action timestamps ride gauges.

Actuation goes through a *provisioner* — any object with
``scale_up() -> str | None`` and
``scale_down(drain_timeout=...) -> str | None`` —
normally the :class:`~predictionio_tpu.serve.gateway.GatewayDeployment`
(in-process replicas on consecutive ports), but a process-per-replica
or k8s-backed provisioner slots in without touching the control loop.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from predictionio_tpu.obs import REGISTRY

logger = logging.getLogger(__name__)

__all__ = ["Autoscaler", "AutoscalerConfig", "Signals", "next_replica_port"]

_DECISIONS = REGISTRY.counter(
    "pio_autoscaler_decisions_total",
    "Autoscaler control-loop decisions per tick: action "
    "(scale_up/scale_down/hold) and why (slo_burn, queue_growth, "
    "below_min, sustained_idle, cooldown, at_max, at_min, steady, "
    "no_victim, error)",
    labels=("action", "reason"),
)
_REPLICA_COUNT = REGISTRY.gauge(
    "pio_autoscaler_replicas",
    "Replicas the autoscaler currently manages (non-draining members "
    "of the gateway registry), refreshed every control tick",
)
_LAST_ACTION = REGISTRY.gauge(
    "pio_autoscaler_last_action_timestamp",
    "Unix timestamp of the autoscaler's last applied action, by "
    "direction (scale_up/scale_down)",
    labels=("action",),
)


def next_replica_port(gateway_port: int, existing_ports: list[int]) -> int:
    """Where the next spawned replica binds: consecutive after the
    fleet's highest port (gateway 8000 over 8001..8003 spawns 8004), or
    ephemeral (0) when the gateway itself bound an ephemeral port —
    tests and benches must never collide on fixed ports."""
    if gateway_port == 0:
        return 0
    return max([gateway_port, *existing_ports]) + 1


@dataclass
class AutoscalerConfig:
    #: replica-count bounds the control loop may never cross
    min_replicas: int = 1
    max_replicas: int = 4
    #: control-tick period; None rides the history sampler's interval
    #: (the signals only refresh that often anyway)
    interval_s: float | None = None
    #: seconds after a scale-up before another scale-up may fire
    scale_up_cooldown_s: float = 30.0
    #: seconds after the last action (EITHER direction — flap damping)
    #: before a scale-down may fire
    scale_down_cooldown_s: float = 180.0
    #: consecutive pressured ticks before queue growth triggers scale-up
    #: (an SLO burn or a below-min deficit scales up immediately)
    pressure_ticks: int = 2
    #: consecutive idle ticks before a scale-down
    idle_ticks: int = 6
    #: a tick is idle only when gateway qps / replica stays under this
    idle_qps_per_replica: float = 1.0
    #: queue-wait p99 beyond this is queue pressure even without 429s
    queue_wait_bound_ms: float = 50.0
    #: micro-batch queue depth beyond this (and rising) is pressure
    queue_depth_bound: float = 8.0
    #: graceful-drain budget per scale-down victim
    drain_timeout_s: float = 10.0
    #: serving SLOs whose fast-window burn triggers a scale-up (ingest
    #: or staleness burns are not solvable with more replicas)
    slo_names: tuple = ("query_availability", "query_latency_p99")


@dataclass
class Signals:
    """One control tick's inputs, separated from the decision so tests
    drive :meth:`Autoscaler.tick_once` with synthetic values."""

    #: serving SLOs whose fast-window burn exceeds their threshold
    burn_hot: list = field(default_factory=list)
    #: latest admission-shed rate (429/s) from the history ring
    rejected_rate: float | None = None
    #: latest windowed queue-wait p99 (ms)
    queue_wait_p99_ms: float | None = None
    #: micro-batch queue depth is rising past its bound
    queue_growing: bool = False
    #: latest gateway qps (replica qps fallback)
    qps: float | None = None
    #: non-draining registry members (the count the bounds apply to)
    n_replicas: int = 0
    #: healthy + suspect members (what routing can actually use)
    n_routable: int = 0


class Autoscaler:
    """The control loop. Build over a gateway + provisioner, then
    ``start()`` — or drive ``tick_once()`` manually (tests, one-shot
    evaluation). One instance per gateway; it also hangs itself off
    ``gateway.autoscaler`` so the status page can report it."""

    def __init__(self, gateway, provisioner,
                 config: AutoscalerConfig | None = None):
        self.gateway = gateway
        self.provisioner = provisioner
        self.config = config or AutoscalerConfig()
        if self.config.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.config.max_replicas < self.config.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self._lock = threading.Lock()  # serializes ticks (thread + manual)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pressure_streak = 0
        self._idle_streak = 0
        self._last_up_t: float | None = None
        self._last_down_t: float | None = None
        self.last_decision: tuple[str, str] = ("hold", "steady")
        self.tick_count = 0
        if gateway is not None:
            gateway.autoscaler = self

    # -- signal collection --------------------------------------------------
    def read_signals(self) -> Signals:
        """Current inputs from the live surfaces: registry membership,
        the SLO engine's last judgment, and the history rings."""
        from predictionio_tpu.obs import history, slo

        sig = Signals()
        replicas = self.gateway.registry.replicas()
        sig.n_replicas = sum(1 for r in replicas if r.state != "draining")
        sig.n_routable = sum(1 for r in replicas
                             if r.state in ("healthy", "suspect"))
        eng = slo.engine()
        if eng is not None:
            for doc in eng.state().get("slos", []):
                if doc["name"] not in self.config.slo_names:
                    continue
                fast = (doc.get("burnRates") or {}).get("fast")
                if fast is not None and fast > doc.get("burnThreshold",
                                                       14.4):
                    sig.burn_hot.append(doc["name"])
        sampler = history.get_sampler()
        if sampler is not None:
            def latest(name):
                # the LAST tick's value only — never scan back for the
                # last non-None: a windowed quantile samples None on
                # quiet ticks, and resurrecting a spike's hot p99 from
                # minutes ago would keep "pressure" on (blocking
                # scale-down and re-triggering scale-up) long after the
                # traffic died
                pts = sampler.points(name)
                return pts[-1][1] if pts else None

            sig.rejected_rate = latest("admission_rejected_per_sec")
            sig.queue_wait_p99_ms = latest("stage_queue_wait_p99_ms")
            sig.qps = latest("gateway_qps")
            if sig.qps is None:
                sig.qps = latest("query_qps")
            depth = [v for _, v in
                     sampler.points("microbatch_queue_depth")
                     if v is not None][-(self.config.pressure_ticks + 1):]
            sig.queue_growing = (
                len(depth) >= 2 and depth[-1] > depth[0]
                and depth[-1] > self.config.queue_depth_bound)
        return sig

    # -- the decision -------------------------------------------------------
    def _decide(self, sig: Signals, now: float) -> tuple[str, str]:
        """(action, reason) for one tick; updates the streak/cooldown
        state. Pure given (signals, clock) — the unit-testable core."""
        cfg = self.config
        pressured = ((sig.rejected_rate or 0.0) > 0.0
                     or (sig.queue_wait_p99_ms or 0.0)
                     > cfg.queue_wait_bound_ms
                     or sig.queue_growing)
        self._pressure_streak = self._pressure_streak + 1 if pressured \
            else 0
        # idle needs EVIDENCE of quiet, not absence of data: qps is None
        # when history is off (or hasn't ticked twice yet), and draining
        # loaded replicas blind would contradict the documented
        # "below-min healing only" degradation
        idle = (not pressured and not sig.burn_hot
                and sig.qps is not None
                and sig.qps
                < cfg.idle_qps_per_replica * max(sig.n_replicas, 1))
        self._idle_streak = self._idle_streak + 1 if idle else 0

        if sig.burn_hot:
            up_reason = "slo_burn"
        elif sig.n_routable < cfg.min_replicas:
            up_reason = "below_min"
        elif self._pressure_streak >= cfg.pressure_ticks:
            up_reason = "queue_growth"
        else:
            up_reason = None

        if up_reason is not None:
            # below-min healing counts ROUTABLE members against the
            # ceiling: a dead replica must not consume capacity, or a
            # full fleet with a DOWN member could never heal
            occupied = (sig.n_routable if up_reason == "below_min"
                        else sig.n_replicas)
            if occupied >= cfg.max_replicas:
                return "hold", "at_max"
            if self._last_up_t is not None and \
                    now - self._last_up_t < cfg.scale_up_cooldown_s:
                return "hold", "cooldown"
            return "scale_up", up_reason

        if self._idle_streak >= cfg.idle_ticks:
            if sig.n_replicas <= cfg.min_replicas \
                    or sig.n_routable <= cfg.min_replicas:
                return "hold", "at_min"
            acted = [t for t in (self._last_up_t, self._last_down_t)
                     if t is not None]
            if acted and now - max(acted) < cfg.scale_down_cooldown_s:
                # flap damping: idle must OUTLAST the cooldown from the
                # last action in either direction
                return "hold", "cooldown"
            return "scale_down", "sustained_idle"
        return "hold", "steady"

    # -- the tick -----------------------------------------------------------
    def tick_once(self, now: float | None = None,
                  signals: Signals | None = None) -> tuple[str, str]:
        """One control-loop pass: read signals, decide, actuate. Returns
        the (action, reason) recorded — after actuation, so a failed
        spawn/drain downgrades to ``hold``."""
        with self._lock:
            now = time.time() if now is None else now
            if self.gateway is not None and \
                    getattr(self.gateway, "stopping", False):
                # graceful undeploy in progress: the drain marks every
                # replica draining, which would read as a below-min
                # deficit and spawn a fresh replica into a dying fleet
                self.last_decision = ("hold", "stopping")
                self.tick_count += 1
                _DECISIONS.inc(action="hold", reason="stopping")
                return "hold", "stopping"
            sig = self.read_signals() if signals is None else signals
            action, reason = self._decide(sig, now)
            if action == "scale_up":
                try:
                    new_id = self.provisioner.scale_up()
                except Exception:
                    logger.exception("autoscaler scale-up failed")
                    new_id = None
                if new_id is None:
                    action, reason = "hold", "error"
                else:
                    self._last_up_t = now
                    self._pressure_streak = 0
                    _LAST_ACTION.set(now, action="scale_up")
                    logger.warning(
                        "autoscaler scaled UP (%s): %d -> %d replicas "
                        "(new %s)", reason, sig.n_replicas,
                        sig.n_replicas + 1, new_id)
            elif action == "scale_down":
                try:
                    victim = self.provisioner.scale_down(
                        drain_timeout=self.config.drain_timeout_s)
                except Exception:
                    logger.exception("autoscaler scale-down failed")
                    victim = None
                if victim is None:
                    action, reason = "hold", "no_victim"
                else:
                    self._last_down_t = now
                    self._idle_streak = 0
                    _LAST_ACTION.set(now, action="scale_down")
                    logger.warning(
                        "autoscaler scaled DOWN (%s): %d -> %d replicas "
                        "(drained %s)", reason, sig.n_replicas,
                        sig.n_replicas - 1, victim)
            _DECISIONS.inc(action=action, reason=reason)
            if self.gateway is not None:
                live = sum(1 for r in self.gateway.registry.replicas()
                           if r.state != "draining")
            else:
                live = sig.n_replicas
            _REPLICA_COUNT.set(live)
            self.last_decision = (action, reason)
            self.tick_count += 1
            return action, reason

    # -- lifecycle ----------------------------------------------------------
    def interval_s(self) -> float:
        # clamped to >= 1 s either way: a 0/negative --scale-interval
        # must degrade to a fast loop, never a busy-spin
        if self.config.interval_s is not None:
            return max(self.config.interval_s, 1.0)
        from predictionio_tpu.obs import history

        return max(history.history_interval_s(), 1.0)

    def start(self) -> None:
        """Start the control thread. Requires history: the sampler is
        the loop's sensory input, so a disabled history
        (PIO_HISTORY_INTERVAL_S=0) leaves the loop running on registry
        membership alone (below-min healing) with a warning."""
        from predictionio_tpu.obs import history

        if history.ensure_started() is None:
            logger.warning(
                "autoscaler started with history disabled "
                "(PIO_HISTORY_INTERVAL_S=0): no burn/queue signals — "
                "only below-min healing will trigger")
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="gateway-autoscaler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s()):
            try:
                self.tick_once()
            except Exception:  # the loop must never die
                logger.exception("autoscaler tick failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- status (gateway GET / + pio doctor) --------------------------------
    def status(self) -> dict:
        cfg = self.config
        return {
            "minReplicas": cfg.min_replicas,
            "maxReplicas": cfg.max_replicas,
            "ticks": self.tick_count,
            "lastDecision": {"action": self.last_decision[0],
                             "reason": self.last_decision[1]},
            "pressureStreak": self._pressure_streak,
            "idleStreak": self._idle_streak,
            "lastScaleUpAt": self._last_up_t,
            "lastScaleDownAt": self._last_down_t,
        }
