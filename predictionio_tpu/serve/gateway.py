"""Serving gateway: the front door for N query-server replicas.

``pio deploy --replicas N`` puts this HTTP server (built on
:mod:`predictionio_tpu.utils.http`, same stack as every other server in
the process) in front of N replicas and gives ``POST /queries.json``
the tail-latency toolkit single-replica serving lacks:

  * **least-outstanding balancing** — pick the replica with the fewest
    in-flight requests (registration order breaks ties), acquired
    atomically under the registry lock;
  * **per-request deadline budget** — every retry/hedge fits inside one
    end-to-end deadline, so a struggling fleet degrades to bounded
    latency instead of unbounded queueing;
  * **hedged retry** — when the primary hasn't answered after a
    p99-derived delay, fire the SAME query at a second replica and take
    whichever answers first (the classic tail-at-scale hedge; predict is
    read-only, so duplicated work is safe);
  * **connect-failure retry** — a replica that can't be reached fails
    over to the next with exponential backoff, inside the deadline;
  * **per-replica circuit breaker** — K consecutive transport failures
    open the breaker and shed that replica; after a cooldown one
    half-open probe decides whether to close it again;
  * **query-result cache** — :mod:`predictionio_tpu.serve.cache`,
    invalidated on ``/reload`` and on redeploy (instance-id change seen
    by the health checker).

Replica HTTP errors (4xx/5xx with a response) pass through untouched —
they are the *query's* problem, not the replica's, and must not trip the
breaker or burn retries.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import queue
import threading
import time
import urllib.parse
from dataclasses import dataclass

from predictionio_tpu.obs import (
    REGISTRY,
    REQUEST_ID_HEADER,
    current_request_id,
    trace,
)
from predictionio_tpu.serve.cache import QueryCache, canonical_query_key
from predictionio_tpu.serve.registry import Replica, ReplicaRegistry
from predictionio_tpu.utils.http import (
    AppServer,
    RawResponse,
    Request,
    Router,
    add_metrics_route,
)

logger = logging.getLogger(__name__)

DEFAULT_GATEWAY_PORT = 8000  # the gateway takes the engine server's door

_GW_REQUESTS = REGISTRY.counter(
    "pio_gateway_requests_total",
    "Gateway /queries.json outcomes (cache_hit answered locally; "
    "no_replica/deadline/error are gateway-side failures)",
    labels=("outcome",),
)
_GW_SECONDS = REGISTRY.histogram(
    "pio_gateway_seconds",
    "End-to-end gateway /queries.json latency, cache hits included",
)
_GW_UPSTREAM_SECONDS = REGISTRY.histogram(
    "pio_gateway_upstream_seconds",
    "Per-attempt replica round-trip latency (hedges and retries each "
    "observe; the merged p99 derives the hedge delay)",
    labels=("replica",),
)
_GW_HEDGES = REGISTRY.counter(
    "pio_gateway_hedges_total",
    "Hedged second requests: fired, and won (hedge answered first)",
    labels=("result",),
)
_GW_RETRIES = REGISTRY.counter(
    "pio_gateway_retries_total",
    "Connect-failure failovers to another replica",
)
_GW_BREAKER_OPEN = REGISTRY.gauge(
    "pio_gateway_breaker_open",
    "1 while a replica's circuit breaker is open",
    labels=("replica",),
)
_GW_COALESCED = REGISTRY.counter(
    "pio_gateway_coalesced_total",
    "Requests that waited on an identical in-flight query instead of "
    "going upstream (cache singleflight)",
)
_FIX_ACTIONS = REGISTRY.counter(
    "pio_doctor_fix_actions_total",
    "Remediation actions applied through POST /fleet/actions "
    "(pio doctor --fix): restart_replica, evict_replica, reset_breaker, "
    "reset_device_route; result ok/dry_run/error/unsupported/unknown",
    labels=("action", "result"),
)

#: the remediation actions POST /fleet/actions accepts
FLEET_ACTIONS = ("restart_replica", "evict_replica", "reset_breaker",
                 "reset_device_route")


def fleet_actions_enabled() -> bool:
    """Whether the remediation surface (gateway ``POST /fleet/actions``
    and the replica's device-route reset) is mounted. On by default —
    it's the actuation side of ``pio doctor`` — and removable with
    ``PIO_FLEET_ACTIONS=0`` for deploys that want triage to stay
    read-only."""
    import os

    return os.environ.get("PIO_FLEET_ACTIONS", "1") != "0"


class CircuitBreaker:
    """Per-replica breaker: closed -> open after ``failures_to_open``
    CONSECUTIVE transport failures; after ``cooldown_sec`` one half-open
    probe is admitted — success closes, failure re-opens. ``now`` is
    injectable for deterministic tests."""

    def __init__(self, failures_to_open: int = 5, cooldown_sec: float = 5.0,
                 now=time.monotonic):
        self.failures_to_open = failures_to_open
        self.cooldown_sec = cooldown_sec
        self._now = now
        self._lock = threading.Lock()
        self.state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False

    def allow(self) -> bool:
        """Whether a request may pass NOW. In half-open this admits (and
        consumes) the single probe slot, so call it only on the replica
        actually being routed to."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._now() - self._opened_at >= self.cooldown_sec:
                    self.state = "half_open"
                    self._probing = True
                    return True
                return False
            # half_open: one probe at a time
            if not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state != "closed":
                logger.info("breaker closing (%s -> closed)", self.state)
            self.state = "closed"
            self._consecutive = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self.state == "half_open" or (
                self._consecutive >= self.failures_to_open
            ):
                if self.state != "open":
                    logger.warning(
                        "breaker opening after %d consecutive failures",
                        self._consecutive,
                    )
                self.state = "open"
                self._opened_at = self._now()
                self._probing = False

    def cancel_probe(self) -> None:
        """Hand back an admitted-but-unused half-open probe slot (the
        caller decided not to send the request after all — e.g. the
        deadline couldn't absorb the retry backoff). Without this the
        slot would stay consumed forever and the replica would never be
        probed again."""
        with self._lock:
            if self.state == "half_open":
                self._probing = False

    def reset(self) -> None:
        """Close unconditionally (a successful health probe proved the
        transport works again)."""
        self.record_success()


@dataclass
class GatewayConfig:
    ip: str = "0.0.0.0"
    port: int = DEFAULT_GATEWAY_PORT
    #: end-to-end budget per /queries.json request; every retry and
    #: hedge fits inside it
    deadline_sec: float = 10.0
    #: hedged retry: fire a second attempt after the (clamped) merged
    #: p99 of replica round trips. hedge_delay_sec pins the delay
    #: (tests, operators who know their tail); None derives it.
    hedge: bool = True
    hedge_delay_sec: float | None = None
    hedge_min_delay_sec: float = 0.01
    hedge_max_delay_sec: float = 1.0
    #: connect-failure failover backoff: base * 2^attempt, capped
    retry_backoff_base_sec: float = 0.02
    retry_backoff_max_sec: float = 0.5
    #: circuit breaker tunables
    breaker_failures: int = 5
    breaker_cooldown_sec: float = 5.0
    #: result cache (0 entries or 0 TTL disables)
    cache_max_entries: int = 1024
    cache_ttl_sec: float = 30.0
    #: replica health checking
    health_interval_sec: float = 1.0
    health_timeout_sec: float = 2.0
    health_down_after: int = 3
    #: extra fleet-federation member: the event server's (host, port),
    #: scraped into GET /metrics/fleet next to the replicas (None = the
    #: serving fleet only)
    event_server: "tuple[str, int] | None" = None
    #: multi-worker event deployments (``pio eventserver --workers N``):
    #: every worker's (host, port), each federated as its own
    #: instance-labelled member; combines with ``event_server`` (the
    #: router/front port) without duplication
    event_servers: "tuple[tuple[str, int], ...]" = ()
    #: per-member scrape timeout for GET /metrics/fleet
    fleet_scrape_timeout_sec: float = 2.0


class Gateway:
    """Routing/hedging/caching front end over a ReplicaRegistry.

    Build, ``add_replica()`` for each backend, then ``start()`` — or let
    :func:`create_gateway_deployment` assemble the whole in-process
    topology (N replicas + gateway) in one call."""

    def __init__(self, config: GatewayConfig | None = None):
        self.config = config or GatewayConfig()
        self.cache = QueryCache(self.config.cache_max_entries,
                                self.config.cache_ttl_sec)
        self.registry = ReplicaRegistry(
            health_interval_sec=self.config.health_interval_sec,
            check_timeout_sec=self.config.health_timeout_sec,
            down_after=self.config.health_down_after,
            on_instance_change=self._on_instance_change,
            on_probe_result=self._on_probe_result,
        )
        self.start_time = time.time()
        self._stop_event = threading.Event()
        #: True from the moment a graceful shutdown begins (before the
        #: drain, well before _stop_event fires) — the autoscaler reads
        #: it so a fleet-wide drain can't look like a replica deficit
        self.stopping = False
        self._breakers: dict[str, CircuitBreaker] = {}
        self._pools: dict[str, list[http.client.HTTPConnection]] = {}
        self._pool_lock = threading.Lock()
        # singleflight: cache key -> Event for queries in flight, so N
        # concurrent identical misses cost ONE replica round trip
        self._inflight: dict[str, threading.Event] = {}
        self._inflight_lock = threading.Lock()
        # per-gateway counters (the pio_gateway_* metrics are process-
        # global; tests and the status page want THIS gateway's numbers)
        self._stats_lock = threading.Lock()
        self.request_count = 0
        self.error_count = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.retries = 0
        #: set by GatewayDeployment (or any replica-lifecycle owner):
        #: restart_replica/stop_replica handles for POST /fleet/actions.
        #: None = the gateway fronts replicas it cannot respawn (remote
        #: ports) — restart answers "unsupported" then.
        self.replica_controller = None
        #: set by serve/autoscaler.Autoscaler when one attaches
        self.autoscaler = None
        self.router = self._build_router()

    # -- assembly -----------------------------------------------------------
    def add_replica(self, host: str, port: int) -> Replica:
        r = self.registry.add(host, port)
        self._breakers[r.id] = CircuitBreaker(
            self.config.breaker_failures, self.config.breaker_cooldown_sec
        )
        _GW_BREAKER_OPEN.set(0, replica=r.id)
        return r

    def remove_replica(self, replica_id: str) -> Replica | None:
        """Evict a replica from routing: registry membership, its
        breaker, and any pooled keep-alive connections all go. In-flight
        requests finish (release only decrements the popped object)."""
        r = self.registry.remove(replica_id)
        self._breakers.pop(replica_id, None)
        _GW_BREAKER_OPEN.set(0, replica=replica_id)
        self.drop_pooled(replica_id)
        return r

    def drop_pooled(self, replica_id: str) -> None:
        """Close this replica's pooled keep-alive connections. A
        restarted replica REQUIRES this: a stopped AppServer's existing
        keep-alive handler threads keep answering until their socket
        closes, so a pooled connection would keep reaching the dead
        service (stopped micro-batcher → 500s) past the restart."""
        with self._pool_lock:
            for conn in self._pools.pop(replica_id, []):
                conn.close()

    def start(self) -> None:
        # one synchronous sweep so routing state and the fleet instance
        # id are populated before the first proxied query (probe-ok
        # results also clear breakers, via _on_probe_result)
        self.registry.check_once()
        self.registry.start()

    def stop(self) -> None:
        self.stopping = True
        self.registry.stop()
        self._stop_event.set()
        with self._pool_lock:
            for conns in self._pools.values():
                for c in conns:
                    c.close()
            self._pools.clear()

    def wait_for_stop(self) -> None:
        self._stop_event.wait()

    def _on_instance_change(self, instance_id: str) -> None:
        dropped = self.cache.invalidate()
        if dropped:
            logger.info(
                "engine instance changed to %s: dropped %d cached results",
                instance_id, dropped,
            )

    def _on_probe_result(self, replica: Replica, ok: bool) -> None:
        """A successful health probe is transport-level proof the replica
        is reachable again: close its breaker so recovery doesn't wait
        for the request path's half-open lottery. Failed probes do NOT
        trip the breaker — the health state machine handles downing, and
        double-counting would open breakers for replicas that merely
        answered a probe slowly."""
        if not ok:
            return
        breaker = self._breakers.get(replica.id)
        if breaker is not None and breaker.state != "closed":
            breaker.reset()
            _GW_BREAKER_OPEN.set(0, replica=replica.id)

    # -- routes -------------------------------------------------------------
    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self.get_status)
        r.add("POST", "/queries.json", self.post_query)
        r.add("GET", "/reload", self.get_reload)
        r.add("GET", "/stop", self.get_stop)
        r.add("GET", "/metrics/fleet", self.get_fleet_metrics)
        r.add("POST", "/fleet/actions", self.post_fleet_action)
        add_metrics_route(r)
        # registered AFTER add_metrics_route so the gateway's fleet-
        # merged view wins the exact-match table over the per-process
        # default handler every server mounts
        r.add("GET", "/debug/quality", self.get_quality)
        r.add("GET", "/debug/logs", self.get_logs)
        return r

    def get_quality(self, request: Request):
        """``GET /debug/quality`` on the gateway: every replica's quality
        doc plus the fleet merge (obs/quality.merge_docs — per-instance
        tallies summed, window stats worst-case). Dead replicas report
        null; the in-process ``--replicas N`` caveat of
        ``GET /metrics/fleet`` applies to the sums here too."""
        from predictionio_tpu.obs import fleet, quality
        from predictionio_tpu.utils.http import HTTPError

        if not quality.quality_enabled():
            raise HTTPError(404, "quality sampling disabled "
                                 "(PIO_QUALITY_SAMPLE=off)")
        replicas = self.registry.replicas()
        # the event server joins feedback in a split deploy — its doc
        # carries the online hit-rate half of the merge
        extra = [(f"event:{host}:{port}", host, port)
                 for host, port in self._event_members()]
        members = [(r.id, r.host, r.port) for r in replicas] + extra
        docs: dict[str, dict | None] = {}
        results: list[dict | None] = [None] * len(members)

        def fetch_one(i: int, host: str, port: int) -> None:
            results[i] = fleet.fetch_json(
                f"http://{host}:{port}/debug/quality",
                timeout=self.config.fleet_scrape_timeout_sec)

        threads = [threading.Thread(target=fetch_one,
                                    args=(i, host, port), daemon=True)
                   for i, (_, host, port) in enumerate(members)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(2.0 * self.config.fleet_scrape_timeout_sec + 0.5)
        for (member_id, _, _), doc in zip(members, results):
            docs[member_id] = doc
        return 200, {
            "role": "gateway",
            "sampleMode": quality.sample_mode(),
            "joinTtlS": quality.join_ttl_s(),
            "replicas": docs,
            "merged": quality.merge_docs(
                [d for d in docs.values() if d]),
        }

    def get_logs(self, request: Request):
        """``GET /debug/logs`` on the gateway: the local ring plus every
        replica's (and the event-server target's, in a split deploy), so
        one request id is traceable gateway → replica → event server
        from a single endpoint. Same fan-out as :meth:`get_quality`;
        merge dedupes the shared process ring of an in-process
        ``--replicas N`` deploy (obs/logs.merge_docs)."""
        from predictionio_tpu.obs import fleet, logs
        from predictionio_tpu.utils.http import HTTPError

        if not logs.logs_enabled():
            raise HTTPError(404, "structured logs disabled (PIO_LOGS=0)")
        params = {k: v for k, v in request.query.items()
                  if k in ("level", "logger", "since", "request_id",
                           "limit") and v}
        qs = urllib.parse.urlencode(params)
        replicas = self.registry.replicas()
        extra = [(f"event:{host}:{port}", host, port)
                 for host, port in self._event_members()]
        members = [(r.id, r.host, r.port) for r in replicas] + extra
        results: list[dict | None] = [None] * len(members)

        def fetch_one(i: int, host: str, port: int) -> None:
            results[i] = fleet.fetch_json(
                f"http://{host}:{port}/debug/logs" + (f"?{qs}" if qs else ""),
                timeout=self.config.fleet_scrape_timeout_sec)

        threads = [threading.Thread(target=fetch_one,
                                    args=(i, host, port), daemon=True)
                   for i, (_, host, port) in enumerate(members)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(2.0 * self.config.fleet_scrape_timeout_sec + 0.5)
        try:
            since = params.get("since")
            limit = params.get("limit")
            local = logs.to_json(
                level=params.get("level"), logger=params.get("logger"),
                since=int(since) if since is not None else None,
                request_id=params.get("request_id"),
                limit=int(limit) if limit is not None else 500)
        except ValueError as e:
            raise HTTPError(400, f"bad filter: {e}") from e
        docs = {member_id: doc
                for (member_id, _, _), doc in zip(members, results)}
        return 200, {
            "role": "gateway",
            "local": local,
            "replicas": docs,
            "merged": logs.merge_docs(
                [local] + [d for d in docs.values() if d]),
        }

    # -- remediation (`pio doctor --fix`) ------------------------------------
    def post_fleet_action(self, request: Request):
        """``POST /fleet/actions``: apply one remediation action —
        ``{"action": ..., "replica": "host:port", "dryRun": bool}``.
        Every action is gated (``PIO_FLEET_ACTIONS=0`` unmounts the
        surface), logged, counted in
        ``pio_doctor_fix_actions_total{action,result}``, and dry-runnable
        (``dryRun`` reports what would happen without acting)."""
        from predictionio_tpu.utils.http import HTTPError

        if not fleet_actions_enabled():
            # disabled must look exactly like the feature not being
            # there (404) — the /debug/faults contract
            raise HTTPError(404, "fleet actions disabled "
                                 "(PIO_FLEET_ACTIONS=0)")
        body = request.json()
        if not isinstance(body, dict):
            raise HTTPError(400, "JSON object expected")
        action = body.get("action")
        replica_id = body.get("replica")
        dry_run = bool(body.get("dryRun"))
        if action not in FLEET_ACTIONS:
            raise HTTPError(
                400, f"unknown action {action!r}; "
                     f"one of {', '.join(FLEET_ACTIONS)}")
        if not isinstance(replica_id, str) or not replica_id:
            raise HTTPError(400, "action needs a replica (host:port)")
        result, detail = self._apply_fleet_action(
            action, replica_id, dry_run)
        _FIX_ACTIONS.inc(action=action, result=result)
        logger.warning("fleet action %s on %s: %s (%s)",
                       action, replica_id, result, detail)
        doc = {"action": action, "replica": replica_id,
               "result": result, "detail": detail}
        status = {"ok": 200, "dry_run": 200, "unknown": 404,
                  "unsupported": 501}.get(result, 502)
        if status == 200:
            return 200, doc
        # non-2xx still carries the structured body so `pio doctor
        # --fix` reports the failure verbatim (and can escalate)
        return status, RawResponse(json.dumps(doc),
                                   "application/json; charset=UTF-8")

    def _apply_fleet_action(self, action: str, replica_id: str,
                            dry_run: bool) -> tuple[str, str]:
        replica = self.registry.find(replica_id)
        if action == "reset_breaker":
            breaker = self._breakers.get(replica_id)
            if breaker is None:
                return "unknown", "no breaker for that replica"
            if dry_run:
                return "dry_run", f"would close breaker ({breaker.state})"
            previous = breaker.state
            breaker.reset()
            _GW_BREAKER_OPEN.set(0, replica=replica_id)
            return "ok", f"breaker {previous} -> closed"
        if action == "evict_replica":
            if replica is None:
                return "unknown", "replica not in registry"
            if dry_run:
                return "dry_run", (f"would evict ({replica.state}, "
                                   f"{replica.outstanding} outstanding)")
            self.remove_replica(replica_id)
            controller = self.replica_controller
            if controller is not None:
                # in-process replica: also stop its server + service so
                # an evicted-but-running replica doesn't leak threads
                try:
                    controller.discard_replica(replica_id)
                except Exception:
                    logger.exception("evicted replica %s but its local "
                                     "teardown failed", replica_id)
            return "ok", "removed from registry"
        if action == "restart_replica":
            controller = self.replica_controller
            if controller is None:
                return "unsupported", (
                    "no replica controller: this gateway fronts "
                    "replicas it cannot respawn — evict instead")
            if replica is None:
                return "unknown", "replica not in registry"
            if dry_run:
                return "dry_run", f"would restart ({replica.state})"
            try:
                controller.restart_replica(replica_id)
            except Exception as e:
                return "error", f"restart failed: {e}"
            # targeted probe so the caller sees the recovery without
            # paying a whole-fleet sweep (doctor runs exactly when other
            # replicas may be dead and slow to time out)
            self.registry.check_replica(replica)
            return "ok", "replica restarted on its port"
        # reset_device_route: the breaker lives in the REPLICA process
        if replica is None:
            return "unknown", "replica not in registry"
        if dry_run:
            return "dry_run", "would reset the device-route breaker"
        try:
            status, body = self._replica_post(
                replica, "/admin/device-route/reset", 10.0)
        except (OSError, ValueError) as e:
            return "error", f"replica unreachable: {e}"
        if status != 200:
            return "error", f"replica answered HTTP {status}: " \
                            f"{body.get('message', '')}"
        return "ok", (f"device route {body.get('previous')} -> "
                      f"{body.get('state')}")

    def _replica_post(self, replica: Replica, path: str,
                      timeout: float) -> tuple[int, dict]:
        """POST a control endpoint on a replica over a fresh direct
        connection (same rationale as _replica_control)."""
        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=timeout)
        try:
            conn.request("POST", path, b"{}",
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read() or b"{}")
            return resp.status, body if isinstance(body, dict) else {}
        finally:
            conn.close()

    # -- fleet federation (obs/fleet.py) ------------------------------------
    def _event_members(self) -> "list[tuple[str, int]]":
        """Every event-tier (host, port) to federate: the singular
        ``event_server`` (router/front port of a worker pool, or the
        lone server) plus each ``event_servers`` worker, deduplicated in
        declaration order. Wildcard binds normalize to loopback — the
        gateway scrapes members from its own host."""
        members: list[tuple[str, int]] = []
        singular = self.config.event_server
        for hp in ((singular,) if singular is not None else ()) \
                + tuple(self.config.event_servers):
            host, port = hp
            if host in ("0.0.0.0", "::"):
                host = "127.0.0.1"
            if (host, port) not in members:
                members.append((host, port))
        return members

    def fleet_targets(self) -> list:
        """Federation membership: the gateway itself (read locally — no
        HTTP round trip into our own process), every registered replica,
        and every configured event-tier member (router + per-process
        workers in a ``--workers N`` deploy, each its own instance)."""
        from predictionio_tpu.obs import fleet

        targets = [fleet.FleetTarget(
            instance="gateway", role="gateway", registry=REGISTRY)]
        for r in self.registry.replicas():
            targets.append(fleet.FleetTarget(
                instance=r.id, host=r.host, port=r.port, role="replica"))
        for host, port in self._event_members():
            targets.append(fleet.FleetTarget(
                instance=f"{host}:{port}", host=host, port=port,
                role="event"))
        return targets

    def get_fleet_metrics(self, request: Request):
        """``GET /metrics/fleet``: scrape every member's /metrics
        concurrently and serve the instance-labelled merge (dead members
        omitted; see obs/fleet.py for the per-kind merge rules)."""
        from predictionio_tpu.obs import fleet
        from predictionio_tpu.utils.http import METRICS_CONTENT_TYPE

        results = fleet.collect(
            self.fleet_targets(),
            timeout=self.config.fleet_scrape_timeout_sec)
        return 200, RawResponse(fleet.federated_exposition(results),
                                METRICS_CONTENT_TYPE)

    def get_status(self, request: Request):
        with self._stats_lock:
            body = {
                "status": "alive",
                "role": "gateway",
                "engineInstanceId": self.registry.instance_id(),
                "requestCount": self.request_count,
                "errorCount": self.error_count,
                "hedgesFired": self.hedges_fired,
                "hedgesWon": self.hedges_won,
                "retries": self.retries,
            }
        breakers = dict(self._breakers)
        body["replicas"] = [
            {**snap, "breaker": getattr(breakers.get(snap["replica"]),
                                        "state", "closed")}
            for snap in self.registry.snapshot()
        ]
        if self.autoscaler is not None:
            body["autoscaler"] = self.autoscaler.status()
        body["cache"] = self.cache.stats()
        p99 = _GW_UPSTREAM_SECONDS.quantile(0.99)
        body["hedgeDelaySec"] = round(self._hedge_delay(), 6)
        if p99 is not None:
            body["upstreamP99Sec"] = round(p99, 6)
        return 200, body

    def _replica_control(self, replica: Replica, path: str,
                         timeout: float) -> tuple[int, dict]:
        """GET a control endpoint (/reload, /stop) on a replica over a
        fresh direct connection — NOT urllib, whose proxy env-var
        handling could reroute gateway→replica traffic that
        /queries.json (http.client, direct) sends straight through."""
        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = json.loads(resp.read() or b"{}")
            return resp.status, body if isinstance(body, dict) else {}
        finally:
            conn.close()

    def get_reload(self, request: Request):
        """Fan /reload out to every replica CONCURRENTLY (a model
        hot-swap takes seconds per replica; paying the max beats paying
        the sum), then invalidate the cache."""
        replicas = [r for r in self.registry.replicas()
                    if r.state != "draining"]
        results: list[dict | None] = [None] * len(replicas)

        def reload_one(i: int, r: Replica) -> None:
            try:
                status, body = self._replica_control(r, "/reload", 30.0)
                if status == 200:
                    results[i] = {"replica": r.id, **body}
                else:
                    results[i] = {"replica": r.id,
                                  "error": f"HTTP {status}", **body}
            except (OSError, ValueError) as e:
                results[i] = {"replica": r.id, "error": str(e)}

        threads = [
            threading.Thread(target=reload_one, args=(i, r), daemon=True)
            for i, r in enumerate(replicas)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.cache.invalidate()
        # pick up the new instance id right away (also re-invalidates
        # via the change callback, which is idempotent on an empty cache)
        self.registry.check_once()
        return 200, {"reloaded": True, "replicas": results}

    def get_stop(self, request: Request):
        """Graceful undeploy: answer 200 immediately, then on a
        background thread drain in-flight traffic, forward /stop to
        every replica, and release ``wait_for_stop``."""

        def shutdown():
            self.stopping = True  # freeze the autoscaler first
            self.registry.stop()
            self.registry.drain(timeout_sec=10.0)
            for r in self.registry.replicas():
                try:
                    self._replica_control(r, "/stop", 5.0)
                except (OSError, ValueError):
                    logger.debug("replica %s already gone", r.id)
            self._stop_event.set()

        threading.Thread(target=shutdown, name="gateway-stop",
                         daemon=True).start()
        return 200, {"message": "Shutting down."}

    # -- the proxied hot path ----------------------------------------------
    def post_query(self, request: Request):
        t0 = time.perf_counter()
        with self._stats_lock:
            self.request_count += 1
        try:
            status, payload = self._proxy_query(request)
        except Exception:
            with self._stats_lock:
                self.error_count += 1
            _GW_REQUESTS.inc(outcome="error")
            raise
        if status >= 500:
            with self._stats_lock:
                self.error_count += 1
        _GW_SECONDS.observe(time.perf_counter() - t0)
        if status in (429, 503) and isinstance(payload, dict) \
                and payload.get("retryAfterSec") is not None:
            # shed/unavailable responses carry the backoff hint as a
            # real Retry-After header, not just a body field
            import math

            sec = max(int(math.ceil(float(payload["retryAfterSec"]))), 1)
            return status, RawResponse(
                json.dumps(payload),
                "application/json; charset=UTF-8",
                headers={"Retry-After": str(sec)},
            )
        return status, payload

    def _proxy_query(self, request: Request) -> tuple[int, object]:
        deadline = time.monotonic() + self.config.deadline_sec
        key = None
        leader = False
        if self.cache.enabled:
            instance = self.registry.instance_id()
            if instance:
                key = canonical_query_key(request.body, instance)
            if key is not None:
                hit = self.cache.get(key)
                if hit is not None:
                    _GW_REQUESTS.inc(outcome="cache_hit")
                    trace.add_event("cache_hit")
                    return 200, hit
                # singleflight: one of N concurrent identical misses
                # goes upstream (the leader); the rest wait for its
                # cached result — a herd of repeats must not multiply
                # device work across the fleet
                while True:
                    with self._inflight_lock:
                        ev = self._inflight.get(key)
                        if ev is None:
                            self._inflight[key] = threading.Event()
                            leader = True
                            break
                    _GW_COALESCED.inc()
                    trace.add_event("singleflight_coalesced")
                    ev.wait(timeout=max(deadline - time.monotonic(), 0.0))
                    hit = self.cache.get(key)
                    if hit is not None:
                        _GW_REQUESTS.inc(outcome="cache_hit")
                        trace.add_event("cache_hit", coalesced=True)
                        return 200, hit
                    # leader failed or the result wasn't cacheable (non-
                    # 200): fall through and fetch (or re-lead) ourselves
                    if deadline - time.monotonic() <= 0:
                        break
        try:
            status, payload = self._fetch(request.body, deadline)
            if status == 200 and key is not None:
                self.cache.put(key, payload)
        finally:
            if leader:
                with self._inflight_lock:
                    ev = self._inflight.pop(key, None)
                if ev is not None:
                    ev.set()
        if isinstance(payload, dict) and "pioGatewayOutcome" in payload:
            outcome = payload.pop("pioGatewayOutcome")  # gateway-side fail
        elif status >= 500:
            outcome = "upstream_error"  # the replica answered 5xx
        else:
            outcome = "ok"
        _GW_REQUESTS.inc(outcome=outcome)
        return status, payload

    def _shed_hint(self) -> float:
        """Retry-After for gateway-side 503s: breaker cooldown plus
        bounded random jitter, so a synchronized client herd spreads its
        retries instead of stampeding the recovering fleet at once."""
        from predictionio_tpu.resilience.admission import retry_after_jitter

        return round(retry_after_jitter(self.config.breaker_cooldown_sec), 3)

    def _hedge_delay(self) -> float:
        if self.config.hedge_delay_sec is not None:
            return self.config.hedge_delay_sec
        p99 = _GW_UPSTREAM_SECONDS.quantile(0.99)
        if p99 is None:  # no traffic yet: be conservative, hedge late
            return self.config.hedge_max_delay_sec
        return min(max(p99, self.config.hedge_min_delay_sec),
                   self.config.hedge_max_delay_sec)

    def _launch(self, replica: Replica, body: bytes, rid: str | None,
                deadline: float, resq: "queue.Queue", kind: str) -> None:
        """Fire one upstream attempt on its own thread; results land on
        ``resq`` as ('ok', status, payload, replica, kind) or
        ('err', exc, None, replica, kind)."""
        # the attempt runs on a fresh thread, where contextvars don't
        # follow — capture the gateway server span HERE (the handler
        # thread) so the upstream client span parents correctly, and
        # hold the trace open so a hedge attempt that hasn't been
        # scheduled yet when the handler answers (primary won) still
        # lands its span before the trace commits
        handle = trace.capture()
        held = trace.hold(handle)

        def run():
            t0 = time.perf_counter()
            try:
                with trace.child_span(handle, "upstream",
                                      replica=replica.id, kind=kind):
                    try:
                        timeout = max(deadline - time.monotonic(), 0.05)
                        status, payload = self._upstream_query(
                            replica, body, rid, timeout)
                    except Exception as e:  # noqa: BLE001 — transport failure
                        self._record_transport(replica, ok=False)
                        resq.put(("err", e, None, replica, kind))
                    else:
                        self._record_transport(replica, ok=True)
                        _GW_UPSTREAM_SECONDS.observe(
                            time.perf_counter() - t0, replica=replica.id)
                        resq.put(("ok", status, payload, replica, kind))
                    finally:
                        self.registry.release(replica)
            finally:
                trace.release(held)

        threading.Thread(target=run, name=f"gw-{kind}", daemon=True).start()

    def _record_transport(self, replica: Replica, ok: bool) -> None:
        breaker = self._breakers.get(replica.id)
        if breaker is None:
            return  # evicted while this attempt was in flight
        if ok:
            breaker.record_success()
        else:
            breaker.record_failure()
        _GW_BREAKER_OPEN.set(
            1 if breaker.state == "open" else 0, replica=replica.id)

    def _admit(self, replica: Replica) -> bool:
        breaker = self._breakers.get(replica.id)
        # a just-evicted replica can linger in a registry snapshot for
        # one acquire; without its breaker there is nothing to consult
        return True if breaker is None else breaker.allow()

    def _acquire(self, exclude: set[str]) -> Replica | None:
        return self.registry.acquire_least_outstanding(
            admit=self._admit, exclude=exclude
        )

    def _fetch(self, body: bytes, deadline: float) -> tuple[int, object]:
        """Balanced + hedged + retried fetch of one query against the
        fleet, inside ``deadline``."""
        cfg = self.config
        if deadline - time.monotonic() <= 0:
            # e.g. a singleflight follower that waited out its whole
            # budget: don't burn a replica's device time on a response
            # nobody will read
            return 504, {"message": "Deadline exceeded.",
                         "pioGatewayOutcome": "deadline"}
        rid = current_request_id()
        resq: "queue.Queue" = queue.Queue()
        tried: set[str] = set()
        if trace.current_trace_id() is not None:
            # the breaker scan runs only under an active span: untraced
            # queries must not pay for building an event they can't keep
            open_breakers = sorted(
                r for r, b in self._breakers.items() if b.state == "open")
            if open_breakers:  # shed replicas this request routes around
                trace.add_event("breaker_open",
                                replicas=",".join(open_breakers))
        primary = self._acquire(exclude=tried)
        if primary is None:
            return 503, {"message": "No replica available.",
                         "retryAfterSec": self._shed_hint(),
                         "pioGatewayOutcome": "no_replica"}
        tried.add(primary.id)
        self._launch(primary, body, rid, deadline, resq, "primary")
        pending = 1
        hedged = not cfg.hedge  # True = don't (or can't) hedge anymore
        backoff = cfg.retry_backoff_base_sec
        last_err: Exception | None = None
        last_shed: tuple[int, object] | None = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            wait = remaining if hedged else min(self._hedge_delay(),
                                                remaining)
            try:
                res = resq.get(timeout=wait)
            except queue.Empty:
                if hedged:
                    break  # deadline spent with attempts still in flight
                hedged = True  # one hedge per request
                hedge_replica = self._acquire(exclude=tried)
                if hedge_replica is not None:
                    tried.add(hedge_replica.id)
                    with self._stats_lock:
                        self.hedges_fired += 1
                    _GW_HEDGES.inc(result="fired")
                    trace.add_event("hedge_fired",
                                    replica=hedge_replica.id)
                    self._launch(hedge_replica, body, rid, deadline, resq,
                                 "hedge")
                    pending += 1
                continue
            tag, a, b, replica, kind = res
            if tag == "ok" and a == 429:
                # upstream admission shed: BACKPRESSURE, not a replica
                # fault — the breaker already recorded the transport
                # success. Fail over to another replica inside the
                # budget; if none answers, the 429 (with its Retry-After
                # hint) surfaces to the client.
                trace.add_event("upstream_backpressure",
                                replica=replica.id)
                last_shed = (a, b)
            elif tag == "ok":
                if kind == "hedge":
                    with self._stats_lock:
                        self.hedges_won += 1
                    _GW_HEDGES.inc(result="won")
                    trace.add_event("hedge_won", replica=replica.id)
                return a, b  # replica's status/payload, 4xx/5xx included
            else:
                last_err = a
            pending -= 1
            if pending > 0:
                continue  # a hedge twin is still running: let it race
            # every launched attempt failed (transport) or shed (429):
            # failover with exponential backoff while the budget lasts.
            # No second lap through already-failed replicas — a fleet
            # that just failed everywhere answers faster with an honest
            # 503 + Retry-After than with more doomed connects.
            retry = self._acquire(exclude=tried)
            if retry is None:
                break
            remaining = deadline - time.monotonic()
            if remaining <= backoff:
                # un-acquire: the budget can't absorb the backoff sleep.
                # Hand back any half-open probe slot allow() consumed,
                # or the unprobed replica would be shed forever
                self.registry.release(retry)
                b = self._breakers.get(retry.id)
                if b is not None:
                    b.cancel_probe()
                break
            time.sleep(backoff)
            backoff = min(backoff * 2, cfg.retry_backoff_max_sec)
            tried.add(retry.id)
            with self._stats_lock:
                self.retries += 1
            _GW_RETRIES.inc()
            trace.add_event("retry_fired", replica=retry.id)
            self._launch(retry, body, rid, deadline, resq, "retry")
            pending += 1
        if last_shed is not None:
            # the fleet is shedding everywhere: pass the backpressure
            # through (429 + Retry-After), never convert it into a 5xx
            status, payload = last_shed
            if isinstance(payload, dict):
                payload = {**payload, "pioGatewayOutcome": "backpressure"}
            return status, payload
        if last_err is not None:
            logger.warning("query failed against all replicas: %s", last_err)
            # every replica failed at the transport level: an honest
            # 503 + Retry-After, well inside the deadline budget — the
            # client backs off instead of piling onto a down fleet
            return 503, {"message": f"All replicas unavailable: {last_err}",
                         "retryAfterSec": self._shed_hint(),
                         "pioGatewayOutcome": "all_down"}
        return 504, {"message": "Deadline exceeded.",
                     "pioGatewayOutcome": "deadline"}

    # -- upstream transport (pooled keep-alive) -----------------------------
    def _pool_get(self, replica: Replica) -> http.client.HTTPConnection | None:
        with self._pool_lock:
            conns = self._pools.get(replica.id)
            if conns:
                return conns.pop()
            return None

    def _pool_put(self, replica: Replica,
                  conn: http.client.HTTPConnection) -> None:
        with self._pool_lock:
            self._pools.setdefault(replica.id, []).append(conn)

    def _upstream_query(self, replica: Replica, body: bytes,
                        rid: str | None, timeout: float):
        """One POST /queries.json round trip. Raises on transport
        failure (connect/read error, malformed response); a pooled
        keep-alive connection that went stale surfaces here too and the
        caller's retry path covers it (predict is read-only, so a
        resend is always safe)."""
        from predictionio_tpu.resilience import faults

        # the chaos suite's replica-transport site: an injected error is
        # indistinguishable from a connect/read failure and exercises the
        # breaker + failover machinery for real
        faults.fault_point("replica.socket")
        conn = self._pool_get(replica)
        if conn is None:
            conn = http.client.HTTPConnection(
                replica.host, replica.port, timeout=timeout)
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        headers = {"Content-Type": "application/json"}
        if rid:
            headers[REQUEST_ID_HEADER] = rid
        # the replica joins this trace: sampled flag + the upstream
        # span (active on this attempt thread) as the remote parent
        trace.inject_headers(headers)
        try:
            conn.request("POST", "/queries.json", body, headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            retry_after = resp.getheader("Retry-After")
        except BaseException:
            conn.close()
            raise
        self._pool_put(replica, conn)
        try:
            payload = json.loads(data or b"null")
        except ValueError:
            payload = {"message": data.decode("utf-8", "replace")}
        if retry_after is not None and isinstance(payload, dict):
            # surface the replica's backoff hint to the failover logic
            # and (on passthrough) to the client
            try:
                payload.setdefault("retryAfterSec", float(retry_after))
            except ValueError:
                pass  # HTTP-date form: ignore, the hint is best-effort
        return status, payload


class GatewayDeployment:
    """One in-process serving topology: N replica query servers plus the
    gateway fronting them. start()/stop() manage every server; the
    gateway's ``/stop`` (hit by ``pio undeploy``) releases
    ``wait_for_stop`` after the graceful drain.

    This is also the fleet's *replica controller*: the autoscaler's
    provisioner (``scale_up``/``scale_down``) and ``pio doctor --fix``'s
    restart/discard handles both live here, because only the deployment
    knows how to build a replica (it holds the engine ServerConfig)."""

    def __init__(self, gateway: Gateway, gateway_server: AppServer,
                 replicas: list, server_config=None):
        self.gateway = gateway
        self.server = gateway_server
        self.replicas = replicas  # [(AppServer, QueryService), ...]
        #: the engine ServerConfig replicas are built from; None =
        #: externally supplied replicas, spawn/restart unavailable
        self.server_config = server_config
        self._replica_lock = threading.Lock()
        if server_config is not None:
            gateway.replica_controller = self

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> None:
        for srv, _service in self.replicas:
            srv.start()
            self.gateway.add_replica(
                "127.0.0.1" if srv.host in ("0.0.0.0", "::") else srv.host,
                srv.port,
            )
        self.gateway.start()
        self.server.start()

    def wait_for_stop(self) -> None:
        self.gateway.wait_for_stop()

    def stop(self) -> None:
        self.gateway.stop()
        self.server.stop()
        with self._replica_lock:
            entries = list(self.replicas)
        for entry in entries:
            self._teardown(entry, remove=False)

    # -- replica lifecycle (autoscaler + doctor --fix) ----------------------
    def _teardown(self, entry, remove: bool = True) -> None:
        """The one replica-teardown sequence: stop the server, drain the
        service's micro-batcher (a mid-flight deferred finalize
        completes) and join its worker threads, and (unless the caller
        keeps the slot, e.g. restart-in-place) drop the entry."""
        srv, service = entry
        srv.stop()
        shutdown = getattr(service, "shutdown", None)
        if shutdown is not None:
            shutdown()
        if remove:
            with self._replica_lock:
                if entry in self.replicas:
                    self.replicas.remove(entry)

    def _find(self, replica_id: str):
        host, _, port = replica_id.rpartition(":")
        try:
            port = int(port)
        except ValueError:
            return None
        with self._replica_lock:
            for entry in self.replicas:
                if entry[0].port == port:
                    return entry
        return None

    def spawn_replica(self) -> str:
        """Build, start, and register one more replica on the next
        consecutive port (ephemeral when the gateway bound port 0).
        Returns the new replica's registry id.

        The ``query_r<N>`` server_name index is the LOWEST one not in
        use (not a monotonic counter): server_name is a metric label,
        and a flapping autoscaled deploy minting query_r57, query_r58,
        ... would grow label cardinality without bound until the
        registry's series guard started dropping exactly the newest
        replicas' metrics."""
        from predictionio_tpu.serve.autoscaler import next_replica_port
        from predictionio_tpu.workflow.create_server import create_server

        if self.server_config is None:
            raise RuntimeError("deployment has no ServerConfig to "
                               "build replicas from")
        with self._replica_lock:
            used = set()
            for _, service in self.replicas:
                name = getattr(getattr(service, "config", None),
                               "server_name", "")
                if name.startswith("query_r") and name[7:].isdigit():
                    used.add(int(name[7:]))
            index = next(i for i in range(len(used) + 1)
                         if i not in used)
            port = next_replica_port(
                self.gateway.config.port,
                [srv.port for srv, _ in self.replicas])
        rcfg = dataclasses.replace(
            self.server_config, port=port, server_name=f"query_r{index}",
            upgrade_check=False,
        )
        srv, service = create_server(rcfg)
        srv.start()
        with self._replica_lock:
            self.replicas.append((srv, service))
        host = "127.0.0.1" if srv.host in ("0.0.0.0", "::") else srv.host
        replica = self.gateway.add_replica(host, srv.port)
        logger.info("spawned replica %s (%s)", replica.id,
                    rcfg.server_name)
        return replica.id

    def stop_replica(self, replica_id: str,
                     drain_timeout: float = 10.0) -> bool:
        """Gracefully retire one replica: draining state (no new
        traffic), wait out in-flight requests, stop its server, drain
        its micro-batcher, drop it from registry + gateway."""
        entry = self._find(replica_id)
        replica = self.gateway.registry.find(replica_id)
        if replica is not None:
            self.gateway.registry.mark_draining(replica)
            self.gateway.registry.wait_drained(replica, drain_timeout)
        if entry is not None:
            self._teardown(entry)
        self.gateway.remove_replica(replica_id)
        return entry is not None or replica is not None

    def discard_replica(self, replica_id: str) -> None:
        """Local teardown behind a gateway-level eviction (the registry
        entry is already gone): stop the server and its service threads
        without a drain — eviction targets replicas presumed dead."""
        entry = self._find(replica_id)
        if entry is None:
            return
        self._teardown(entry)

    def restart_replica(self, replica_id: str) -> str:
        """Rebuild a (presumed dead) replica ON ITS PORT: stop whatever
        is left of the old server, create a fresh server + service from
        the same ServerConfig, start it. The registry entry survives —
        the next health probe marks it healthy again."""
        from predictionio_tpu.workflow.create_server import create_server

        entry = self._find(replica_id)
        if entry is None:
            raise ValueError(f"unknown replica {replica_id}")
        old_srv, old_service = entry
        self._teardown(entry, remove=False)  # slot reused below
        # pin the BOUND port: ephemeral-port replicas (ServerConfig
        # port=0) must come back on the address the registry knows
        rcfg = dataclasses.replace(
            old_service.config, port=old_srv.port, upgrade_check=False)
        srv, service = create_server(rcfg)
        srv.start()
        with self._replica_lock:
            idx = self.replicas.index(entry)
            self.replicas[idx] = (srv, service)
        # stale keep-alive connections would still reach the old
        # (stopped) service's handler threads
        self.gateway.drop_pooled(replica_id)
        logger.warning("restarted replica %s (%s)", replica_id,
                       rcfg.server_name)
        return replica_id

    # -- autoscaler provisioner protocol ------------------------------------
    def scale_up(self) -> str | None:
        return self.spawn_replica()

    def scale_down(self, drain_timeout: float | None = None) -> str | None:
        """Retire the newest routable replica (LIFO keeps the original
        fleet's stable ports). None when no routable victim exists."""
        candidates = [r for r in self.gateway.registry.replicas()
                      if r.state in ("healthy", "suspect")]
        if not candidates:
            return None
        victim = max(candidates, key=lambda r: r.seq)
        ok = self.stop_replica(
            victim.id,
            drain_timeout=10.0 if drain_timeout is None else drain_timeout)
        return victim.id if ok else None


def create_gateway_deployment(server_config, n_replicas: int,
                              gateway_config: GatewayConfig | None = None
                              ) -> GatewayDeployment:
    """Assemble gateway + N in-process replicas from one engine
    ServerConfig. Replica ports: consecutive after the gateway's port
    (gateway 8000 -> replicas 8001..8000+N), or all ephemeral when the
    gateway binds port 0 (tests/bench).

    In-process replicas each load their own model copy and serve on
    their own port — on a multi-core host the device calls and HTTP
    handling overlap across replicas; process-per-replica layouts can
    point the same gateway at remote ports instead (add_replica)."""
    from predictionio_tpu.workflow.create_server import create_server

    if n_replicas < 1:
        raise ValueError("need at least one replica")
    gateway_config = gateway_config or GatewayConfig()
    replicas = []
    for i in range(n_replicas):
        rport = 0 if gateway_config.port == 0 else gateway_config.port + 1 + i
        rcfg = dataclasses.replace(
            server_config, port=rport, server_name=f"query_r{i}",
            # one upgrade probe per deployment is plenty; replica 0 keeps
            # the daily check, siblings skip the redundant timers
            upgrade_check=server_config.upgrade_check and i == 0,
        )
        replicas.append(create_server(rcfg))
    gateway = Gateway(gateway_config)
    server = AppServer(gateway.router, gateway_config.ip,
                       gateway_config.port, server_name="gateway")
    return GatewayDeployment(gateway, server, replicas,
                             server_config=server_config)
