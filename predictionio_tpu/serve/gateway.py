"""Serving gateway: the front door for N query-server replicas.

``pio deploy --replicas N`` puts this HTTP server (built on
:mod:`predictionio_tpu.utils.http`, same stack as every other server in
the process) in front of N replicas and gives ``POST /queries.json``
the tail-latency toolkit single-replica serving lacks:

  * **least-outstanding balancing** — pick the replica with the fewest
    in-flight requests (registration order breaks ties), acquired
    atomically under the registry lock;
  * **per-request deadline budget** — every retry/hedge fits inside one
    end-to-end deadline, so a struggling fleet degrades to bounded
    latency instead of unbounded queueing;
  * **hedged retry** — when the primary hasn't answered after a
    p99-derived delay, fire the SAME query at a second replica and take
    whichever answers first (the classic tail-at-scale hedge; predict is
    read-only, so duplicated work is safe);
  * **connect-failure retry** — a replica that can't be reached fails
    over to the next with exponential backoff, inside the deadline;
  * **per-replica circuit breaker** — K consecutive transport failures
    open the breaker and shed that replica; after a cooldown one
    half-open probe decides whether to close it again;
  * **query-result cache** — :mod:`predictionio_tpu.serve.cache`,
    invalidated on ``/reload`` and on redeploy (instance-id change seen
    by the health checker).

Replica HTTP errors (4xx/5xx with a response) pass through untouched —
they are the *query's* problem, not the replica's, and must not trip the
breaker or burn retries.
"""

from __future__ import annotations

import http.client
import json
import logging
import queue
import threading
import time
from dataclasses import dataclass

from predictionio_tpu.obs import (
    REGISTRY,
    REQUEST_ID_HEADER,
    current_request_id,
    trace,
)
from predictionio_tpu.serve.cache import QueryCache, canonical_query_key
from predictionio_tpu.serve.registry import Replica, ReplicaRegistry
from predictionio_tpu.utils.http import (
    AppServer,
    RawResponse,
    Request,
    Router,
    add_metrics_route,
)

logger = logging.getLogger(__name__)

DEFAULT_GATEWAY_PORT = 8000  # the gateway takes the engine server's door

_GW_REQUESTS = REGISTRY.counter(
    "pio_gateway_requests_total",
    "Gateway /queries.json outcomes (cache_hit answered locally; "
    "no_replica/deadline/error are gateway-side failures)",
    labels=("outcome",),
)
_GW_SECONDS = REGISTRY.histogram(
    "pio_gateway_seconds",
    "End-to-end gateway /queries.json latency, cache hits included",
)
_GW_UPSTREAM_SECONDS = REGISTRY.histogram(
    "pio_gateway_upstream_seconds",
    "Per-attempt replica round-trip latency (hedges and retries each "
    "observe; the merged p99 derives the hedge delay)",
    labels=("replica",),
)
_GW_HEDGES = REGISTRY.counter(
    "pio_gateway_hedges_total",
    "Hedged second requests: fired, and won (hedge answered first)",
    labels=("result",),
)
_GW_RETRIES = REGISTRY.counter(
    "pio_gateway_retries_total",
    "Connect-failure failovers to another replica",
)
_GW_BREAKER_OPEN = REGISTRY.gauge(
    "pio_gateway_breaker_open",
    "1 while a replica's circuit breaker is open",
    labels=("replica",),
)
_GW_COALESCED = REGISTRY.counter(
    "pio_gateway_coalesced_total",
    "Requests that waited on an identical in-flight query instead of "
    "going upstream (cache singleflight)",
)


class CircuitBreaker:
    """Per-replica breaker: closed -> open after ``failures_to_open``
    CONSECUTIVE transport failures; after ``cooldown_sec`` one half-open
    probe is admitted — success closes, failure re-opens. ``now`` is
    injectable for deterministic tests."""

    def __init__(self, failures_to_open: int = 5, cooldown_sec: float = 5.0,
                 now=time.monotonic):
        self.failures_to_open = failures_to_open
        self.cooldown_sec = cooldown_sec
        self._now = now
        self._lock = threading.Lock()
        self.state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False

    def allow(self) -> bool:
        """Whether a request may pass NOW. In half-open this admits (and
        consumes) the single probe slot, so call it only on the replica
        actually being routed to."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._now() - self._opened_at >= self.cooldown_sec:
                    self.state = "half_open"
                    self._probing = True
                    return True
                return False
            # half_open: one probe at a time
            if not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state != "closed":
                logger.info("breaker closing (%s -> closed)", self.state)
            self.state = "closed"
            self._consecutive = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self.state == "half_open" or (
                self._consecutive >= self.failures_to_open
            ):
                if self.state != "open":
                    logger.warning(
                        "breaker opening after %d consecutive failures",
                        self._consecutive,
                    )
                self.state = "open"
                self._opened_at = self._now()
                self._probing = False

    def cancel_probe(self) -> None:
        """Hand back an admitted-but-unused half-open probe slot (the
        caller decided not to send the request after all — e.g. the
        deadline couldn't absorb the retry backoff). Without this the
        slot would stay consumed forever and the replica would never be
        probed again."""
        with self._lock:
            if self.state == "half_open":
                self._probing = False

    def reset(self) -> None:
        """Close unconditionally (a successful health probe proved the
        transport works again)."""
        self.record_success()


@dataclass
class GatewayConfig:
    ip: str = "0.0.0.0"
    port: int = DEFAULT_GATEWAY_PORT
    #: end-to-end budget per /queries.json request; every retry and
    #: hedge fits inside it
    deadline_sec: float = 10.0
    #: hedged retry: fire a second attempt after the (clamped) merged
    #: p99 of replica round trips. hedge_delay_sec pins the delay
    #: (tests, operators who know their tail); None derives it.
    hedge: bool = True
    hedge_delay_sec: float | None = None
    hedge_min_delay_sec: float = 0.01
    hedge_max_delay_sec: float = 1.0
    #: connect-failure failover backoff: base * 2^attempt, capped
    retry_backoff_base_sec: float = 0.02
    retry_backoff_max_sec: float = 0.5
    #: circuit breaker tunables
    breaker_failures: int = 5
    breaker_cooldown_sec: float = 5.0
    #: result cache (0 entries or 0 TTL disables)
    cache_max_entries: int = 1024
    cache_ttl_sec: float = 30.0
    #: replica health checking
    health_interval_sec: float = 1.0
    health_timeout_sec: float = 2.0
    health_down_after: int = 3
    #: extra fleet-federation member: the event server's (host, port),
    #: scraped into GET /metrics/fleet next to the replicas (None = the
    #: serving fleet only)
    event_server: "tuple[str, int] | None" = None
    #: per-member scrape timeout for GET /metrics/fleet
    fleet_scrape_timeout_sec: float = 2.0


class Gateway:
    """Routing/hedging/caching front end over a ReplicaRegistry.

    Build, ``add_replica()`` for each backend, then ``start()`` — or let
    :func:`create_gateway_deployment` assemble the whole in-process
    topology (N replicas + gateway) in one call."""

    def __init__(self, config: GatewayConfig | None = None):
        self.config = config or GatewayConfig()
        self.cache = QueryCache(self.config.cache_max_entries,
                                self.config.cache_ttl_sec)
        self.registry = ReplicaRegistry(
            health_interval_sec=self.config.health_interval_sec,
            check_timeout_sec=self.config.health_timeout_sec,
            down_after=self.config.health_down_after,
            on_instance_change=self._on_instance_change,
            on_probe_result=self._on_probe_result,
        )
        self.start_time = time.time()
        self._stop_event = threading.Event()
        self._breakers: dict[str, CircuitBreaker] = {}
        self._pools: dict[str, list[http.client.HTTPConnection]] = {}
        self._pool_lock = threading.Lock()
        # singleflight: cache key -> Event for queries in flight, so N
        # concurrent identical misses cost ONE replica round trip
        self._inflight: dict[str, threading.Event] = {}
        self._inflight_lock = threading.Lock()
        # per-gateway counters (the pio_gateway_* metrics are process-
        # global; tests and the status page want THIS gateway's numbers)
        self._stats_lock = threading.Lock()
        self.request_count = 0
        self.error_count = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.retries = 0
        self.router = self._build_router()

    # -- assembly -----------------------------------------------------------
    def add_replica(self, host: str, port: int) -> Replica:
        r = self.registry.add(host, port)
        self._breakers[r.id] = CircuitBreaker(
            self.config.breaker_failures, self.config.breaker_cooldown_sec
        )
        _GW_BREAKER_OPEN.set(0, replica=r.id)
        return r

    def start(self) -> None:
        # one synchronous sweep so routing state and the fleet instance
        # id are populated before the first proxied query (probe-ok
        # results also clear breakers, via _on_probe_result)
        self.registry.check_once()
        self.registry.start()

    def stop(self) -> None:
        self.registry.stop()
        self._stop_event.set()
        with self._pool_lock:
            for conns in self._pools.values():
                for c in conns:
                    c.close()
            self._pools.clear()

    def wait_for_stop(self) -> None:
        self._stop_event.wait()

    def _on_instance_change(self, instance_id: str) -> None:
        dropped = self.cache.invalidate()
        if dropped:
            logger.info(
                "engine instance changed to %s: dropped %d cached results",
                instance_id, dropped,
            )

    def _on_probe_result(self, replica: Replica, ok: bool) -> None:
        """A successful health probe is transport-level proof the replica
        is reachable again: close its breaker so recovery doesn't wait
        for the request path's half-open lottery. Failed probes do NOT
        trip the breaker — the health state machine handles downing, and
        double-counting would open breakers for replicas that merely
        answered a probe slowly."""
        if not ok:
            return
        breaker = self._breakers.get(replica.id)
        if breaker is not None and breaker.state != "closed":
            breaker.reset()
            _GW_BREAKER_OPEN.set(0, replica=replica.id)

    # -- routes -------------------------------------------------------------
    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self.get_status)
        r.add("POST", "/queries.json", self.post_query)
        r.add("GET", "/reload", self.get_reload)
        r.add("GET", "/stop", self.get_stop)
        r.add("GET", "/metrics/fleet", self.get_fleet_metrics)
        add_metrics_route(r)
        return r

    # -- fleet federation (obs/fleet.py) ------------------------------------
    def fleet_targets(self) -> list:
        """Federation membership: the gateway itself (read locally — no
        HTTP round trip into our own process), every registered replica,
        and the configured event server."""
        from predictionio_tpu.obs import fleet

        targets = [fleet.FleetTarget(
            instance="gateway", role="gateway", registry=REGISTRY)]
        for r in self.registry.replicas():
            targets.append(fleet.FleetTarget(
                instance=r.id, host=r.host, port=r.port, role="replica"))
        if self.config.event_server is not None:
            host, port = self.config.event_server
            if host in ("0.0.0.0", "::"):
                host = "127.0.0.1"
            targets.append(fleet.FleetTarget(
                instance=f"{host}:{port}", host=host, port=port,
                role="event"))
        return targets

    def get_fleet_metrics(self, request: Request):
        """``GET /metrics/fleet``: scrape every member's /metrics
        concurrently and serve the instance-labelled merge (dead members
        omitted; see obs/fleet.py for the per-kind merge rules)."""
        from predictionio_tpu.obs import fleet
        from predictionio_tpu.utils.http import METRICS_CONTENT_TYPE

        results = fleet.collect(
            self.fleet_targets(),
            timeout=self.config.fleet_scrape_timeout_sec)
        return 200, RawResponse(fleet.federated_exposition(results),
                                METRICS_CONTENT_TYPE)

    def get_status(self, request: Request):
        with self._stats_lock:
            body = {
                "status": "alive",
                "role": "gateway",
                "engineInstanceId": self.registry.instance_id(),
                "requestCount": self.request_count,
                "errorCount": self.error_count,
                "hedgesFired": self.hedges_fired,
                "hedgesWon": self.hedges_won,
                "retries": self.retries,
            }
        body["replicas"] = [
            {**snap, "breaker": self._breakers[snap["replica"]].state}
            for snap in self.registry.snapshot()
        ]
        body["cache"] = self.cache.stats()
        p99 = _GW_UPSTREAM_SECONDS.quantile(0.99)
        body["hedgeDelaySec"] = round(self._hedge_delay(), 6)
        if p99 is not None:
            body["upstreamP99Sec"] = round(p99, 6)
        return 200, body

    def _replica_control(self, replica: Replica, path: str,
                         timeout: float) -> tuple[int, dict]:
        """GET a control endpoint (/reload, /stop) on a replica over a
        fresh direct connection — NOT urllib, whose proxy env-var
        handling could reroute gateway→replica traffic that
        /queries.json (http.client, direct) sends straight through."""
        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = json.loads(resp.read() or b"{}")
            return resp.status, body if isinstance(body, dict) else {}
        finally:
            conn.close()

    def get_reload(self, request: Request):
        """Fan /reload out to every replica CONCURRENTLY (a model
        hot-swap takes seconds per replica; paying the max beats paying
        the sum), then invalidate the cache."""
        replicas = [r for r in self.registry.replicas()
                    if r.state != "draining"]
        results: list[dict | None] = [None] * len(replicas)

        def reload_one(i: int, r: Replica) -> None:
            try:
                status, body = self._replica_control(r, "/reload", 30.0)
                if status == 200:
                    results[i] = {"replica": r.id, **body}
                else:
                    results[i] = {"replica": r.id,
                                  "error": f"HTTP {status}", **body}
            except (OSError, ValueError) as e:
                results[i] = {"replica": r.id, "error": str(e)}

        threads = [
            threading.Thread(target=reload_one, args=(i, r), daemon=True)
            for i, r in enumerate(replicas)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.cache.invalidate()
        # pick up the new instance id right away (also re-invalidates
        # via the change callback, which is idempotent on an empty cache)
        self.registry.check_once()
        return 200, {"reloaded": True, "replicas": results}

    def get_stop(self, request: Request):
        """Graceful undeploy: answer 200 immediately, then on a
        background thread drain in-flight traffic, forward /stop to
        every replica, and release ``wait_for_stop``."""

        def shutdown():
            self.registry.stop()
            self.registry.drain(timeout_sec=10.0)
            for r in self.registry.replicas():
                try:
                    self._replica_control(r, "/stop", 5.0)
                except (OSError, ValueError):
                    logger.debug("replica %s already gone", r.id)
            self._stop_event.set()

        threading.Thread(target=shutdown, name="gateway-stop",
                         daemon=True).start()
        return 200, {"message": "Shutting down."}

    # -- the proxied hot path ----------------------------------------------
    def post_query(self, request: Request):
        t0 = time.perf_counter()
        with self._stats_lock:
            self.request_count += 1
        try:
            status, payload = self._proxy_query(request)
        except Exception:
            with self._stats_lock:
                self.error_count += 1
            _GW_REQUESTS.inc(outcome="error")
            raise
        if status >= 500:
            with self._stats_lock:
                self.error_count += 1
        _GW_SECONDS.observe(time.perf_counter() - t0)
        if status in (429, 503) and isinstance(payload, dict) \
                and payload.get("retryAfterSec") is not None:
            # shed/unavailable responses carry the backoff hint as a
            # real Retry-After header, not just a body field
            import math

            sec = max(int(math.ceil(float(payload["retryAfterSec"]))), 1)
            return status, RawResponse(
                json.dumps(payload),
                "application/json; charset=UTF-8",
                headers={"Retry-After": str(sec)},
            )
        return status, payload

    def _proxy_query(self, request: Request) -> tuple[int, object]:
        deadline = time.monotonic() + self.config.deadline_sec
        key = None
        leader = False
        if self.cache.enabled:
            instance = self.registry.instance_id()
            if instance:
                key = canonical_query_key(request.body, instance)
            if key is not None:
                hit = self.cache.get(key)
                if hit is not None:
                    _GW_REQUESTS.inc(outcome="cache_hit")
                    trace.add_event("cache_hit")
                    return 200, hit
                # singleflight: one of N concurrent identical misses
                # goes upstream (the leader); the rest wait for its
                # cached result — a herd of repeats must not multiply
                # device work across the fleet
                while True:
                    with self._inflight_lock:
                        ev = self._inflight.get(key)
                        if ev is None:
                            self._inflight[key] = threading.Event()
                            leader = True
                            break
                    _GW_COALESCED.inc()
                    trace.add_event("singleflight_coalesced")
                    ev.wait(timeout=max(deadline - time.monotonic(), 0.0))
                    hit = self.cache.get(key)
                    if hit is not None:
                        _GW_REQUESTS.inc(outcome="cache_hit")
                        trace.add_event("cache_hit", coalesced=True)
                        return 200, hit
                    # leader failed or the result wasn't cacheable (non-
                    # 200): fall through and fetch (or re-lead) ourselves
                    if deadline - time.monotonic() <= 0:
                        break
        try:
            status, payload = self._fetch(request.body, deadline)
            if status == 200 and key is not None:
                self.cache.put(key, payload)
        finally:
            if leader:
                with self._inflight_lock:
                    ev = self._inflight.pop(key, None)
                if ev is not None:
                    ev.set()
        if isinstance(payload, dict) and "pioGatewayOutcome" in payload:
            outcome = payload.pop("pioGatewayOutcome")  # gateway-side fail
        elif status >= 500:
            outcome = "upstream_error"  # the replica answered 5xx
        else:
            outcome = "ok"
        _GW_REQUESTS.inc(outcome=outcome)
        return status, payload

    def _hedge_delay(self) -> float:
        if self.config.hedge_delay_sec is not None:
            return self.config.hedge_delay_sec
        p99 = _GW_UPSTREAM_SECONDS.quantile(0.99)
        if p99 is None:  # no traffic yet: be conservative, hedge late
            return self.config.hedge_max_delay_sec
        return min(max(p99, self.config.hedge_min_delay_sec),
                   self.config.hedge_max_delay_sec)

    def _launch(self, replica: Replica, body: bytes, rid: str | None,
                deadline: float, resq: "queue.Queue", kind: str) -> None:
        """Fire one upstream attempt on its own thread; results land on
        ``resq`` as ('ok', status, payload, replica, kind) or
        ('err', exc, None, replica, kind)."""
        # the attempt runs on a fresh thread, where contextvars don't
        # follow — capture the gateway server span HERE (the handler
        # thread) so the upstream client span parents correctly, and
        # hold the trace open so a hedge attempt that hasn't been
        # scheduled yet when the handler answers (primary won) still
        # lands its span before the trace commits
        handle = trace.capture()
        held = trace.hold(handle)

        def run():
            t0 = time.perf_counter()
            try:
                with trace.child_span(handle, "upstream",
                                      replica=replica.id, kind=kind):
                    try:
                        timeout = max(deadline - time.monotonic(), 0.05)
                        status, payload = self._upstream_query(
                            replica, body, rid, timeout)
                    except Exception as e:  # noqa: BLE001 — transport failure
                        self._record_transport(replica, ok=False)
                        resq.put(("err", e, None, replica, kind))
                    else:
                        self._record_transport(replica, ok=True)
                        _GW_UPSTREAM_SECONDS.observe(
                            time.perf_counter() - t0, replica=replica.id)
                        resq.put(("ok", status, payload, replica, kind))
                    finally:
                        self.registry.release(replica)
            finally:
                trace.release(held)

        threading.Thread(target=run, name=f"gw-{kind}", daemon=True).start()

    def _record_transport(self, replica: Replica, ok: bool) -> None:
        breaker = self._breakers[replica.id]
        if ok:
            breaker.record_success()
        else:
            breaker.record_failure()
        _GW_BREAKER_OPEN.set(
            1 if breaker.state == "open" else 0, replica=replica.id)

    def _acquire(self, exclude: set[str]) -> Replica | None:
        return self.registry.acquire_least_outstanding(
            admit=lambda r: self._breakers[r.id].allow(), exclude=exclude
        )

    def _fetch(self, body: bytes, deadline: float) -> tuple[int, object]:
        """Balanced + hedged + retried fetch of one query against the
        fleet, inside ``deadline``."""
        cfg = self.config
        if deadline - time.monotonic() <= 0:
            # e.g. a singleflight follower that waited out its whole
            # budget: don't burn a replica's device time on a response
            # nobody will read
            return 504, {"message": "Deadline exceeded.",
                         "pioGatewayOutcome": "deadline"}
        rid = current_request_id()
        resq: "queue.Queue" = queue.Queue()
        tried: set[str] = set()
        if trace.current_trace_id() is not None:
            # the breaker scan runs only under an active span: untraced
            # queries must not pay for building an event they can't keep
            open_breakers = sorted(
                r for r, b in self._breakers.items() if b.state == "open")
            if open_breakers:  # shed replicas this request routes around
                trace.add_event("breaker_open",
                                replicas=",".join(open_breakers))
        primary = self._acquire(exclude=tried)
        if primary is None:
            return 503, {"message": "No replica available.",
                         "retryAfterSec": self.config.breaker_cooldown_sec,
                         "pioGatewayOutcome": "no_replica"}
        tried.add(primary.id)
        self._launch(primary, body, rid, deadline, resq, "primary")
        pending = 1
        hedged = not cfg.hedge  # True = don't (or can't) hedge anymore
        backoff = cfg.retry_backoff_base_sec
        last_err: Exception | None = None
        last_shed: tuple[int, object] | None = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            wait = remaining if hedged else min(self._hedge_delay(),
                                                remaining)
            try:
                res = resq.get(timeout=wait)
            except queue.Empty:
                if hedged:
                    break  # deadline spent with attempts still in flight
                hedged = True  # one hedge per request
                hedge_replica = self._acquire(exclude=tried)
                if hedge_replica is not None:
                    tried.add(hedge_replica.id)
                    with self._stats_lock:
                        self.hedges_fired += 1
                    _GW_HEDGES.inc(result="fired")
                    trace.add_event("hedge_fired",
                                    replica=hedge_replica.id)
                    self._launch(hedge_replica, body, rid, deadline, resq,
                                 "hedge")
                    pending += 1
                continue
            tag, a, b, replica, kind = res
            if tag == "ok" and a == 429:
                # upstream admission shed: BACKPRESSURE, not a replica
                # fault — the breaker already recorded the transport
                # success. Fail over to another replica inside the
                # budget; if none answers, the 429 (with its Retry-After
                # hint) surfaces to the client.
                trace.add_event("upstream_backpressure",
                                replica=replica.id)
                last_shed = (a, b)
            elif tag == "ok":
                if kind == "hedge":
                    with self._stats_lock:
                        self.hedges_won += 1
                    _GW_HEDGES.inc(result="won")
                    trace.add_event("hedge_won", replica=replica.id)
                return a, b  # replica's status/payload, 4xx/5xx included
            else:
                last_err = a
            pending -= 1
            if pending > 0:
                continue  # a hedge twin is still running: let it race
            # every launched attempt failed (transport) or shed (429):
            # failover with exponential backoff while the budget lasts.
            # No second lap through already-failed replicas — a fleet
            # that just failed everywhere answers faster with an honest
            # 503 + Retry-After than with more doomed connects.
            retry = self._acquire(exclude=tried)
            if retry is None:
                break
            remaining = deadline - time.monotonic()
            if remaining <= backoff:
                # un-acquire: the budget can't absorb the backoff sleep.
                # Hand back any half-open probe slot allow() consumed,
                # or the unprobed replica would be shed forever
                self.registry.release(retry)
                self._breakers[retry.id].cancel_probe()
                break
            time.sleep(backoff)
            backoff = min(backoff * 2, cfg.retry_backoff_max_sec)
            tried.add(retry.id)
            with self._stats_lock:
                self.retries += 1
            _GW_RETRIES.inc()
            trace.add_event("retry_fired", replica=retry.id)
            self._launch(retry, body, rid, deadline, resq, "retry")
            pending += 1
        if last_shed is not None:
            # the fleet is shedding everywhere: pass the backpressure
            # through (429 + Retry-After), never convert it into a 5xx
            status, payload = last_shed
            if isinstance(payload, dict):
                payload = {**payload, "pioGatewayOutcome": "backpressure"}
            return status, payload
        if last_err is not None:
            logger.warning("query failed against all replicas: %s", last_err)
            # every replica failed at the transport level: an honest
            # 503 + Retry-After, well inside the deadline budget — the
            # client backs off instead of piling onto a down fleet
            return 503, {"message": f"All replicas unavailable: {last_err}",
                         "retryAfterSec": self.config.breaker_cooldown_sec,
                         "pioGatewayOutcome": "all_down"}
        return 504, {"message": "Deadline exceeded.",
                     "pioGatewayOutcome": "deadline"}

    # -- upstream transport (pooled keep-alive) -----------------------------
    def _pool_get(self, replica: Replica) -> http.client.HTTPConnection | None:
        with self._pool_lock:
            conns = self._pools.get(replica.id)
            if conns:
                return conns.pop()
            return None

    def _pool_put(self, replica: Replica,
                  conn: http.client.HTTPConnection) -> None:
        with self._pool_lock:
            self._pools.setdefault(replica.id, []).append(conn)

    def _upstream_query(self, replica: Replica, body: bytes,
                        rid: str | None, timeout: float):
        """One POST /queries.json round trip. Raises on transport
        failure (connect/read error, malformed response); a pooled
        keep-alive connection that went stale surfaces here too and the
        caller's retry path covers it (predict is read-only, so a
        resend is always safe)."""
        from predictionio_tpu.resilience import faults

        # the chaos suite's replica-transport site: an injected error is
        # indistinguishable from a connect/read failure and exercises the
        # breaker + failover machinery for real
        faults.fault_point("replica.socket")
        conn = self._pool_get(replica)
        if conn is None:
            conn = http.client.HTTPConnection(
                replica.host, replica.port, timeout=timeout)
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        headers = {"Content-Type": "application/json"}
        if rid:
            headers[REQUEST_ID_HEADER] = rid
        # the replica joins this trace: sampled flag + the upstream
        # span (active on this attempt thread) as the remote parent
        trace.inject_headers(headers)
        try:
            conn.request("POST", "/queries.json", body, headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            retry_after = resp.getheader("Retry-After")
        except BaseException:
            conn.close()
            raise
        self._pool_put(replica, conn)
        try:
            payload = json.loads(data or b"null")
        except ValueError:
            payload = {"message": data.decode("utf-8", "replace")}
        if retry_after is not None and isinstance(payload, dict):
            # surface the replica's backoff hint to the failover logic
            # and (on passthrough) to the client
            try:
                payload.setdefault("retryAfterSec", float(retry_after))
            except ValueError:
                pass  # HTTP-date form: ignore, the hint is best-effort
        return status, payload


class GatewayDeployment:
    """One in-process serving topology: N replica query servers plus the
    gateway fronting them. start()/stop() manage every server; the
    gateway's ``/stop`` (hit by ``pio undeploy``) releases
    ``wait_for_stop`` after the graceful drain."""

    def __init__(self, gateway: Gateway, gateway_server: AppServer,
                 replicas: list):
        self.gateway = gateway
        self.server = gateway_server
        self.replicas = replicas  # [(AppServer, QueryService), ...]

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> None:
        for srv, _service in self.replicas:
            srv.start()
            self.gateway.add_replica(
                "127.0.0.1" if srv.host in ("0.0.0.0", "::") else srv.host,
                srv.port,
            )
        self.gateway.start()
        self.server.start()

    def wait_for_stop(self) -> None:
        self.gateway.wait_for_stop()

    def stop(self) -> None:
        self.gateway.stop()
        self.server.stop()
        for srv, service in self.replicas:
            srv.stop()
            # drain each replica's micro-batcher (a mid-flight deferred
            # finalize completes) and join its worker threads, so a
            # `pio stop-all`-driven teardown can't race them
            shutdown = getattr(service, "shutdown", None)
            if shutdown is not None:
                shutdown()


def create_gateway_deployment(server_config, n_replicas: int,
                              gateway_config: GatewayConfig | None = None
                              ) -> GatewayDeployment:
    """Assemble gateway + N in-process replicas from one engine
    ServerConfig. Replica ports: consecutive after the gateway's port
    (gateway 8000 -> replicas 8001..8000+N), or all ephemeral when the
    gateway binds port 0 (tests/bench).

    In-process replicas each load their own model copy and serve on
    their own port — on a multi-core host the device calls and HTTP
    handling overlap across replicas; process-per-replica layouts can
    point the same gateway at remote ports instead (add_replica)."""
    import dataclasses

    from predictionio_tpu.workflow.create_server import create_server

    if n_replicas < 1:
        raise ValueError("need at least one replica")
    gateway_config = gateway_config or GatewayConfig()
    replicas = []
    for i in range(n_replicas):
        rport = 0 if gateway_config.port == 0 else gateway_config.port + 1 + i
        rcfg = dataclasses.replace(
            server_config, port=rport, server_name=f"query_r{i}",
            # one upgrade probe per deployment is plenty; replica 0 keeps
            # the daily check, siblings skip the redundant timers
            upgrade_check=server_config.upgrade_check and i == 0,
        )
        replicas.append(create_server(rcfg))
    gateway = Gateway(gateway_config)
    server = AppServer(gateway.router, gateway_config.ip,
                       gateway_config.port, server_name="gateway")
    return GatewayDeployment(gateway, server, replicas)
