"""Query-result cache for the serving gateway: LRU + TTL.

Predict is a pure function of (query, deployed engine instance): the same
canonicalized query against the same instance id returns the same
prediction, so the gateway can answer repeats without a replica round
trip — the result-cache layer of Cloudflow-style prediction serving
(arXiv:2007.05832 §4). Keys carry the engine-instance id, so a redeploy
(new instance id observed by the health checker) or an explicit
``/reload`` naturally invalidates every cached answer.

NOT safe with the feedback loop: a cache hit skips the replica, so no
predict event is logged and no fresh ``prId`` is minted. `pio deploy
--feedback --replicas N` therefore disables the cache (tools/cli.py).
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Any

from predictionio_tpu.obs import REGISTRY

_CACHE_HITS = REGISTRY.counter(
    "pio_gateway_cache_hits_total",
    "Gateway query-result cache hits (answered without a replica)",
)
_CACHE_MISSES = REGISTRY.counter(
    "pio_gateway_cache_misses_total",
    "Gateway query-result cache misses (expired entries count here too)",
)
_CACHE_EVICTIONS = REGISTRY.counter(
    "pio_gateway_cache_evictions_total",
    "Gateway cache entries evicted by capacity (TTL expiry not counted)",
)
_CACHE_ENTRIES = REGISTRY.gauge(
    "pio_gateway_cache_entries",
    "Live entries in the gateway query-result cache",
)


def canonical_query_key(body: bytes, instance_id: str) -> str | None:
    """Cache key for a raw ``/queries.json`` body against one deployed
    engine instance, or None when the body isn't a JSON object (those
    requests 400 at the replica; never cache them). Canonicalization is
    key-order-insensitive: ``{"user":"u1","num":3}`` and
    ``{"num":3,"user":"u1"}`` share an entry."""
    try:
        obj = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(obj, dict):
        return None
    return instance_id + "|" + json.dumps(
        obj, sort_keys=True, separators=(",", ":")
    )


class QueryCache:
    """Thread-safe LRU + TTL map from canonical query key to the
    replica's 200 payload. Per-instance hit/miss/eviction counts feed the
    gateway status page; the module-level ``pio_gateway_cache_*`` metrics
    aggregate across gateways for ``/metrics``."""

    def __init__(self, max_entries: int = 1024, ttl_sec: float = 30.0):
        self.max_entries = max_entries
        self.ttl_sec = ttl_sec
        self._lock = threading.Lock()
        self._data: OrderedDict[str, tuple[float, Any]] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def enabled(self) -> bool:
        return self.max_entries > 0 and self.ttl_sec > 0

    def get(self, key: str) -> Any | None:
        """The cached payload, or None on miss/expiry. A live hit is
        refreshed to most-recently-used."""
        now = time.monotonic()
        with self._lock:
            entry = self._data.get(key)
            if entry is not None and entry[0] > now:
                self._data.move_to_end(key)
                self.hits += 1
                _CACHE_HITS.inc()
                return entry[1]
            if entry is not None:  # expired: drop so capacity stays honest
                del self._data[key]
                _CACHE_ENTRIES.set(len(self._data))
            self.misses += 1
            _CACHE_MISSES.inc()
            return None

    def put(self, key: str, payload: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
            elif len(self._data) >= self.max_entries:
                self._data.popitem(last=False)  # LRU out
                self.evictions += 1
                _CACHE_EVICTIONS.inc()
            self._data[key] = (time.monotonic() + self.ttl_sec, payload)
            _CACHE_ENTRIES.set(len(self._data))

    def invalidate(self) -> int:
        """Drop everything (on ``/reload`` and on redeploy, i.e. an
        engine-instance-id change); returns the number dropped."""
        with self._lock:
            n = len(self._data)
            self._data.clear()
            _CACHE_ENTRIES.set(0)
            return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._data),
                "maxEntries": self.max_entries,
                "ttlSec": self.ttl_sec,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
