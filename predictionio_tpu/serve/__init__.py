"""Serving gateway subsystem: replica routing, hedged retries, circuit
breaking, and a query-result cache in front of N query-server replicas.

``pio deploy --replicas N`` (tools/cli.py) assembles the whole topology;
the pieces compose independently:

  * :mod:`predictionio_tpu.serve.registry` — replica set with periodic
    health checks (healthy -> suspect -> down state machine, graceful
    drain) and least-outstanding acquisition;
  * :mod:`predictionio_tpu.serve.gateway` — the HTTP front door:
    balancing, per-request deadline budget, one hedged retry after a
    p99-derived delay, exponential-backoff failover on connect failure,
    per-replica circuit breaker;
  * :mod:`predictionio_tpu.serve.cache` — LRU+TTL query-result cache
    keyed on canonicalized query JSON + engine-instance id, invalidated
    on ``/reload`` and redeploy;
  * :mod:`predictionio_tpu.serve.autoscaler` — the closed control loop:
    scale up on fast-window SLO burn or sustained queue growth, drain
    idle replicas back down, with cooldowns and flap damping
    (``pio deploy --max-replicas N``).

Everything exposes ``pio_gateway_*`` metrics through the process
registry (``GET /metrics`` on the gateway port).
"""

from predictionio_tpu.serve.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerConfig,
)
from predictionio_tpu.serve.cache import (  # noqa: F401
    QueryCache,
    canonical_query_key,
)
from predictionio_tpu.serve.gateway import (  # noqa: F401
    CircuitBreaker,
    Gateway,
    GatewayConfig,
    GatewayDeployment,
    create_gateway_deployment,
)
from predictionio_tpu.serve.registry import (  # noqa: F401
    Replica,
    ReplicaRegistry,
)
