"""Replica registry for the serving gateway: health checks + routing state.

Each query-server replica runs through a per-replica state machine driven
by periodic probes of its ``GET /`` status endpoint:

    healthy --(failed check)--> suspect --(more failures)--> down
       ^                          |                            |
       +-------(successful check)-+----------------------------+

``suspect`` replicas still take traffic (one blip shouldn't halve
capacity); ``down`` replicas are skipped by routing until a probe
succeeds — the fleet-level health-checking layer of large serving
systems (arXiv:2501.10546 §3). ``draining`` is the terminal state used
by graceful undeploy: no new requests, wait for outstanding to hit zero,
then forward ``/stop``.

The registry also tracks per-replica outstanding request counts (the
gateway's least-outstanding balancing reads them under the registry
lock) and the engine-instance id each replica reports, so the gateway
can invalidate its result cache when a redeploy swaps the instance.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
from dataclasses import dataclass
from typing import Callable

from predictionio_tpu.obs import REGISTRY

logger = logging.getLogger(__name__)

STATES = ("healthy", "suspect", "down", "draining")

_HEALTH_CHECKS = REGISTRY.counter(
    "pio_gateway_health_checks_total",
    "Replica health-probe outcomes",
    labels=("result",),
)
_REPLICA_STATES = REGISTRY.gauge(
    "pio_gateway_replicas",
    "Replicas per health state after the last sweep",
    labels=("state",),
)


@dataclass
class Replica:
    host: str
    port: int
    seq: int  # registration order: the stable tie-break for balancing
    state: str = "healthy"
    outstanding: int = 0
    consecutive_failures: int = 0
    instance_id: str | None = None

    @property
    def id(self) -> str:
        return f"{self.host}:{self.port}"

    def snapshot(self) -> dict:
        return {
            "replica": self.id,
            "state": self.state,
            "outstanding": self.outstanding,
            "consecutiveFailures": self.consecutive_failures,
            "engineInstanceId": self.instance_id,
        }


class ReplicaRegistry:
    """Thread-safe replica set + background health checker."""

    def __init__(self, health_interval_sec: float = 1.0,
                 check_timeout_sec: float = 2.0, down_after: int = 3,
                 on_instance_change: Callable[[str], None] | None = None,
                 on_probe_result: Callable[["Replica", bool], None] | None
                 = None):
        self.health_interval_sec = health_interval_sec
        self.check_timeout_sec = check_timeout_sec
        #: consecutive failed probes before suspect becomes down (the
        #: first failure is always just suspect)
        self.down_after = max(down_after, 2)
        self.on_instance_change = on_instance_change
        #: called after every probe with (replica, probe_ok) — the
        #: gateway closes a recovered replica's circuit breaker here
        self.on_probe_result = on_probe_result
        self.lock = threading.Lock()
        self._replicas: list[Replica] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._instance_id: str | None = None
        #: monotonically increasing: replicas can be removed (autoscaler
        #: scale-down, doctor eviction), so len() would recycle seqs
        self._next_seq = 0

    # -- membership ---------------------------------------------------------
    def add(self, host: str, port: int) -> Replica:
        with self.lock:
            r = Replica(host=host, port=port, seq=self._next_seq)
            self._next_seq += 1
            self._replicas.append(r)
            return r

    def find(self, replica_id: str) -> Replica | None:
        with self.lock:
            for r in self._replicas:
                if r.id == replica_id:
                    return r
            return None

    def remove(self, replica_id: str) -> Replica | None:
        """Drop a replica from membership (no more routing, no more
        probes). Returns the removed Replica, or None if unknown."""
        with self.lock:
            for i, r in enumerate(self._replicas):
                if r.id == replica_id:
                    del self._replicas[i]
                    return r
            return None

    def replicas(self) -> list[Replica]:
        with self.lock:
            return list(self._replicas)

    def snapshot(self) -> list[dict]:
        with self.lock:
            return [r.snapshot() for r in self._replicas]

    def instance_id(self) -> str | None:
        """The engine-instance id the fleet last reported (None before
        the first successful probe)."""
        with self.lock:
            return self._instance_id

    # -- routing-side bookkeeping ------------------------------------------
    def acquire_least_outstanding(
        self, admit: Callable[[Replica], bool], exclude: set[str] = frozenset()
    ) -> Replica | None:
        """Pick the routable replica with the fewest outstanding requests
        (registration order breaks ties), skipping ``exclude`` and any
        the ``admit`` predicate (the breaker) rejects, and bump its
        outstanding count atomically — selection and acquisition share
        the registry lock so concurrent handlers can't all pick the same
        idle replica before any increment lands.

        Falls back to down/suspect replicas (still honoring ``admit`` and
        ``exclude``) when nothing routable remains: stale health state
        must degrade to a live-fire probe, not a guaranteed 503."""
        with self.lock:
            for pool in (
                [r for r in self._replicas
                 if r.state in ("healthy", "suspect")],
                [r for r in self._replicas if r.state == "down"],
            ):
                for r in sorted(pool, key=lambda r: (r.outstanding, r.seq)):
                    if r.id in exclude:
                        continue
                    if admit(r):
                        r.outstanding += 1
                        return r
            return None

    def release(self, replica: Replica) -> None:
        with self.lock:
            replica.outstanding = max(replica.outstanding - 1, 0)

    # -- health checking ----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="gateway-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.health_interval_sec):
            try:
                self.check_once()
            except Exception:  # the checker must never die
                logger.exception("health sweep failed")

    def probe(self, replica: Replica) -> dict | None:
        """One GET / against a replica; its status JSON or None."""
        try:
            conn = http.client.HTTPConnection(
                replica.host, replica.port, timeout=self.check_timeout_sec
            )
            try:
                conn.request("GET", "/")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return None
                data = json.loads(body or b"{}")
                return data if isinstance(data, dict) else {}
            finally:
                conn.close()
        except (OSError, ValueError):
            return None

    def check_replica(self, r: Replica) -> bool:
        """Probe ONE replica and advance its state machine (the probe
        runs outside the lock — it blocks on the network; the
        transition applies under it). Shared by the sweep and by
        targeted recovery checks (a restarted replica gets probed alone
        instead of paying a whole-fleet sweep). Returns probe success.
        No-op on draining replicas — including ones that STARTED
        draining mid-probe: a scale-down's graceful drain must never be
        resurrected to ``healthy`` by a concurrent sweep."""
        if r.state == "draining":
            return False
        status = self.probe(r)
        changed_instance = None
        with self.lock:
            if r.state == "draining":
                # mark_draining raced our probe: the drain decision wins
                return status is not None
            if status is not None:
                _HEALTH_CHECKS.inc(result="ok")
                if r.state != "healthy":
                    logger.info("replica %s recovered (%s -> healthy)",
                                r.id, r.state)
                r.state = "healthy"
                r.consecutive_failures = 0
                iid = status.get("engineInstanceId")
                if isinstance(iid, str):
                    r.instance_id = iid
                    if self._instance_id != iid:
                        changed_instance = iid
                        self._instance_id = iid
            else:
                _HEALTH_CHECKS.inc(result="fail")
                r.consecutive_failures += 1
                if r.consecutive_failures >= self.down_after:
                    if r.state != "down":
                        logger.warning("replica %s is down "
                                       "(%d consecutive failed probes)",
                                       r.id, r.consecutive_failures)
                    r.state = "down"
                else:
                    if r.state == "healthy":
                        logger.warning("replica %s is suspect", r.id)
                    r.state = "suspect"
        if self.on_probe_result is not None:
            self.on_probe_result(r, status is not None)
        if changed_instance is not None and self.on_instance_change:
            # a redeploy swapped the engine instance: stale cached
            # answers must go (the cache key carries the id, but
            # dropping them bounds memory and the status page's lie)
            self.on_instance_change(changed_instance)
        return status is not None

    def check_once(self) -> None:
        """One sweep: probe every non-draining replica and advance its
        state machine, then refresh the per-state gauge."""
        for r in self.replicas():
            self.check_replica(r)
        counts = {s: 0 for s in STATES}
        for r in self.replicas():
            counts[r.state] += 1
        for s, n in counts.items():
            _REPLICA_STATES.set(n, state=s)

    # -- per-replica graceful drain (autoscaler scale-down path) ------------
    def mark_draining(self, replica: Replica) -> None:
        """Terminal-state a single replica: routing skips it immediately
        (acquire_least_outstanding only considers healthy/suspect/down),
        the health sweep stops probing it, in-flight requests finish."""
        with self.lock:
            replica.state = "draining"

    def wait_drained(self, replica: Replica, timeout_sec: float = 10.0
                     ) -> bool:
        """Wait for one draining replica's outstanding count to reach
        zero. True when fully drained inside the budget."""
        import time

        deadline = time.monotonic() + timeout_sec
        while time.monotonic() < deadline:
            with self.lock:
                if replica.outstanding == 0:
                    return True
            time.sleep(0.02)
        with self.lock:
            leftover = replica.outstanding
        logger.warning("replica %s drain timed out with %d outstanding",
                       replica.id, leftover)
        return False

    # -- graceful drain (undeploy path) -------------------------------------
    def drain(self, timeout_sec: float = 10.0) -> bool:
        """Stop routing (every replica -> draining), then wait for
        outstanding requests to finish. True when fully drained."""
        import time

        with self.lock:
            for r in self._replicas:
                r.state = "draining"
        deadline = time.monotonic() + timeout_sec
        while time.monotonic() < deadline:
            with self.lock:
                if all(r.outstanding == 0 for r in self._replicas):
                    return True
            time.sleep(0.05)
        with self.lock:
            leftover = sum(r.outstanding for r in self._replicas)
        logger.warning("drain timed out with %d requests outstanding",
                       leftover)
        return False
