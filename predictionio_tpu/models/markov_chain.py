"""First-order Markov chain with top-N sparsified transitions.

Re-design of the reference's e2 MarkovChain
(ref: e2/src/main/scala/io/prediction/e2/engine/MarkovChain.scala:32-89):
train builds a row-normalized transition matrix keeping only the top-N
probabilities per row; predict is distribution × matrix. The matrix is kept
as dense [S, topN] (indices + probs) so predictNext is a gather + segment
sum — static shapes, XLA-friendly."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class MarkovChainModel:
    """ref: MarkovChainModel (transitionVectors, n)"""

    top_indices: np.ndarray  # [S, topN] int32 next-state ids (pad -1)
    top_probs: np.ndarray  # [S, topN] float32 row-normalized probs (pad 0)
    n_states: int

    def transition_row(self, state: int) -> dict[int, float]:
        out = {}
        for j, p in zip(self.top_indices[state], self.top_probs[state]):
            if j >= 0 and p > 0:
                out[int(j)] = float(p)
        return out

    def predict_next(self, current: np.ndarray) -> np.ndarray:
        """distribution [S] → next distribution [S]
        (ref: MarkovChainModel.predict = vector × matrix)."""
        current = np.asarray(current, dtype=np.float32)
        nxt = np.zeros(self.n_states, np.float32)
        valid = self.top_indices >= 0
        src = np.repeat(np.arange(self.n_states), self.top_indices.shape[1])
        flat_idx = self.top_indices.ravel()
        contrib = (current[src] * self.top_probs.ravel())
        mask = valid.ravel()
        np.add.at(nxt, flat_idx[mask], contrib[mask])
        return nxt


def train_markov_chain(
    from_idx: np.ndarray,
    to_idx: np.ndarray,
    counts: np.ndarray,
    n_states: int,
    top_n: int = 10,
) -> MarkovChainModel:
    """ref: MarkovChain.train:32-60 — CoordinateMatrix → row-normalize →
    keep top-N per row. Works on the sparse triplets directly (O(nnz)
    memory), never densifying the [S, S] matrix."""
    from_idx = np.asarray(from_idx, np.int64)
    to_idx = np.asarray(to_idx, np.int64)
    counts = np.asarray(counts, np.float64)
    top_n = min(top_n, n_states)
    # coalesce duplicate (from, to) pairs
    flat = from_idx * n_states + to_idx
    uniq, inv = np.unique(flat, return_inverse=True)
    summed = np.zeros(len(uniq), np.float64)
    np.add.at(summed, inv, counts)
    rows = (uniq // n_states).astype(np.int64)
    cols = (uniq % n_states).astype(np.int32)
    row_sums = np.zeros(n_states, np.float64)
    np.add.at(row_sums, rows, summed)
    probs = summed / row_sums[rows]

    top_idx = np.full((n_states, top_n), -1, np.int32)
    top_probs = np.zeros((n_states, top_n), np.float32)
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, probs_s = rows[order], cols[order], probs[order]
    boundaries = np.searchsorted(rows_s, np.arange(n_states + 1))
    for state in np.unique(rows_s):
        lo, hi = boundaries[state], boundaries[state + 1]
        seg_p, seg_c = probs_s[lo:hi], cols_s[lo:hi]
        if len(seg_p) > top_n:
            keep = np.argpartition(-seg_p, top_n - 1)[:top_n]
            seg_p, seg_c = seg_p[keep], seg_c[keep]
        sort = np.argsort(-seg_p)
        seg_p, seg_c = seg_p[sort], seg_c[sort]
        top_idx[state, : len(seg_c)] = seg_c
        top_probs[state, : len(seg_p)] = seg_p
    return MarkovChainModel(top_idx, top_probs, n_states)
