"""Generic k-fold cross-validation splitter.

Re-design of the reference's e2 helper
(ref: e2/src/main/scala/io/prediction/e2/evaluation/CrossValidation.scala:
33-64 ``CommonHelperFunctions.splitData``): splits indexed data into k
folds shaped exactly as ``read_eval`` needs —
``[(training_points, eval_info, [(query, actual)])]``."""

from __future__ import annotations

from typing import Callable, Sequence, TypeVar

import numpy as np

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
    k: int,
    data: Sequence[D],
    make_training_data: Callable[[list[D]], TD],
    make_eval_info: Callable[[list[D]], EI],
    make_query_actual: Callable[[D], tuple[Q, A]],
    seed: int = 0,
) -> list[tuple[TD, EI, list[tuple[Q, A]]]]:
    if k < 2:
        raise ValueError("k must be >= 2")
    rng = np.random.default_rng(seed)
    fold_of = rng.integers(0, k, len(data))
    folds = []
    for fold in range(k):
        training = [d for d, f in zip(data, fold_of) if f != fold]
        testing = [d for d, f in zip(data, fold_of) if f == fold]
        folds.append(
            (
                make_training_data(training),
                make_eval_info(training),
                [make_query_actual(d) for d in testing],
            )
        )
    return folds
