"""Categorical Naive Bayes over string features.

Re-design of the reference's e2 algorithm library version
(ref: e2/src/main/scala/io/prediction/e2/engine/CategoricalNaiveBayes.scala:
29-176): features are categorical strings per position; the model keeps log
priors and per-(feature-position, value) log likelihoods, with a pluggable
default log-likelihood for unseen values (``logScore`` with default
function, ref :82-176). Training is a vocabulary-encode + the same one-hot
count reduction as multinomial NB; data volumes here are metadata-small so
counting runs host-side in numpy.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from dataclasses import dataclass
from typing import Callable, Sequence


@dataclass(frozen=True)
class LabeledPoint:
    """ref: e2/.../engine/LabeledPoint (label + categorical feature vector)"""

    label: str
    features: tuple[str, ...]


@dataclass
class CategoricalNaiveBayesModel:
    """ref: CategoricalNaiveBayes.scala Model:82"""

    priors: dict[str, float]  # label → log prior
    likelihoods: dict[str, list[dict[str, float]]]  # label → per-pos {value: log p}

    def _log_score_internal(
        self,
        label: str,
        features: Sequence[str],
        default_likelihood: Callable[[list[float]], float],
    ) -> float:
        # ref: logScoreInternal — unseen values get defaultLikelihood
        pos_likelihoods = self.likelihoods[label]
        if len(features) != len(pos_likelihoods):
            raise ValueError(
                f"feature vector length {len(features)} != model "
                f"{len(pos_likelihoods)}"
            )
        total = self.priors[label]
        for pos, value in enumerate(features):
            ll = pos_likelihoods[pos].get(value)
            if ll is None:
                ll = default_likelihood(list(pos_likelihoods[pos].values()))
            total += ll
        return total

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[list[float]], float] = (
            lambda lls: float("-inf")
        ),
    ) -> float | None:
        """Log score of (features, label); None when the label is unknown
        (ref: CategoricalNaiveBayes.scala logScore:103-115)."""
        if point.label not in self.priors:
            return None
        return self._log_score_internal(
            point.label, point.features, default_likelihood
        )

    def score_all(
        self,
        features: Sequence[str],
        default_likelihood: Callable[[list[float]], float] = (
            lambda lls: float("-inf")
        ),
    ) -> dict[str, float]:
        return {
            label: self._log_score_internal(label, features, default_likelihood)
            for label in self.priors
        }

    def predict(self, features: Sequence[str]) -> str:
        """Label with the highest score (ref: predict:137-151); unseen values
        score -inf per the reference default."""
        scores = self.score_all(features)
        return max(scores, key=scores.get)


def train_categorical_nb(points: Sequence[LabeledPoint]) -> CategoricalNaiveBayesModel:
    """ref: CategoricalNaiveBayes.train:29-80"""
    if not points:
        raise ValueError("no labeled points")
    n_features = len(points[0].features)
    label_counts: Counter = Counter()
    value_counts: dict[str, list[Counter]] = defaultdict(
        lambda: [Counter() for _ in range(n_features)]
    )
    for p in points:
        if len(p.features) != n_features:
            raise ValueError("inconsistent feature vector length")
        label_counts[p.label] += 1
        for pos, v in enumerate(p.features):
            value_counts[p.label][pos][v] += 1
    total = sum(label_counts.values())
    priors = {
        label: math.log(c) - math.log(total) for label, c in label_counts.items()
    }
    likelihoods = {
        label: [
            {
                v: math.log(c) - math.log(label_counts[label])
                for v, c in pos_counter.items()
            }
            for pos_counter in value_counts[label]
        ]
        for label in label_counts
    }
    return CategoricalNaiveBayesModel(priors, likelihoods)
