"""Two-tower deep retrieval model (sampled softmax, mesh-sharded negatives).

The new engine family named in BASELINE.json configs[4] — no reference
counterpart (the reference predates deep retrieval); designed TPU-first:

- **Towers**: id-embedding + MLP per side, bfloat16 matmuls on the MXU,
  float32 accumulation for the loss.
- **In-batch sampled softmax with cross-device negatives**: the batch is
  sharded over the mesh ``data`` axis; inside ``shard_map`` each device
  ``all_gather``s the item-tower embeddings of the WHOLE global batch over
  ICI, so every positive scores against global-batch negatives — the
  all-to-all negative sharing pattern of large-scale retrieval training.
- **Model parallelism**: embedding tables can be column-sharded over the
  ``model`` axis (each device holds a slice of every embedding vector);
  activations stay sharded until the final dot product.
- **Serving**: corpus item embeddings precomputed once into HBM; queries are
  one user-tower forward + the shared ``top_k_scores`` kernel.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.parallel.mesh import (
    ComputeContext,
    DATA_AXIS,
    MODEL_AXIS,
    shard_map,
)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TwoTowerParams:
    embed_dim: int = 64
    hidden_dims: tuple[int, ...] = (128,)
    out_dim: int = 32
    batch_size: int = 1024  # global batch (split over the data axis)
    steps: int = 1000
    learning_rate: float = 1e-3
    temperature: float = 0.05
    seed: int = 0
    #: in-batch-softmax column chunk: ``None`` = auto (dense logits only
    #: up to 1024 negatives, 2048-column online-softmax chunks above);
    #: 0 = always dense; >0 = explicit chunk size
    loss_chunk: int | None = None
    #: ``"adam"`` (default) or ``"rowwise_adam"``. The train step is
    #: optimizer-HBM-bound (docs/perf.md §6: adam streams ~7 passes of
    #: the [n, d] embedding tables per step); rowwise_adam keeps ONE
    #: second-moment scalar per embedding ROW (the DLRM rowwise-adagrad
    #: idea applied to adam), cutting v-state traffic d-fold — measured
    #: +15% steps/s at the bench config (740 -> 852) with comparable
    #: loss. MLP weights keep full per-parameter moments either way.
    optimizer: str = "adam"
    #: Sparse embedding-update path (docs/perf.md §17): dedup the batch's
    #: row ids, segment-sum per-example embedding gradients into one
    #: row-gradient per touched row, run the optimizer (adam OR
    #: rowwise_adam, with the exact lazy-decay staleness correction) over
    #: the touched-row slices only, and scatter-apply into the donated
    #: [n, d] buffers — per-step optimizer HBM traffic scales with
    #: O(batch) touched rows instead of O(n) table rows
    #: (sparse_update_bytes_per_step vs adam_bytes_per_step). Applies on
    #: data-parallel meshes; tensor-parallel (model-axis) runs keep the
    #: dense update (column-sharded tables make row scatter a cross-
    #: device exchange the dense path already amortizes).
    sparse_update: bool = True


#: auto mode: largest negatives count whose dense [B, B] logits are kept.
#: Measured on a v5e across batch 1k-32k: the checkpointed chunked CE
#: ties dense at 1024 negatives and WINS everywhere above (4096: 494 vs
#: 341 steps/s; 8192: 338 vs 115 — 2.77M examples/s, the throughput
#: peak; 16384: 84 vs 38) — the dense [B, B] logits' HBM traffic costs
#: more than the chunked backward's recompute as soon as the logits
#: outgrow ~VMEM scale. Dense is kept only where chunking is a no-op.
_DENSE_LOGITS_MAX = 1024
_AUTO_CHUNK = 2048
#: smallest worthwhile chunk: below this the scan degenerates toward
#: per-column work and dense logits are the lesser evil
_MIN_CHUNK = 64


def mlp_n_params(p: TwoTowerParams) -> int:
    """Parameters of both towers' MLP stacks (embedding tables excluded)."""
    dims = [p.embed_dim, *p.hidden_dims, p.out_dim]
    return 2 * sum((a + 1) * b for a, b in zip(dims, dims[1:]))


def n_params(p: TwoTowerParams, n_users: int, n_items: int) -> int:
    """Parameter count shared by the MFU and HBM roofline models
    (moved here from bench.py so the live ``pio_device_mfu`` accounting
    and the bench figures read ONE model)."""
    return (n_users + n_items) * p.embed_dim + mlp_n_params(p)


def flops_per_step(p: TwoTowerParams, n_users: int, n_items: int,
                   batch: int) -> float:
    """Model FLOPs of one training step: both towers' MLPs (forward +
    dx/dW backward = 3x forward), the in-batch logits (forward + both
    operand grads = 3x; +1x recompute when the chunked CE is active),
    and the optimizer update (~10 ops/param) — over EVERY parameter on
    the dense path, over the MLP + the batch's touched embedding rows on
    the sparse path (docs/perf.md §17)."""
    dims = [p.embed_dim, *p.hidden_dims, p.out_dim]
    mlp = sum(2 * a * b for a, b in zip(dims, dims[1:]))  # per example
    towers = 2 * 3 * batch * mlp
    logit_passes = 4 if batch > _DENSE_LOGITS_MAX else 3
    logits = logit_passes * 2 * batch * batch * p.out_dim
    if p.sparse_update:
        opt_params = mlp_n_params(p) + 2.0 * batch * p.embed_dim
    else:
        opt_params = n_params(p, n_users, n_items)
    return towers + logits + 10.0 * opt_params


def adam_bytes_per_step(p: TwoTowerParams, n_users: int,
                        n_items: int) -> float:
    """HBM bytes of the DENSE adam update: params + dense grads + two
    moment tensors, read and written (~7 array passes of 4 bytes/param).
    The embedding tables made this the step's true roofline until the
    sparse path (below) cut the traffic to O(batch) rows."""
    return 7.0 * 4.0 * n_params(p, n_users, n_items)


def sparse_update_bytes_per_step(p: TwoTowerParams, n_users: int,
                                 n_items: int, batch: int) -> float:
    """HBM bytes of the SPARSE optimizer update: the MLP's dense adam
    (7 passes of its tiny parameter count) plus O(touched) row traffic
    per embedding table — param-row gather + scatter-add, m read/write,
    v read/write, and the segment-summed gradient rows (~8 four-byte row
    passes; rowwise_adam's [n, 1] v drops two of them). Scales with the
    batch's touched rows (<= batch per table), NOT the [n, d] tables —
    the analytic model bench.py reports as
    ``two_tower_sparse_mb_per_step`` next to the dense
    ``adam_bytes_per_step`` roofline it replaced. ``n_users``/``n_items``
    only cap the touched-row count (a catalog smaller than the batch
    cannot touch more rows than it has)."""
    touched = min(batch, n_users) + min(batch, n_items)
    row_passes = 6.0 if p.optimizer == "rowwise_adam" else 8.0
    return (7.0 * 4.0 * mlp_n_params(p)
            + row_passes * 4.0 * touched * p.embed_dim)


def _resolve_chunk(p: TwoTowerParams, n_negatives: int) -> int | None:
    """Column-chunk size for the in-batch softmax, or None for dense.
    The online softmax needs equal chunks, so the requested (or auto)
    size is rounded DOWN to the largest divisor of the padded batch —
    falling back to dense would silently rematerialize the [B, B]
    logits whose memory blowup this feature exists to avoid."""
    if p.loss_chunk is not None and p.loss_chunk < 0:
        raise ValueError(f"loss_chunk must be >= 0, got {p.loss_chunk}")
    if p.loss_chunk == 0:
        return None
    want = p.loss_chunk
    if want is None:
        if n_negatives <= _DENSE_LOGITS_MAX:
            return None
        want = _AUTO_CHUNK
    want = max(1, min(want, n_negatives))
    chunk = next(c for c in range(want, 0, -1) if n_negatives % c == 0)
    if chunk < _MIN_CHUNK and chunk < n_negatives:
        logger.warning(
            "two-tower loss_chunk: no useful divisor of batch %d near %d "
            "(best %d); using dense [B, B] logits", n_negatives, want, chunk)
        return None
    return chunk


def _chunked_softmax_ce(u, v_pairs, v_all, temperature, chunk: int):
    """Per-row in-batch sampled-softmax CE without materializing the
    [rows, negatives] logits: an exact online logsumexp over column
    chunks of ``v_all`` (the flash-attention trick applied to the loss).
    ``v_pairs`` holds each row's positive item embedding."""
    rows = u.shape[0]
    pos = (u * v_pairs).sum(-1) / temperature
    nc = v_all.shape[0] // chunk

    @jax.checkpoint
    def step(carry, vc):
        m, s = carry
        lg = (u @ vc.T) / temperature  # [rows, chunk]
        m2 = jnp.maximum(m, lg.max(-1))
        s = s * jnp.exp(m - m2) + jnp.exp(lg - m2[:, None]).sum(-1)
        return (m2, s), None

    # jax.checkpoint on the step is what makes the chunking actually save
    # memory under value_and_grad: without it the scan stacks per-chunk
    # logits/exp residuals for the backward pass — the same total bytes
    # as the dense [rows, B] logits this path exists to avoid. The
    # backward instead recomputes each chunk's logits (extra matmul work
    # — why dense stays faster whenever the logits fit HBM; see
    # _DENSE_LOGITS_MAX).
    m0 = jnp.full((rows,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((rows,), jnp.float32)
    (m, s), _ = jax.lax.scan(
        step, (m0, s0), v_all.reshape(nc, chunk, v_all.shape[1]))
    return -(pos - (m + jnp.log(s)))


@dataclass
class TwoTowerModel:
    params: dict  # pytree of host numpy arrays
    hyper: TwoTowerParams
    item_embeddings: np.ndarray  # [n_items, out_dim] precomputed corpus
    user_embeddings: np.ndarray  # [n_users, out_dim] precomputed queries


def _init_tower(key, n_entities: int, p: TwoTowerParams) -> dict:
    k_emb, *k_mlp = jax.random.split(key, 2 + len(p.hidden_dims))
    tower = {
        "embed": jax.random.normal(k_emb, (n_entities, p.embed_dim)) * 0.05,
        "layers": [],
    }
    dims = [p.embed_dim, *p.hidden_dims, p.out_dim]
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        tower["layers"].append(
            {
                "w": jax.random.normal(k_mlp[i], (d_in, d_out))
                * (2.0 / d_in) ** 0.5,
                "b": jnp.zeros((d_out,)),
            }
        )
    return tower


def _mlp_stack(layers: list, x):
    """The tower's MLP from pre-gathered embeddings: bfloat16 matmuls
    (MXU), f32 normalize — shared by the dense path's gather+MLP forward
    and the sparse path (which differentiates wrt the gathered rows so
    the embedding gradient comes back as [batch, d], never [n, d])."""
    x = x.astype(jnp.bfloat16)
    for i, layer in enumerate(layers):
        x = x @ layer["w"].astype(jnp.bfloat16) + layer["b"].astype(jnp.bfloat16)
        if i < len(layers) - 1:
            x = jax.nn.relu(x)
    x = x.astype(jnp.float32)
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-6)


def _tower_forward(tower: dict, idx):
    """Embed + MLP in bfloat16 (MXU), normalize output in f32."""
    return _mlp_stack(tower["layers"], tower["embed"][idx])


def init_params(n_users: int, n_items: int, p: TwoTowerParams) -> dict:
    ku, ki = jax.random.split(jax.random.PRNGKey(p.seed))
    return {"user": _init_tower(ku, n_users, p), "item": _init_tower(ki, n_items, p)}


def rowwise_adam(
    lr: float, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
) -> optax.GradientTransformation:
    """Adam with a per-ROW second moment on the embedding tables.

    Leaves named ``embed`` (selected by tree path, so an MLP weight can
    never be misclassified by its shape) carry ``v`` of shape ``[n, 1]``
    — the row-mean of the squared gradient — instead of ``[n, d]``;
    every other leaf gets standard per-parameter Adam. The adaptive
    scale of an embedding row is shared across its features, which is
    the standard production-recsys compromise (rowwise AdaGrad/Adam):
    near-Adam quality at a fraction of the optimizer state bandwidth,
    which is what bounds the two-tower step (docs/perf.md §6)."""

    def _is_embed_path(path) -> bool:
        return any(
            getattr(k, "key", None) == "embed" for k in path
        )

    def init(params):
        m = jax.tree.map(jnp.zeros_like, params)
        v = jax.tree_util.tree_map_with_path(
            lambda path, x: jnp.zeros((x.shape[0], 1), x.dtype)
            if _is_embed_path(path) else jnp.zeros_like(x),
            params,
        )
        return (jnp.zeros((), jnp.int32), m, v)

    def update(grads, state, params=None):
        del params
        step, m, v = state
        step = step + 1
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, m, grads)

        def upd_v(v_, g):
            if v_.shape != g.shape:  # rowwise leaf
                return b2 * v_ + (1 - b2) * jnp.mean(
                    g * g, axis=1, keepdims=True)
            return b2 * v_ + (1 - b2) * g * g

        v = jax.tree.map(upd_v, v, grads)
        bc1 = 1 - b1 ** step.astype(jnp.float32)
        bc2 = 1 - b2 ** step.astype(jnp.float32)
        updates = jax.tree.map(
            lambda m_, v_: -lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps),
            m, v,
        )
        return updates, (step, m, v)

    return optax.GradientTransformation(init, update)


def _make_optimizer(p: TwoTowerParams) -> optax.GradientTransformation:
    if p.optimizer == "rowwise_adam":
        return rowwise_adam(p.learning_rate)
    if p.optimizer == "adam":
        return optax.adam(p.learning_rate)
    raise ValueError(
        f"unknown optimizer {p.optimizer!r}: expected 'adam' or "
        "'rowwise_adam'"
    )


def _make_step(loss_fn, tx):
    """Shared optimizer-step wrapper around a loss function. Returns the
    jitted per-step function (callback path) AND the raw traceable step so
    the no-callback path can fuse the whole run into one ``fori_loop``."""

    def step(params, opt_state, u_idx, i_idx):
        loss, grads = jax.value_and_grad(loss_fn)(params, u_idx, i_idx)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(step), step


def make_train_step(ctx: ComputeContext, p: TwoTowerParams, tx):
    """Build the jitted global train step. The loss runs under shard_map:
    per-device towers on the local batch shard, then an ICI all_gather of
    item embeddings so every device scores against ALL global-batch
    negatives."""
    mesh = ctx.mesh

    def loss_fn(params, u_idx, i_idx):
        def shard_loss(params, u_idx, i_idx):
            u = _tower_forward(params["user"], u_idx)  # [b_local, d]
            v = _tower_forward(params["item"], i_idx)  # [b_local, d]
            # negatives from every device: ICI all_gather over the data axis
            v_all = jax.lax.all_gather(v, DATA_AXIS, tiled=True)  # [b_glob, d]
            chunk = _resolve_chunk(p, v_all.shape[0])
            if chunk is not None:
                losses = _chunked_softmax_ce(u, v, v_all, p.temperature,
                                             chunk)
            else:
                logits = (u @ v_all.T) / p.temperature  # [b_local, b_glob]
                shard_idx = jax.lax.axis_index(DATA_AXIS)
                b_local = u.shape[0]
                labels = shard_idx * b_local + jnp.arange(b_local)
                losses = -jax.nn.log_softmax(logits, axis=-1)[
                    jnp.arange(b_local), labels
                ]
            return jax.lax.pmean(losses.mean(), DATA_AXIS)

        return shard_map(
            shard_loss,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(),
            check_vma=False,
        )(params, u_idx, i_idx)

    return _make_step(loss_fn, tx)


def shard_params(ctx: ComputeContext, params: dict):
    """Tensor-parallel placement over the ``model`` axis: embedding tables
    and MLP weights column-sharded (each device holds a slice of every
    vector), biases replicated. With these placements the plain-jit loss
    lets GSPMD insert the ICI collectives (the scaling-book recipe)."""
    mesh = ctx.mesh

    def place(tower: dict) -> dict:
        return {
            "embed": jax.device_put(
                tower["embed"], NamedSharding(mesh, P(None, MODEL_AXIS))
            ),
            "layers": [
                {
                    "w": jax.device_put(
                        layer["w"], NamedSharding(mesh, P(None, MODEL_AXIS))
                    ),
                    "b": jax.device_put(
                        layer["b"], NamedSharding(mesh, P(MODEL_AXIS))
                    ),
                }
                for layer in tower["layers"]
            ],
        }

    return {"user": place(params["user"]), "item": place(params["item"])}


def make_train_step_gspmd(ctx: ComputeContext, p: TwoTowerParams, tx):
    """dp×tp train step without shard_map: the batch is sharded over
    ``data``, parameters over ``model``, and XLA's SPMD partitioner inserts
    every collective (all-gather of negatives, gradient reduce-scatter)."""

    def loss_fn(params, u_idx, i_idx):
        u = _tower_forward(params["user"], u_idx)  # [B, d]
        v = _tower_forward(params["item"], i_idx)  # [B, d]
        chunk = _resolve_chunk(p, v.shape[0])
        if chunk is not None:
            return _chunked_softmax_ce(u, v, v, p.temperature, chunk).mean()
        logits = (u @ v.T) / p.temperature  # [B, B]: global in-batch softmax
        b = u.shape[0]
        labels = jnp.arange(b)
        return -jax.nn.log_softmax(logits, axis=-1)[labels, labels].mean()

    return _make_step(loss_fn, tx)


class _SparseTx:
    """Optimizer-state builder for the sparse path — duck-types the
    ``tx.init(params)`` surface :func:`train_two_tower` uses. The state
    pytree: the global step, optax adam over the MLP subtree, and per
    table the (m, v, last_step) buffers the touched-row updates scatter
    into (``v`` is [n, 1] under rowwise_adam)."""

    def __init__(self, p: TwoTowerParams, placement=None):
        if p.optimizer not in ("adam", "rowwise_adam"):
            raise ValueError(
                f"unknown optimizer {p.optimizer!r}: expected 'adam' or "
                "'rowwise_adam'")
        self.p = p
        self.rowwise = p.optimizer == "rowwise_adam"
        self.mlp_tx = optax.adam(p.learning_rate)
        self.placement = placement

    @staticmethod
    def mlp_of(params: dict) -> dict:
        return {"user": params["user"]["layers"],
                "item": params["item"]["layers"]}

    def init(self, params: dict):
        from predictionio_tpu.ops import sparse_update as su

        state = {"step": jnp.zeros((), jnp.int32),
                 "mlp": self.mlp_tx.init(self.mlp_of(params))}
        for side in ("user", "item"):
            m, v, last = su.init_table_state(
                params[side]["embed"], rowwise=self.rowwise)
            state[side] = {"m": m, "v": v, "last": last}
        if self.placement is not None:
            # commit the fresh state: UNcommitted first-call operands
            # would give the compiled program a different argument
            # mapping than every later call (whose inputs are committed
            # jit outputs) — one invisible extra XLA compile per trainer
            # the retrace guard now pins away
            state = jax.device_put(state, self.placement)
        return state


def make_sparse_train_step(ctx: ComputeContext, p: TwoTowerParams):
    """The sparse embedding-update train step (docs/perf.md §17).

    The loss is differentiated wrt the GATHERED embedding rows (explicit
    [batch, d] inputs), so the embedding gradient never materializes as a
    dense [n, d] scatter; the per-example rows are then deduped +
    segment-summed and the optimizer runs over exactly the touched-row
    slices (ops/sparse_update.sparse_table_update), scatter-applied into
    the donated tables. The in-batch softmax is the GSPMD-form global
    loss (every positive against the whole global batch — identical
    objective to the shard_map form; XLA partitions it over the data
    axis)."""
    tx = _SparseTx(p, placement=ctx.replicated)

    def loss_fn(mlp, e_u, e_i):
        u = _mlp_stack(mlp["user"], e_u)  # [B, d]
        v = _mlp_stack(mlp["item"], e_i)  # [B, d]
        chunk = _resolve_chunk(p, v.shape[0])
        if chunk is not None:
            return _chunked_softmax_ce(u, v, v, p.temperature, chunk).mean()
        logits = (u @ v.T) / p.temperature  # [B, B]
        b = u.shape[0]
        labels = jnp.arange(b)
        return -jax.nn.log_softmax(logits, axis=-1)[labels, labels].mean()

    def step(params, opt_state, u_idx, i_idx):
        from predictionio_tpu.ops import sparse_update as su

        e_u = params["user"]["embed"][u_idx]  # [B, d] gathers — the only
        e_i = params["item"]["embed"][i_idx]  # table reads this step makes
        mlp = tx.mlp_of(params)
        loss, (g_mlp, g_eu, g_ei) = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2))(mlp, e_u, e_i)
        step_no = opt_state["step"] + 1
        mlp_updates, mlp_state = tx.mlp_tx.update(g_mlp, opt_state["mlp"])
        mlp_new = optax.apply_updates(mlp, mlp_updates)
        new_params, new_state = {}, {"step": step_no, "mlp": mlp_state}
        for side, idx, g in (("user", u_idx, g_eu), ("item", i_idx, g_ei)):
            st = opt_state[side]
            table, m, v, last = su.sparse_table_update(
                params[side]["embed"], st["m"], st["v"], st["last"],
                idx, g, step_no, p.learning_rate, rowwise=tx.rowwise)
            new_params[side] = {"embed": table, "layers": mlp_new[side]}
            new_state[side] = {"m": m, "v": v, "last": last}
        return new_params, new_state, loss

    return tx, step


#: Host-side layout + routing facts of the most recent SHARDED two-tower
#: train (shard count, per-shard HBM bytes, the full-table bytes no
#: device ever holds, touched-row skew) — the acceptance pin that the
#: embedding tables are never whole on any device, and bench.py's
#: synth_bigtable section doc. Mirrors als_dense.last_sharded_stats.
last_sharded_stats: dict = {}


class _ShardedSparseTx:
    """Optimizer-state builder for the ROW-SHARDED sparse path: the MLP
    subtree keeps replicated optax adam, each table's (m, v, last)
    buffers live in the ``[D, rows_per, ...]`` sharded layout next to
    the table rows they correct (ops/sharded_table). Duck-types the
    ``tx.init(params)`` surface like :class:`_SparseTx`."""

    def __init__(self, ctx: ComputeContext, p: TwoTowerParams):
        if p.optimizer not in ("adam", "rowwise_adam"):
            raise ValueError(
                f"unknown optimizer {p.optimizer!r}: expected 'adam' or "
                "'rowwise_adam'")
        self.ctx = ctx
        self.p = p
        self.rowwise = p.optimizer == "rowwise_adam"
        self.mlp_tx = optax.adam(p.learning_rate)

    mlp_of = staticmethod(_SparseTx.mlp_of)

    def init(self, params: dict):
        from predictionio_tpu.ops import sharded_table as stbl

        mesh = self.ctx.mesh
        state = {"step": jnp.zeros((), jnp.int32),
                 "mlp": self.mlp_tx.init(self.mlp_of(params))}
        # commit like _SparseTx.init: uncommitted first-call operands
        # would change the compiled argument mapping vs later calls
        state = jax.device_put(state, self.ctx.replicated)
        for side in ("user", "item"):
            tbl = params[side]["embed"]  # [D, rows_per, d] sharded
            d, rp, dim = tbl.shape
            m = stbl.put_sharded(mesh, np.zeros((d, rp, dim), np.float32))
            v = stbl.put_sharded(mesh, np.zeros(
                (d, rp, 1 if self.rowwise else dim), np.float32))
            last = stbl.put_sharded(mesh, np.zeros((d, rp), np.int32))
            state[side] = {"m": m, "v": v, "last": last}
        return state


def make_sharded_sparse_train_step(ctx: ComputeContext, p: TwoTowerParams,
                                   n_users: int, n_items: int, batch: int):
    """The ROW-SHARDED sparse train step (docs/perf.md §19).

    Embedding tables live ``[D, rows_per, d]`` over the mesh ``data``
    axis (strided ownership — ops/sharded_table); the batch splits over
    the same axis. Inside one shard_map program each shard dedups its
    local ids, ONE all_to_all routes the requests to the owner shards,
    the owners answer with embedding rows over the reverse exchange, the
    towers + global in-batch softmax run on the local batch shard
    (negatives still cross-device via the all_gather of item-tower
    outputs — its autodiff transpose routes the cross-shard v-gradients
    back), and the gradient push re-rides the id route so the PR-15
    touched-row adam runs shard-locally. MLP gradients psum into a
    replicated adam update. Neither the optimizer nor table residency
    binds the step — the table can exceed one device's HBM."""
    from predictionio_tpu.ops import sharded_table as stbl
    from predictionio_tpu.ops import sparse_update as su

    mesh = ctx.mesh
    ndev = ctx.data_axis_size
    bl = batch // ndev
    cap_env = stbl.requested_dedup_cap()
    cap = min(cap_env, bl) if cap_env else bl
    tx = _ShardedSparseTx(ctx, p)
    rowwise = tx.rowwise

    def loss_fn(mlp, e_u, e_i):
        u = _mlp_stack(mlp["user"], e_u)  # [bl, d]
        v = _mlp_stack(mlp["item"], e_i)  # [bl, d]
        v_all = jax.lax.all_gather(v, DATA_AXIS, tiled=True)  # [B, d]
        chunk = _resolve_chunk(p, batch)
        if chunk is not None:
            losses = _chunked_softmax_ce(u, v, v_all, p.temperature, chunk)
        else:
            logits = (u @ v_all.T) / p.temperature  # [bl, B]
            labels = (jax.lax.axis_index(DATA_AXIS) * bl
                      + jnp.arange(bl))
            losses = -jax.nn.log_softmax(logits, axis=-1)[
                jnp.arange(bl), labels]
        # local partial of the GLOBAL batch mean: gradients from every
        # shard sum through the collective transposes, so scaling by the
        # global batch here reproduces the single-device objective
        return losses.sum() / batch

    def step_local(params, opt_state, u_idx, i_idx):
        t_u = params["user"]["embed"][0]  # [rows_per, d] local block
        t_i = params["item"]["embed"][0]
        mlp = {"user": params["user"]["layers"],
               "item": params["item"]["layers"]}
        rt_u = stbl.build_route(u_idx, n_rows=n_users, ndev=ndev, cap=cap)
        rt_i = stbl.build_route(i_idx, n_rows=n_items, ndev=ndev, cap=cap)
        e_u = stbl.route_gather(t_u, rt_u, ndev=ndev, cap=cap)[rt_u.inv]
        e_i = stbl.route_gather(t_i, rt_i, ndev=ndev, cap=cap)[rt_i.inv]
        loss, (g_mlp, g_eu, g_ei) = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2))(mlp, e_u, e_i)
        g_mlp = jax.lax.psum(g_mlp, DATA_AXIS)
        step_no = opt_state["step"] + 1
        mlp_updates, mlp_state = tx.mlp_tx.update(g_mlp, opt_state["mlp"])
        mlp_new = optax.apply_updates(mlp, mlp_updates)
        new_params = {}
        new_state = {"step": step_no, "mlp": mlp_state}
        for side, rt, g, tbl, st, nr in (
                ("user", rt_u, g_eu, t_u, opt_state["user"], n_users),
                ("item", rt_i, g_ei, t_i, opt_state["item"], n_items)):
            g_unique = su.segment_rows(g, rt.inv, cap)
            t2, m2, v2, l2 = stbl.route_update(
                tbl, st["m"][0], st["v"][0], st["last"][0], rt, g_unique,
                step_no, p.learning_rate, n_rows=nr, ndev=ndev, cap=cap,
                rowwise=rowwise)
            new_params[side] = {"embed": t2[None],
                                "layers": mlp_new[side]}
            new_state[side] = {"m": m2[None], "v": v2[None],
                               "last": l2[None]}
        return new_params, new_state, jax.lax.psum(loss, DATA_AXIS)

    emb3 = P(DATA_AXIS, None, None)
    params_spec = {"user": {"embed": emb3, "layers": P()},
                   "item": {"embed": emb3, "layers": P()}}

    def side_spec():
        return {"m": emb3, "v": emb3, "last": P(DATA_AXIS, None)}

    state_spec = {"step": P(), "mlp": P(),
                  "user": side_spec(), "item": side_spec()}
    raw_step = shard_map(
        step_local, mesh=mesh,
        in_specs=(params_spec, state_spec, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(params_spec, state_spec, P()),
        check_vma=False)
    return tx, raw_step


#: (mesh devices, model-axis size, compile-relevant params, batch) →
#: (optax transform, fused whole-run jit, per-step jit). jax.jit caches per
#: function object, so rebuilding the closures every train_two_tower call
#: would recompile — benchmarks and repeated trains (FastEval sweeps)
#: reuse the compiled programs through this cache. Bounded FIFO so long
#: hyperparameter sweeps don't pin one executable set per combination.
_TRAINER_CACHE: dict = {}
_TRAINER_CACHE_MAX = 8


def _get_trainer(ctx: ComputeContext, p: TwoTowerParams, batch: int,
                 n_users: int = 0, n_items: int = 0):
    from predictionio_tpu.ops import sharded_table as stbl

    sparse = p.sparse_update and ctx.model_axis_size == 1
    # the row-sharded path binds table sizes into the route programs, so
    # it only engages when the caller supplies them (train_two_tower and
    # bench do; legacy direct callers keep the single-device sparse path)
    sharded = (sparse and ctx.data_axis_size > 1 and n_users > 0
               and n_items > 0 and stbl.requested_shards() >= 2)
    # steps and seed are runtime inputs to the compiled programs, not part
    # of their shape — exclude them so e.g. a 2-step warmup compiles the
    # same programs a 10k-step run reuses
    key = (
        tuple(id(d) for d in ctx.mesh.devices.flat),
        ctx.model_axis_size, dataclasses.replace(p, steps=0, seed=0), batch,
        (n_users, n_items, stbl.requested_dedup_cap()) if sharded else None,
    )
    hit = _TRAINER_CACHE.pop(key, None)
    if hit is not None:
        _TRAINER_CACHE[key] = hit  # LRU refresh: hot entries stay resident
        return hit
    # the FLOPs model must describe the RESOLVED path: a tensor-parallel
    # run keeps the dense optimizer even with sparse_update=True, and
    # feeding the sparse-sized model to its MFU accounting would omit
    # the dense-adam ops it actually executes
    p_flops = dataclasses.replace(p, sparse_update=sparse)
    if sharded:
        # row-sharded tables: id/gradient exchange via ONE all_to_all
        # per direction, shard-local touched-row adam
        tx, raw_step = make_sharded_sparse_train_step(
            ctx, p, n_users, n_items, batch)
    elif sparse:
        # sparse embedding updates: optimizer traffic O(batch) rows
        tx, raw_step = make_sparse_train_step(ctx, p)
    elif ctx.model_axis_size > 1:
        # dp×tp: params tensor-sharded over the model axis, GSPMD
        # collectives; column-sharded tables keep the dense update
        tx = _make_optimizer(p)
        _, raw_step = make_train_step_gspmd(ctx, p, tx)
    else:
        # dense fallback (sparse_update=False): explicit shard_map loss
        # with ICI all_gather negatives
        tx = _make_optimizer(p)
        _, raw_step = make_train_step(ctx, p, tx)
    bshard = ctx.batch_sharding()

    def sample_batch(u_all, i_all, key, s):
        """On-device batch: ONE index draw selects paired (user, item)
        interaction rows; the gathered batches are constrained onto the
        data axis so GSPMD keeps the batch split under dp×tp (params are
        only model-sharded, so nothing else seeds that propagation)."""
        ks = jax.random.fold_in(key, s)
        sel = jax.random.randint(
            ks, (batch,), 0, u_all.shape[0], dtype=jnp.int32
        )
        return (
            jax.lax.with_sharding_constraint(u_all[sel], bshard),
            jax.lax.with_sharding_constraint(i_all[sel], bshard),
        )

    @partial(jax.jit, donate_argnums=(0, 1))
    def run(params, opt_state, u_all, i_all, key, steps, start=0):
        """``start`` offsets the on-device RNG step index so segmented
        runs (mid-training checkpointing) sample the same batch sequence
        an uninterrupted run would."""

        def body(s, carry):
            params, opt_state, _ = carry
            u, i = sample_batch(u_all, i_all, key, s)
            return raw_step(params, opt_state, u, i)

        zero = jnp.zeros((), jnp.float32)
        return jax.lax.fori_loop(
            start, start + steps, body, (params, opt_state, zero)
        )

    @partial(jax.jit, donate_argnums=(0, 1))
    def one_step(params, opt_state, u_all, i_all, key, s):
        u, i = sample_batch(u_all, i_all, key, s)
        return raw_step(params, opt_state, u, i)

    # device-runtime accounting for the fused run (obs/device.py): each
    # trainer-cache entry is its own expected-compile bucket; steps ride
    # the flops model so a 2-step warmup and a 2000-step run report the
    # same utilization series
    trainer_bucket = (batch, ctx.model_axis_size,
                      ctx.data_axis_size if sharded else 0,
                      repr(dataclasses.replace(p, steps=0, seed=0)))
    if sharded:
        program = "two_tower_sharded_step"
    else:
        program = "two_tower_sparse_step" if sparse else "two_tower_step"

    def _rows(emb):
        # sharded tables are [shards, rows_per, d]; flat tables [n, d]
        return emb.shape[0] * emb.shape[1] if emb.ndim == 3 else emb.shape[0]

    run = device_obs.profiled_program(
        program,
        flops=lambda params, opt_state, u_all, i_all, key, steps,
        start=0: float(steps) * flops_per_step(
            p_flops, _rows(params["user"]["embed"]),
            _rows(params["item"]["embed"]), batch),
        # operand shapes join the bucket: one cached trainer can serve
        # datasets of different sizes (embed tables, event count), and
        # those recompiles are expected — only a same-shape re-lowering
        # (dtype/weak-type flap) should read as a retrace
        bucket=lambda *a, **kw: (
            trainer_bucket, device_obs.shape_bucket(*a)),
        sync=True,
    )(run)

    entry = (tx, run, one_step)
    if len(_TRAINER_CACHE) >= _TRAINER_CACHE_MAX:
        _TRAINER_CACHE.pop(next(iter(_TRAINER_CACHE)))
    _TRAINER_CACHE[key] = entry
    return entry


def train_two_tower(
    ctx: ComputeContext,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    n_users: int,
    n_items: int,
    p: TwoTowerParams,
    callback=None,
    checkpointer=None,
) -> TwoTowerModel:
    """``checkpointer`` (utils.checkpoint.TrainCheckpointer) splits the
    fused run into ``checkpointer.every``-step segments, saving
    (params, opt_state) after each; a restart resumes from the newest
    segment boundary with the identical batch trajectory (the on-device
    sampler keys off the absolute step index)."""
    if user_idx.size == 0:
        raise ValueError("train_two_tower called with zero interactions")
    from predictionio_tpu.ops import sharded_table as stbl
    from predictionio_tpu.parallel import mesh as mesh_mod

    want = stbl.requested_shards()
    if p.sparse_update and ctx.model_axis_size == 1 and want >= 2:
        # PIO_EMB_SHARDS: row-shard the embedding tables over (up to)
        # that many data-axis devices. Resolve the sub-context ONCE here
        # so staging, placement, and the trainer all see the same mesh.
        ctx = mesh_mod.data_subcontext(ctx, want)
    sharded = (p.sparse_update and ctx.model_axis_size == 1
               and want >= 2 and ctx.data_axis_size > 1)
    nshards = ctx.data_axis_size if sharded else 1
    # global batch must split evenly over the data axis
    batch = ctx.pad_to_multiple(min(p.batch_size, max(len(user_idx), 1)))
    tx, run, one_step = _get_trainer(
        ctx, p, batch, *((n_users, n_items) if sharded else ()))
    params = init_params(n_users, n_items, p)
    if sharded:
        # [n, d] host tables → [shards, rows_per, d] strided layout; the
        # MLP stacks stay replicated (they're tiny and every shard's
        # local batch runs the full towers)
        params = {
            side: {
                "embed": stbl.put_sharded(ctx.mesh, stbl.shard_table(
                    np.asarray(params[side]["embed"]), nshards)),
                "layers": jax.device_put(
                    params[side]["layers"], ctx.replicated),
            }
            for side in ("user", "item")
        }
    elif ctx.model_axis_size > 1:
        params = shard_params(ctx, params)
    else:
        params = jax.device_put(params, ctx.replicated)
    opt_state = tx.init(params)
    start_step = 0
    fingerprint = ""
    if checkpointer is not None:
        import dataclasses

        from predictionio_tpu.utils.checkpoint import fingerprint_arrays

        # bind checkpoints to this run's data + shape-affecting config
        # (steps excluded: extending an interrupted run is a legal resume)
        fingerprint = fingerprint_arrays(
            dataclasses.replace(p, steps=0), n_users, n_items,
            user_idx.astype(np.int32), item_idx.astype(np.int32),
        )
        hit = checkpointer.load_latest((params, opt_state), fingerprint)
        if hit is not None:
            last, (h_params, h_opt) = hit
            start_step = last + 1
            if sharded:
                # restored host leaves already carry the checkpoint
                # template's [shards, rows_per, d] layout — re-pin each
                # with the template leaf's sharding
                params = jax.tree.map(
                    lambda h, t: jax.device_put(h, t.sharding),
                    h_params, params)
            elif ctx.model_axis_size > 1:
                params = shard_params(ctx, h_params)
            else:
                params = jax.device_put(h_params, ctx.replicated)
            # restored host leaves stay UNcommitted (like tx.init's fresh
            # arrays): jit places them via sharding propagation, so they
            # never conflict with the replicated/sharded params
            opt_state = h_opt
            logger.info("two-tower: resuming at step %d", start_step)

    # batches are sampled ON DEVICE (fold_in per step) from the resident
    # interaction arrays — the host batch sampler and per-step transfers
    # (an RTT each through a tunneled TPU) stay out of the loop, and the
    # trajectory is identical with or without a progress callback. The
    # interaction arrays stream up through the ChunkStager (pack/upload
    # of chunk k+1 overlaps chunk k's in-flight put — the ALS densify
    # stream's contract, PIO_TRANSFER_* tunable)
    from predictionio_tpu.io import transfer

    u_all, i_all = transfer.stage_training_arrays(
        (user_idx.astype(np.int32), item_idx.astype(np.int32)),
        sharding=ctx.replicated, name="two_tower_inputs")
    key = jax.random.PRNGKey(p.seed)
    # params + optimizer state own HBM for the whole training run
    # (the 297 MB/step adam-traffic story of ROADMAP item 4 starts
    # with seeing this number live on the hbm gauge); the replicated
    # index datasets ride train_data like sasrec's sequence tensors
    _params_alloc = device_obs.arena("neural_params").register(
        (params, opt_state), label="two_tower")
    _data_alloc = device_obs.arena("train_data").register(
        (u_all, i_all), label="two_tower")
    from predictionio_tpu.obs import runlog

    _shard_allocs = []
    if sharded:
        vdim = 1 if p.optimizer == "rowwise_adam" else p.embed_dim
        row_bytes = p.embed_dim * 4 * 2 + vdim * 4 + 4  # table+m, v, last
        per_shard = sum(
            rp * row_bytes
            for rp in (stbl.rows_per_shard(n_users, nshards),
                       stbl.rows_per_shard(n_items, nshards)))
        for d in range(nshards):
            _shard_allocs.append(device_obs.arena(f"emb_shard{d}").register(
                per_shard, label="two_tower"))
        # host-side representative routing stats over one batch of raw
        # interactions (touched rows, skew, exchange bytes) — feeds the
        # pio_emb_shard_* metrics and the doctor imbalance finding
        # without syncing the device loop
        win = min(len(user_idx), batch)
        st_u = stbl.route_stats(user_idx[:win], n_users, nshards,
                                p.embed_dim)
        st_i = stbl.route_stats(item_idx[:win], n_items, nshards,
                                p.embed_dim)
        imb = max(st_u["imbalance"], st_i["imbalance"])
        runlog.note("emb_shard_imbalance", round(float(imb), 3))
        runlog.note("emb_shards", nshards)
        # shard observatory (obs/shards.py): per-shard touched-row
        # loads (user + item ownership of the representative batch)
        from predictionio_tpu.obs import shards as shard_obs

        shard_obs.OBSERVATORY.program_meta(
            "two_tower_sharded_step", shards=nshards,
            arena_prefix="emb_shard")
        shard_obs.OBSERVATORY.record_shard_load(
            "two_tower_sharded_step",
            [a + b for a, b in zip(st_u["touched_per_shard"],
                                   st_i["touched_per_shard"])],
            kind="touched rows")
        last_sharded_stats.clear()
        last_sharded_stats.update({
            "shards": nshards,
            "per_shard_hbm_bytes": per_shard,
            # the single-device sparse path's table residency (table +
            # touched-row optimizer state, same row_bytes accounting) —
            # the working set NO device holds whole under sharding
            "full_table_bytes": (n_users + n_items) * row_bytes,
            "emb_shard_imbalance": float(imb),
            "alltoall_bytes_per_step": float(
                st_u["alltoall_bytes_per_step"]
                + st_i["alltoall_bytes_per_step"]),
        })
    try:
        loss = None
        if callback is None:
            import time as _time

            step = start_step
            while step < p.steps:  # whole run = ONE dispatch per segment
                seg = (
                    min(checkpointer.every, p.steps - step)
                    if checkpointer is not None
                    else p.steps - step
                )
                if sharded:
                    from predictionio_tpu.obs import shards as shard_obs

                    shard_obs.OBSERVATORY.program_meta(
                        "two_tower_sharded_step",
                        steps_per_dispatch=seg)
                t0 = _time.perf_counter()
                params, opt_state, loss = run(
                    params, opt_state, u_all, i_all, key, seg, step
                )
                step += seg
                # run-ledger progress per fused segment (per-step
                # average): the neural path keeps its one-dispatch-per-
                # segment shape. The scalar-loss sync is unconditional
                # so the step histogram never records enqueue time —
                # its cost is one scalar readback per SEGMENT, and the
                # serving-corpus export below blocks anyway
                jax.block_until_ready(loss)
                dt = _time.perf_counter() - t0
                runlog.step(
                    "two_tower_step", iteration=step, total=p.steps,
                    seconds=dt / max(seg, 1),
                    examples_per_sec=(seg * batch / dt if dt > 0 else None))
                if checkpointer is not None:
                    # also save the final segment so fused and callback modes
                    # leave identical checkpoint state behind
                    checkpointer.save(step - 1, (params, opt_state), fingerprint)
        else:
            # per-step dispatch so the callback sees progress; at most one step
            # in flight (on oversubscribed CPU test meshes async pile-up
            # starves the collective rendezvous and XLA aborts on its
            # stuck-timeout)
            last_saved = None
            st = runlog.StepTimer("two_tower_step", total=p.steps,
                                  start=start_step,
                                  examples_per_step=batch)
            for step in range(start_step, p.steps):
                params, opt_state, loss = one_step(
                    params, opt_state, u_all, i_all, key, step
                )
                loss.block_until_ready()
                st.step(step + 1,
                        loss=(float(loss) if runlog.active() is not None
                              else None))
                if (step + 1) % 100 == 0:
                    callback(step, float(loss))
                if checkpointer is not None and checkpointer.should_save(step):
                    checkpointer.save(step, (params, opt_state), fingerprint)
                    last_saved = step
            # save the final (possibly partial) segment too, mirroring the
            # fused path — both modes leave identical checkpoint state behind
            if (checkpointer is not None and p.steps > start_step
                    and last_saved != p.steps - 1):
                checkpointer.save(p.steps - 1, (params, opt_state), fingerprint)
        if loss is not None:
            logger.info("two-tower final loss: %.4f", float(loss))
    finally:
        device_obs.arena("neural_params").free(_params_alloc)
        device_obs.arena("train_data").free(_data_alloc)
        for d, alloc in enumerate(_shard_allocs):
            device_obs.arena(f"emb_shard{d}").free(alloc)

    if sharded:
        from predictionio_tpu.obs import shards as shard_obs

        ex_frac = shard_obs.OBSERVATORY.exchange_frac(
            "two_tower_sharded_step")
        if ex_frac is not None:
            runlog.note("exchange_frac", round(ex_frac, 4))
            last_sharded_stats["exchange_frac"] = round(ex_frac, 4)
        # collapse the [shards, rows_per, d] tables back to the flat
        # host layout the serving corpora, fold-in, and checkpoints of
        # the returned model expect (trailing pad rows drop here)
        params = {
            side: {
                "embed": stbl.unshard_table(
                    np.asarray(params[side]["embed"]), nr),
                "layers": jax.tree.map(np.asarray, params[side]["layers"]),
            }
            for side, nr in (("user", n_users), ("item", n_items))
        }
    # precompute BOTH serving corpora at train time: queries at serve time
    # are then pure embedding lookups + one matmul — no tower forward, no
    # host→device parameter upload on the /queries.json hot path
    forward = jax.jit(_tower_forward)
    item_emb = np.asarray(
        forward(jax.device_put(params["item"], ctx.replicated),
                jnp.arange(n_items))
    )
    user_emb = np.asarray(
        forward(jax.device_put(params["user"], ctx.replicated),
                jnp.arange(n_users))
    )
    host_params = jax.tree.map(np.asarray, params)
    return TwoTowerModel(host_params, p, item_emb, user_emb)


def embed_users(model: TwoTowerModel, user_idx: np.ndarray) -> np.ndarray:
    """Precomputed lookup for known users (the serving path)."""
    return model.user_embeddings[np.atleast_1d(user_idx)]


# ---------------------------------------------------------------------------
# Neural fold-in: warm-start rows for entities first seen in a delta
# ---------------------------------------------------------------------------


def _pow2_floor8(n: int) -> int:
    n = max(int(n), 8)
    return 1 << (n - 1).bit_length()


@partial(jax.jit, static_argnames=("p", "old_nu", "old_ni", "steps"))
def _foldin_refresh(params, u_idx, i_idx, *, p: TwoTowerParams,
                    old_nu: int, old_ni: int, steps: int):
    """A few sparse-update steps over the delta interactions, applied
    ONLY to the appended rows (``update_rows_from`` redirects existing-
    row scatters to the drop id) — parent rows AND the MLP stay
    byte-identical, which is the fold-in parity contract
    (tests/test_foldin.py)."""
    from predictionio_tpu.ops import sparse_update as su

    rowwise = p.optimizer == "rowwise_adam"

    def loss_fn(e_u, e_i, mlp):
        u = _mlp_stack(mlp["user"], e_u)
        v = _mlp_stack(mlp["item"], e_i)
        logits = (u @ v.T) / p.temperature
        b = u.shape[0]
        labels = jnp.arange(b)
        return -jax.nn.log_softmax(logits, axis=-1)[labels, labels].mean()

    mlp = _SparseTx.mlp_of(params)

    def body(s, carry):
        tu, ti = carry
        table_u, mu, vu, lu = tu
        table_i, mi, vi, li = ti
        e_u = table_u[u_idx]
        e_i = table_i[i_idx]
        g_eu, g_ei = jax.grad(loss_fn, argnums=(0, 1))(e_u, e_i, mlp)
        step_no = s + 1
        tu = su.sparse_table_update(
            table_u, mu, vu, lu, u_idx, g_eu, step_no, p.learning_rate,
            rowwise=rowwise, update_rows_from=old_nu)
        ti = su.sparse_table_update(
            table_i, mi, vi, li, i_idx, g_ei, step_no, p.learning_rate,
            rowwise=rowwise, update_rows_from=old_ni)
        return tu, ti

    state = tuple(
        (params[side]["embed"],
         *su.init_table_state(params[side]["embed"], rowwise=rowwise))
        for side in ("user", "item"))
    (tu, ti) = jax.lax.fori_loop(0, steps, body, state)
    return tu[0], ti[0]


def fold_in_two_tower(model: TwoTowerModel, delta_u: np.ndarray,
                      delta_i: np.ndarray, n_users: int, n_items: int,
                      refresh_steps: int = 3) -> TwoTowerModel:
    """Fold new entities into a trained two-tower model (ROADMAP item 2's
    neural analog of the ALS fold-in).

    ``delta_u``/``delta_i`` are the delta interactions encoded against
    the EXTENDED id space (new entities at indices past the parent table
    sizes); ``n_users``/``n_items`` are the extended counts. New rows
    warm-start as the mean of their delta counterparts' trained input
    embeddings (mean-of-neighbors — a new user lands where the items it
    touched live), then ``refresh_steps`` sparse-update steps over the
    delta refine ONLY the appended rows. Parent embedding rows, the MLP,
    and the parent slices of both serving corpora come back
    byte-identical; the new entities' corpus rows are computed with the
    parent towers."""
    p = model.hyper
    params = model.params
    old_nu = int(params["user"]["embed"].shape[0])
    old_ni = int(params["item"]["embed"].shape[0])
    delta_u = np.asarray(delta_u, np.int32)
    delta_i = np.asarray(delta_i, np.int32)

    def extend(table: np.ndarray, n_new: int, new_lo: int, own_idx,
               other_idx, other_table: np.ndarray) -> np.ndarray:
        """Append ``n_new`` rows: each initialized to the mean of its
        delta counterparts' EXISTING trained rows (zeros when every
        counterpart is itself new — the refresh steps then train it from
        its interactions alone)."""
        if n_new <= 0:
            return table
        rows = np.zeros((n_new, table.shape[1]), table.dtype)
        counts = np.zeros(n_new)
        sel = (own_idx >= new_lo) & (other_idx < other_table.shape[0])
        np.add.at(rows, own_idx[sel] - new_lo, other_table[other_idx[sel]])
        np.add.at(counts, own_idx[sel] - new_lo, 1.0)
        rows /= np.maximum(counts, 1.0)[:, None]
        return np.vstack([table, rows.astype(table.dtype)])

    uf = np.asarray(params["user"]["embed"], np.float32)
    itf = np.asarray(params["item"]["embed"], np.float32)
    new_params = {
        "user": {"embed": extend(uf, n_users - old_nu, old_nu, delta_u,
                                 delta_i, itf),
                 "layers": params["user"]["layers"]},
        "item": {"embed": extend(itf, n_items - old_ni, old_ni, delta_i,
                                 delta_u, uf),
                 "layers": params["item"]["layers"]},
    }
    if refresh_steps > 0 and len(delta_u) \
            and (n_users > old_nu or n_items > old_ni):
        # refresh only when the delta actually minted entities: with no
        # new rows every update would redirect to the drop id and the
        # device program would be guaranteed-byte-identical busywork
        # pad the delta batch onto the pow2 ladder (repeating the last
        # pair — updates apply only to new rows, so duplicates merely
        # reweight the warm-start refinement) to bound compile count
        bp = _pow2_floor8(len(delta_u))
        du = np.concatenate(
            [delta_u, np.full(bp - len(delta_u), delta_u[-1], np.int32)])
        di = np.concatenate(
            [delta_i, np.full(bp - len(delta_i), delta_i[-1], np.int32)])
        emb_u, emb_i = _foldin_refresh(
            new_params, du, di, p=dataclasses.replace(p, steps=0, seed=0),
            old_nu=old_nu, old_ni=old_ni, steps=refresh_steps)
        new_params["user"]["embed"] = np.asarray(emb_u)
        new_params["item"]["embed"] = np.asarray(emb_i)
    # serving corpora: parent slices byte-identical, new rows through the
    # parent towers
    forward = jax.jit(_tower_forward, static_argnames=())
    item_emb = model.item_embeddings
    user_emb = model.user_embeddings
    if n_items > old_ni:
        new_rows = np.asarray(forward(
            new_params["item"], jnp.arange(old_ni, n_items)))
        item_emb = np.vstack([item_emb, new_rows.astype(item_emb.dtype)])
    if n_users > old_nu:
        new_rows = np.asarray(forward(
            new_params["user"], jnp.arange(old_nu, n_users)))
        user_emb = np.vstack([user_emb, new_rows.astype(user_emb.dtype)])
    host = jax.tree.map(np.asarray, new_params)
    return TwoTowerModel(host, p, item_emb, user_emb)
