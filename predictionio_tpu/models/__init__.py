"""Algorithm library (L7): XLA-native model kernels.

Plays the role of MLlib + the reference's e2 module: ALS matrix
factorization (explicit + implicit), categorical naive Bayes, Markov chain,
binary vectorizer, two-tower retrieval. All hot paths are jit-compiled XLA
programs over the ComputeContext mesh.
"""
