"""Multinomial Naive Bayes on TPU.

Replaces MLlib's ``NaiveBayes.train`` (used by the reference's
classification template, ref: examples/scala-parallel-classification/
add-algorithm/src/main/scala/NaiveBayesAlgorithm.scala:16-28) with an XLA
program: class-conditional sums are one one-hot matmul on the MXU, with the
feature rows sharded over the mesh ``data`` axis (the contraction over the
sharded axis compiles to an ICI all-reduce — MLlib's ``aggregateByKey``
analog). Laplace smoothing matches MLlib's ``lambda``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass
class NaiveBayesModel:
    pi: np.ndarray  # [C] log priors
    theta: np.ndarray  # [C, F] log conditional probabilities
    labels: list  # class index → label value


@partial(jax.jit, static_argnames=("n_classes",))
def _nb_sums(features, labels_idx, weights, n_classes: int):
    onehot = jax.nn.one_hot(labels_idx, n_classes, dtype=features.dtype)
    onehot = onehot * weights[:, None]
    class_counts = onehot.sum(axis=0)  # [C]
    feature_sums = onehot.T @ features  # [C, F] — MXU matmul + all-reduce
    return class_counts, feature_sums


@jax.jit
def _nb_log_probs(class_counts, feature_sums, lambda_):
    n = class_counts.sum()
    n_classes = class_counts.shape[0]
    pi = jnp.log(class_counts + lambda_) - jnp.log(n + n_classes * lambda_)
    denom = feature_sums.sum(axis=1, keepdims=True) + lambda_ * feature_sums.shape[1]
    theta = jnp.log(feature_sums + lambda_) - jnp.log(denom)
    return pi, theta


def train_naive_bayes(
    ctx: ComputeContext,
    features: np.ndarray,  # [N, F] non-negative
    labels: np.ndarray,  # [N] any hashable values
    lambda_: float = 1.0,
) -> NaiveBayesModel:
    label_list = sorted(set(labels.tolist()))
    label_to_idx = {v: i for i, v in enumerate(label_list)}
    labels_idx = np.fromiter(
        (label_to_idx[v] for v in labels.tolist()), dtype=np.int32,
        count=len(labels),
    )
    features = np.asarray(features, dtype=np.float32)
    if (features < 0).any():
        raise ValueError("Multinomial NB requires non-negative features")
    f, n_valid = ctx.device_put_sharded_rows(features)
    y, _ = ctx.device_put_sharded_rows(labels_idx)
    w = np.zeros(f.shape[0], np.float32)
    w[:n_valid] = 1.0
    w = jax.device_put(w, ctx.batch_sharding())
    class_counts, feature_sums = _nb_sums(f, y, w, len(label_list))
    pi, theta = _nb_log_probs(class_counts, feature_sums, lambda_)
    return NaiveBayesModel(np.asarray(pi), np.asarray(theta), label_list)


@jax.jit
def _nb_scores(pi, theta, x):
    return pi + x @ theta.T  # [B, C]


def predict_naive_bayes(model: NaiveBayesModel, features: np.ndarray):
    """Batched predict: returns (labels, log joint scores [B, C]).

    The score program is a few-KFLOP matmul, so latency-aware placement
    (parallel/placement.py) runs it on the host CPU backend whenever the
    accelerator's link RTT dominates; model arrays are device-cached."""
    from predictionio_tpu.parallel.placement import (
        device_cache_put,
        serving_device,
    )

    x = np.atleast_2d(np.asarray(features, dtype=np.float32))
    place = serving_device(2.0 * x.shape[0] * model.theta.size)
    pi = device_cache_put(model.pi, device=place)
    theta = device_cache_put(model.theta, device=place)
    if place is not None:
        x = jax.device_put(x, place)
    scores = np.asarray(_nb_scores(pi, theta, x))
    idx = scores.argmax(axis=1)
    return [model.labels[i] for i in idx], scores
