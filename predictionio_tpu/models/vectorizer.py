"""Binary one-hot vectorizer for (feature, value) string pairs.

Re-design of the reference's e2 BinaryVectorizer
(ref: e2/src/main/scala/io/prediction/e2/engine/BinaryVectorizer.scala:24-60):
builds an index over distinct (property, value) pairs and encodes maps of
properties into dense one-hot vectors for the TPU classifiers."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Sequence

import numpy as np


@dataclass
class BinaryVectorizer:
    index: dict[tuple[str, str], int]

    @staticmethod
    def fit(
        maps: Iterable[Mapping[str, str]], properties: Sequence[str]
    ) -> "BinaryVectorizer":
        """ref: BinaryVectorizer.apply — distinct (property, value) pairs."""
        seen: dict[tuple[str, str], int] = {}
        for m in maps:
            for prop in properties:
                if prop in m:
                    key = (prop, str(m[prop]))
                    if key not in seen:
                        seen[key] = len(seen)
        return BinaryVectorizer(seen)

    @property
    def n_features(self) -> int:
        return len(self.index)

    def transform(self, m: Mapping[str, str]) -> np.ndarray:
        """ref: BinaryVectorizer.toBinary — O(len(m)) lookups."""
        out = np.zeros(len(self.index), np.float32)
        for prop, value in m.items():
            i = self.index.get((prop, str(value)))
            if i is not None:
                out[i] = 1.0
        return out

    def transform_batch(self, maps: Sequence[Mapping[str, str]]) -> np.ndarray:
        return np.stack([self.transform(m) for m in maps]) if maps else (
            np.zeros((0, len(self.index)), np.float32)
        )
