"""SASRec-style sequential recommendation transformer.

The reference has no sequence model (it predates LLMs; SURVEY.md §5
"Long-context: absent") — this is the TPU build's long-context model family:
a causal self-attention transformer over each user's interaction history
(SASRec, arxiv 1808.09781 pattern), built on the shared attention ops
(:mod:`predictionio_tpu.ops.attention`), which scale to long histories via
the flash kernel and ring attention.

Design notes (TPU-first):
- item id 0 is the padding id; embeddings row 0 stays zero-masked out of
  attention and loss.
- training step is one jitted program: forward over [B, L], sampled-negative
  binary CE at every position (the SASRec objective), adam update. Batch
  rows shard over the mesh ``data`` axis; parameters are replicated
  (dp — GSPMD inserts the gradient all-reduce).
- serving scores are one matmul of the last hidden state against the item
  embedding table + ``lax.top_k`` (same shape as the ALS serving path).
- the forward routes attention by ``attn_impl``: ``"mha"`` (XLA
  reference), ``"flash"`` (pallas blockwise kernel — long histories on one
  chip), ``"ring"`` (sequence-parallel ring over a ``seq`` mesh axis —
  histories beyond one device's HBM), or ``"auto"`` (flash on TPU once the
  history window is at least one MXU tile for serving / once the O(L²)
  score matrix dominates HBM for training, else mha). Sequences are
  left-padded, so padding enters all three paths as a ``kv_start`` valid-key
  window bound. Since round 5 every path is differentiable — the flash
  kernel carries a recompute-from-lse custom VJP and the ring path's
  ppermute scan transposes — so long-history TRAINING routes through
  flash/ring too; the choice is numerically transparent — all paths share
  one masking semantics (tests/test_sasrec.py parity + grad-parity tests).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from jax.sharding import PartitionSpec as P

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.ops.attention import flash_attention, mha_attention
from predictionio_tpu.parallel.mesh import ComputeContext, DATA_AXIS, shard_map

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class SASRecParams:
    max_len: int = 50
    embed_dim: int = 64
    num_blocks: int = 2
    num_heads: int = 2
    ffn_dim: int = 128
    dropout: float = 0.2
    learning_rate: float = 1e-3
    batch_size: int = 128
    num_epochs: int = 20
    l2_emb: float = 0.0
    seed: int = 0
    attn_impl: str = "auto"  # auto | mha | flash | ring (serving forward)
    #: Sparse item-embedding updates (docs/perf.md §17): the three
    #: gathers a step makes (sequence forward, positive and negative
    #: targets) are differentiated wrt the GATHERED rows, deduped +
    #: segment-summed, and adam runs over the touched-row slices only —
    #: optimizer traffic O(batch · seq_len) rows instead of the full
    #: [n_items + 1, d] table. The transformer blocks / pos_emb / ln
    #: keep dense adam. Ignored (dense fallback) when ``l2_emb > 0``:
    #: the whole-table L2 term has an inherently dense gradient.
    sparse_update: bool = True


def init_params(n_items: int, p: SASRecParams, key=None) -> dict:
    """Parameter pytree. ``n_items`` excludes the padding id; the embedding
    table has ``n_items + 1`` rows with row 0 = padding."""
    if key is None:
        key = jax.random.PRNGKey(p.seed)
    d, h = p.embed_dim, p.ffn_dim
    keys = jax.random.split(key, 2 + 6 * p.num_blocks)
    scale = 0.02
    params = {
        "item_emb": scale * jax.random.normal(keys[0], (n_items + 1, d)),
        "pos_emb": scale * jax.random.normal(keys[1], (p.max_len, d)),
        "blocks": [],
        "ln_f": {"g": jnp.ones(d), "b": jnp.zeros(d)},
    }
    for i in range(p.num_blocks):
        k = keys[2 + 6 * i : 8 + 6 * i]
        params["blocks"].append(
            {
                "wq": scale * jax.random.normal(k[0], (d, d)),
                "wk": scale * jax.random.normal(k[1], (d, d)),
                "wv": scale * jax.random.normal(k[2], (d, d)),
                "wo": scale * jax.random.normal(k[3], (d, d)),
                "ln1": {"g": jnp.ones(d), "b": jnp.zeros(d)},
                "ln2": {"g": jnp.ones(d), "b": jnp.zeros(d)},
                "w1": scale * jax.random.normal(k[4], (d, h)),
                "b1": jnp.zeros(h),
                "w2": scale * jax.random.normal(k[5], (h, d)),
                "b2": jnp.zeros(d),
            }
        )
    return params


def _layer_norm(x, g, b, eps=1e-6):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def _flash_block(l: int) -> int:
    """Largest divisor of ``l`` that fits a 128-row MXU tile."""
    for bs in range(min(l, 128), 0, -1):
        if l % bs == 0:
            return bs
    return 1


def _resolve_attn(p: SASRecParams, *, serving: bool, l: int) -> str:
    """Pick the attention path for this call. Every impl is usable for
    BOTH training and serving since round 5 (the pallas flash kernel
    grew a custom VJP; the ring path's ppermute scan was always
    differentiable). ``auto`` = flash on TPU once the window is at
    least one MXU tile for serving, and once the O(L²) score
    activations stop fitting HBM comfortably for training — measured
    crossover on the v5e (B=8-16, d=64, 2 blocks): mha wins to L=4096
    (7.7 vs 17.1 ms/step at 2048, 25 vs 42 at 4096), flash wins 5.5x
    at L=8192 (178 vs 981 ms/step), so the training threshold is
    8192."""
    impl = p.attn_impl
    if impl not in ("auto", "mha", "flash", "ring"):
        raise ValueError(f"unknown attn_impl {impl!r}")
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        min_l = 128 if serving else 8192
        if on_tpu and l >= min_l and _flash_block(l) >= 32:
            return "flash"
        return "mha"
    return impl


def _ring_mesh():
    """All visible devices on a ``seq`` axis (batch axis 1): the serving
    layout for histories sharded beyond one device."""
    from jax.sharding import Mesh

    devices = np.array(jax.devices())
    return Mesh(devices.reshape(1, -1), ("data", "seq"))


def _attend(q, k, v, seqs, impl: str, mesh=None):
    """One attention call [B, L, H, Dh] with SASRec's left-padded masking:
    causal + valid-key window starting at the first real item. All three
    impls share the same ``kv_start`` window semantics by construction."""
    l = seqs.shape[1]
    kv_start = (l - (seqs > 0).sum(axis=1)).astype(jnp.int32)  # [B]
    if impl == "mha":
        return mha_attention(q, k, v, causal=True, kv_start=kv_start)
    if impl == "flash":
        bs = _flash_block(l)
        if bs < 8:
            raise ValueError(
                f"attn_impl='flash' needs max_len ({l}) with a tile-sized "
                f"divisor (>= 8; ideally a multiple of 128); best found {bs}"
            )
        return flash_attention(
            q, k, v, causal=True, kv_start=kv_start, blk_q=bs, blk_k=bs,
            interpret=jax.default_backend() != "tpu",
        )
    if impl == "ring":
        from predictionio_tpu.ops.ring_attention import ring_self_attention

        n_seq = mesh.shape["seq"]
        if l % n_seq:
            raise ValueError(
                f"ring attention needs max_len ({l}) divisible by the seq "
                f"axis ({n_seq} devices)"
            )
        return ring_self_attention(
            mesh, q, k, v, causal=True, kv_start=kv_start
        )
    raise ValueError(f"unknown attention impl {impl!r}")


def forward(params: dict, seqs, p: SASRecParams, *, dropout_key=None,
            mesh=None, x_emb=None):
    """Hidden states [B, L, D] for padded item-id sequences [B, L] (0=pad).
    ``dropout_key`` enables dropout (training); None disables (serving).
    ``mesh`` overrides the device mesh for the ring-attention path.
    ``x_emb`` supplies pre-gathered item embeddings [B, L, D] (the sparse
    train step differentiates wrt the gathered rows, so the table
    gradient never materializes as a dense [n, d] scatter).

    Sequences shorter than ``max_len`` (the serving seq-length buckets,
    docs/perf.md §16) take the TAIL of the position table: left-padded
    histories then see the SAME absolute positions at every padded
    length, so a bucketed forward is numerically the max_len forward."""
    b, l = seqs.shape
    d = p.embed_dim
    valid = (seqs > 0)[..., None]  # [B, L, 1]
    x = (params["item_emb"][seqs] if x_emb is None else x_emb) \
        * jnp.sqrt(jnp.asarray(d, jnp.float32))
    n_pos = params["pos_emb"].shape[0]
    x = x + params["pos_emb"][None, n_pos - l:]
    x = jnp.where(valid, x, 0.0)

    def dropout(key, t):
        if dropout_key is None or p.dropout <= 0.0:
            return t
        keep = jax.random.bernoulli(key, 1.0 - p.dropout, t.shape)
        return jnp.where(keep, t / (1.0 - p.dropout), 0.0)

    keys = (
        jax.random.split(dropout_key, 2 * p.num_blocks + 1)
        if dropout_key is not None
        else [None] * (2 * p.num_blocks + 1)
    )
    x = dropout(keys[0], x) if dropout_key is not None else x
    n_heads = p.num_heads
    head_dim = d // n_heads
    impl = _resolve_attn(p, serving=dropout_key is None, l=l)
    if impl == "ring" and mesh is None:
        mesh = _ring_mesh()  # resolve once, not per transformer block
    for i, blk in enumerate(params["blocks"]):
        h = _layer_norm(x, blk["ln1"]["g"], blk["ln1"]["b"])
        q = (h @ blk["wq"]).reshape(b, l, n_heads, head_dim)
        k = (h @ blk["wk"]).reshape(b, l, n_heads, head_dim)
        v = (h @ blk["wv"]).reshape(b, l, n_heads, head_dim)
        attn = _attend(q, k, v, seqs, impl, mesh=mesh).reshape(b, l, d)
        attn = attn @ blk["wo"]
        if dropout_key is not None:
            attn = dropout(keys[1 + 2 * i], attn)
        x = jnp.where(valid, x + attn, 0.0)
        h = _layer_norm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        f = jax.nn.relu(h @ blk["w1"] + blk["b1"]) @ blk["w2"] + blk["b2"]
        if dropout_key is not None:
            f = dropout(keys[2 + 2 * i], f)
        x = jnp.where(valid, x + f, 0.0)
    return _layer_norm(x, params["ln_f"]["g"], params["ln_f"]["b"])


def _loss_fn(params, seqs, pos, neg, key, p: SASRecParams):
    """SASRec objective: binary CE of (positive next item vs one sampled
    negative) at every non-pad position. pos/neg are [B, L] target ids."""
    h = forward(params, seqs, p, dropout_key=key)  # [B, L, D]
    pos_logit = jnp.einsum("bld,bld->bl", h, params["item_emb"][pos])
    neg_logit = jnp.einsum("bld,bld->bl", h, params["item_emb"][neg])
    mask = (pos > 0).astype(jnp.float32)
    loss = -(
        jax.nn.log_sigmoid(pos_logit) + jax.nn.log_sigmoid(-neg_logit)
    ) * mask
    loss = loss.sum() / jnp.maximum(mask.sum(), 1.0)
    if p.l2_emb > 0.0:
        loss = loss + p.l2_emb * (params["item_emb"] ** 2).sum()
    return loss


def _raw_train_step(params, opt_state, seqs, pos, neg, key, tx_lr,
                    p: SASRecParams):
    loss, grads = jax.value_and_grad(_loss_fn)(params, seqs, pos, neg, key, p)
    updates, opt_state = optax.adam(tx_lr).update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


def _use_sparse(p: SASRecParams) -> bool:
    """Sparse item-table updates apply unless the whole-table L2 term
    (inherently dense gradient) is on."""
    return p.sparse_update and p.l2_emb <= 0.0


def _split_dense(params: dict) -> dict:
    """The densely-updated subtree: everything but the item table."""
    return {k: v for k, v in params.items() if k != "item_emb"}


def init_opt_state(params: dict, p: SASRecParams):
    """Optimizer state for the train step: plain adam over the whole
    pytree on the dense path; on the sparse path, adam over the dense
    subtree plus the item table's (m, v, last_step) touched-row buffers
    (ops/sparse_update) and the global step counter."""
    if not _use_sparse(p):
        return optax.adam(p.learning_rate).init(params)
    from predictionio_tpu.ops import sparse_update as su

    m, v, last = su.init_table_state(params["item_emb"])
    return {
        "step": jnp.zeros((), jnp.int32),
        "dense": optax.adam(p.learning_rate).init(_split_dense(params)),
        "item": {"m": m, "v": v, "last": last},
    }


def _raw_sparse_step(params, opt_state, seqs, pos, neg, key, tx_lr,
                     p: SASRecParams):
    """One training step with sparse item-table updates: the three
    gathers (sequence, positive, negative) enter the loss as explicit
    [B, L, D] inputs, their gradients dedup + segment-sum into touched-
    row gradients, and adam applies over the touched slices only —
    scatter-applied into the donated table (docs/perf.md §17). The
    padding row 0 receives exactly-zero summed gradients (every masked
    position), so it stays zero like the dense path keeps it."""
    from predictionio_tpu.ops import sparse_update as su

    table = params["item_emb"]
    d = table.shape[1]
    e_seq = table[seqs]
    e_pos = table[pos]
    e_neg = table[neg]
    dense = _split_dense(params)

    def loss_fn(dense, e_seq, e_pos, e_neg):
        h = forward({**dense, "item_emb": table}, seqs, p,
                    dropout_key=key, x_emb=e_seq)
        pos_logit = jnp.einsum("bld,bld->bl", h, e_pos)
        neg_logit = jnp.einsum("bld,bld->bl", h, e_neg)
        mask = (pos > 0).astype(jnp.float32)
        loss = -(
            jax.nn.log_sigmoid(pos_logit) + jax.nn.log_sigmoid(-neg_logit)
        ) * mask
        return loss.sum() / jnp.maximum(mask.sum(), 1.0)

    loss, (g_dense, g_seq, g_pos, g_neg) = jax.value_and_grad(
        loss_fn, argnums=(0, 1, 2, 3))(dense, e_seq, e_pos, e_neg)
    step_no = opt_state["step"] + 1
    updates, dense_state = optax.adam(tx_lr).update(
        g_dense, opt_state["dense"], dense)
    dense_new = optax.apply_updates(dense, updates)
    idx = jnp.concatenate(
        [seqs.reshape(-1), pos.reshape(-1), neg.reshape(-1)])
    grads = jnp.concatenate(
        [g_seq.reshape(-1, d), g_pos.reshape(-1, d),
         g_neg.reshape(-1, d)])
    st = opt_state["item"]
    table, m, v, last = su.sparse_table_update(
        table, st["m"], st["v"], st["last"], idx, grads, step_no, tx_lr)
    new_params = {**dense_new, "item_emb": table}
    new_state = {"step": step_no, "dense": dense_state,
                 "item": {"m": m, "v": v, "last": last}}
    return new_params, new_state, loss


@device_obs.profiled_program(
    "sasrec_epoch",
    bucket=lambda params, opt_state, seqs, *a, **kw: (
        tuple(seqs.shape), tuple(sorted(
            (k, repr(v)) for k, v in kw.items()))),
    sync=True,  # per-epoch dispatch: one tiny readback per epoch is
    # noise, and callers read float(loss) right after anyway
)
@partial(
    jax.jit,
    static_argnames=("p", "steps_per_epoch", "bs", "n_items"),
    donate_argnums=(0, 1),
)
def _train_epoch(
    params, opt_state, seqs, pos, key, epoch, tx_lr,
    *, p: SASRecParams, steps_per_epoch: int, bs: int, n_items: int,
):
    """One epoch as a single dispatch: on-device shuffle, on-device negative
    sampling, ``fori_loop`` over the full batches — the host (and, through
    a tunneled TPU, a per-step RPC + batch transfer) stays out of the
    training loop."""
    n = seqs.shape[0]
    ekey = jax.random.fold_in(key, epoch)
    order = jax.random.permutation(ekey, n).astype(jnp.int32)

    def body(s, carry):
        params, opt_state, _ = carry
        idx = jax.lax.dynamic_slice_in_dim(order, s * bs, bs)
        sb, pb = seqs[idx], pos[idx]
        kneg = jax.random.fold_in(ekey, 1 + 2 * s)
        neg = jax.random.randint(
            kneg, (bs, p.max_len), 1, n_items + 1, dtype=jnp.int32
        )
        neg = jnp.where(pb > 0, neg, 0)
        kstep = jax.random.fold_in(ekey, 2 + 2 * s)
        step_fn = _raw_sparse_step if _use_sparse(p) else _raw_train_step
        return step_fn(params, opt_state, sb, pb, neg, kstep, tx_lr, p)

    zero = jnp.zeros((), jnp.float32)
    return jax.lax.fori_loop(
        0, steps_per_epoch, body, (params, opt_state, zero)
    )


def _raw_sharded_sparse_step(params_loc, opt_loc, sb, pb, neg, key, tx_lr,
                             *, p: SASRecParams, n_items: int,
                             nshards: int, bl: int, cap: int):
    """Per-shard body of one ROW-SHARDED training step (runs inside the
    shard_map'd epoch, docs/perf.md §19): slice this shard's batch rows,
    dedup the three gathers' ids locally, exchange them with the owner
    shards over ONE all_to_all (ops/sharded_table routes), run the
    transformer on the local slice, and push the touched-row gradients
    back over the same route for the shard-local adam. The dense
    transformer subtree stays replicated with psum'd gradients."""
    from predictionio_tpu.ops import sharded_table as stbl
    from predictionio_tpu.ops import sparse_update as su

    table = params_loc["item_emb"][0]  # [rows_per, d] local block
    d = table.shape[1]
    n_rows = n_items + 1
    off = jax.lax.axis_index(DATA_AXIS) * bl
    sb = jax.lax.dynamic_slice_in_dim(sb, off, bl)
    pb = jax.lax.dynamic_slice_in_dim(pb, off, bl)
    neg = jax.lax.dynamic_slice_in_dim(neg, off, bl)
    dense = _split_dense(params_loc)
    ids = jnp.concatenate(
        [sb.reshape(-1), pb.reshape(-1), neg.reshape(-1)])
    rt = stbl.build_route(ids, n_rows=n_rows, ndev=nshards, cap=cap)
    e = stbl.route_gather(table, rt, ndev=nshards, cap=cap)[rt.inv]
    m = bl * sb.shape[1]
    e_seq = e[:m].reshape(bl, -1, d)
    e_pos = e[m:2 * m].reshape(bl, -1, d)
    e_neg = e[2 * m:].reshape(bl, -1, d)

    def loss_fn(dense, e_seq, e_pos, e_neg):
        h = forward(dense, sb, p, dropout_key=key, x_emb=e_seq)
        pos_logit = jnp.einsum("bld,bld->bl", h, e_pos)
        neg_logit = jnp.einsum("bld,bld->bl", h, e_neg)
        mask = (pb > 0).astype(jnp.float32)
        num = -((jax.nn.log_sigmoid(pos_logit)
                 + jax.nn.log_sigmoid(-neg_logit)) * mask).sum()
        # local partial of the GLOBAL masked mean: the denominator is
        # psum'd so per-shard gradients sum to the single-device ones
        denom = jax.lax.psum(mask.sum(), DATA_AXIS)
        return num / jnp.maximum(denom, 1.0)

    loss, (g_dense, g_seq, g_pos, g_neg) = jax.value_and_grad(
        loss_fn, argnums=(0, 1, 2, 3))(dense, e_seq, e_pos, e_neg)
    g_dense = jax.lax.psum(g_dense, DATA_AXIS)
    step_no = opt_loc["step"] + 1
    updates, dense_state = optax.adam(tx_lr).update(
        g_dense, opt_loc["dense"], dense)
    dense_new = optax.apply_updates(dense, updates)
    grads = jnp.concatenate(
        [g_seq.reshape(-1, d), g_pos.reshape(-1, d), g_neg.reshape(-1, d)])
    g_unique = su.segment_rows(grads, rt.inv, cap)
    st = opt_loc["item"]
    t2, m2, v2, l2 = stbl.route_update(
        table, st["m"][0], st["v"][0], st["last"][0], rt, g_unique,
        step_no, tx_lr, n_rows=n_rows, ndev=nshards, cap=cap)
    new_params = {**dense_new, "item_emb": t2[None]}
    new_state = {"step": step_no, "dense": dense_state,
                 "item": {"m": m2[None], "v": v2[None], "last": l2[None]}}
    return new_params, new_state, jax.lax.psum(loss, DATA_AXIS)


#: (mesh devices, compile-relevant statics) → compiled sharded epoch
#: program. Module-level like the two-tower trainer cache: fresh
#: value-equal meshes (same device ids) must reuse the executable, so a
#: re-train dispatches with ZERO retraces (tests/test_retrace_guard.py).
_SHARDED_EPOCH_PROGRAMS: dict = {}


def _sharded_epoch_program(mesh, *, p: SASRecParams, steps_per_epoch: int,
                           bs: int, n_items: int, nshards: int, cap: int):
    """The row-sharded twin of :func:`_train_epoch`: identical on-device
    shuffle + negative sampling (replicated RNG — the batch trajectory
    matches the single-device path), with the per-step body swapped for
    the all_to_all-routed sharded step."""
    key_ = (tuple(id(d) for d in mesh.devices.flat),
            dataclass_replace_epochs(p), steps_per_epoch, bs, n_items,
            nshards, cap)
    hit = _SHARDED_EPOCH_PROGRAMS.get(key_)
    if hit is not None:
        return hit
    bl = bs // nshards

    def epoch_local(params, opt_state, seqs, pos, key, epoch, tx_lr):
        n = seqs.shape[0]
        ekey = jax.random.fold_in(key, epoch)
        order = jax.random.permutation(ekey, n).astype(jnp.int32)

        def body(s, carry):
            params, opt_state, _ = carry
            idx = jax.lax.dynamic_slice_in_dim(order, s * bs, bs)
            sb, pb = seqs[idx], pos[idx]
            kneg = jax.random.fold_in(ekey, 1 + 2 * s)
            neg = jax.random.randint(
                kneg, (bs, p.max_len), 1, n_items + 1, dtype=jnp.int32)
            neg = jnp.where(pb > 0, neg, 0)
            kstep = jax.random.fold_in(ekey, 2 + 2 * s)
            return _raw_sharded_sparse_step(
                params, opt_state, sb, pb, neg, kstep, tx_lr,
                p=p, n_items=n_items, nshards=nshards, bl=bl, cap=cap)

        zero = jnp.zeros((), jnp.float32)
        return jax.lax.fori_loop(
            0, steps_per_epoch, body, (params, opt_state, zero))

    emb3 = P(DATA_AXIS, None, None)
    pspec = {"item_emb": emb3, "pos_emb": P(), "blocks": P(), "ln_f": P()}
    sspec = {"step": P(), "dense": P(),
             "item": {"m": emb3, "v": emb3, "last": P(DATA_AXIS, None)}}
    fn = shard_map(epoch_local, mesh=mesh,
                   in_specs=(pspec, sspec, P(), P(), P(), P(), P()),
                   out_specs=(pspec, sspec, P()), check_vma=False)
    fn = jax.jit(fn, donate_argnums=(0, 1))
    fn = device_obs.profiled_program(
        "sasrec_sharded_step",
        bucket=lambda params, opt_state, seqs, *a: (
            tuple(seqs.shape), bs, nshards, steps_per_epoch,
            repr(dataclass_replace_epochs(p))),
        sync=True,
    )(fn)
    _SHARDED_EPOCH_PROGRAMS[key_] = fn
    return fn


@partial(jax.jit, static_argnames=("k",))
def _score_last(item_emb, last, k: int, exclude_mask=None):
    """Top-k of last-hidden-state scores against the item table."""
    scores = last @ item_emb.T  # [B, n_items+1]
    scores = scores.at[:, 0].set(-jnp.inf)  # never recommend padding
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


@device_obs.profiled_program(
    "sasrec_predict",
    # params join via shape_bucket: the item-table row count is a model
    # property p alone doesn't pin, and a second model in one process
    # is an expected recompile, not a retrace
    bucket=lambda params, seqs, k, p, exclude_mask=None: (
        device_obs.shape_bucket(params, seqs), k, repr(p),
        exclude_mask is not None),
)
@partial(jax.jit, static_argnames=("k", "p"))
def _predict_top_k_jit(params, seqs, k: int, p: SASRecParams,
                       exclude_mask=None):
    h = forward(params, seqs, p)  # [B, L, D]
    # sequences are LEFT-padded, so the last real item is always at L-1
    return _score_last(params["item_emb"], h[:, -1], k, exclude_mask)


def predict_top_k(params, seqs, k: int, p: SASRecParams, exclude_mask=None,
                  mesh=None):
    """Top-k next items for padded sequences [B, L]: last hidden state @
    item embedding table. ``exclude_mask`` [B, n_items+1] True → drop
    (padding id and seen items). The ring-attention path runs the forward
    eagerly (it places sequence shards itself); mha/flash go through one
    jitted program.

    Host-numpy parameter pytrees (the post-checkpoint serving state) are
    device-cached per leaf and placed by the latency-aware serving policy
    (parallel/placement.py): the forward+score FLOPs of one query batch
    are small enough that a high-RTT accelerator link loses to the host
    CPU backend, while a co-located chip keeps the work."""
    if _resolve_attn(p, serving=True, l=seqs.shape[1]) == "ring":
        h = forward(params, seqs, p, mesh=mesh)
        return _score_last(params["item_emb"], h[:, -1], k, exclude_mask)
    leaves = jax.tree.leaves(params)
    if leaves and isinstance(leaves[0], np.ndarray):
        from predictionio_tpu.parallel.placement import (
            device_cache_put,
            serving_device,
        )

        b, l = np.shape(seqs)
        d = p.embed_dim
        n_rows = int(np.shape(params["item_emb"])[0])
        # attention/FFN stack + final catalog score, per padded batch
        fwd = 2.0 * b * l * d * (4 * d + 2 * p.ffn_dim) * p.num_blocks
        fwd += 2.0 * b * l * l * d * p.num_blocks  # attention scores
        place = serving_device(fwd + 2.0 * b * n_rows * d)
        params = jax.tree.map(
            lambda a: device_cache_put(a, device=place), params
        )
        if place is not None:
            seqs = jax.device_put(np.asarray(seqs), place)
            if exclude_mask is not None and not isinstance(
                exclude_mask, np.ndarray
            ):
                # a device-resident mask must follow the serving device
                exclude_mask = jax.device_put(exclude_mask, place)
    return _predict_top_k_jit(params, seqs, k, p, exclude_mask)


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def seq_bucket_len(max_history: int, max_len: int) -> int:
    """The pow2 sequence-length bucket for a serving tick whose longest
    real history is ``max_history`` items: next power of two (floor 8),
    capped at ``max_len`` (the top rung, pow2 or not) — the same ladder
    shape as the serving batch buckets, so varying histories reuse a
    handful of compiled programs. With the tail-aligned position table
    (see :func:`forward`) a bucketed forward scores identically to the
    max_len one."""
    b = _pow2(max(max_history, 1))
    return min(max(b, 8), max_len)


def predict_flops(p: SASRecParams, n_rows: int, b: int, l: int) -> float:
    """Model FLOPs of one serving tick: attention/FFN stack + the final
    catalog score (the placement decision's accelerator-side payload)."""
    d = p.embed_dim
    fwd = 2.0 * b * l * d * (4 * d + 2 * p.ffn_dim) * p.num_blocks
    fwd += 2.0 * b * l * l * d * p.num_blocks  # attention scores
    return fwd + 2.0 * b * n_rows * d


def serving_tick_on_device(p: SASRecParams, n_rows: int, n_queries: int,
                           l: int) -> bool:
    """Cheap pre-gate (the ALS twin): would a SASRec tick of this shape
    route to the device? Decided WITHOUT the mask-upload term — a False
    is final, a True still gets the exact decision (mask bytes included)
    inside :func:`serve_sasrec_topk_batched`."""
    from predictionio_tpu.parallel.placement import serving_device

    bp = _pow2(max(n_queries, 1))
    return serving_device(predict_flops(p, n_rows, bp, l), bp * l * 4,
                          overlapped=True) is None


def pin_sasrec_serving_state(params: dict, p: SASRecParams,
                             max_batch: int = 64) -> int:
    """Deploy-time HBM promotion of a SASRec model's parameter pytree
    (``serving_models`` arena): every leaf goes device-resident through
    the identity cache, so the first serving tick finds the transformer
    + item table warm instead of paying the upload inline. Decided at a
    representative full tick (``max_batch`` queries at ``max_len``);
    returns the pinned byte count (0 = the placement decision keeps
    serving on the host)."""
    from predictionio_tpu.parallel.placement import (
        device_cache_put,
        serving_device,
    )

    leaves = jax.tree.leaves(params)
    if not leaves or not isinstance(leaves[0], np.ndarray):
        return 0
    n_rows = int(params["item_emb"].shape[0])
    bp = _pow2(max_batch)
    place = serving_device(
        predict_flops(p, n_rows, bp, p.max_len), bp * p.max_len * 4,
        overlapped=True)
    if place is not None:
        return 0
    jax.tree.map(lambda a: device_cache_put(a, device=place), params)
    return int(sum(a.nbytes for a in leaves))


#: Per-tick result buffers — registered so a failed dispatch/finalize is
#: leak-checkable, like the ALS serving ticks (models/als._TICK_ARENA).
_SASREC_TICK_ARENA = device_obs.arena("serving_ticks")


def serve_sasrec_topk_batched(params: dict, seqs: np.ndarray, k: int,
                              p: SASRecParams, exclude_mask=None):
    """One FUSED device dispatch for a drained SASRec serving tick, or
    None.

    ``seqs`` [b, l] are the tick's left-padded histories (already on the
    pow2 sequence-length bucket — :func:`seq_bucket_len`); the whole
    transformer forward, the catalog score, the per-row exclusion mask
    and the top-k run as ONE jitted program (the same
    ``sasrec_predict``-profiled program the host route compiles) against
    the HBM-pinned parameter pytree — the host ships only the int32
    histories and the masks. Batch and k pad to pow2 so the
    micro-batcher's varying drain sizes reuse a handful of compiled
    programs.

    Returns None when the tick belongs on the host (placement decision,
    non-host-numpy params) — the caller falls back to the legacy
    per-tick :func:`predict_top_k` route. Otherwise returns a zero-arg
    ``finalize`` whose blocking readback the caller may defer: dispatch
    AND async d2h copies are in flight when this returns, so calling
    ``finalize()`` from the batcher's finalizer thread overlaps tick N's
    readback with tick N+1's dispatch. ``finalize()`` returns
    (scores [b, k], indices [b, k]) as host numpy."""
    from predictionio_tpu.parallel.placement import (
        device_cache_put,
        serving_device,
    )

    leaves = jax.tree.leaves(params)
    if not leaves or not isinstance(leaves[0], np.ndarray):
        return None
    seqs = np.asarray(seqs, np.int32)
    b, l = seqs.shape
    if b == 0:
        return None
    n_rows = int(params["item_emb"].shape[0])
    k = min(k, n_rows - 1)
    if k <= 0:
        return None
    if _resolve_attn(p, serving=True, l=l) == "ring":
        return None  # the ring path places its own sequence shards
    bp = _pow2(b)
    upload = bp * l * 4
    if exclude_mask is not None:
        exclude_mask = np.asarray(exclude_mask, bool)
        upload += bp * n_rows
    place = serving_device(predict_flops(p, n_rows, bp, l), upload,
                           overlapped=True)
    if place is not None:
        return None  # host route wins at this tick shape
    if bp != b:
        # padding rows repeat the last real history: always a valid
        # forward, results sliced off at finalize
        seqs = np.concatenate([seqs, np.repeat(seqs[-1:], bp - b, 0)])
        if exclude_mask is not None:
            exclude_mask = np.concatenate(
                [exclude_mask, np.zeros((bp - b, n_rows), bool)])
    kp = min(_pow2(k), n_rows - 1)
    dev_params = jax.tree.map(
        lambda a: device_cache_put(a, device=place), params)
    from predictionio_tpu.resilience import faults

    # the chaos suite's device-dispatch site (shared with the ALS route):
    # an injected error here is the fused program failing to launch —
    # exactly what the device-route breaker must absorb
    seqs = faults.fault_point("serving.dispatch", seqs)
    scores, idx = _predict_top_k_jit(dev_params, seqs, kp, p,
                                     exclude_mask)
    from predictionio_tpu.io import transfer

    resolve = transfer.begin_readback((scores, idx), name="serving")
    alloc = _SASREC_TICK_ARENA.register((scores, idx), label=f"b{bp}")

    def finalize():
        try:
            s, i = resolve()
        finally:
            _SASREC_TICK_ARENA.free(alloc)
        return s[:b, :k], i[:b, :k]

    return finalize


def dataclass_replace_epochs(p: SASRecParams) -> SASRecParams:
    """The fingerprint ignores num_epochs: extending an interrupted run
    to more epochs is a legitimate resume."""
    import dataclasses

    return dataclasses.replace(p, num_epochs=0)


class SASRec:
    """Training driver mirroring the ALS driver's shape."""

    def __init__(self, ctx: ComputeContext, params: SASRecParams):
        self.ctx = ctx
        self.p = params

    def train(self, sequences: list[list[int]], n_items: int,
              callback=None, checkpointer=None) -> dict:
        """``sequences``: per-user item-id lists (ids 1..n_items, time
        order). Returns the trained parameter pytree.

        ``checkpointer`` (utils.checkpoint.TrainCheckpointer) saves
        (params, opt_state) per epoch and resumes from the newest
        checkpoint — the per-epoch RNG derives from (seed, epoch), so a
        resumed run follows the exact trajectory of an uninterrupted one
        (asserted by tests/test_checkpoint_resume.py)."""
        p = self.p
        seqs, pos = _make_training_arrays(sequences, p.max_len)
        n = len(seqs)
        if n == 0:
            raise ValueError("SASRec.train called with no sequences")
        from predictionio_tpu.ops import sharded_table as stbl
        from predictionio_tpu.parallel import mesh as mesh_mod

        ctx = self.ctx
        want = stbl.requested_shards()
        if _use_sparse(p) and want >= 2 and ctx.model_axis_size == 1:
            # PIO_EMB_SHARDS: row-shard the item table over (up to) that
            # many data-axis devices; one sub-context for everything
            ctx = mesh_mod.data_subcontext(ctx, want)
        sharded = (_use_sparse(p) and want >= 2
                   and ctx.model_axis_size == 1 and ctx.data_axis_size > 1)
        nshards = ctx.data_axis_size if sharded else 1
        bs = min(p.batch_size, n)
        if sharded:
            bs = max(bs - bs % nshards, nshards)  # local slices must tile
        params = init_params(n_items, p)
        opt_state = init_opt_state(params, p)
        if sharded:
            params = {
                **{k: jax.device_put(v, ctx.replicated)
                   for k, v in _split_dense(params).items()},
                "item_emb": stbl.put_sharded(ctx.mesh, stbl.shard_table(
                    np.asarray(params["item_emb"]), nshards)),
            }
            opt_state = {
                "step": jax.device_put(opt_state["step"], ctx.replicated),
                "dense": jax.device_put(opt_state["dense"], ctx.replicated),
                "item": {kk: stbl.put_sharded(ctx.mesh, stbl.shard_table(
                    np.asarray(vv), nshards))
                    for kk, vv in opt_state["item"].items()},
            }
        key = jax.random.PRNGKey(p.seed)
        start_epoch = 0
        fingerprint = ""
        if checkpointer is not None:
            from predictionio_tpu.utils.checkpoint import fingerprint_arrays

            # bind checkpoints to this exact run: different data or
            # shape-affecting hyperparameters must not resume (num_epochs
            # excluded so an interrupted run can be extended)
            fingerprint = fingerprint_arrays(
                dataclass_replace_epochs(p), n_items, seqs, pos
            )
            hit = checkpointer.load_latest((params, opt_state), fingerprint)
            if hit is not None:
                last_epoch, (h_params, h_opt) = hit
                if sharded:
                    # restored host leaves carry the sharded template's
                    # [shards, rows_per, d] layout; re-pin per template
                    h_params = jax.tree.map(
                        lambda h, t: jax.device_put(h, t.sharding),
                        h_params, params)
                    h_opt = jax.tree.map(
                        lambda h, t: jax.device_put(h, t.sharding),
                        h_opt, opt_state)
                params, opt_state = h_params, h_opt
                start_epoch = last_epoch + 1
                logger.info("SASRec: resuming after epoch %d", last_epoch)
        steps_per_epoch = max(n // bs, 1)
        # dataset resident on device for the run, streamed up through the
        # ChunkStager (pack/upload of chunk k+1 overlaps chunk k's put)
        from predictionio_tpu.io import transfer

        seqs_d, pos_d = transfer.stage_training_arrays(
            (seqs, pos), name="sasrec_inputs",
            **({"sharding": ctx.replicated} if sharded else {}))
        loss = None
        # params + optimizer state under neural_params (the adam-traffic
        # figure, same as two_tower); the device-resident dataset — which
        # can dwarf the model — is its own arena so neither number lies
        alloc = device_obs.arena("neural_params").register(
            (params, opt_state), label="sasrec")
        data_alloc = device_obs.arena("train_data").register(
            (seqs_d, pos_d), label="sasrec")
        from predictionio_tpu.obs import runlog

        shard_allocs = []
        epoch_fn = None
        if sharded:
            bl = bs // nshards
            cap_env = stbl.requested_dedup_cap()
            cap = 3 * bl * p.max_len
            cap = min(cap_env, cap) if cap_env else cap
            epoch_fn = _sharded_epoch_program(
                ctx.mesh, p=p, steps_per_epoch=steps_per_epoch, bs=bs,
                n_items=n_items, nshards=nshards, cap=cap)
            rp = stbl.rows_per_shard(n_items + 1, nshards)
            per_shard = rp * (p.embed_dim * 4 * 3 + 4)  # table+m+v, last
            for d in range(nshards):
                shard_allocs.append(
                    device_obs.arena(f"emb_shard{d}").register(
                        per_shard, label="sasrec"))
            # representative routing stats over the first batch's ids
            # (host-side: feeds pio_emb_shard_* and the doctor finding
            # without syncing the epoch loop)
            ids0 = np.concatenate([seqs[:bs].ravel(), pos[:bs].ravel()])
            rs = stbl.route_stats(ids0[ids0 > 0], n_items + 1, nshards,
                                  p.embed_dim)
            runlog.note("emb_shard_imbalance", round(rs["imbalance"], 3))
            runlog.note("emb_shards", nshards)
            # shard observatory (obs/shards.py): one dispatch per epoch
            # executes steps_per_epoch sharded steps
            from predictionio_tpu.obs import shards as shard_obs

            shard_obs.OBSERVATORY.program_meta(
                "sasrec_sharded_step", shards=nshards,
                arena_prefix="emb_shard",
                steps_per_dispatch=steps_per_epoch)
            shard_obs.OBSERVATORY.record_shard_load(
                "sasrec_sharded_step", rs["touched_per_shard"],
                kind="touched rows")
        try:
            st = runlog.StepTimer(
                "sasrec_epoch", total=p.num_epochs, start=start_epoch,
                phase="train", examples_per_step=steps_per_epoch * bs)
            for epoch in range(start_epoch, p.num_epochs):
                if sharded:
                    params, opt_state, loss = epoch_fn(
                        params, opt_state, seqs_d, pos_d, key,
                        jnp.int32(epoch), p.learning_rate)
                else:
                    params, opt_state, loss = _train_epoch(
                        params, opt_state, seqs_d, pos_d, key, epoch,
                        p.learning_rate,
                        p=p, steps_per_epoch=steps_per_epoch, bs=bs,
                        n_items=n_items,
                    )
                st.step(epoch + 1, sync=loss,
                        loss=(float(loss) if runlog.active() is not None
                              else None))
                if callback is not None:
                    callback(epoch, float(loss))
                if checkpointer is not None \
                        and checkpointer.should_save(epoch):
                    checkpointer.save(
                        epoch, (params, opt_state), fingerprint)
        finally:
            device_obs.arena("neural_params").free(alloc)
            device_obs.arena("train_data").free(data_alloc)
            for d, a in enumerate(shard_allocs):
                device_obs.arena(f"emb_shard{d}").free(a)
        out = jax.tree_util.tree_map(np.asarray, params)
        if sharded:
            from predictionio_tpu.obs import shards as shard_obs

            ex_frac = shard_obs.OBSERVATORY.exchange_frac(
                "sasrec_sharded_step")
            if ex_frac is not None:
                runlog.note("exchange_frac", round(ex_frac, 4))
            # collapse back to the flat [n_items + 1, d] layout serving
            # and checkpoint consumers expect (pad rows drop here)
            out["item_emb"] = stbl.unshard_table(
                out["item_emb"], n_items + 1)
        return out


def _make_training_arrays(sequences: list[list[int]], max_len: int):
    """Left-pad each user's last ``max_len+1`` items into input [n, L] and
    next-item target [n, L] arrays."""
    seqs = np.zeros((len(sequences), max_len), dtype=np.int32)
    pos = np.zeros((len(sequences), max_len), dtype=np.int32)
    for i, s in enumerate(sequences):
        s = s[-(max_len + 1):]
        inp, tgt = s[:-1], s[1:]
        if not inp:
            continue
        seqs[i, -len(inp):] = inp
        pos[i, -len(tgt):] = tgt
    return seqs, pos
