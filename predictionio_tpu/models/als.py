"""Alternating Least Squares matrix factorization on TPU.

Replaces MLlib's ``ALS.train`` / ``ALS.trainImplicit`` (used by the
reference's recommendation templates, e.g.
examples/scala-parallel-recommendation/custom-serving/src/main/scala/
ALSAlgorithm.scala:27-67) with an XLA-native design in the style of ALX
(arxiv 2112.02194, PAPERS.md):

- Ratings are grouped host-side into **degree buckets** (entities by
  neighbor count); the host ships only narrow sorted COO arrays + per-
  bucket CSR pointers, and the padded dense tiles are built ON DEVICE per
  solve chunk, so every device step is a large static-shape batched
  contraction + unrolled Cholesky — no sparse scatter/gather loops, no
  dynamic shapes, no tile-sized host transfers.
- Each half-iteration solves all entities of one side: gather the *fixed*
  side's factors (replicated in HBM), form per-entity normal equations
  ``(Yᵀ C Y + λ n I) x = Yᵀ C r``, batched ``cho_solve``, and scatter rows
  back — the row batch is sharded over the mesh ``data`` axis, so the
  scatter into the replicated factor matrix compiles to an ICI all-gather,
  which is exactly the factor exchange MLlib implements as a block shuffle.
- Implicit feedback uses the Hu-Koren trick: the dense ``YᵀY`` Gram term is
  one small replicated matmul per half-step; observed entries contribute
  only the ``(c-1) y yᵀ`` correction.

Regularization matches MLlib 1.3's ALS-WR weighting: λ is scaled by each
entity's rating count.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.parallel.mesh import ComputeContext
# host-array-identity device cache: without it each query would re-ship
# the whole catalog over the host link (~RTT-sized latency per call
# through a tunneled TPU); lives beside the latency-aware placement policy
from predictionio_tpu.parallel.placement import (
    device_cache_put as _as_device,
    host_cache_transform,
    serving_device,
)

logger = logging.getLogger(__name__)

@dataclass(frozen=True)
class ALSParams:
    """Hyperparameters (ref template engine.json defaults: rank 10,
    numIterations 20, lambda 0.01, seed)."""

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    implicit_prefs: bool = False
    alpha: float = 1.0  # implicit confidence weight (MLlib default 1.0)
    seed: int | None = None
    max_degree: int = 4096  # per-entity neighbor cap (oversized rows truncate)
    #: Finer widths cut tile padding (HBM traffic scales with sum(n*k)):
    #: at ML-20M the geometric ladder below pads ~1.4x vs ~2.2x for the
    #: coarse (16,64,256,1024,4096) ladder.
    bucket_widths: tuple[int, ...] = (
        16, 32, 64, 128, 256, 512, 1024, 2048, 4096
    )
    #: dtype of the gathered fixed-side factors in the normal-equation
    #: assembly (Gram/rhs einsums accumulate in f32 either way, and the
    #:  solve itself is f32). bf16 halves the dominant HBM gather traffic;
    #: set "float32" for bit-level parity studies.
    gather_dtype: str = "bfloat16"
    #: HBM budget for a bucket solve's gathered-factor tensor, expressed as
    #: f32-equivalent elements (i.e. a BYTE budget of 4x this value): the
    #: effective element bound is scaled by 4/itemsize(gather_dtype), so
    #: the default bf16 path fits 2x the elements in the same HBM — see
    #: :func:`_effective_max_elems`. Buckets above the budget solve in
    #: sequential ``lax.map`` row chunks so the gather temp is O(chunk),
    #: not O(bucket) — at ML-20M rank 64 the unchunked gather alone is
    #: >12 GB, past a v5e chip.
    max_solve_elems: int = 1 << 28
    #: Solver choice. ``auto`` picks ``dense`` (whole-catalog int8
    #: matmul normal equations, models/als_dense.py) when the densified
    #: rating matrix fits the HBM budget and the ratings are int8-encodable
    #: — ~14x the bucket solver's rate at ML-20M, where the bucket path is
    #: HBM-gather-tile-amplification-bound (docs/perf.md). ``bucket`` is
    #: the ALX-style degree-bucketed gather solve (the general fallback:
    #: any catalog size, sharded meshes); ``segment`` builds the normal
    #: equations by sorted segment-sum over ratings — correct and
    #: memory-lean, but its scatter-based reduction measured slower on v5e.
    solver: str = "auto"


@dataclass
class ALSFactors:
    user_features: np.ndarray  # [n_users, rank] float32
    item_features: np.ndarray  # [n_items, rank] float32


@dataclass
class _TileSpec:
    """One degree bucket, described by per-entity CSR pointers instead of
    materialized [n, k] tiles: the dense tiles are built ON DEVICE from the
    sorted rating arrays (a [n, k] iota + two gathers), so the host ships
    ~12 bytes/rating instead of ~24 and no tile buffers at all."""

    rows: np.ndarray  # [n] int32 entity indices (padding aliases rows[0])
    starts: np.ndarray  # [n] int32 offset into the sorted rating arrays
    counts: np.ndarray  # [n] int32 ratings per entity (0 for padding rows)
    width: int  # tile width k
    nc: int = 1  # solve in this many sequential row chunks (see max_solve_elems)


def _chunk_plan(
    n_real: int, width: int, rank: int, max_elems: int, unit: int
) -> tuple[int, int]:
    """(n_padded, nc): pad ``n_real`` rows to ``nc`` equal chunks of ``c``
    rows, ``c`` a multiple of the data-axis size ``unit``, such that one
    chunk's gathered-factor tensor ``c*width*rank`` fits ``max_elems``
    (bottoming out at one row-block per device)."""
    nc = 1
    while True:
        c = ((n_real + nc * unit - 1) // (nc * unit)) * unit
        if c * width * max(rank, 1) <= max_elems or c == unit:
            return nc * c, nc
        nc *= 2


def _effective_max_elems(params: ALSParams) -> int:
    """The chunk planner's element budget: ``max_solve_elems`` is an
    f32-equivalent (byte) budget, so narrower gather dtypes fit
    proportionally more elements (fewer/larger chunks measured ~1.5x
    faster at ML-20M rank 64). Shared with bench.py's FLOP/pad model."""
    return max(
        params.max_solve_elems * 4 // jnp.dtype(params.gather_dtype).itemsize,
        1,
    )


def _narrow_nbr(neighbor_sorted: np.ndarray, n_other: int):
    """Neighbor ids in the narrowest lossless wire format: uint16 when they
    fit, a (lo: uint16, hi: uint8) pair for ids < 2^24 (3 bytes/row instead
    of 4 — the item-side solve's user ids are the largest single transfer),
    int32 otherwise. :func:`_widen_nbr` reassembles on device."""
    # ids are in [0, n_other), so n_other == 2^16 still fits uint16
    if n_other <= (1 << 16):
        return neighbor_sorted.astype(np.uint16)
    if n_other <= (1 << 24):
        arr = neighbor_sorted.astype(np.uint32)
        return (
            (arr & 0xFFFF).astype(np.uint16), (arr >> 16).astype(np.uint8)
        )
    return neighbor_sorted.astype(np.int32)


def _widen_nbr(nbr) -> "jnp.ndarray":
    """Device-side inverse of :func:`_narrow_nbr` → int32 indices."""
    if isinstance(nbr, tuple):
        lo, hi = nbr
        return lo.astype(jnp.int32) | (hi.astype(jnp.int32) << 16)
    return nbr.astype(jnp.int32)


def _val_fits_int8(ratings: np.ndarray) -> bool:
    return bool(
        np.all(ratings == np.rint(ratings)) and np.all(np.abs(ratings) <= 127)
    )


def _histogram(entity_idx: np.ndarray, n_entities: int):
    """(counts_all, starts_all): degree histogram + exclusive cumsum — the
    CSR layout shared by the tile specs and the counting-sort ETL."""
    counts_all = np.bincount(entity_idx, minlength=n_entities)
    starts_all = np.zeros(len(counts_all), dtype=np.int64)
    np.cumsum(counts_all[:-1], out=starts_all[1:])
    return counts_all, starts_all


def _bucketize(
    ctx: ComputeContext,
    counts_all: np.ndarray,
    starts_all: np.ndarray,
    params: ALSParams,
) -> list[_TileSpec]:
    """Group one side's entities by degree into tile *specs* (ALX §3.2-style
    density bucketing) from the CSR histogram. The starts are valid because
    the counting-sort ETL (:func:`_sort_perm`) groups entities in ascending
    order with stable ties — the load-bearing invariant between the two."""
    uniq = np.flatnonzero(counts_all).astype(np.int32)
    starts = starts_all[uniq].astype(np.int32)
    counts = counts_all[uniq].astype(np.int32)
    widths = [w for w in params.bucket_widths if w <= params.max_degree]
    if not widths or widths[-1] < params.max_degree:
        widths.append(params.max_degree)
    max_elems = _effective_max_elems(params)
    specs: list[_TileSpec] = []
    for bi, width in enumerate(widths):
        lo = widths[bi - 1] if bi > 0 else 0
        if bi == len(widths) - 1:
            sel = counts > lo  # oversized degrees land here, truncated
        else:
            sel = (counts > lo) & (counts <= width)
        if not sel.any():
            continue
        b_entities = uniq[sel]
        n, nc = _chunk_plan(
            len(b_entities), width, params.rank, max_elems,
            ctx.n_devices,
        )
        rows = np.zeros(n, dtype=np.int32)
        b_starts = np.zeros(n, dtype=np.int32)
        b_counts = np.zeros(n, dtype=np.int32)
        rows[: len(b_entities)] = b_entities
        # padding rows must alias an entity already being solved in this
        # bucket (their count stays 0): the scatter clears target[rows], so
        # pointing padding at an out-of-bucket entity would wipe its factors
        rows[len(b_entities):] = b_entities[0]
        b_starts[: len(b_entities)] = starts[sel]
        b_counts[: len(b_entities)] = np.minimum(counts[sel], width)
        specs.append(_TileSpec(rows, b_starts, b_counts, width, nc))
    return specs


def _native_sort_lib(symbol: str):
    """The compiled sort library when available and carrying ``symbol``,
    else None (callers fall back to numpy)."""
    from predictionio_tpu.native import eventlog_lib

    lib = eventlog_lib()
    if lib is not None and hasattr(lib, symbol):
        return lib
    return None


def _sort_perm(entity_idx: np.ndarray, starts_all: np.ndarray) -> np.ndarray:
    """Stable ascending sort permutation over entity ids — the ETL step
    that groups ratings per entity. Fast path: a one-pass C counting sort
    (native/eventlog.cc pio_counting_sort_perm, ~0.1s for 20M rows; keys
    are bounded by the entity count so counting sort is O(n)). Fallback:
    numpy's stable argsort (~3s) when no toolchain is available. A device
    `jnp.argsort` was measured SLOWER than either (~7s — TPU sorts are
    comparison networks)."""
    import ctypes

    lib = _native_sort_lib("pio_counting_sort_perm")
    if lib is not None:
        keys = np.ascontiguousarray(entity_idx, dtype=np.int32)
        next_pos = starts_all.copy()  # the C pass mutates its cursors
        perm = np.empty(len(keys), dtype=np.int32)
        rc = lib.pio_counting_sort_perm(
            keys.ctypes.data_as(ctypes.c_void_p), len(keys), len(next_pos),
            next_pos.ctypes.data_as(ctypes.c_void_p),
            perm.ctypes.data_as(ctypes.c_void_p),
        )
        if rc == 0:
            return perm
    return np.argsort(entity_idx, kind="stable").astype(np.int32)


def _sorted_side(
    entity_idx: np.ndarray,
    starts_all: np.ndarray,
    neighbor_idx: np.ndarray,
    ratings: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """(neighbors, ratings) grouped by entity in one fused C pass — the
    counting sort applies the payloads while sorting, replacing a
    permutation plus two 20M-row fancy-index gathers. Falls back to the
    :func:`_sort_perm` + gather route without a toolchain."""
    import ctypes

    lib = _native_sort_lib("pio_counting_sort_apply")
    if lib is not None:
        keys = np.ascontiguousarray(entity_idx, dtype=np.int32)
        ids = np.ascontiguousarray(neighbor_idx, dtype=np.int32)
        vals = np.ascontiguousarray(ratings, dtype=np.float32)
        next_pos = starts_all.copy()
        out_ids = np.empty(len(keys), dtype=np.int32)
        out_vals = np.empty(len(keys), dtype=np.float32)
        rc = lib.pio_counting_sort_apply(
            keys.ctypes.data_as(ctypes.c_void_p), len(keys), len(next_pos),
            next_pos.ctypes.data_as(ctypes.c_void_p),
            ids.ctypes.data_as(ctypes.c_void_p),
            vals.ctypes.data_as(ctypes.c_void_p),
            out_ids.ctypes.data_as(ctypes.c_void_p),
            out_vals.ctypes.data_as(ctypes.c_void_p),
        )
        if rc == 0:
            return out_ids, out_vals
    perm = _sort_perm(entity_idx, starts_all)
    return neighbor_idx[perm], ratings[perm]


#: Ranks up to this solve via the unrolled structure-of-arrays Cholesky —
#: measured ~6x faster than batched `lax.linalg.cholesky` at rank 10 on
#: v5e (tiny batched linalg serializes poorly and its [n, r, r] operands
#: tile-pad ~20x). Above it, unrolling r(r+1)/2 lane ops bloats the program.
_SOA_SOLVE_MAX_RANK = 16


def _soa_cho_solve(gram, rhs, reg, rank: int):
    """Batched SPD solve in structure-of-arrays form: every L[i][j] is an
    [n]-vector, the r(r+1)/2-step Cholesky-Banachiewicz recurrence is
    unrolled at trace time, and all arithmetic is full-lane VPU ops."""
    gram_t = jnp.transpose(gram, (1, 2, 0))  # [r, r, n] — n on lanes
    a = [[gram_t[i, j] for j in range(rank)] for i in range(rank)]
    return _soa_cho_solve_from(a, rhs.T, reg, rank)


def _soa_cho_solve_from(a, rhs_t, reg, rank: int):
    """The SoA Cholesky-solve core on prebuilt entries: ``a[i][j]`` is the
    [n]-vector of gram entries, ``rhs_t`` [r, n]. Callers that already
    hold the gram in packed upper-triangle columns (the dense solver's
    matmul output) index those directly and skip the [n, r, r]
    materialization + relayout entirely."""
    l = [[None] * rank for _ in range(rank)]
    for j in range(rank):
        s = a[j][j] + reg
        for k in range(j):
            s = s - l[j][k] * l[j][k]
        d = jnp.sqrt(s)
        l[j][j] = d
        inv_d = 1.0 / d
        for i in range(j + 1, rank):
            s = a[i][j]
            for k in range(j):
                s = s - l[i][k] * l[j][k]
            l[i][j] = s * inv_d
    y = [None] * rank
    for i in range(rank):
        s = rhs_t[i]
        for k in range(i):
            s = s - l[i][k] * y[k]
        y[i] = s / l[i][i]
    x = [None] * rank
    for i in reversed(range(rank)):
        s = y[i]
        for k in range(i + 1, rank):
            s = s - l[k][i] * x[k]
        x[i] = s / l[i][i]
    return jnp.stack(x, axis=1)  # [n, r]


#: Panel width of the blocked batched Cholesky below. 16 keeps each
#: panel's unrolled SoA recurrences small (fast compile) while the
#: trailing updates run as [n, 16p, 16]-shaped batched matmuls.
_CHO_BLOCK = 16


def _soa_cho_factor(blk, reg=None):
    """Lower-Cholesky factor of SPD ``blk`` [B, B, n] (batch on LANES)
    via the unrolled SoA recurrence — the factor-only half of
    _soa_cho_solve; ``reg`` [n] adds to the diagonal."""
    b = blk.shape[0]
    l = [[None] * b for _ in range(b)]
    for j in range(b):
        s = blk[j, j] + (reg if reg is not None else 0.0)
        for k in range(j):
            s = s - l[j][k] * l[j][k]
        d = jnp.sqrt(s)
        l[j][j] = d
        inv_d = 1.0 / d
        for i in range(j + 1, b):
            s = blk[i, j]
            for k in range(j):
                s = s - l[i][k] * l[j][k]
            l[i][j] = s * inv_d
    rows = [
        jnp.stack([l[i][j] if j <= i else jnp.zeros_like(l[i][i])
                   for j in range(b)])
        for i in range(b)
    ]
    return jnp.stack(rows)  # [B, B, n] lower-triangular


def _right_trisolve(a, l_kk):
    """X with (per batch) X @ l_kkᵀ = a: a [B, B, n] (rows, cols, batch),
    l_kk [B, B, n] lower. B unrolled column steps of [B, n] vector math."""
    b = l_kk.shape[0]
    cols = []
    for j in range(b):
        s = a[:, j]
        for m in range(j):
            s = s - cols[m] * l_kk[j, m][None, :]
        cols.append(s / l_kk[j, j][None, :])
    return jnp.stack(cols, axis=1)  # [B, B, n]


def _forward_sub(l_kk, b_vec):
    """y with l_kk @ y = b_vec per batch: b_vec [B, n]."""
    b = l_kk.shape[0]
    y = []
    for j in range(b):
        s = b_vec[j]
        for m in range(j):
            s = s - l_kk[j, m] * y[m]
        y.append(s / l_kk[j, j])
    return jnp.stack(y)


def _backward_sub(l_kk, b_vec):
    """x with l_kkᵀ @ x = b_vec per batch: b_vec [B, n]."""
    b = l_kk.shape[0]
    x = [None] * b
    for j in reversed(range(b)):
        s = b_vec[j]
        for m in range(j + 1, b):
            s = s - l_kk[m, j] * x[m]
        x[j] = s / l_kk[j, j]
    return jnp.stack(x)


def _blocked_cho_solve(gram, rhs, reg, rank: int, block: int = _CHO_BLOCK):
    """Batched SPD solve for ranks beyond the SoA unroll budget:
    right-looking blocked Cholesky with ``block``-wide panels, entirely
    in the SoA layout ([r, r, n]: the batch rides the LANE axis, every
    scalar of the recurrence is an [n]-vector). Diagonal panels factor
    through a small SoA unroll; panel solves are B-step substitution
    unrolls; the O(r³) trailing updates are einsums contracting the tiny
    panel dims with n broadcast — full-lane VPU work. Replaces XLA:TPU's
    batched Cholesky custom call, which lane-pads [n, 64, 64] by 2x and
    measured ~11 GFLOP/s at rank 64 (the rank-64 ALS iteration was ~70%
    THIS solve, not the pairs dot — docs/perf.md §5). Blocking bounds
    trace size at ~p²·B ops (rank 64: ~1k), where the flat SoA unroll's
    ~r³/6 did not finish compiling.

    Ranks that aren't a multiple of ``block`` are padded with an
    identity diagonal (zero rhs rows solve to zero and are sliced off).
    """
    p = -(-rank // block)
    rp = p * block
    gram_t = jnp.transpose(gram, (1, 2, 0))  # [r, r, n]
    rhs_t = rhs.T  # [r, n]
    if rp != rank:
        pad = rp - rank
        gram_t = jnp.pad(gram_t, ((0, pad), (0, pad), (0, 0)))
        eye_pad = jnp.concatenate(
            [jnp.zeros((rank,), gram.dtype), jnp.ones((pad,), gram.dtype)])
        gram_t = gram_t + jnp.eye(rp, dtype=gram.dtype)[
            :, :, None] * eye_pad[:, None, None]
        rhs_t = jnp.pad(rhs_t, ((0, pad), (0, 0)))

    def blk(i, j):
        return (slice(i * block, (i + 1) * block),
                slice(j * block, (j + 1) * block))

    t = {(i, j): gram_t[blk(i, j)] for i in range(p) for j in range(i + 1)}
    return _blocked_cho_core(t, rhs_t, reg, rank, block)


def _blocked_cho_core(t, rhs_t, reg, rank: int, block: int = _CHO_BLOCK):
    """The blocked-Cholesky core on prebuilt lower-triangle panel blocks:
    ``t[(i, j)]`` [B, B, n] for j <= i (i, j in panel units covering the
    block-padded rank), ``rhs_t`` [pB, n]. See _blocked_cho_solve."""
    p = -(-rank // block)
    t = dict(t)  # trailing updates replace entries; don't mutate caller's
    # HIGHEST keeps every contraction f32-exact: a default-precision
    # einsum on TPU rounds operands through bf16, and ~1e-3 errors inside
    # the Schur-complement updates can push a trailing diagonal negative
    # → sqrt → NaN (the same hazard _pairs_payload documents for the gram)
    hi = jax.lax.Precision.HIGHEST
    l: dict = {}
    for k in range(p):
        l[(k, k)] = _soa_cho_factor(t[(k, k)], reg)
        for i in range(k + 1, p):
            l[(i, k)] = _right_trisolve(t[(i, k)], l[(k, k)])
        # trailing (Schur) updates, STACKED: one einsum over the whole
        # trailing panel column instead of one per (i, j) pair. Same
        # contractions, same order, bit-identical results — but XLA:TPU
        # lowers the many small [B, B, n] einsums catastrophically (the
        # round-4 rank-64 solve spent ~400 ms here; the stacked form
        # measures ~24 ms, an 18x). The stacked einsum computes the
        # upper-triangle blocks it discards (~2x FLOPs of the needed
        # half) and still wins by an order of magnitude.
        s = p - k - 1
        if s:
            stack = jnp.concatenate([l[(i, k)] for i in range(k + 1, p)])
            upd = jnp.einsum("abn,cbn->acn", stack, stack, precision=hi)
            for ii in range(s):
                for jj in range(ii + 1):
                    i, j = k + 1 + ii, k + 1 + jj
                    t[(i, j)] = t[(i, j)] - upd[
                        ii * block:(ii + 1) * block,
                        jj * block:(jj + 1) * block]
    y = []
    for i in range(p):
        b_vec = rhs_t[i * block:(i + 1) * block]
        for k in range(i):
            b_vec = b_vec - jnp.einsum(
                "abn,bn->an", l[(i, k)], y[k], precision=hi)
        y.append(_forward_sub(l[(i, i)], b_vec))
    x = [None] * p
    for i in reversed(range(p)):
        b_vec = y[i]
        for k in range(i + 1, p):
            b_vec = b_vec - jnp.einsum(
                "abn,an->bn", l[(k, i)], x[k], precision=hi)
        x[i] = _backward_sub(l[(i, i)], b_vec)
    out = jnp.concatenate(x, axis=0)  # [rp, n]
    return out[:rank].T


def _reg_solve(gram, rhs, reg, rank: int):
    """(gram + reg I) x = rhs, batched over the leading axis."""
    if rank <= _SOA_SOLVE_MAX_RANK:
        return _soa_cho_solve(gram, rhs, reg, rank)
    return _blocked_cho_solve(gram, rhs, reg, rank)


def _reg_solve_packed(pairs, rhs, reg, rank: int, block: int = _CHO_BLOCK):
    """(gram + reg I) x = rhs where the gram arrives as packed upper-
    triangle columns ``pairs`` [n, r(r+1)/2] — the dense solver's matmul
    output layout. Feeds the SoA/blocked cores by INDEXING the packed
    rows, skipping the [n, r, r] scatter-assembly and the [n, r, r] →
    [r, r, n] relayout the gram-based path pays (round-4 profile: at
    rank 64 those cost more than the factorization itself)."""
    n = pairs.shape[0]
    n_pairs = rank * (rank + 1) // 2
    iu, ju = np.triu_indices(rank)
    col = np.zeros((rank, rank), np.int64)
    col[iu, ju] = np.arange(n_pairs)
    col[ju, iu] = np.arange(n_pairs)
    pairs_t = pairs.T  # [P, n]
    if rank <= _SOA_SOLVE_MAX_RANK:
        a = [[pairs_t[col[i, j]] for j in range(rank)]
             for i in range(rank)]
        return _soa_cho_solve_from(a, rhs.T, reg, rank)
    p = -(-rank // block)
    rp = p * block
    # two sentinel rows: zeros (off-diagonal padding) and ones (identity
    # diagonal for the padded tail — solves the zero rhs rows to zero)
    idx = np.full((rp, rp), n_pairs, np.int64)
    idx[:rank, :rank] = col
    idx[np.arange(rank, rp), np.arange(rank, rp)] = n_pairs + 1
    aug = jnp.concatenate([
        pairs_t,
        jnp.zeros((1, n), pairs.dtype),
        jnp.ones((1, n), pairs.dtype),
    ])
    t = {}
    for i in range(p):
        for j in range(i + 1):
            blk_idx = jnp.asarray(
                idx[i * block:(i + 1) * block,
                    j * block:(j + 1) * block].reshape(-1))
            t[(i, j)] = jnp.take(aug, blk_idx, axis=0).reshape(
                block, block, n)
    rhs_t = rhs.T
    if rp != rank:
        rhs_t = jnp.pad(rhs_t, ((0, rp - rank), (0, 0)))
    return _blocked_cho_core(t, rhs_t, reg, rank, block)


def _chunk_solutions(
    fixed,  # [n_other, rank] fixed-side factors (replicated)
    nbr,  # [nnz] int32 sorted neighbor indices (replicated)
    val,  # [nnz] f32 sorted ratings (replicated)
    starts,  # [c] int32 CSR offsets
    counts,  # [c] int32 per-entity degrees (0 → padding row)
    width: int,
    yty,  # [rank, rank] — YᵀY for implicit, zeros for explicit
    lambda_: float,
    alpha: float,
    implicit: bool,
    rank: int,
    gather_dtype: str = "bfloat16",
):
    """Normal-equation solutions for one row chunk of a bucket.

    The [c, k] tile is built here on device (iota + CSR gather) instead of
    being shipped from the host. The gathered factor tile [c, k, r] is the
    dominant HBM traffic (its r-minor layout tile-pads r → 128 lanes, a
    12.8x byte amplification at rank 10), so the gather and the Gram/rhs
    contractions run in ``gather_dtype`` (bf16 halves the bytes and doubles
    MXU rate) while accumulating and solving in f32."""
    dt = jnp.dtype(gather_dtype)
    iota = jnp.arange(width, dtype=jnp.int32)[None, :]
    in_row = iota < counts[:, None]  # [c, k] bool validity mask
    idx = jnp.where(in_row, starts[:, None] + iota, 0)
    cols = nbr[idx]  # [c, k] — padded lanes alias nbr[0], masked below
    weights = in_row.astype(jnp.float32)
    ratings = val[idx] * weights
    y = fixed.astype(dt)[cols]  # [c, k, r] gather, local (fixed replicated)
    n_ratings = counts.astype(jnp.float32)  # [c]
    if implicit:
        conf_minus1 = alpha * ratings * weights  # (c-1), only observed
        yw = y * conf_minus1[..., None].astype(dt)
        gram = yty[None, :, :] + jnp.einsum(
            "nkr,nks->nrs", yw, y, preferred_element_type=jnp.float32
        )
        rhs = jnp.einsum(
            "nkr,nk->nr", y, ((1.0 + conf_minus1) * weights).astype(dt),
            preferred_element_type=jnp.float32,
        )
    else:
        yw = y * weights[..., None].astype(dt)
        gram = jnp.einsum(
            "nkr,nks->nrs", yw, y, preferred_element_type=jnp.float32
        )
        rhs = jnp.einsum(
            "nkr,nk->nr", y, (ratings * weights).astype(dt),
            preferred_element_type=jnp.float32,
        )
    # ALS-WR: λ scaled by per-entity rating count; +ε keeps padded rows SPD
    reg = lambda_ * jnp.maximum(n_ratings, 1.0) + 1e-8
    return _reg_solve(gram, rhs, reg, rank)


def _solve_bucket(
    target,  # [n_entities, rank] factor matrix being updated (replicated)
    fixed,  # [n_other, rank] fixed-side factors (replicated)
    nbr,  # [nnz] int32 sorted neighbors (replicated)
    val,  # [nnz] f32 sorted ratings (replicated)
    rows,  # [n] int32
    starts,  # [n] int32
    counts,  # [n] int32
    yty,  # [rank, rank] — YᵀY for implicit, zeros for explicit
    lambda_: float,
    alpha: float,
    implicit: bool,
    rank: int,
    width: int,
    nc: int = 1,
    shard=None,
    gather_dtype: str = "bfloat16",
):
    """One bucket's batched normal-equation solve. ``rows/starts/counts``
    are sharded over the mesh ``data`` axis; ``target``/``fixed``/``nbr``/
    ``val`` replicated, so the row scatter at the end compiles to an ICI
    all-gather. Buckets whose gather temp would exceed
    ALSParams.max_solve_elems arrive with ``nc>1`` and solve in sequential
    ``lax.map`` row chunks so HBM stays bounded. Traced inside the train
    loop — not jitted on its own."""
    if nc > 1:
        n = rows.shape[0]
        c = n // nc
        xs = tuple(x.reshape(nc, c) for x in (starts, counts))
        if shard is not None:
            cs = NamedSharding(shard.mesh, P(None, *shard.spec))
            xs = tuple(jax.lax.with_sharding_constraint(x, cs) for x in xs)
        sol = jax.lax.map(
            lambda t: _chunk_solutions(
                fixed, nbr, val, *t, width, yty, lambda_, alpha, implicit,
                rank, gather_dtype,
            ),
            xs,
        ).reshape(n, rank)
    else:
        sol = _chunk_solutions(
            fixed, nbr, val, starts, counts, width, yty, lambda_, alpha,
            implicit, rank, gather_dtype,
        )
    row_valid = (counts > 0).astype(sol.dtype)
    sol = sol * row_valid[:, None]  # padded rows contribute nothing
    # scatter solved rows; padding rows alias an in-bucket entity and are
    # masked to zero, so add-after-clear keeps every row correct
    cleared = target.at[rows].multiply(0.0)
    return cleared.at[rows].add(sol)


def _put(x, sharding):
    """Host → device placement: explicit sharding on a multi-chip mesh
    (``sharding is None`` on a single chip → default device). Maps over
    pytrees (the (lo, hi) neighbor pairs from _narrow_nbr)."""
    if sharding is not None:
        return jax.device_put(x, sharding)
    return jax.device_put(x)


def _gram(fixed):
    return fixed.T @ fixed


@partial(jax.jit, static_argnames=("n", "rank"))
def _init_factors(key, n: int, rank: int):
    """MLlib-style init: small random factors scaled by 1/sqrt(rank).
    Jitted so the factors are BORN on device — a host round trip per factor
    matrix costs ~250ms through a tunneled TPU."""
    return jax.random.normal(key, (n, rank), jnp.float32) / jnp.sqrt(
        jnp.asarray(rank, jnp.float32)
    )


@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "meta", "shard", "gather_dtype"),
    donate_argnums=(0, 1),
)
def _als_train(
    user_f,
    item_f,
    u_nbr,  # [nnz] uint16/int32 user-sorted item indices (replicated)
    u_val,  # [nnz] int8/f32 user-sorted ratings (replicated)
    i_nbr,  # [nnz] item-sorted user indices (replicated)
    i_val,  # [nnz] item-sorted ratings (replicated)
    u_tiles,  # per-bucket (rows, starts, counts) tuples, sharded over `data`
    i_tiles,
    lambda_: float,
    alpha: float,
    iters,  # TRACED loop bound — iteration count changes reuse the compile
    *,
    implicit: bool,
    rank: int,
    meta: tuple,  # ((user (width, nc)...), (item (width, nc)...)) — static
    shard=None,
    gather_dtype: str = "bfloat16",
):
    """The WHOLE training run as one XLA dispatch.

    The host ships only the narrow sorted COO arrays (uint16/int8 where
    lossless) plus tiny per-bucket CSR pointers; dense tiles are built on
    device inside each solve chunk. A single dispatch with a ``fori_loop``
    keeps the host (and a tunneled TPU's per-call RPC and re-transfer)
    entirely out of the training loop — at ML-20M scale that overhead
    rivalled the compute itself."""
    u_nbr = _widen_nbr(u_nbr)
    i_nbr = _widen_nbr(i_nbr)
    u_val = u_val.astype(jnp.float32)
    i_val = i_val.astype(jnp.float32)
    u_meta, i_meta = meta

    def body(_i, carry):
        uf, itf = carry
        return _iteration_body(
            uf, itf, u_nbr, u_val, i_nbr, i_val, u_tiles, i_tiles,
            u_meta, i_meta, lambda_, alpha, implicit, rank, shard,
            gather_dtype,
        )

    return jax.lax.fori_loop(0, iters, body, (user_f, item_f))


@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "meta", "shard", "gather_dtype"),
    donate_argnums=(0, 1),
)
def _als_iteration(
    user_f,
    item_f,
    u_nbr,
    u_val,
    i_nbr,
    i_val,
    u_tiles,
    i_tiles,
    lambda_: float,
    alpha: float,
    *,
    implicit: bool,
    rank: int,
    meta: tuple,
    shard=None,
    gather_dtype: str = "bfloat16",
):
    """One ALS iteration as its own dispatch — the callback path (per-
    iteration convergence probes); training without a callback goes through
    :func:`_als_train`."""
    u_meta, i_meta = meta
    return _iteration_body(
        user_f, item_f, _widen_nbr(u_nbr), u_val.astype(jnp.float32),
        _widen_nbr(i_nbr), i_val.astype(jnp.float32),
        u_tiles, i_tiles, u_meta, i_meta, lambda_, alpha, implicit, rank,
        shard, gather_dtype,
    )


def _iteration_body(
    user_f, item_f, u_nbr, u_val, i_nbr, i_val, u_tiles, i_tiles,
    u_meta, i_meta, lambda_, alpha, implicit, rank, shard=None,
    gather_dtype="bfloat16",
):
    zeros_gram = jnp.zeros((rank, rank), user_f.dtype)
    yty = _gram(item_f) if implicit else zeros_gram
    for (rows, starts, counts), (width, nc) in zip(u_tiles, u_meta):
        user_f = _solve_bucket(
            user_f, item_f, u_nbr, u_val, rows, starts, counts, yty,
            lambda_, alpha, implicit, rank, width, nc, shard, gather_dtype,
        )
    xtx = _gram(user_f) if implicit else zeros_gram
    for (rows, starts, counts), (width, nc) in zip(i_tiles, i_meta):
        item_f = _solve_bucket(
            item_f, user_f, i_nbr, i_val, rows, starts, counts, xtx,
            lambda_, alpha, implicit, rank, width, nc, shard, gather_dtype,
        )
    return user_f, item_f


# ---------------------------------------------------------------------------
# Segment-sum solver (small ranks)
# ---------------------------------------------------------------------------
#
# The bucketed solver's per-entity Gram matmuls execute as batched r x r
# contractions: on the MXU those pad to 128x128 output tiles, a ~160x FLOP
# amplification at the stock rank 10 (measured: 0.15 iter/s on ML-20M, MFU
# ~0). For small ranks the normal equations are instead accumulated as a
# *sorted segment reduction over ratings*:
#
#   gram[e]  = sum_{(e,j) in R}  w * y_j (x) y_j     -> r(r+1)/2 lanes
#   rhs[e]   = sum_{(e,j) in R}  w * r * y_j         -> r lanes
#
# which is pure VPU elementwise work + `segment_sum` with
# ``indices_are_sorted`` (ratings are host-sorted by entity once per run),
# followed by one batched Cholesky solve over all entities. No degree
# buckets, no padded tiles, no scatter at the end — the solve covers every
# entity and zero-degree rows keep their previous factors by a `where`.


@dataclass
class _SegSide:
    """One side's host-prepared, entity-sorted rating arrays."""

    seg: np.ndarray  # [nnz_pad] int32 entity index per rating (sorted)
    nbr: np.ndarray  # [nnz_pad] int32 fixed-side index per rating
    val: np.ndarray  # [nnz_pad] f32 rating
    wgt: np.ndarray  # [nnz_pad] f32 1.0 valid / 0.0 padding
    n_entities: int
    nc: int  # scan chunk count


def _segment_prepare(
    ctx: ComputeContext,
    entity_idx: np.ndarray,
    neighbor_idx: np.ndarray,
    ratings: np.ndarray,
    n_entities: int,
    params: ALSParams,
) -> _SegSide:
    order = np.argsort(entity_idx, kind="stable")
    seg = entity_idx[order]
    nbr = neighbor_idx[order]
    val = ratings[order]
    lanes = params.rank * (params.rank + 1) // 2 + params.rank + 1
    n, nc = _chunk_plan(
        len(seg), 1, lanes, params.max_solve_elems, ctx.n_devices
    )
    pad = n - len(seg)
    if pad:
        # padding carries weight 0 (contributes nothing) and reuses the
        # LAST segment id so the ids stay ascending — segment_sum is called
        # with indices_are_sorted=True, which is UB on unsorted ids
        last = seg[-1] if len(seg) else np.int32(0)
        seg = np.concatenate([seg, np.full(pad, last, np.int32)])
        nbr = np.concatenate([nbr, np.zeros(pad, np.int32)])
        val = np.concatenate([val, np.zeros(pad, np.float32)])
    wgt = np.ones(n, np.float32)
    if pad:
        wgt[len(order):] = 0.0
    return _SegSide(seg, nbr, val, wgt, n_entities, nc)


def _segment_half_solve(
    prev,  # [n_entities, rank] factors being updated (replicated)
    fixed,  # [n_other, rank] fixed-side factors (replicated)
    seg, nbr, val, wgt,  # [nnz_pad] rating arrays, sharded over `data`
    yty,  # [rank, rank] — YtY for implicit, zeros for explicit
    lambda_: float,
    alpha: float,
    implicit: bool,
    rank: int,
    n_entities: int,
    nc: int,
    shard=None,
):
    iu, ju = np.triu_indices(rank)
    n_pairs = len(iu)

    def chunk_stats(carry, xs):
        c_seg, c_nbr, c_val, c_wgt = xs
        y = fixed[c_nbr]  # [c, r]
        if implicit:
            cm1 = alpha * c_val * c_wgt  # (confidence - 1), observed only
            pair_w = cm1
            rhs_w = (1.0 + cm1) * c_wgt
        else:
            pair_w = c_wgt
            rhs_w = c_val * c_wgt
        data = jnp.concatenate(
            [
                y[:, iu] * y[:, ju] * pair_w[:, None],  # [c, r(r+1)/2]
                y * rhs_w[:, None],  # [c, r]
                c_wgt[:, None],  # [c, 1] rating counts
            ],
            axis=1,
        )
        carry = carry + jax.ops.segment_sum(
            data, c_seg, num_segments=n_entities, indices_are_sorted=True
        )
        return carry, None

    stats0 = jnp.zeros((n_entities, n_pairs + rank + 1), fixed.dtype)
    if nc > 1:
        c = seg.shape[0] // nc
        xs = tuple(x.reshape(nc, c) for x in (seg, nbr, val, wgt))
        if shard is not None:
            cs = NamedSharding(shard.mesh, P(None, *shard.spec))
            xs = tuple(jax.lax.with_sharding_constraint(x, cs) for x in xs)
        stats, _ = jax.lax.scan(chunk_stats, stats0, xs)
    else:
        stats, _ = chunk_stats(stats0, (seg, nbr, val, wgt))

    pairs = stats[:, :n_pairs]
    rhs = stats[:, n_pairs : n_pairs + rank]
    counts = stats[:, -1]
    gram = jnp.zeros((n_entities, rank, rank), fixed.dtype)
    gram = gram.at[:, iu, ju].set(pairs)
    gram = gram.at[:, ju, iu].set(pairs)  # symmetrize (diag overwritten same)
    if implicit:
        gram = gram + yty[None, :, :]
    reg = lambda_ * jnp.maximum(counts, 1.0) + 1e-8
    gram = gram + reg[:, None, None] * jnp.eye(rank, dtype=gram.dtype)
    sol = jax.scipy.linalg.cho_solve(
        (jnp.linalg.cholesky(gram), True), rhs[..., None]
    )[..., 0]
    # zero-degree entities keep their previous factors (init preservation)
    return jnp.where(counts[:, None] > 0, sol, prev)


@partial(
    jax.jit,
    static_argnames=(
        "implicit", "rank", "n_users", "n_items", "user_nc", "item_nc",
        "shard",
    ),
    donate_argnums=(0, 1),
)
def _als_iteration_segment(
    user_f,
    item_f,
    u_seg, u_nbr, u_val, u_wgt,
    i_seg, i_nbr, i_val, i_wgt,
    lambda_: float,
    alpha: float,
    *,
    implicit: bool,
    rank: int,
    n_users: int,
    n_items: int,
    user_nc: int,
    item_nc: int,
    shard=None,
):
    """One full ALS iteration via segment-sum normal equations."""
    zeros_gram = jnp.zeros((rank, rank), user_f.dtype)
    yty = _gram(item_f) if implicit else zeros_gram
    user_f = _segment_half_solve(
        user_f, item_f, u_seg, u_nbr, u_val, u_wgt, yty,
        lambda_, alpha, implicit, rank, n_users, user_nc, shard,
    )
    xtx = _gram(user_f) if implicit else zeros_gram
    item_f = _segment_half_solve(
        item_f, user_f, i_seg, i_nbr, i_val, i_wgt, xtx,
        lambda_, alpha, implicit, rank, n_items, item_nc, shard,
    )
    return user_f, item_f


@partial(jax.jit, static_argnames=("nc",))
def _rmse_terms(user_f, item_f, u_idx, i_idx, rating, weight, nc: int = 1):
    """Weighted squared-error sum. ``nc`` > 1 evaluates in sequential row
    chunks: the factor row-gathers tile-pad rank -> 128 lanes (~12.8x), so
    an unchunked 20M-row gather materializes ~10 GB of temps — past HBM."""

    def terms(args):
        u, i, r, w = args
        pred = jnp.einsum("nr,nr->n", user_f[u], item_f[i])
        err = (pred - r) ** 2 * w
        return err.sum(), w.sum()

    if nc == 1:
        return terms((u_idx, i_idx, rating, weight))
    c = u_idx.shape[0] // nc
    xs = tuple(x.reshape(nc, c) for x in (u_idx, i_idx, rating, weight))
    sq, wt = jax.lax.map(terms, xs)
    return sq.sum(), wt.sum()


#: Row-chunk target for _rmse_terms: the [c, rank] gathers' lane-padded
#: temps stay ~1 GB at this chunk size.
_RMSE_CHUNK = 2_000_000


class ALS:
    """Training driver. Usage::

        als = ALS(ctx, params)
        factors = als.train(user_idx, item_idx, ratings, n_users, n_items)
    """

    def __init__(self, ctx: ComputeContext, params: ALSParams):
        self.ctx = ctx
        self.params = params

    def train(
        self,
        user_idx: np.ndarray,
        item_idx: np.ndarray,
        ratings: np.ndarray,
        n_users: int,
        n_items: int,
        callback=None,
        resume=None,
        checkpoint=None,
    ) -> ALSFactors:
        """``resume`` = ``(start_iter, user_f, item_f)`` restores a
        crash-safe checkpoint (utils/checkpoint.TrainCheckpointer): the
        solve continues from ``start_iter`` on the given host factors
        instead of the seeded init. Supported on the dense paths — the
        single-device solver AND the SPMD sharded solver (which
        re-shards a resume tuple across the current device count);
        other solvers log and start fresh — a resume must never
        silently corrupt a solver that can't honor it.

        ``checkpoint`` (utils/checkpoint.TrainCheckpointSpec) hands the
        SPMD sharded path a bound checkpointer: it saves per-shard
        factor slabs + a layout manifest every ``every`` iterations and
        (when ``checkpoint.resume``) resumes from the newest valid one,
        re-sharding across a different device count. Single-device
        callers keep driving saves through ``callback`` instead."""
        p = self.params
        ctx = self.ctx
        user_idx = np.asarray(user_idx, dtype=np.int32)
        item_idx = np.asarray(item_idx, dtype=np.int32)
        ratings = np.asarray(ratings, dtype=np.float32)
        if user_idx.size == 0:
            raise ValueError("ALS.train called with zero ratings")

        if p.solver not in ("auto", "bucket", "segment", "dense"):
            raise ValueError(
                "ALSParams.solver must be auto/dense/bucket/segment, "
                f"got {p.solver!r}"
            )
        if resume is not None and p.solver == "segment":
            logger.warning(
                "ALS resume is only supported on the dense solver; "
                "solver=%r starts from scratch", p.solver)
            resume = None
        if checkpoint is not None and p.solver == "segment":
            logger.warning(
                "ALS checkpointing is only supported on the dense solver "
                "paths; solver=%r trains without snapshots", p.solver)
            checkpoint = None
        if p.solver == "segment":
            return self._train_segment(
                user_idx, item_idx, ratings, n_users, n_items, callback
            )
        if p.solver in ("auto", "dense"):
            from predictionio_tpu.models import als_dense

            if p.solver == "dense" and not als_dense.dense_eligible_on(
                    ctx, n_users, n_items, ratings):
                raise ValueError(
                    "solver='dense' requires int8-encodable ratings and a "
                    "rating matrix within the dense budget (single device: "
                    f"n_users*n_items <= {als_dense.DENSE_MAX_BYTES} cells; "
                    "mesh: one int32-addressable row-block per data shard)"
                )
            if p.solver == "dense" or als_dense.auto_pick(
                    ctx, n_users, n_items, ratings):
                if ctx.mesh.devices.size > 1:
                    if als_dense.sharded_block_fits(
                            ctx, n_users, n_items, ratings.size):
                        # SPMD (ALX layout): users and items both
                        # row-shard over `data`; per-iteration exchange
                        # ships only referenced factor slices
                        user_f, item_f = als_dense.train_dense_sharded(
                            ctx, p, user_idx, item_idx, ratings, n_users,
                            n_items, callback=callback, resume=resume,
                            checkpoint=checkpoint)
                        if checkpoint is not None:
                            # the run completed; its snapshots are
                            # obsolete
                            checkpoint.checkpointer.clear()
                        return ALSFactors(
                            np.asarray(user_f), np.asarray(item_f))
                    # explicit solver="dense" on a mesh whose per-device
                    # row-block exceeds the SPMD layout's int32/HBM
                    # bounds: the single-device path below device_puts
                    # every block UNSHARDED onto the default device —
                    # possible OOM at sizes the mesh was meant to absorb
                    logger.warning(
                        "ALS(dense): %d-device mesh present but the "
                        "per-device row-block of %d users x %d items "
                        "exceeds the SPMD dense layout's bounds; falling "
                        "back to the SINGLE-DEVICE dense path on the "
                        "default device",
                        ctx.mesh.devices.size, n_users, n_items)
                if checkpoint is not None:
                    # single-device dense: whole-factor snapshots ride
                    # the per-iteration callback; resume restores global
                    # host factors through the structure-checked loader
                    ck = checkpoint.checkpointer
                    fp = checkpoint.fingerprint
                    if resume is None and checkpoint.resume:
                        like = {
                            "user": np.zeros((n_users, p.rank),
                                             np.float32),
                            "item": np.zeros((n_items, p.rank),
                                             np.float32),
                        }
                        got = ck.load_latest(like, fingerprint=fp)
                        if got is not None:
                            step, state = got
                            resume = (step + 1, state["user"],
                                      state["item"])
                            logger.info(
                                "ALS train resuming from checkpoint "
                                "step %d (iteration %d of %d)", step,
                                step + 1, p.num_iterations)
                    inner_cb = callback

                    def callback(it, user_f, item_f, _inner=inner_cb,
                                 _ck=ck, _fp=fp):
                        if _ck.should_save(it):
                            _ck.save(it, {"user": np.asarray(user_f),
                                          "item": np.asarray(item_f)},
                                     fingerprint=_fp)
                        if _inner is not None:
                            _inner(it, user_f, item_f)

                user_f, item_f = als_dense.train_dense(
                    ctx, p, user_idx, item_idx, ratings, n_users, n_items,
                    callback, resume=resume)
                t0 = time.perf_counter()
                if als_dense._pipeline_enabled():
                    # chunked async readback: train_dense already started
                    # the user-factor copy while the final item half-step
                    # was still executing, so this mostly waits on the
                    # item side
                    from predictionio_tpu.io import transfer

                    uf_host, if_host = transfer.async_readback(
                        (user_f, item_f), name="als_factors")
                else:
                    # PIO_TRANSFER_PIPELINE=0 restores the round-5
                    # monolithic path END TO END — readback included
                    packed = np.asarray(
                        jnp.concatenate([user_f, item_f], axis=0))
                    uf_host, if_host = packed[:n_users], packed[n_users:]
                als_dense.last_train_phases["readback_s"] = round(
                    time.perf_counter() - t0, 3)
                if checkpoint is not None:
                    # the run completed; its snapshots are obsolete
                    checkpoint.checkpointer.clear()
                return ALSFactors(uf_host, if_host)

        if resume is not None:
            logger.warning(
                "ALS resume is only supported on the dense solver path; "
                "the bucketed solver starts from scratch")
        if checkpoint is not None:
            logger.warning(
                "ALS checkpointing is only supported on the dense solver "
                "paths; the bucketed solver trains without snapshots")
        multi = ctx.mesh.devices.size > 1
        key = jax.random.PRNGKey(p.seed if p.seed is not None else 0)
        ku, ki = jax.random.split(key)
        user_f = _init_factors(ku, n_users, p.rank)
        item_f = _init_factors(ki, n_items, p.rank)
        if multi:  # single-chip: factors already live where they must
            user_f = jax.device_put(user_f, ctx.replicated)
            item_f = jax.device_put(item_f, ctx.replicated)

        # ETL: each side's ratings grouped per entity by a one-pass C
        # counting sort (see _sort_perm), then shipped ONCE in the
        # narrowest lossless dtypes (uint16 ids when they fit, int8
        # integer ratings) + tiny per-bucket CSR pointers (sharded over
        # `data`). Dense tiles are built on device, so nothing [n, k]-sized
        # ever crosses the host link. The two sides' host prep runs on
        # parallel threads; the transfers are issued afterwards on THIS
        # thread in a fixed order — in a multi-process SPMD run every
        # process must issue sharded puts in the same order, so they must
        # never race (async dispatch still overlaps them with each other).
        shard = ctx.batch_sharding() if multi else None
        repl = ctx.replicated if multi else None
        int8_vals = _val_fits_int8(ratings)

        def prep_side(entity_idx, n_entities, neighbor_idx, n_other):
            counts, starts = _histogram(entity_idx, n_entities)
            specs = _bucketize(ctx, counts, starts, p)
            ids, vals = _sorted_side(entity_idx, starts, neighbor_idx, ratings)
            if int8_vals:  # integrality is permutation-invariant
                vals = vals.astype(np.int8)
            return specs, _narrow_nbr(ids, n_other), vals

        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=2) as ex:
            fut_u = ex.submit(prep_side, user_idx, n_users, item_idx, n_items)
            fut_i = ex.submit(prep_side, item_idx, n_items, user_idx, n_users)
            u_specs, u_ids, u_vals = fut_u.result()
            i_specs, i_ids, i_vals = fut_i.result()
        u_nbr = _put(u_ids, repl)
        u_val = _put(u_vals, repl)
        i_nbr = _put(i_ids, repl)
        i_val = _put(i_vals, repl)
        u_tiles = tuple(
            tuple(_put(x, shard) for x in (s.rows, s.starts, s.counts))
            for s in u_specs
        )
        i_tiles = tuple(
            tuple(_put(x, shard) for x in (s.rows, s.starts, s.counts))
            for s in i_specs
        )
        logger.info(
            "ALS: %d ratings, %d users (%d buckets), %d items (%d buckets), rank %d",
            ratings.size, n_users, len(u_specs), n_items,
            len(i_specs), p.rank,
        )
        meta = (
            tuple((s.width, s.nc) for s in u_specs),
            tuple((s.width, s.nc) for s in i_specs),
        )
        static = dict(
            implicit=p.implicit_prefs, rank=p.rank, meta=meta, shard=shard,
            gather_dtype=p.gather_dtype,
        )

        from predictionio_tpu.obs import runlog

        if callback is None and not runlog.want_steps():
            # the whole training run in ONE device dispatch (fori_loop):
            # per-call host/RPC overhead would otherwise rival the compute
            t0 = time.perf_counter()
            user_f, item_f = _als_train(
                user_f, item_f, u_nbr, u_val, i_nbr, i_val,
                u_tiles, i_tiles, p.lambda_, p.alpha, p.num_iterations,
                **static,
            )
            # tiny sync so the fused telemetry times the solve, not its
            # enqueue — free here: the full factor readback follows
            # immediately below
            np.asarray(jax.device_get(item_f[:1, :1]))
            runlog.fused_steps("als_bucket", p.num_iterations,
                               time.perf_counter() - t0)
        else:
            from predictionio_tpu.resilience import faults

            st = runlog.StepTimer("als_bucket", total=p.num_iterations,
                                  phase="solve")
            for it in range(p.num_iterations):
                # crash-safe-training chaos site (same name as the dense
                # path's): an injected error is a mid-train kill between
                # checkpoint intervals
                faults.fault_point("train.iteration")
                user_f, item_f = _als_iteration(
                    user_f, item_f, u_nbr, u_val, i_nbr, i_val,
                    u_tiles, i_tiles, p.lambda_, p.alpha, **static,
                )
                if callback is not None:
                    callback(it, user_f, item_f)
                st.step(it + 1, sync=item_f)

        # one readback for both factor matrices
        packed = np.asarray(jnp.concatenate([user_f, item_f], axis=0))
        return ALSFactors(packed[:n_users], packed[n_users:])

    def _train_segment(
        self, user_idx, item_idx, ratings, n_users, n_items, callback=None
    ) -> ALSFactors:
        """Segment-sum solver driver (see module section above)."""
        p = self.params
        ctx = self.ctx
        us = _segment_prepare(ctx, user_idx, item_idx, ratings, n_users, p)
        it = _segment_prepare(ctx, item_idx, user_idx, ratings, n_items, p)
        logger.info(
            "ALS(segment): %d ratings, %d users (%d chunks), %d items "
            "(%d chunks), rank %d",
            ratings.size, n_users, us.nc, n_items, it.nc, p.rank,
        )
        multi = ctx.mesh.devices.size > 1
        key = jax.random.PRNGKey(p.seed if p.seed is not None else 0)
        ku, ki = jax.random.split(key)
        user_f = _init_factors(ku, n_users, p.rank)
        item_f = _init_factors(ki, n_items, p.rank)
        shard = None
        if multi:
            user_f = jax.device_put(user_f, ctx.replicated)
            item_f = jax.device_put(item_f, ctx.replicated)
            shard = ctx.batch_sharding()

        u_arrs = tuple(
            _put(x, shard) for x in (us.seg, us.nbr, us.val, us.wgt))
        i_arrs = tuple(
            _put(x, shard) for x in (it.seg, it.nbr, it.val, it.wgt))

        from predictionio_tpu.obs import runlog

        st = runlog.StepTimer("als_segment", total=p.num_iterations,
                              phase="solve")
        for step in range(p.num_iterations):
            user_f, item_f = _als_iteration_segment(
                user_f, item_f, *u_arrs, *i_arrs, p.lambda_, p.alpha,
                implicit=p.implicit_prefs, rank=p.rank,
                n_users=n_users, n_items=n_items,
                user_nc=us.nc, item_nc=it.nc, shard=shard,
            )
            if callback is not None:
                callback(step, user_f, item_f)
            st.step(step + 1, sync=item_f)

        packed = np.asarray(jnp.concatenate([user_f, item_f], axis=0))
        return ALSFactors(packed[:n_users], packed[n_users:])

    def rmse(
        self,
        factors: ALSFactors,
        user_idx: np.ndarray,
        item_idx: np.ndarray,
        ratings: np.ndarray,
    ) -> float:
        ctx = self.ctx
        n = len(user_idx)
        nc = max(1, -(-n // _RMSE_CHUNK))
        unit = ctx.n_devices
        c = -(-n // (nc * unit)) * unit
        total = nc * c

        def put(x, dtype):
            x = np.asarray(x, dtype)
            if len(x) != total:
                x = np.concatenate([x, np.zeros(total - len(x), dtype)])
            return jax.device_put(x, ctx.batch_sharding())

        u = put(user_idx, np.int32)
        i = put(item_idx, np.int32)
        r = put(ratings, np.float32)
        w = np.zeros(total, np.float32)
        w[:n] = 1.0
        w = jax.device_put(w, ctx.batch_sharding())
        uf = jax.device_put(jnp.asarray(factors.user_features), ctx.replicated)
        vf = jax.device_put(jnp.asarray(factors.item_features), ctx.replicated)
        sq, cnt = _rmse_terms(uf, vf, u, i, r, w, nc=nc)
        return float(np.sqrt(sq / cnt))


# ---------------------------------------------------------------------------
# Serving-side kernels
# ---------------------------------------------------------------------------

#: Catalogs larger than this route through the chunked MIPS scan
#: (ops/topk.chunked_topk_scores) instead of one dense [b, n_items] score
#: matrix — peak serving memory stays O(chunk), not O(n_items). Every
#: template's predict inherits the dispatch through these two functions.
CHUNKED_TOPK_THRESHOLD = 32768
CHUNKED_TOPK_CHUNK = 8192


@device_obs.profiled_program(
    "topk_dense",
    # the serving hot program: buckets are the pow2-padded batch ladder
    # times catalog shape and k — exactly the expected-compile set the
    # tier-1 retrace guard (tests/test_retrace_guard.py) pins. A new
    # signature INSIDE a bucket (dtype drift, mask flapping per shape)
    # is the per-request-retrace regression the guard exists to catch.
    bucket=lambda q, items, k, exclude_mask=None: (
        tuple(q.shape), tuple(items.shape), k, exclude_mask is not None),
)
@partial(jax.jit, static_argnames=("k",))
def _top_k_dense(query_vecs, item_features, k: int, exclude_mask=None):
    scores = query_vecs @ item_features.T  # [b, n_items]
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)



def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


#: Per-tick serving result buffers ([b, k] scores + indices) — tiny, but
#: registered so a failed dispatch/finalize is leak-CHECKABLE: the
#: resilience tests assert this arena is empty after injected failures.
_TICK_ARENA = device_obs.arena("serving_ticks")


@device_obs.profiled_program(
    "serving_fused_topk",
    # the device-resident serving hot program: ONE dispatch per drained
    # micro-batcher tick. Expected compile axes: the pow2-padded batch
    # ladder (uidx shape), the resident factor/catalog shapes, k, and the
    # mask/no-mask branch split — the tier-1 retrace guard drives exactly
    # this set and pins one compile per bucket under concurrent load.
    # ``k`` and ``chunk`` are static PROGRAM axes the abstract signature
    # cannot see — they must ride the bucket key or their recompiles
    # would read as retraces (profiled_program docstring contract)
    bucket=lambda user_f, item_f, uidx, k, exclude_mask=None, chunk=None: (
        tuple(user_f.shape), tuple(item_f.shape), tuple(uidx.shape), k,
        exclude_mask is not None, chunk),
)
@partial(jax.jit, static_argnames=("k", "chunk"))
def _serving_fused_topk(user_f, item_f, uidx, k, exclude_mask=None,
                        chunk=None):
    from predictionio_tpu.ops.topk import fused_gather_topk

    return fused_gather_topk(user_f, item_f, uidx, k=k, chunk=chunk,
                             exclude_mask=exclude_mask)


@device_obs.profiled_program(
    "sharded_topk",
    # the sharded serving hot program: one dispatch per drained tick
    # against a mesh-sharded catalog. Expected compile axes: the pow2
    # batch ladder, the sharded catalog shape AND its shard count (a
    # re-shard is a new layout = a new program), k, mask branch — the
    # retrace guard drives this ladder and pins one compile per bucket
    # across fresh value-equal meshes.
    bucket=lambda user_f, catalog, uidx, k, exclude_mask=None: (
        tuple(user_f.shape), tuple(catalog.items.shape),
        int(catalog.mesh.shape[catalog.axis]), tuple(uidx.shape), k,
        exclude_mask is not None),
)
def _serving_sharded_topk(user_f, catalog, uidx, k, exclude_mask=None):
    from predictionio_tpu.obs import shards as shard_obs
    from predictionio_tpu.ops.topk import sharded_fused_topk

    # shard observatory: one serving tick = one dispatch; the candidate
    # all-gather's trace-time bytes replay per tick (obs/shards.py)
    shard_obs.OBSERVATORY.program_meta(
        "sharded_topk", shards=int(catalog.mesh.shape[catalog.axis]),
        steps_per_dispatch=1)
    return sharded_fused_topk(user_f, catalog, uidx, k=k,
                              chunk=CHUNKED_TOPK_CHUNK,
                              exclude_mask=exclude_mask)


def serving_tick_on_device(n_queries: int, n_items: int, rank: int) -> bool:
    """Cheap pre-gate for ``batch_predict_deferred`` implementations:
    would a tick of this shape route to the device? Decided WITHOUT the
    mask-upload term, which only ever makes the accelerator look worse —
    so a False here is final (skip the per-query host prep entirely and
    fall back), while a True still gets the exact decision, mask bytes
    included, inside :func:`serve_top_k_batched`."""
    bp = _pow2(max(n_queries, 1))
    return serving_device(2.0 * bp * n_items * rank, bp * 4,
                          overlapped=True) is None


def pin_serving_factors(user_features, item_features,
                        max_batch: int = 64) -> int:
    """Deploy-time HBM promotion of an engine's factor matrices.

    Puts both factor matrices device-resident through the identity cache
    (``serving_models`` arena) so the first real serving tick finds them
    pinned instead of paying the catalog upload inline. The decision uses
    the batched-amortization placement model at a representative full
    tick (``max_batch`` queries): when even an amortized tick belongs on
    the host (``PIO_SERVING_DEVICE=cpu``, dead accelerator link), nothing
    is pinned and 0 is returned — the host route holds. Returns the
    pinned byte count."""
    if not (isinstance(user_features, np.ndarray)
            and isinstance(item_features, np.ndarray)):
        return 0
    n_items, rank = item_features.shape
    bp = _pow2(max_batch)
    place = serving_device(2.0 * bp * n_items * rank, bp * 4,
                           overlapped=True)
    if place is not None:
        return 0
    _as_device(user_features, tag="serve")
    _as_device(item_features)
    return int(user_features.nbytes) + int(item_features.nbytes)


def serve_top_k_batched(user_features, item_features, uidx, k,
                        exclude_mask=None):
    """One FUSED device dispatch for a drained serving tick, or None.

    ``uidx`` [b] are the tick's query rows into ``user_features``; the
    factor gather, the (chunked) MIPS against the resident catalog, the
    per-row ``exclude_mask`` [b, n_items] (seen items, blacklists,
    category filters) and the top-k all run in ONE jitted program against
    the HBM-pinned matrices — the host ships only the int32 row ids and
    the masks. The batch pads to the pow2 ladder and k to pow2, so the
    micro-batcher's varying drain sizes reuse a handful of compiled
    programs (the post-deploy warmup compiles exactly these).

    Returns None when the tick belongs on the host (the batched-
    amortization placement decision picked the CPU backend, the catalog
    is mesh-sharded, or the factors aren't plain host arrays) — the
    caller then falls back to the legacy :func:`top_k_scores` route.
    Otherwise returns a zero-arg ``finalize`` whose blocking readback the
    caller may defer: the dispatch AND its async d2h copies
    (io/transfer.begin_readback) are already in flight when this function
    returns, so calling ``finalize()`` from a separate thread overlaps
    tick N's readback with tick N+1's dispatch. ``finalize()`` returns
    (scores [b, k], indices [b, k]) as host numpy."""
    from predictionio_tpu.ops.topk import ShardedCatalog

    if isinstance(item_features, ShardedCatalog):
        return _serve_sharded_tick(user_features, item_features, uidx, k,
                                   exclude_mask)
    if not (isinstance(user_features, np.ndarray)
            and isinstance(item_features, np.ndarray)):
        return None
    uidx = np.asarray(uidx, np.int32)
    b = int(uidx.shape[0])
    if b == 0:
        return None
    n_items, rank = item_features.shape
    k = min(k, n_items)
    if k <= 0:
        # e.g. query num=0: nothing to dispatch — fall back to the legacy
        # route (which answers empty) rather than minting a no-op
        # "device" tick that would skew the route counters even under
        # PIO_SERVING_DEVICE=cpu
        return None
    bp = _pow2(b)
    upload = bp * 4  # the padded uidx row ids
    if exclude_mask is not None:
        exclude_mask = np.asarray(exclude_mask, bool)
        upload += bp * n_items  # per-row bool masks ship per tick
    place = serving_device(2.0 * bp * n_items * rank, upload,
                           overlapped=True)
    if place is not None:
        return None  # host route: legacy per-tick host math wins
    uf = _as_device(user_features, tag="serve")
    items = _as_device(item_features)
    kp = min(_pow2(k), n_items)
    if bp != b:
        # padding rows repeat the last real query's row: always a valid
        # gather index, and their results are sliced off at finalize
        uidx = np.concatenate([uidx, np.full(bp - b, uidx[-1], np.int32)])
        if exclude_mask is not None:
            exclude_mask = np.concatenate(
                [exclude_mask, np.zeros((bp - b, n_items), bool)])
    chunk = CHUNKED_TOPK_CHUNK if n_items > CHUNKED_TOPK_THRESHOLD else None
    from predictionio_tpu.resilience import faults

    # the chaos suite's device-dispatch site: an injected error here is
    # indistinguishable from the fused program failing to launch, which
    # is exactly what the device-route breaker must absorb; corrupt-shape
    # truncates the tick's row ids, so the readback comes up short and
    # the finalize-failure heal path fires instead
    uidx = faults.fault_point("serving.dispatch", uidx)
    scores, idx = _serving_fused_topk(uf, items, uidx, kp, exclude_mask,
                                      chunk)
    from predictionio_tpu.io import transfer

    resolve = transfer.begin_readback((scores, idx), name="serving")
    # the tick's result buffers are the only per-tick HBM this route
    # allocates; registering them makes "a failed tick leaked nothing"
    # an assertable invariant (freed in finalize's finally — failure
    # paths included, since the buffers die with the dropped resolver)
    alloc = _TICK_ARENA.register((scores, idx), label=f"b{bp}")

    def finalize():
        try:
            s, i = resolve()
        finally:
            _TICK_ARENA.free(alloc)
        return s[:b, :k], i[:b, :k]

    return finalize


def _serve_sharded_tick(user_features, catalog, uidx, k, exclude_mask=None):
    """The sharded-catalog arm of :func:`serve_top_k_batched`: the same
    deferred-readback tick protocol, dispatched as the fused shard_map
    MIPS (``sharded_topk`` program). No host-vs-device placement decision
    applies — the catalog's mesh IS the placement, and a catalog bigger
    than one HBM has no host copy to fall back to. The host ships the
    padded int32 row ids plus the column-sharded masks; the per-shard
    working set is the local catalog slice + O(b · k) candidate lists."""
    if not isinstance(user_features, np.ndarray):
        return None
    uidx = np.asarray(uidx, np.int32)
    b = int(uidx.shape[0])
    if b == 0:
        return None
    n_items = catalog.n
    k = min(k, n_items)
    if k <= 0:
        return None  # same no-op-tick rule as the dense arm
    mesh = catalog.mesh
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    bp = _pow2(b)
    kp = min(_pow2(k), n_items)
    if bp != b:
        # padding rows repeat the last real query's row (always a valid
        # gather index); their results are sliced off at finalize
        uidx = np.concatenate([uidx, np.full(bp - b, uidx[-1], np.int32)])
    padded_n = catalog.items.shape[0]
    em = None
    if exclude_mask is not None:
        em = np.asarray(exclude_mask, bool)
        if em.shape[0] == 1 and bp != 1:  # broadcast masks materialize
            em = np.broadcast_to(em, (b, em.shape[1]))
        if em.shape[0] != bp:  # padding rows exclude nothing
            em = np.concatenate(
                [em, np.zeros((bp - em.shape[0], em.shape[1]), bool)])
        if em.shape[1] != padded_n:  # catalog pad rows are masked inside
            em = np.concatenate(
                [em, np.zeros((bp, padded_n - em.shape[1]), bool)], axis=1)
        em = jax.device_put(
            em, NamedSharding(mesh, PSpec(None, catalog.axis)))
    # the replicated user-factor pin rides the identity cache exactly
    # like the dense arm's HBM promotion — one put per deploy, not per
    # tick (the NamedSharding keys the cache entry to this mesh)
    uf = _as_device(user_features, tag="serve_sharded",
                    device=NamedSharding(mesh, PSpec()))
    from predictionio_tpu.resilience import faults

    # same chaos site as the dense arm: an injected error here is a
    # failed launch for the device-route breaker; corrupt-shape truncates
    # the row ids so the finalize-failure heal path fires
    uidx = faults.fault_point("serving.dispatch", uidx)
    uidx_d = jax.device_put(np.asarray(uidx, np.int32),
                            NamedSharding(mesh, PSpec()))
    scores, idx = _serving_sharded_topk(uf, catalog, uidx_d, kp, em)
    from predictionio_tpu.io import transfer

    resolve = transfer.begin_readback((scores, idx), name="serving")
    alloc = _TICK_ARENA.register(
        (scores, idx),
        label=f"b{bp}s{int(mesh.shape[catalog.axis])}")

    def finalize():
        try:
            s, i = resolve()
        finally:
            _TICK_ARENA.free(alloc)
        return s[:b, :k], i[:b, :k]

    return finalize


def top_k_scores(query_vecs, item_features, k: int, exclude_mask=None):
    """Batched recommend: scores = q @ Yᵀ (one MXU matmul) + lax.top_k.
    ``exclude_mask`` [b, n_items] True → drop (seen items, blacklists — the
    serve-time filters of the ecommerce template). Catalogs above
    ``CHUNKED_TOPK_THRESHOLD`` rows stream through the chunked MIPS kernel.

    The catalog matrix is device-cached across calls, batch/k are padded
    to powers of two so the micro-batcher's varying batch sizes hit a
    handful of compiled programs instead of one per size, and the results
    come back as host numpy in one readback.

    Placement: host-numpy queries go through latency-aware serving
    placement (parallel/placement.py) — the call runs on the CPU backend
    when the score matmul is too small to out-pay the accelerator's
    measured link RTT. Device-resident queries (e.g. a tower forward that
    already ran on the accelerator) keep their device.

    Catalogs beyond one chip's HBM arrive as an ops.topk.ShardedCatalog
    (mesh-row-sharded, see shard_catalog); those route through the
    shard_map MIPS with a cross-device candidate merge — placement logic
    does not apply (the catalog's mesh IS the placement)."""
    from predictionio_tpu.ops.topk import ShardedCatalog

    if isinstance(item_features, ShardedCatalog):
        from predictionio_tpu.ops.topk import sharded_topk_scores

        kk = min(k, item_features.n)
        b = int(np.shape(query_vecs)[0])
        if kk <= 0:
            return np.zeros((b, 0), np.float32), np.zeros((b, 0), np.int32)
        # pow2-pad batch and k like the dense path: the micro-batcher's
        # varying drain sizes must reuse a handful of compiled shard_map
        # programs, not one per size
        bp = _pow2(b)
        kp = min(_pow2(kk), item_features.n)
        if bp != b:
            query_vecs = np.concatenate(
                [np.asarray(query_vecs),
                 np.zeros((bp - b,) + np.shape(query_vecs)[1:],
                          np.asarray(query_vecs).dtype)])
            if exclude_mask is not None and np.shape(exclude_mask)[0] == b:
                em = np.asarray(exclude_mask)
                exclude_mask = np.concatenate(
                    [em, np.zeros((bp - b,) + em.shape[1:], em.dtype)])
        scores, idx = sharded_topk_scores(
            query_vecs, item_features, k=kp,
            chunk=CHUNKED_TOPK_CHUNK, exclude_mask=exclude_mask)
        scores, idx = jax.device_get((scores[:b, :kk], idx[:b, :kk]))
        return scores, idx
    n_items = int(np.shape(item_features)[0])
    rank = int(np.shape(item_features)[1])
    b = int(np.shape(query_vecs)[0])
    host_q = isinstance(query_vecs, np.ndarray)
    if host_q:
        up = _pow2(b) * rank * query_vecs.dtype.itemsize
        if isinstance(exclude_mask, np.ndarray):
            up += exclude_mask.nbytes
        place = serving_device(2.0 * _pow2(b) * n_items * rank, up)
    else:
        place = None
    items = _as_device(item_features, device=place)
    k = min(k, items.shape[0])
    if k <= 0:  # e.g. query num=0 — an empty result, not one item
        return (
            np.zeros((b, 0), np.float32), np.zeros((b, 0), np.int32)
        )
    bp = _pow2(b)
    kp = min(_pow2(k), items.shape[0])
    if bp != b and host_q:
        # pad host-side so q ships to the serving device in one put
        query_vecs = np.concatenate(
            [query_vecs,
             np.zeros((bp - b,) + query_vecs.shape[1:], query_vecs.dtype)]
        )
    if place is not None:
        q = jax.device_put(query_vecs, place)
        if exclude_mask is not None and not isinstance(exclude_mask, np.ndarray):
            # a device-resident mask must follow the serving device so one
            # call never mixes committed devices
            exclude_mask = jax.device_put(exclude_mask, place)
    else:
        q = jnp.asarray(query_vecs)
    if bp != b:
        if not host_q:
            q = jnp.concatenate(
                [q, jnp.zeros((bp - b,) + q.shape[1:], q.dtype)]
            )
        if exclude_mask is not None and np.shape(exclude_mask)[0] == b:
            # [1, n_items] broadcast masks need no padding. Per-row host
            # masks pad host-side (keeps them placement-neutral: the jit
            # call ships them to whichever device the query committed to);
            # device-resident masks (already moved to the serving device
            # above) pad on device — no host round trip.
            if isinstance(exclude_mask, np.ndarray):
                exclude_mask = np.concatenate(
                    [exclude_mask,
                     np.zeros((bp - b,) + exclude_mask.shape[1:],
                              exclude_mask.dtype)]
                )
            else:
                em = jnp.asarray(exclude_mask)
                exclude_mask = jnp.concatenate(
                    [em, jnp.zeros((bp - b,) + em.shape[1:], em.dtype)]
                )
    if items.shape[0] > CHUNKED_TOPK_THRESHOLD:
        from predictionio_tpu.ops.topk import chunked_topk_scores

        scores, idx = chunked_topk_scores(
            q, items, k=kp, chunk=CHUNKED_TOPK_CHUNK,
            exclude_mask=exclude_mask,
        )
    else:
        scores, idx = _top_k_dense(q, items, kp, exclude_mask)
    # ONE readback for the whole batch: per-row np.asarray() in callers
    # would pay a host-link round trip per query
    scores, idx = jax.device_get((scores[:b, :k], idx[:b, :k]))
    return scores, idx


# ---------------------------------------------------------------------------
# Batched sweep metric kernels (candidate axis)
# ---------------------------------------------------------------------------


@device_obs.profiled_program(
    "sweep_topk",
    bucket=lambda user_stack, item_stack, uidx, *a, k=None, **kw: (
        tuple(user_stack.shape), tuple(item_stack.shape),
        tuple(uidx.shape), k),
)
@partial(jax.jit, static_argnames=("k",))
def batched_topk_hit_counts(user_stack, item_stack, uidx, target, kq,
                            hit_mask, k: int):
    """Held-out top-k hit counts for EVERY sweep candidate in one dispatch.

    ``user_stack`` [C, n_users, r] / ``item_stack`` [C, n_items, r] are the
    stacked per-candidate factors; ``uidx`` [Q] the queries' user rows,
    ``target`` [Q] each query's held-out item (−1 = unseen in training:
    can never match a catalog index), ``kq`` [Q] the per-query cutoff
    (min(query.num, metric k)), ``hit_mask`` [Q] whether a hit may count
    (False for threshold-excluded actuals and unknown users — the latter
    still enter the metric denominator host-side, scoring 0, exactly like
    the sequential empty-prediction path). Returns [C] float hit counts —
    the only readback a sweep's scoring needs, replacing Q×C Python
    ``calculate_qpa`` calls. Catalogs above the serving chunk threshold
    stream through the same chunked MIPS scan the predict path uses."""
    from predictionio_tpu.ops.topk import chunked_topk_scores

    n_items = item_stack.shape[1]
    in_cut = jnp.arange(k, dtype=jnp.int32)[None, :] < kq[:, None]

    def per_cand(uf, itf):
        q = uf[uidx]  # [Q, r]
        if n_items > CHUNKED_TOPK_THRESHOLD:
            _s, idx = chunked_topk_scores(
                q, itf, k=k, chunk=CHUNKED_TOPK_CHUNK)
        else:
            _s, idx = jax.lax.top_k(q @ itf.T, k)
        hit = (idx == target[:, None]) & in_cut
        return (hit.any(axis=1) & hit_mask).sum().astype(jnp.float32)

    return jax.vmap(per_cand)(user_stack, item_stack)


@jax.jit
def batched_rmse(user_stack, item_stack, u_idx, i_idx, ratings):
    """Held-out RMSE for every sweep candidate in one dispatch:
    [C] root-mean-square error of ``dot(u, i)`` predictions against the
    held-out ratings — the candidate-axis twin of :meth:`ALS.rmse`.
    An empty held-out set scores NaN (the sweep's empty-scores
    convention: compare_key orders NaN last), never a perfect 0.0."""

    def per_cand(uf, itf):
        pred = jnp.einsum("nr,nr->n", uf[u_idx], itf[i_idx])
        return ((pred - ratings) ** 2).sum()

    sq = jax.vmap(per_cand)(user_stack, item_stack)
    n = ratings.shape[0]
    if n == 0:  # static shape: decided at trace time
        return jnp.full(sq.shape, jnp.nan, sq.dtype)
    return jnp.sqrt(sq / n)


@partial(jax.jit)
def _l2_normalize(x):
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-9)


def top_k_cosine(query_vecs, item_features, k: int, exclude_mask=None):
    """Item-to-item cosine similarity (similarproduct template's scoring,
    ref: examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala).
    Normalizing both sides reduces cosine to inner product, so large
    catalogs share the chunked MIPS dispatch of :func:`top_k_scores`
    (including its latency-aware placement: host queries normalize
    host-side so they stay numpy through the placement decision)."""
    def _host_l2(a):
        a = np.asarray(a, np.float32)
        return a / (np.linalg.norm(a, axis=-1, keepdims=True) + 1e-9)

    if isinstance(query_vecs, np.ndarray):
        q = _host_l2(query_vecs)
    else:
        q = _l2_normalize(query_vecs)
    if isinstance(item_features, np.ndarray):
        items = host_cache_transform(item_features, "l2", _host_l2)
    else:
        items = _as_device(item_features, tag="l2", transform=_l2_normalize)
    return top_k_scores(q, items, k, exclude_mask)
