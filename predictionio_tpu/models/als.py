"""Alternating Least Squares matrix factorization on TPU.

Replaces MLlib's ``ALS.train`` / ``ALS.trainImplicit`` (used by the
reference's recommendation templates, e.g.
examples/scala-parallel-recommendation/custom-serving/src/main/scala/
ALSAlgorithm.scala:27-67) with an XLA-native design in the style of ALX
(arxiv 2112.02194, PAPERS.md):

- Ratings are preprocessed host-side into **degree-bucketed dense tiles**:
  entities are grouped by neighbor count and each bucket is padded to a
  fixed width, so every device step is a large static-shape batched einsum +
  Cholesky solve on the MXU — no sparse scatter/gather loops, no dynamic
  shapes.
- Each half-iteration solves all entities of one side: gather the *fixed*
  side's factors (replicated in HBM), form per-entity normal equations
  ``(Yᵀ C Y + λ n I) x = Yᵀ C r``, batched ``cho_solve``, and scatter rows
  back — the row batch is sharded over the mesh ``data`` axis, so the
  scatter into the replicated factor matrix compiles to an ICI all-gather,
  which is exactly the factor exchange MLlib implements as a block shuffle.
- Implicit feedback uses the Hu-Koren trick: the dense ``YᵀY`` Gram term is
  one small replicated matmul per half-step; observed entries contribute
  only the ``(c-1) y yᵀ`` correction.

Regularization matches MLlib 1.3's ALS-WR weighting: λ is scaled by each
entity's rating count.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import ComputeContext

logger = logging.getLogger(__name__)

#: Replicating the packed rating blobs costs n_devices × blob bytes of HBM;
#: above this size, ALS.train switches to per-bucket sharded transfers.
_PACK_REPLICATE_MAX_BYTES = 128 * 1024 * 1024


@dataclass(frozen=True)
class ALSParams:
    """Hyperparameters (ref template engine.json defaults: rank 10,
    numIterations 20, lambda 0.01, seed)."""

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    implicit_prefs: bool = False
    alpha: float = 1.0  # implicit confidence weight (MLlib default 1.0)
    seed: int | None = None
    max_degree: int = 4096  # per-entity neighbor cap (oversized rows truncate)
    bucket_widths: tuple[int, ...] = (16, 64, 256, 1024, 4096)
    #: Multi-chip transfer strategy cutover (see ALS.train): packed blobs up
    #: to this size are replicated (one transfer, n_devices × HBM copies);
    #: larger jobs transfer per-bucket with the batch sharding so each
    #: device holds 1/n of the rating data.
    pack_replicate_max_bytes: int = _PACK_REPLICATE_MAX_BYTES
    #: HBM bound on a bucket solve's gathered-factor tensor ([rows, k, rank]
    #: elements). Buckets above it solve in sequential row chunks via
    #: ``lax.map`` so the gather temp is O(chunk), not O(bucket) — at
    #: ML-20M rank 64 the unchunked gather alone is >12 GB, past a v5e chip.
    max_solve_elems: int = 1 << 28


@dataclass
class ALSFactors:
    user_features: np.ndarray  # [n_users, rank] float32
    item_features: np.ndarray  # [n_items, rank] float32


@dataclass
class _Bucket:
    """One degree bucket of the bipartite graph, padded to static shape.
    ``rows`` indexes the entity side being solved; ``cols`` the fixed side."""

    rows: np.ndarray  # [n] int32 entity indices (padded with 0, weight 0)
    cols: np.ndarray  # [n, k] int32 neighbor indices (padded 0)
    ratings: np.ndarray  # [n, k] float32
    weights: np.ndarray  # [n, k] float32, 1.0 valid / 0.0 padding
    row_valid: np.ndarray  # [n] float32, 1.0 for real rows
    nc: int = 1  # solve in this many sequential row chunks (see max_solve_elems)


def _chunk_plan(
    n_real: int, width: int, rank: int, max_elems: int, unit: int
) -> tuple[int, int]:
    """(n_padded, nc): pad ``n_real`` rows to ``nc`` equal chunks of ``c``
    rows, ``c`` a multiple of the data-axis size ``unit``, such that one
    chunk's gathered-factor tensor ``c*width*rank`` fits ``max_elems``
    (bottoming out at one row-block per device)."""
    nc = 1
    while True:
        c = ((n_real + nc * unit - 1) // (nc * unit)) * unit
        if c * width * max(rank, 1) <= max_elems or c == unit:
            return nc * c, nc
        nc *= 2


def _bucketize(
    ctx: ComputeContext,
    entity_idx: np.ndarray,
    neighbor_idx: np.ndarray,
    ratings: np.ndarray,
    n_entities: int,
    params: ALSParams,
) -> list[_Bucket]:
    """Group entities by degree into padded dense tiles (ALX §3.2-style
    density bucketing). Host-side, one-time per training run."""
    order = np.argsort(entity_idx, kind="stable")
    entity_sorted = entity_idx[order]
    neighbor_sorted = neighbor_idx[order]
    ratings_sorted = ratings[order]
    uniq, starts, counts = np.unique(
        entity_sorted, return_index=True, return_counts=True
    )
    widths = [w for w in params.bucket_widths if w <= params.max_degree]
    if not widths or widths[-1] < params.max_degree:
        widths.append(params.max_degree)
    buckets: list[_Bucket] = []
    for bi, width in enumerate(widths):
        lo = widths[bi - 1] if bi > 0 else 0
        if bi == len(widths) - 1:
            sel = counts > lo  # oversized degrees land here, truncated
        else:
            sel = (counts > lo) & (counts <= width)
        if not sel.any():
            continue
        b_entities = uniq[sel]
        b_starts = starts[sel]
        b_counts = np.minimum(counts[sel], width)
        n, nc = _chunk_plan(
            len(b_entities), width, params.rank, params.max_solve_elems,
            ctx.n_devices,
        )
        cols = np.zeros((n, width), dtype=np.int32)
        rates = np.zeros((n, width), dtype=np.float32)
        weights = np.zeros((n, width), dtype=np.float32)
        rows = np.zeros(n, dtype=np.int32)
        row_valid = np.zeros(n, dtype=np.float32)
        rows[: len(b_entities)] = b_entities
        # padding rows must alias an entity already being solved in this
        # bucket: the scatter clears target[rows], so pointing padding at an
        # out-of-bucket entity (e.g. index 0) would wipe its factors
        rows[len(b_entities):] = b_entities[0]
        row_valid[: len(b_entities)] = 1.0
        for j, (s, c) in enumerate(zip(b_starts, b_counts)):
            cols[j, :c] = neighbor_sorted[s : s + c]
            rates[j, :c] = ratings_sorted[s : s + c]
            weights[j, :c] = 1.0
        buckets.append(_Bucket(rows, cols, rates, weights, row_valid, nc))
    return buckets


def _chunk_solutions(
    fixed,  # [n_other, rank] fixed-side factors (replicated)
    cols,  # [c, k] int32
    ratings,  # [c, k] f32
    weights,  # [c, k] f32
    yty,  # [rank, rank] — YᵀY for implicit, zeros for explicit
    lambda_: float,
    alpha: float,
    implicit: bool,
    rank: int,
):
    """Normal-equation solutions for one row chunk of a bucket."""
    y = fixed[cols]  # [c, k, r] gather, local (fixed is replicated)
    n_ratings = weights.sum(axis=1)  # [c]
    if implicit:
        conf_minus1 = alpha * ratings * weights  # (c-1), only observed
        gram = yty[None, :, :] + jnp.einsum(
            "nk,nkr,nks->nrs", conf_minus1, y, y, optimize=True
        )
        rhs = jnp.einsum("nk,nkr->nr", (1.0 + conf_minus1) * weights, y)
    else:
        gram = jnp.einsum("nk,nkr,nks->nrs", weights, y, y, optimize=True)
        rhs = jnp.einsum("nk,nkr->nr", ratings * weights, y)
    # ALS-WR: λ scaled by per-entity rating count; +ε keeps padded rows SPD
    reg = lambda_ * jnp.maximum(n_ratings, 1.0) + 1e-8
    gram = gram + reg[:, None, None] * jnp.eye(rank, dtype=gram.dtype)
    return jax.scipy.linalg.cho_solve(
        (jnp.linalg.cholesky(gram), True), rhs[..., None]
    )[..., 0]


def _solve_bucket(
    target,  # [n_entities, rank] factor matrix being updated (replicated)
    fixed,  # [n_other, rank] fixed-side factors (replicated)
    rows,  # [n] int32
    cols,  # [n, k] int32
    ratings,  # [n, k] f32
    weights,  # [n, k] f32
    row_valid,  # [n] f32
    yty,  # [rank, rank] — YᵀY for implicit, zeros for explicit
    lambda_: float,
    alpha: float,
    implicit: bool,
    rank: int,
    nc: int = 1,
    shard=None,
):
    """One bucket's batched normal-equation solve. ``rows/cols/...`` are
    sharded over the mesh ``data`` axis; ``target``/``fixed`` replicated, so
    the row scatter at the end compiles to an ICI all-gather. Buckets whose
    gather temp would exceed ALSParams.max_solve_elems arrive with ``nc>1``
    and solve in sequential ``lax.map`` row chunks so HBM stays bounded.
    Traced inside :func:`_als_iteration` — not jitted on its own."""
    if nc > 1:
        n = rows.shape[0]
        c = n // nc
        xs = tuple(
            x.reshape((nc, c) + x.shape[1:]) for x in (cols, ratings, weights)
        )
        if shard is not None:
            cs = NamedSharding(shard.mesh, P(None, *shard.spec))
            xs = tuple(jax.lax.with_sharding_constraint(x, cs) for x in xs)
        sol = jax.lax.map(
            lambda t: _chunk_solutions(
                fixed, *t, yty, lambda_, alpha, implicit, rank
            ),
            xs,
        ).reshape(n, rank)
    else:
        sol = _chunk_solutions(
            fixed, cols, ratings, weights, yty, lambda_, alpha, implicit, rank
        )
    sol = sol * row_valid[:, None]  # padded rows contribute nothing
    # scatter solved rows; padding rows alias an in-bucket entity and are
    # masked to zero, so add-after-clear keeps every row correct
    cleared = target.at[rows].multiply(0.0)
    return cleared.at[rows].add(sol)


def _gram(fixed):
    return fixed.T @ fixed


@partial(jax.jit, static_argnames=("n", "rank"))
def _init_factors(key, n: int, rank: int):
    """MLlib-style init: small random factors scaled by 1/sqrt(rank).
    Jitted so the factors are BORN on device — a host round trip per factor
    matrix costs ~250ms through a tunneled TPU."""
    return jax.random.normal(key, (n, rank), jnp.float32) / jnp.sqrt(
        jnp.asarray(rank, jnp.float32)
    )


def _pack_buckets(buckets: list[_Bucket]) -> tuple[np.ndarray, np.ndarray, tuple]:
    """Flatten a side's buckets into ONE int32 and ONE float32 host array.

    Host→device transfer latency (not bandwidth) dominates small training
    jobs — 5 arrays × buckets × 2 sides is dozens of round trips; packing
    makes it two. Shapes are returned as a static tuple so the on-device
    unpack in :func:`_als_iteration` is plain static slicing."""
    if not buckets:  # a side with no ratings solves nothing
        return (
            np.empty(0, dtype=np.int32), np.empty(0, dtype=np.float32), ()
        )
    ints = np.concatenate(
        [np.concatenate([b.rows, b.cols.ravel()]) for b in buckets]
    ).astype(np.int32)
    floats = np.concatenate(
        [
            np.concatenate([b.ratings.ravel(), b.weights.ravel(), b.row_valid])
            for b in buckets
        ]
    ).astype(np.float32)
    shapes = tuple((len(b.rows), b.cols.shape[1], b.nc) for b in buckets)
    return ints, floats, shapes


def _unpack_buckets(ints, floats, shapes, shard):
    """Static-offset slicing of the packed arrays back into bucket tensors,
    resharding each onto the mesh ``data`` axis (ICI, cheap) so the solves
    run with the same layout as individually-transferred buckets."""
    out = []
    oi = of = 0
    for n, k, _nc in shapes:
        rows = ints[oi : oi + n]
        cols = ints[oi + n : oi + n + n * k].reshape(n, k)
        oi += n + n * k
        ratings = floats[of : of + n * k].reshape(n, k)
        weights = floats[of + n * k : of + 2 * n * k].reshape(n, k)
        row_valid = floats[of + 2 * n * k : of + 2 * n * k + n]
        of += 2 * n * k + n
        b = (rows, cols, ratings, weights, row_valid)
        if shard is not None:
            b = tuple(jax.lax.with_sharding_constraint(x, shard) for x in b)
        out.append(b)
    return out


def _packed_len(shapes: tuple) -> tuple[int, int]:
    """(int32 length, float32 length) of one side's packed blob."""
    ints = sum(n + n * k for n, k, _nc in shapes)
    floats = sum(2 * n * k + n for n, k, _nc in shapes)
    return ints, floats


@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "user_shapes", "item_shapes", "shard"),
    donate_argnums=(0, 1),
)
def _als_iteration(
    user_f,
    item_f,
    ints,  # both sides' packed int32 blob (user first)
    floats,  # both sides' packed float32 blob (user first)
    lambda_: float,
    alpha: float,
    *,
    implicit: bool,
    rank: int,
    user_shapes: tuple,
    item_shapes: tuple,
    shard=None,
):
    """One full ALS iteration — both half-solves over every degree bucket —
    as a single XLA program. Fusing the whole iteration removes per-bucket
    dispatch overhead (the dominant cost at small problem sizes) and lets
    XLA overlap the bucket solves' gathers/scatters."""
    ui_len, uf_len = _packed_len(user_shapes)
    user_buckets = _unpack_buckets(
        ints[:ui_len], floats[:uf_len], user_shapes, shard
    )
    item_buckets = _unpack_buckets(
        ints[ui_len:], floats[uf_len:], item_shapes, shard
    )
    return _iteration_body(
        user_f, item_f, user_buckets, item_buckets,
        tuple(s[2] for s in user_shapes), tuple(s[2] for s in item_shapes),
        lambda_, alpha, implicit, rank, shard,
    )


@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "user_nc", "item_nc", "shard"),
    donate_argnums=(0, 1),
)
def _als_iteration_sharded(
    user_f,
    item_f,
    user_buckets,  # pytree of per-bucket tuples, already sharded on device
    item_buckets,
    lambda_: float,
    alpha: float,
    *,
    implicit: bool,
    rank: int,
    user_nc: tuple = (),
    item_nc: tuple = (),
    shard=None,
):
    """Large-job variant: buckets were transferred individually with the
    batch sharding, so each device holds 1/n of the rating data for the whole
    run (no replication of the blobs — see ALS.train's size cutover)."""
    user_nc = user_nc or (1,) * len(user_buckets)
    item_nc = item_nc or (1,) * len(item_buckets)
    return _iteration_body(
        user_f, item_f, user_buckets, item_buckets, user_nc, item_nc,
        lambda_, alpha, implicit, rank, shard,
    )


def _iteration_body(
    user_f, item_f, user_buckets, item_buckets, user_nc, item_nc,
    lambda_, alpha, implicit, rank, shard=None,
):
    zeros_gram = jnp.zeros((rank, rank), user_f.dtype)
    yty = _gram(item_f) if implicit else zeros_gram
    for b, nc in zip(user_buckets, user_nc):
        user_f = _solve_bucket(
            user_f, item_f, *b, yty, lambda_, alpha, implicit, rank, nc, shard
        )
    xtx = _gram(user_f) if implicit else zeros_gram
    for b, nc in zip(item_buckets, item_nc):
        item_f = _solve_bucket(
            item_f, user_f, *b, xtx, lambda_, alpha, implicit, rank, nc, shard
        )
    return user_f, item_f


@jax.jit
def _rmse_terms(user_f, item_f, u_idx, i_idx, rating, weight):
    pred = jnp.einsum("nr,nr->n", user_f[u_idx], item_f[i_idx])
    err = (pred - rating) ** 2 * weight
    return err.sum(), weight.sum()


class ALS:
    """Training driver. Usage::

        als = ALS(ctx, params)
        factors = als.train(user_idx, item_idx, ratings, n_users, n_items)
    """

    def __init__(self, ctx: ComputeContext, params: ALSParams):
        self.ctx = ctx
        self.params = params

    def train(
        self,
        user_idx: np.ndarray,
        item_idx: np.ndarray,
        ratings: np.ndarray,
        n_users: int,
        n_items: int,
        callback=None,
    ) -> ALSFactors:
        p = self.params
        ctx = self.ctx
        user_idx = np.asarray(user_idx, dtype=np.int32)
        item_idx = np.asarray(item_idx, dtype=np.int32)
        ratings = np.asarray(ratings, dtype=np.float32)
        if user_idx.size == 0:
            raise ValueError("ALS.train called with zero ratings")

        user_buckets = _bucketize(ctx, user_idx, item_idx, ratings, n_users, p)
        item_buckets = _bucketize(ctx, item_idx, user_idx, ratings, n_items, p)
        logger.info(
            "ALS: %d ratings, %d users (%d buckets), %d items (%d buckets), rank %d",
            ratings.size, n_users, len(user_buckets), n_items, len(item_buckets),
            p.rank,
        )

        multi = ctx.mesh.devices.size > 1
        key = jax.random.PRNGKey(p.seed if p.seed is not None else 0)
        ku, ki = jax.random.split(key)
        user_f = _init_factors(ku, n_users, p.rank)
        item_f = _init_factors(ki, n_items, p.rank)
        if multi:  # single-chip: factors already live where they must
            user_f = jax.device_put(user_f, ctx.replicated)
            item_f = jax.device_put(item_f, ctx.replicated)

        u_ints, u_floats, u_shapes = _pack_buckets(user_buckets)
        i_ints, i_floats, i_shapes = _pack_buckets(item_buckets)
        packed_bytes = (
            u_ints.nbytes + u_floats.nbytes + i_ints.nbytes + i_floats.nbytes
        )
        # Two transfer strategies (latency vs HBM): small jobs pack ALL
        # rating data into ONE int32 + ONE float32 replicated transfer
        # (host→device round trips dominate at this scale); large multi-chip
        # jobs transfer per-bucket with the batch sharding so each device
        # holds 1/n of the data instead of a full replica.
        pack = not multi or packed_bytes <= p.pack_replicate_max_bytes
        if pack:
            ints = np.concatenate([u_ints, i_ints])
            floats = np.concatenate([u_floats, i_floats])
            if multi:
                ints, floats = jax.device_put((ints, floats), ctx.replicated)
            else:
                ints, floats = jnp.asarray(ints), jnp.asarray(floats)
            shard = ctx.batch_sharding() if multi else None
        else:
            bshard = ctx.batch_sharding()
            dev_user_buckets = tuple(
                tuple(
                    jax.device_put(x, bshard)
                    for x in (b.rows, b.cols, b.ratings, b.weights, b.row_valid)
                )
                for b in user_buckets
            )
            dev_item_buckets = tuple(
                tuple(
                    jax.device_put(x, bshard)
                    for x in (b.rows, b.cols, b.ratings, b.weights, b.row_valid)
                )
                for b in item_buckets
            )

        for it in range(p.num_iterations):
            if pack:
                user_f, item_f = _als_iteration(
                    user_f, item_f, ints, floats, p.lambda_, p.alpha,
                    implicit=p.implicit_prefs, rank=p.rank,
                    user_shapes=u_shapes, item_shapes=i_shapes, shard=shard,
                )
            else:
                user_f, item_f = _als_iteration_sharded(
                    user_f, item_f, dev_user_buckets, dev_item_buckets,
                    p.lambda_, p.alpha,
                    implicit=p.implicit_prefs, rank=p.rank,
                    user_nc=tuple(b.nc for b in user_buckets),
                    item_nc=tuple(b.nc for b in item_buckets),
                    shard=bshard,
                )
            if callback is not None:
                callback(it, user_f, item_f)

        # one readback for both factor matrices
        packed = np.asarray(jnp.concatenate([user_f, item_f], axis=0))
        return ALSFactors(packed[:n_users], packed[n_users:])

    def rmse(
        self,
        factors: ALSFactors,
        user_idx: np.ndarray,
        item_idx: np.ndarray,
        ratings: np.ndarray,
    ) -> float:
        ctx = self.ctx
        u, n = ctx.device_put_sharded_rows(np.asarray(user_idx, np.int32))
        i, _ = ctx.device_put_sharded_rows(np.asarray(item_idx, np.int32))
        r, _ = ctx.device_put_sharded_rows(np.asarray(ratings, np.float32))
        w = np.zeros(u.shape[0], np.float32)
        w[:n] = 1.0
        w = jax.device_put(w, ctx.batch_sharding())
        uf = jax.device_put(jnp.asarray(factors.user_features), ctx.replicated)
        vf = jax.device_put(jnp.asarray(factors.item_features), ctx.replicated)
        sq, cnt = _rmse_terms(uf, vf, u, i, r, w)
        return float(np.sqrt(sq / cnt))


# ---------------------------------------------------------------------------
# Serving-side kernels
# ---------------------------------------------------------------------------

#: Catalogs larger than this route through the chunked MIPS scan
#: (ops/topk.chunked_topk_scores) instead of one dense [b, n_items] score
#: matrix — peak serving memory stays O(chunk), not O(n_items). Every
#: template's predict inherits the dispatch through these two functions.
CHUNKED_TOPK_THRESHOLD = 32768
CHUNKED_TOPK_CHUNK = 8192


@partial(jax.jit, static_argnames=("k",))
def _top_k_dense(query_vecs, item_features, k: int, exclude_mask=None):
    scores = query_vecs @ item_features.T  # [b, n_items]
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def top_k_scores(query_vecs, item_features, k: int, exclude_mask=None):
    """Batched recommend: scores = q @ Yᵀ (one MXU matmul) + lax.top_k.
    ``exclude_mask`` [b, n_items] True → drop (seen items, blacklists — the
    serve-time filters of the ecommerce template). Catalogs above
    ``CHUNKED_TOPK_THRESHOLD`` rows stream through the chunked MIPS kernel."""
    if item_features.shape[0] > CHUNKED_TOPK_THRESHOLD:
        from predictionio_tpu.ops.topk import chunked_topk_scores

        return chunked_topk_scores(
            jnp.asarray(query_vecs), jnp.asarray(item_features), k=k,
            chunk=CHUNKED_TOPK_CHUNK, exclude_mask=exclude_mask,
        )
    return _top_k_dense(query_vecs, item_features, k, exclude_mask)


@partial(jax.jit)
def _l2_normalize(x):
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-9)


def top_k_cosine(query_vecs, item_features, k: int, exclude_mask=None):
    """Item-to-item cosine similarity (similarproduct template's scoring,
    ref: examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala).
    Normalizing both sides reduces cosine to inner product, so large
    catalogs share the chunked MIPS dispatch of :func:`top_k_scores`."""
    return top_k_scores(
        _l2_normalize(jnp.asarray(query_vecs)),
        _l2_normalize(jnp.asarray(item_features)),
        k,
        exclude_mask,
    )
