"""Dense-operand ALS solver: normal equations as whole-catalog MXU matmuls.

Replaces the degree-bucketed *gather* formulation (models/als.py) for
problems whose rating matrix fits HBM densified. Motivation (round-3 perf
study, docs/perf.md): TPU gathers are HBM-tile-granular, so the bucket
solver's per-rating factor-row gather reads a ~4 KB tile for every ~40 B
logical row — it runs at ~60% of the HBM roofline yet delivers <1% useful
bytes. The fix is a *reformulation*, not a faster gather: materialize the
rating matrix ``A`` once as dense int8 (constant across iterations) and
compute each half-step's normal equations as two large dense matmuls —

    explicit:  gram pairs = ind(A) @ [pairs(Y) | 1]      (count column)
               rhs        = A @ Y / scale
    implicit:  corrections= A @ [pairs(Y) | Y]           (Hu-Koren c-1)
               rhs/count  = ind(A) @ [Y | 1]

which the MXU executes at O(TFLOP/s) instead of the gather's
O(10 GFLOP/s). One rating cell is one int8 byte, so HBM traffic per
iteration is ~2 x bytes(A) instead of ~4 KB x nnz: at MovieLens-20M
(138k x 27k, 20M ratings, rank 10) this is ~37 ms/iteration vs ~360 ms
for the gather path — both measured on one v5e chip.

Exactness: the dense matrix holds each cell's single rating (times a
lossless x2 scale when ratings are half-stars). Cells rated more than
once (possible in synthetic/test data; real MovieLens rates each pair
once) and zero-valued ratings cannot ride the dense cells, so they are
collapsed host-side into a per-cell (count, value-sum) side-COO and
applied as f32 segment-sum corrections to the normal equations — every
input edge contributes exactly once, like MLlib's. One deliberate
difference from the bucket solver: ``ALSParams.max_degree`` is that
solver's tile-capacity cap (entities beyond it get their excess edges
TRUNCATED); the dense formulation has no tiles and uses all edges, so
for entities above max_degree the two solvers legitimately differ — the
dense result is the faithful one.

The solve itself reuses models/als.py's structure-of-arrays Cholesky and
ALS-WR count-scaled regularization (ref MLlib semantics:
examples/scala-parallel-recommendation/custom-serving/src/main/scala/
ALSAlgorithm.scala:55-61).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.io import transfer
from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

#: HBM arena for the densified-A cache entries (_A_CACHE below): the
#: single biggest long-lived device allocation in a training process.
_A_ARENA = device_obs.arena("dense_a_cache")

#: HBM arena for the factor matrices alive during a dense solve.
_FACTORS_ARENA = device_obs.arena("train_factors")

#: Cross-shard factor-slice traffic of one sharded-ALS iteration: the
#: forward gather of referenced opposite-side factor rows plus the
#: reverse routing of per-slice-slot partial grams, summed over all
#: shards (both all_to_all directions). The replicated layout this
#: design replaces would ship the whole item matrix instead.
SHARD_GATHER_BYTES = REGISTRY.histogram(
    "pio_als_shard_gather_bytes",
    "Factor-slice bytes exchanged across the mesh per sharded-ALS "
    "iteration (slice gather + reverse gram scatter, all shards)",
    buckets=transfer.BYTES_BUCKETS,
)

#: Shard load balance of the most recent sharded prepare: max cells on
#: one shard / mean cells per shard. 1.0 = perfectly balanced; `pio
#: doctor` WARNs past PIO_SHARD_IMBALANCE_WARN (default 2.0) — straggler
#: shards are the classic sharded-ALS failure mode.
SHARD_IMBALANCE = REGISTRY.gauge(
    "pio_als_shard_imbalance",
    "max/mean rating cells per data shard of the most recent sharded "
    "ALS prepare (1.0 = perfectly balanced)",
)


def iteration_flops(n_users: int, n_items: int, rank: int) -> float:
    """Executed FLOPs of one dense-solver iteration: both half-steps run
    an indicator dot (pairs + count column) and a value dot (rhs) over
    every user x item cell — 2·U·I·C per dot. The SINGLE source of the
    dense FLOP model: bench.py's offline MFU and the live
    ``pio_device_mfu`` gauge (obs/device.py, via the profiled entry
    points below) both read it, so the two figures cannot drift."""
    c_ind = rank * (rank + 1) // 2 + 1
    c_val = rank
    per_side = 2.0 * n_users * n_items * (c_ind + c_val)
    solve = (n_users + n_items) * (rank**3 / 3 + 2 * rank * rank)
    return 2 * per_side + solve


def _dense_bucket(*args, **kw) -> tuple:
    """Retrace bucket for the dense programs: every operand leaf's
    shape (the correction-cell count is data-dependent — new ratings
    are an EXPECTED recompile axis) plus the shape/branch-static
    kwargs. A new abstract signature within one bucket — same shapes,
    drifted dtype or weak-type — is the anomaly."""
    return (device_obs.shape_bucket(*args), tuple(sorted(kw.items())))

#: Auto-gate budget for the densified rating matrix, in bytes (int8: one
#: byte per user x item cell). ML-20M is ~3.7 GB; a v5e chip has ~15 GB
#: usable, and the solver needs ~2x(A block) of bf16 transients on top.
DENSE_MAX_BYTES = 6_000_000_000

#: Target bytes per row-block of A. Blocks bound the scatter transient
#: (XLA promotes int8 scatter operands internally) and set the unit the
#: iteration loop walks.
_BLOCK_BYTES = 1_000_000_000


def _int8_scale(vals: np.ndarray) -> int:
    """Lossless int8 encoding scale for the rating values: 1 (integers),
    2 (half-steps, e.g. MovieLens 0.5..5.0 stars), or 0 (not encodable —
    the dense solver does not apply)."""
    for s in (1, 2):
        v = vals * s
        if np.all(v == np.rint(v)) and np.all(np.abs(v) <= 127):
            return s
    return 0


def dense_eligible(n_users: int, n_items: int, ratings: np.ndarray,
                   max_bytes: int | None = None) -> bool:
    """Whether the dense solver applies: the densified matrix fits the
    byte budget and the values are losslessly int8-encodable.
    ``max_bytes`` defaults to DENSE_MAX_BYTES read at call time (a def-
    time default would freeze out runtime tuning of the module budget)."""
    cells = int(n_users) * int(n_items)
    budget = DENSE_MAX_BYTES if max_bytes is None else max_bytes
    return cells <= budget and _int8_scale(ratings) != 0


def sharded_block_fits(ctx, n_users: int, n_items: int, nnz: int) -> bool:
    """Whether the SPMD dense path's one-row-block-per-device layout fits:
    each data shard holds cells/data_shards int8 cells, so capacity scales
    with the data axis. At the default DENSE_MAX_BYTES the binding
    constraint is int32 flat-cell-id addressing (~2.1 GB of cells per
    device, well under the 6 GB budget); the byte-budget clause only bites
    when DENSE_MAX_BYTES is lowered below it. This is the single source of
    truth for the bound — ALS.train's router and train_dense_sharded's
    guard both call it."""
    ub_est = -(-int(n_users) // int(ctx.mesh.shape["data"]))
    block_cells = ub_est * int(n_items)
    return (
        block_cells + int(nnz) < 2**31
        and block_cells <= DENSE_MAX_BYTES
    )


def dense_eligible_on(ctx, n_users: int, n_items: int,
                      ratings: np.ndarray) -> bool:
    """Mesh-aware eligibility for explicit ``solver="dense"``: int8-
    encodable values, and EITHER the SPMD per-device row-block bound (on a
    mesh) OR the single-device total-cells budget — explicit dense must
    never be stricter than what ``auto`` would happily run on the same
    topology."""
    if _int8_scale(ratings) == 0:
        return False
    if ctx.mesh.devices.size > 1 and sharded_block_fits(
            ctx, n_users, n_items, ratings.size):
        return True
    return int(n_users) * int(n_items) <= DENSE_MAX_BYTES


def auto_pick(ctx, n_users: int, n_items: int, ratings: np.ndarray) -> bool:
    """The ``solver="auto"`` gate, shared by ALS.train and bench.py:
    density above ~1/2000 (below that the gather's nnz-proportional
    traffic beats reading every dense cell), the HBM byte budget (per
    device: on a mesh each data shard holds one row-block, so the budget
    scales with the data axis), SPMD int32 addressing on a mesh, and
    int8-encodable values — cheap checks first, the full ratings scan
    last. Meshes take the SPMD path (train_dense_sharded), validated by
    the multichip dryrun and the 8-device parity suite."""
    cells = int(n_users) * int(n_items)
    if ratings.size * 2000 < cells:
        return False
    if ctx.mesh.devices.size > 1:
        if not sharded_block_fits(ctx, n_users, n_items, ratings.size):
            return False
    elif cells > DENSE_MAX_BYTES:
        return False
    return _int8_scale(ratings) != 0


@dataclass
class _DupSide:
    """Collapsed correction cells for one solve direction, sorted by the
    entity being solved: cells rated more than once contribute
    (count-1 extra multiplicity, value-sum minus the densified rating),
    zero-valued cells contribute (count, 0)."""

    seg: np.ndarray  # [nd] int32 entity index (sorted ascending)
    nbr: np.ndarray  # [nd] int32 fixed-side index
    cnt: np.ndarray  # [nd] f32 extra multiplicity for the gram/count terms
    val: np.ndarray  # [nd] f32 extra value mass for the rhs term


@dataclass
class _DensePlan:
    """Host-prepared dense-solve inputs (see ``_dense_prepare``)."""

    nb: int  # number of user-row blocks of A
    ub: int  # rows per block (padded; nb*ub >= n_users)
    #: Compact COO per block — the host→device payload is the dominant
    #: full-train cost through a slow link, so the flat cell ids are NOT
    #: shipped: item indices ride uint16 when the catalog allows (2 B/edge
    #: instead of a 4 B int32 flat id) plus one tiny [ub+1] CSR row-starts
    #: vector, and the device reconstructs flat = row * n_items + item
    #: (row via cumsum over boundary marks) before the scatter.
    items: list  # nb x [m_b] u16/i32 item index (0 on padding)
    vals: list  # nb x [m_b] int8 scaled rating (0 on padding)
    row_starts: list  # nb x [ub+1] int32 block-local CSR edge offsets
    counts: list  # nb x int — real edges per block (m_b - padding)
    scale: int  # rating -> int8 multiplier (1 or 2)
    dup_u: _DupSide | None  # corrections for the user-side solve
    dup_i: _DupSide | None  # corrections for the item-side solve
    n_users: int
    n_items: int


def _sort_by_cell(ui, ii, vals, n_users: int, n_items: int):
    """(u, i, v) sorted by (user, item): two stable counting-sort passes
    (item first, then user) through models/als.py's C fast path — ~4x
    faster than one 20M-row int64 argsort."""
    from predictionio_tpu.models.als import _histogram, _sorted_side

    counts_i, starts_i = _histogram(ii, n_items)
    u_by_item, v_by_item = _sorted_side(ii, starts_i, ui, vals)
    item_keys = np.repeat(
        np.arange(n_items, dtype=np.int32), counts_i.astype(np.int64))
    _c, starts_u = _histogram(u_by_item, n_users)
    si, sv = _sorted_side(u_by_item, starts_u, item_keys, v_by_item)
    counts_u = np.diff(np.append(starts_u, len(ui)))
    su = np.repeat(
        np.arange(n_users, dtype=np.int32), counts_u.astype(np.int64))
    return su, si, sv


def _collapse_corrections(su, si, sv, main_mask):
    """Per-cell (entity-sorted) correction arrays from the cell-sorted
    edges. ``main_mask`` marks the one edge per cell carried by the dense
    matrix (False everywhere for zero-valued cells)."""
    extra = ~main_mask
    if not extra.any():
        return None, None
    # collapse the extra edges per cell: multiplicity + value mass
    eu, ei = su[extra], si[extra]
    cell_start = np.flatnonzero(np.concatenate(
        [[True], (eu[1:] != eu[:-1]) | (ei[1:] != ei[:-1])]))
    cnt = np.diff(np.append(cell_start, len(eu))).astype(np.float32)
    valsum = np.add.reduceat(
        sv[extra].astype(np.float64), cell_start).astype(np.float32)
    du = eu[cell_start]
    di = ei[cell_start]
    # user-side view is already (u, i)-sorted; item side needs its own sort
    u_side = _DupSide(du.astype(np.int32), di.astype(np.int32), cnt, valsum)
    o = np.argsort(di, kind="stable")
    i_side = _DupSide(
        di[o].astype(np.int32), du[o].astype(np.int32), cnt[o], valsum[o])
    return u_side, i_side


def _sorted_main_and_corrections(ui, ii, vals, n_users: int, n_items: int,
                                 scale: int):
    """The host sort + correction collapse shared by the plan builder and
    the streamed staging path: (mu, mi, mv, dup_u, dup_i) — the cell-
    sorted densifiable edges (mv already int8-scaled) plus the
    per-direction correction sides."""
    su, si, sv = _sort_by_cell(ui, ii, vals, n_users, n_items)
    first = np.concatenate(
        [[True], (su[1:] != su[:-1]) | (si[1:] != si[:-1])])
    # the densified edge per cell: its first occurrence — unless the value
    # is 0 (indistinguishable from an empty cell), which rides corrections
    main = first & (sv != 0)
    dup_u, dup_i = _collapse_corrections(su, si, sv, main)
    if dup_u is None:  # common case: all cells rated once, nonzero
        mu, mi = su, si
        mv = (sv * scale).astype(np.int8) if scale != 1 else sv.astype(np.int8)
    else:
        mu, mi, mv = su[main], si[main], (sv[main] * scale).astype(np.int8)
    return mu, mi, mv, dup_u, dup_i


def _block_split(mu, n_users: int, n_items: int, nb: int | None,
                 max_block_bytes: int | None = None):
    """(nb, ub, starts, item_dtype): the row-block layout over the
    cell-sorted edges. ``max_block_bytes`` caps the per-block cell bytes
    when ``nb`` is not forced (defaults to _BLOCK_BYTES)."""
    if nb is None:
        cap = _BLOCK_BYTES if max_block_bytes is None else max_block_bytes
        ub = max(cap // max(n_items, 1), 1)
        nb = max((n_users + ub - 1) // ub, 1)
    ub = (n_users + nb - 1) // nb
    bounds = np.searchsorted(mu, np.arange(1, nb) * ub)
    starts = np.concatenate([[0], bounds, [len(mu)]])
    item_dtype = np.uint16 if n_items <= np.iinfo(np.uint16).max else np.int32
    return nb, ub, starts, item_dtype


def _pack_block(b: int, mu, mi, mv, starts, ub: int, m: int | None,
                item_dtype):
    """One row-block's compact COO payload: (items, vals, row_starts, k).
    ``m`` forces the padded size (uniform blocks); None pads to the next
    multiple of 1024: XLA's TPU scatter strategy choice is size-sensitive
    (awkward update counts fall off a ~40x perf cliff — measured round
    3); padding entries become ascending distinct out-of-range flat ids
    on device, dropped by the scatter while keeping
    indices_are_sorted/unique_indices true."""
    lo, hi = starts[b], starts[b + 1]
    k = int(hi - lo)
    if m is None:
        m = max((k + 1023) // 1024 * 1024, 1024)
    f = np.zeros(m, item_dtype)
    v = np.zeros(m, np.int8)
    f[:k] = mi[lo:hi].astype(item_dtype)
    v[:k] = mv[lo:hi]
    row_starts = np.searchsorted(
        mu[lo:hi], b * ub + np.arange(ub + 1)).astype(np.int32)
    return f, v, row_starts, k


def _dense_prepare(ui, ii, vals, n_users: int, n_items: int,
                   scale: int | None = None,
                   nb: int | None = None,
                   uniform_m: bool = False) -> _DensePlan:
    """``nb`` forces the row-block count (the SPMD path wants one block
    per device); ``uniform_m`` pads every block's COO to one common size
    (stackable into a [nb, m] sharded array)."""
    if scale is None:
        scale = _int8_scale(vals)
    assert scale, "dense solver requires int8-encodable ratings"
    mu, mi, mv, dup_u, dup_i = _sorted_main_and_corrections(
        ui, ii, vals, n_users, n_items, scale)
    nb, ub, starts, item_dtype = _block_split(mu, n_users, n_items, nb)
    sizes = np.diff(starts)
    common_m = max(int(sizes.max()) + 1023, 1024) // 1024 * 1024
    items, bvals, row_starts, counts = [], [], [], []
    for b in range(nb):
        f, v, rs, k = _pack_block(
            b, mu, mi, mv, starts, ub, common_m if uniform_m else None,
            item_dtype)
        items.append(f)
        bvals.append(v)
        row_starts.append(rs)
        counts.append(k)
    return _DensePlan(nb, ub, items, bvals, row_starts, counts, scale,
                      dup_u, dup_i, n_users, n_items)


@partial(jax.jit, static_argnames=("ub", "n_items"))
def _scatter_block(items, vals, row_starts, k, ub: int, n_items: int):
    """One row-block of the densified rating matrix, scattered flat (1D):
    TPU lowers 1D sorted-unique scatters markedly better than 2D ones.
    The flat cell ids are reconstructed ON DEVICE from the compact
    (item, CSR row-starts) upload: a cumsum over row-boundary marks
    yields each edge's local row. Positions past ``k`` (the padding) get
    ascending out-of-range ids and are dropped by the scatter."""
    m = items.shape[0]
    marks = jnp.zeros((m,), jnp.int32)
    # boundaries of trailing empty rows land at position k: harmlessly in
    # the padding region when k < m, OUT of range (dropped) when k == m —
    # mode="drop" is load-bearing for exactly-full blocks
    marks = marks.at[row_starts[1:-1]].add(1, mode="drop")
    row = jnp.cumsum(marks)
    iota = jnp.arange(m, dtype=jnp.int32)
    oor = ub * n_items
    flat = jnp.where(
        iota < k,
        row * n_items + items.astype(jnp.int32),
        oor + (iota - k),
    )
    a = jnp.zeros((ub * n_items,), jnp.int8)
    return a.at[flat].set(
        vals, unique_indices=True, indices_are_sorted=True, mode="drop"
    ).reshape(ub, n_items)


def _pairs_payload(f, rank: int):
    """[n, pairs+rank+1] f32 payload: upper-triangle factor pair products,
    the factors, and a ones count column — the matmul right-hand sides.

    Numerical contract (learned the hard way, round 3): the payload stays
    **f32** and the dots run at ``Precision.HIGHEST``. The gram is
    assembled from independently-rounded pair-sum dot outputs, so it is
    only PSD up to the dot's rounding error — and TPU default-precision
    f32 dots round through bf16 (~1e-3 relative), orders of magnitude
    above the ALS-WR regularization floor for low-degree entities, which
    NaN'd the Cholesky. The *left* operands are exact in bf16 (0/1
    indicators and small-integer ratings), so bf16 x f32 @ HIGHEST
    measures f32-exact (rel ~4e-7) at the same speed as a default bf16
    dot."""
    iu, ju = np.triu_indices(rank)
    return jnp.concatenate(
        [f[:, iu] * f[:, ju], f, jnp.ones((f.shape[0], 1), jnp.float32)],
        axis=1)


#: Payload width (columns of the PSD-critical dot) above which the
#: explicit 2-term bf16 split replaces XLA's HIGHEST mixed dot. Measured
#: round 5 (v5e, ML-20M rank 64, 2081-column payload): XLA emits a
#: 3-pass emulation for bf16 x f32 @ HIGHEST when several such dots
#: share a program (~246 ms for the 4-block phase), while the explicit
#: split is exactly 2 bf16-rate passes (~134-167 ms) AND more accurate
#: (err/scale 8.7e-9 vs HIGHEST's 3.1e-8 against float64). At narrow
#: payloads (rank 10: 56 columns) the dots are memory-bound and the
#: difference is under tunnel-measurement noise, so HIGHEST keeps the
#: round-3/4 behavior there.
_PSD_SPLIT_MIN_COLS = 256


def _psd_split(rank: int) -> bool:
    """Whether the PSD-critical dot uses the explicit 2-term split.
    ``PIO_DENSE_PSD_DOT``: auto (width policy), split, highest."""
    import os

    mode = os.environ.get("PIO_DENSE_PSD_DOT", "auto")
    if mode == "split":
        return True
    if mode == "highest":
        return False
    return rank * (rank + 1) // 2 + 1 >= _PSD_SPLIT_MIN_COLS


def _split2(x):
    """f32 -> (hi, lo) bf16 terms with hi + lo ~ x to ~2^-17 relative:
    the explicit two-pass emulation of a HIGHEST mixed dot. The
    optimization_barrier is load-bearing: XLA:TPU otherwise folds
    ``x - bf16(x)`` to literal zero (it treats the f32->bf16->f32 round
    trip as value-preserving), silently degrading the split to one
    default-precision pass — measured round 5."""
    hi = jax.lax.optimization_barrier(x.astype(jnp.bfloat16))
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def use_kernel() -> bool:
    """Whether the dense half-steps run the fused Pallas dual-dot kernel
    (ops/dense_dots.py) instead of two XLA dots. ``PIO_DENSE_KERNEL``:
    ``auto`` (default — currently XLA everywhere), ``pallas`` (force the
    kernel; interpret-mode off-TPU, the CPU test path), ``xla`` (never).

    Measured round 4 (docs/perf.md §5): XLA wins. Its mixed
    ``bf16 x f32 @ HIGHEST`` dot costs ~1 MXU pass on v5e, while Mosaic
    rejects mixed-precision matmuls ("Bad lhs type"), forcing the kernel
    into a 3-term bf16 split — 3x the MXU passes for the same numerics.
    The kernel's single-read fusion cannot buy that back (the iteration
    is ~40% MXU / ~50% HBM); it measured ~79 ms/iter vs XLA's ~38 at
    ML-20M rank 10. Kept env-selectable for future Mosaic versions."""
    import os

    mode = os.environ.get("PIO_DENSE_KERNEL", "auto")
    if mode == "pallas":
        return True
    return False


def _make_dots(implicit: bool, exact: bool, kernel: bool = False,
               rank: int | None = None):
    """The pair of payload matmuls of one half-step, with the precision
    placement both solver paths must share: bf16 left operands are EXACT
    (0/1 and |scaled rating| <= 127 are all bf16-representable), and the
    dot whose payload carries the gram PAIRS must be f32-faithful (see
    _pairs_payload's numerical contract) — the indicator dot in explicit
    mode, the value dot in implicit mode. The other dot only feeds rhs
    (and exactly-representable counts: f32 accumulation keeps integer
    sums exact), where bf16-payload rounding is the same accepted error
    class as the bucket solver's bf16 gather — relaxed unless the caller
    asked for the f32 parity mode.

    The f32-faithful dot has two implementations, chosen by payload
    width (``rank``, see _PSD_SPLIT_MIN_COLS): XLA's HIGHEST mixed dot
    at narrow payloads, the explicit 2-term bf16 split (_split2) at wide
    ones, where HIGHEST's emulation spends 3 MXU passes and the split
    spends exactly 2 with better accuracy (round-5 measurement).

    ``kernel=True`` routes both dots through the fused Pallas kernel:
    one pass over the int8 block feeds both operand views, and the
    HIGHEST contract is reproduced by an in-kernel 3-term bf16 split
    (ops/dense_dots.py) — blocks must be padded to the kernel tile grid
    (prepare_device_inputs(pad_for_kernel=True))."""
    hi = jax.lax.Precision.HIGHEST
    if kernel:
        from predictionio_tpu.ops.dense_dots import fused_dual_dot

        s_hi, s_lo = 3, 3 if exact else 1
        si, sv = (s_lo, s_hi) if implicit else (s_hi, s_lo)
        interp = jax.default_backend() != "tpu"

        def dots(a, ip, vp, dims):
            assert dims in (((1,), (0,)), ((0,), (0,)))
            return fused_dual_dot(
                a, ip, vp, contract_rows=dims == ((0,), (0,)),
                splits_ind=si, splits_val=sv, interpret=interp)

        return dots

    if not exact and rank is not None and _psd_split(rank):
        def dots(a, ip, vp, dims):
            ai = (a != 0).astype(jnp.bfloat16)
            av = a.astype(jnp.bfloat16)

            def faithful(lhs, payload):
                out = 0.0
                for t in _split2(payload):
                    out = out + jax.lax.dot_general(
                        lhs, t, (dims, ((), ())),
                        preferred_element_type=jnp.float32)
                return out

            def relaxed(lhs, payload):
                return jax.lax.dot_general(
                    lhs, payload.astype(jnp.bfloat16), (dims, ((), ())),
                    preferred_element_type=jnp.float32)

            if implicit:
                return relaxed(ai, ip), faithful(av, vp)
            return faithful(ai, ip), relaxed(av, vp)

        return dots

    lo = hi if exact else None
    ind_prec, val_prec = (lo, hi) if implicit else (hi, lo)

    def dots(a, ip, vp, dims):
        ai = (a != 0).astype(jnp.bfloat16)
        av = a.astype(jnp.bfloat16)
        gi = jax.lax.dot_general(ai, ip, (dims, ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=ind_prec)
        gv = jax.lax.dot_general(av, vp, (dims, ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=val_prec)
        return gi, gv

    return dots


def _dup_correction(dup, fixed, rank: int, n_entities: int, alpha,
                    implicit: bool):
    """f32 segment-sum of the correction cells' normal-equation terms →
    [n_entities, pairs+rank+1] in the same column layout as the matmul
    payload (pairs-weight, rhs, count)."""
    seg, nbr, cnt, val = dup
    y = fixed[nbr]  # [nd, r] gather — nd is the (small) correction count
    iu, ju = np.triu_indices(rank)
    z = y[:, iu] * y[:, ju]
    if implicit:
        pair_w = alpha * val  # sum of (c-1) = alpha * value mass
        rhs_w = cnt + alpha * val  # sum of (1 + alpha r)
    else:
        pair_w = cnt
        rhs_w = val
    data = jnp.concatenate(
        [z * pair_w[:, None], y * rhs_w[:, None], cnt[:, None]], axis=1)
    return jax.ops.segment_sum(
        data, seg, num_segments=n_entities, indices_are_sorted=True)


def _dense_half_solve(
    prev,  # [n, r] f32 factors being updated
    fixed,  # [n_other, r] f32 fixed-side factors
    blocks,  # tuple of [ub, n_other] int8 (user side) — or None (item side)
    tblocks,  # tuple of [ub, n] int8 to contract over dim 0 — or None
    dup,  # (seg, nbr, cnt, val) correction arrays or None
    lambda_, alpha, implicit: bool, rank: int, scale: int, ub: int,
    exact: bool = False, kernel: bool = False,
):
    """One half-iteration: payload matmuls over the dense blocks + f32
    corrections + SoA Cholesky solve. Exactly one of ``blocks`` (row
    blocks: entities on rows) / ``tblocks`` (transposed contraction:
    entities on columns) is set. ``ub`` is the plan's real-rows-per-block
    (_DensePlan.ub — the block shapes may be kernel-padded beyond it).
    With ``kernel`` the blocks are padded to the Pallas tile grid (zero
    cells: they contribute to neither dot); payloads are padded to match
    and outputs sliced back."""
    n = prev.shape[0]
    ind_payload, val_payload = _local_half_inputs(fixed, rank, implicit)
    dots = _make_dots(implicit, exact, kernel, rank)

    if blocks is not None:
        n_other = ind_payload.shape[0]
        k_dim = blocks[0].shape[1]
        if k_dim != n_other:  # kernel padding on the contracted dim
            ind_payload = jnp.pad(
                ind_payload, ((0, k_dim - n_other), (0, 0)))
            val_payload = jnp.pad(
                val_payload, ((0, k_dim - n_other), (0, 0)))
        gis, gvs = [], []
        for a in blocks:
            gi, gv = dots(a, ind_payload, val_payload, ((1,), (0,)))
            gis.append(gi[:ub])
            gvs.append(gv[:ub])
        gi = jnp.concatenate(gis)[:n]
        gv = jnp.concatenate(gvs)[:n]
    else:
        ub_p = tblocks[0].shape[0]  # padded block rows (== ub without kernel)
        nb = len(tblocks)
        n_other = ind_payload.shape[0]
        # pad the payloads to the blocked row count: the blocks' padding
        # rows are all-zero, but an unpadded dynamic_slice would CLAMP the
        # last block's start and misalign every row in it
        up = nb * ub
        if up != n_other:
            ind_payload = jnp.pad(
                ind_payload, ((0, up - n_other), (0, 0)))
            val_payload = jnp.pad(
                val_payload, ((0, up - n_other), (0, 0)))
        gi = gv = 0.0
        for b, a in enumerate(tblocks):
            ip = jax.lax.dynamic_slice(
                ind_payload, (b * ub, 0), (ub, ind_payload.shape[1]))
            vp = jax.lax.dynamic_slice(
                val_payload, (b * ub, 0), (ub, val_payload.shape[1]))
            if ub_p != ub:  # kernel padding: match the block's row count
                ip = jnp.pad(ip, ((0, ub_p - ub), (0, 0)))
                vp = jnp.pad(vp, ((0, ub_p - ub), (0, 0)))
            d_gi, d_gv = dots(a, ip, vp, ((0,), (0,)))
            gi, gv = gi + d_gi, gv + d_gv
        gi = gi[:n]
        gv = gv[:n]

    corr = None
    if dup is not None:
        corr = _dup_correction(dup, fixed, rank, n, alpha, implicit)
    return _normal_eq_solve(prev, gi, gv, corr, fixed, lambda_, alpha,
                            implicit, rank, scale)


def _iteration_dense(user_f, item_f, blocks, dup_u, dup_i, lambda_, alpha,
                     implicit, rank, scale, ub, exact, kernel=False):
    user_f = _dense_half_solve(
        user_f, item_f, blocks, None, dup_u, lambda_, alpha, implicit,
        rank, scale, ub, exact, kernel)
    item_f = _dense_half_solve(
        item_f, user_f, None, blocks, dup_i, lambda_, alpha, implicit,
        rank, scale, ub, exact, kernel)
    return user_f, item_f


@device_obs.profiled_program(
    # rank-labelled program: "als_dense_rank64" is the MFU series the
    # bench headline reads back (obs/device.program_mfu)
    lambda *a, **kw: f"als_dense_rank{kw['rank']}",
    flops=lambda user_f, item_f, blocks, dup_u, dup_i, lam, al, iters,
    **kw: float(iters) * iteration_flops(
        user_f.shape[0], item_f.shape[0], kw["rank"]),
    bucket=_dense_bucket,
    sync=True,  # seconds-scale dispatch: one tiny-readback RTT makes
    # the recorded wall time device-true (and feeds the MFU gauge)
)
@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "scale", "ub", "exact", "kernel"),
    donate_argnums=(0, 1),
)
def _dense_train(
    user_f, item_f, blocks, dup_u, dup_i, lambda_, alpha, iters,
    *, implicit: bool, rank: int, scale: int, ub: int,
    exact: bool = False, kernel: bool = False,
):
    """The whole dense training run as one XLA dispatch (fori_loop) —
    per-call dispatch through a tunneled TPU costs ~15 ms, which would
    rival the ~25 ms iteration itself."""
    def body(_i, carry):
        uf, itf = carry
        return _iteration_dense(uf, itf, blocks, dup_u, dup_i, lambda_,
                                alpha, implicit, rank, scale, ub, exact,
                                kernel)

    return jax.lax.fori_loop(0, iters, body, (user_f, item_f))


@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "scale", "ub", "exact", "kernel"),
    donate_argnums=(0, 1),
)
def _dense_iteration(
    user_f, item_f, blocks, dup_u, dup_i, lambda_, alpha,
    *, implicit: bool, rank: int, scale: int, ub: int,
    exact: bool = False, kernel: bool = False,
):
    """One iteration as its own dispatch — the per-iteration callback path
    (convergence probes)."""
    return _iteration_dense(
        user_f, item_f, blocks, dup_u, dup_i, lambda_, alpha, implicit,
        rank, scale, ub, exact, kernel)


@device_obs.profiled_program(
    lambda *a, **kw: f"als_dense_user_half_rank{kw['rank']}",
    bucket=_dense_bucket,
    # NO sync: the pipelined final iteration exists so the user-factor
    # d2h copy overlaps the item half — the histogram measures enqueue
)
@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "scale", "ub", "exact", "kernel"),
    donate_argnums=(0,),
)
def _dense_user_half(
    user_f, item_f, blocks, dup_u, lambda_, alpha,
    *, implicit: bool, rank: int, scale: int, ub: int,
    exact: bool = False, kernel: bool = False,
):
    """The user half-step as its own dispatch — the pipelined train runs
    the FINAL iteration as two half dispatches so the finished user
    factors' device→host copy overlaps the item half still executing."""
    return _dense_half_solve(
        user_f, item_f, blocks, None, dup_u, lambda_, alpha, implicit,
        rank, scale, ub, exact, kernel)


@device_obs.profiled_program(
    lambda *a, **kw: f"als_dense_item_half_rank{kw['rank']}",
    bucket=_dense_bucket,
)
@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "scale", "ub", "exact", "kernel"),
    donate_argnums=(0,),
)
def _dense_item_half(
    item_f, user_f, blocks, dup_i, lambda_, alpha,
    *, implicit: bool, rank: int, scale: int, ub: int,
    exact: bool = False, kernel: bool = False,
):
    """The item half-step twin of :func:`_dense_user_half`."""
    return _dense_half_solve(
        item_f, user_f, None, blocks, dup_i, lambda_, alpha, implicit,
        rank, scale, ub, exact, kernel)


#: Merged-A gate: concatenating the row blocks into ONE [nb*ub, n_items]
#: array needs headroom for the in-place build (the full array plus one
#: block's scatter transient); past this many cells the per-block layout
#: is kept. ML-20M (3.7e9 cells) merges.
_MERGE_MAX_CELLS = 4_500_000_000


@partial(jax.jit, static_argnames=("ub", "n_items"),
         donate_argnums=(4,))
def _place_block(items, vals, row_starts, k, acc, b: int, ub: int,
                 n_items: int):
    """Scatter one block and write it into the merged A at row b*ub —
    donation makes the update in place, so the peak transient stays one
    block (the reason blocks exist at all: XLA promotes int8 scatter
    operands internally, and a whole-A scatter would blow HBM)."""
    a = _scatter_block(items, vals, row_starts, k, ub=ub, n_items=n_items)
    return jax.lax.dynamic_update_slice(acc, a, (b * ub, 0))


def prepare_device_inputs(plan: _DensePlan, pad_for_kernel: bool = False,
                          merge: bool = False):
    """(blocks, dup_u, dup_i) device arrays from a host plan — the
    scatter-densified int8 row blocks plus the correction-cell arrays.
    Shared by train_dense and bench.py's steady-state timer so both time
    the same program. ``pad_for_kernel`` zero-pads each block to the
    Pallas tile grid (both dims to PAD_MULTIPLE, since either dim can be
    the contraction) — done once per train, and zero cells contribute to
    neither dot. ``merge`` returns ONE [nb*ub, n_items] array (a 1-tuple)
    instead of nb row blocks: each half-step's payload matmuls then run
    as a single dot pair, which measured ~20% faster than four per-block
    dot pairs at rank 64 (round 5) and shrinks the program; callers must
    treat the plan's row count as ``nb * ub`` (see merged_ub)."""
    if merge and plan.nb > 1 and not pad_for_kernel:
        acc = jnp.zeros((plan.nb * plan.ub, plan.n_items), jnp.int8)
        for b in range(plan.nb):
            acc = _place_block(
                jax.device_put(plan.items[b]), jax.device_put(plan.vals[b]),
                jax.device_put(plan.row_starts[b]),
                jnp.int32(plan.counts[b]), acc, b,
                ub=plan.ub, n_items=plan.n_items)
        blocks = (acc,)
    else:
        blocks = tuple(
            _scatter_block(
                jax.device_put(plan.items[b]), jax.device_put(plan.vals[b]),
                jax.device_put(plan.row_starts[b]),
                jnp.int32(plan.counts[b]),
                ub=plan.ub, n_items=plan.n_items)
            for b in range(plan.nb)
        )
    if pad_for_kernel:
        from predictionio_tpu.ops.dense_dots import PAD_MULTIPLE

        def up(x: int) -> int:
            return -(-x // PAD_MULTIPLE) * PAD_MULTIPLE

        ub_p, items_p = up(plan.ub), up(plan.n_items)
        if (ub_p, items_p) != (plan.ub, plan.n_items):
            blocks = tuple(
                jnp.pad(a, ((0, ub_p - plan.ub),
                            (0, items_p - plan.n_items)))
                for a in blocks
            )
    dup_u, dup_i = _device_dups(plan.dup_u, plan.dup_i)
    return blocks, dup_u, dup_i


def should_merge(plan: _DensePlan, kernel: bool) -> bool:
    """Single-device merge policy: one dot pair per half-step unless the
    kernel path (per-block tile padding) or the in-place build headroom
    (_MERGE_MAX_CELLS) says otherwise. Shared by train_dense and bench's
    steady timer so both run the same program."""
    return should_merge_dims(plan.nb, plan.ub, plan.n_items, kernel)


def merged_ub(plan: _DensePlan, merged: bool) -> int:
    """Rows-per-block the solver should assume: the whole padded row
    count when the blocks were merged into one."""
    return plan.nb * plan.ub if merged else plan.ub


def _pipeline_enabled() -> bool:
    """Whether staging/readback ride the overlapped transfer pipeline
    (``PIO_TRANSFER_PIPELINE``, default on). The ``0`` escape hatch keeps
    the round-5 monolithic path runnable for A/B measurement and as a
    fallback if a backend misbehaves under threaded device puts."""
    import os

    return os.environ.get("PIO_TRANSFER_PIPELINE", "1") != "0"


def _device_dups(dup_u, dup_i):
    """Correction sides as device arrays (tiny; one put each)."""
    if dup_u is None:
        return None, None
    du = tuple(jax.device_put(x) for x in (
        dup_u.seg, dup_u.nbr, dup_u.cnt, dup_u.val))
    di = tuple(jax.device_put(x) for x in (
        dup_i.seg, dup_i.nbr, dup_i.cnt, dup_i.val))
    return du, di


def _stream_device_inputs(mu, mi, mv, dup_u, dup_i, scale: int,
                          n_users: int, n_items: int, kernel: bool,
                          phases: dict) -> dict:
    """Chunk-streamed build of the densified device inputs: a background
    worker packs + uploads row-block ``k+1``'s compact COO while this
    thread enqueues the device densify of block ``k`` — so host prepare,
    the host→device copies, and the device scatters all overlap instead
    of running as three serial phases. Returns the same entry dict as the
    monolithic ``prepare_device_inputs`` path and records the stager's
    overlap accounting into ``phases`` (``overlap_frac`` is the fraction
    of host staging time hidden behind device consumption).

    Chunk sizing: PIO_TRANSFER_CHUNK_MB refines the streaming unit ONLY
    when the chunks merge into one A (each chunk is then a transient
    scatter+place — the solve program never sees it). Non-merged
    configs (kernel path, matrices past _MERGE_MAX_CELLS) keep the
    _BLOCK_BYTES solve-block layout: their blocks feed _dense_half_solve
    directly, and letting a *staging* tunable multiply the per-iteration
    dot dispatches would be a silent solve regression."""
    nb, ub, starts, item_dtype = _block_split(
        mu, n_users, n_items, None,
        max_block_bytes=min(_BLOCK_BYTES, transfer.transfer_chunk_bytes()))
    merge = should_merge_dims(nb, ub, n_items, kernel)
    if not merge:
        nb, ub, starts, item_dtype = _block_split(mu, n_users, n_items,
                                                  None)

    def pack(b: int):
        return b, _pack_block(b, mu, mi, mv, starts, ub, None, item_dtype)

    def upload(packed):
        b, (f, v, rs, k) = packed
        return (b, jax.device_put(f), jax.device_put(v),
                jax.device_put(rs), jnp.int32(k))

    ub_p = items_p = None
    if kernel:
        from predictionio_tpu.ops.dense_dots import PAD_MULTIPLE

        ub_p = -(-ub // PAD_MULTIPLE) * PAD_MULTIPLE
        items_p = -(-n_items // PAD_MULTIPLE) * PAD_MULTIPLE

    stager = transfer.ChunkStager(name="als_densify")
    acc = jnp.zeros((nb * ub, n_items), jnp.int8) if merge else None
    blocks_list = []
    for _idx, (b, fd, vd, rsd, kd) in stager.stream(
            range(nb), pack, upload=upload):
        if merge:
            acc = _place_block(fd, vd, rsd, kd, acc, b,
                               ub=ub, n_items=n_items)
        else:
            a = _scatter_block(fd, vd, rsd, kd, ub=ub, n_items=n_items)
            if kernel and (ub_p, items_p) != (ub, n_items):
                a = jnp.pad(a, ((0, ub_p - ub), (0, items_p - n_items)))
            blocks_list.append(a)
    blocks = (acc,) if merge else tuple(blocks_list)
    du, di = _device_dups(dup_u, dup_i)
    nd = 0 if dup_u is None else len(dup_u.seg)
    phases["transfer_chunks"] = nb
    phases["transfer_stage_s"] = round(stager.staged_s, 3)
    phases["transfer_wait_s"] = round(stager.wait_s, 3)
    phases["overlap_frac"] = round(stager.overlap_frac(), 3)
    logger.info(
        "ALS(dense): %d edges -> %d x %d int8 cells streamed in %d "
        "chunk(s)%s, %d correction cells, scale %d, dots=%s, "
        "overlap %.0f%%",
        len(mu), n_users, n_items, nb, " (merged)" if merge else "",
        nd, scale, "pallas" if kernel else "xla",
        100 * phases["overlap_frac"])
    return dict(blocks=blocks, dup_u=du, dup_i=di, scale=scale,
                ub=nb * ub if merge else ub, nb=nb, nd=nd)


def should_merge_dims(nb: int, ub: int, n_items: int, kernel: bool) -> bool:
    """`should_merge` on raw block dimensions (the streamed path has no
    _DensePlan to hand over)."""
    return (not kernel and nb > 1
            and nb * ub * n_items <= _MERGE_MAX_CELLS)


#: Phase seconds of the most recent train_dense call, for bench/ops
#: reporting: fingerprint_s, prepare_s, upload_densify_s, solve_s,
#: cache_hit (ALS.train adds readback_s for the dense path). The device
#: phases are sync-accurate only under PIO_DENSE_PHASE_TIMING=1 (each
#: sync costs one ~100ms tunnel RTT, so the default records host-side
#: enqueue times and lumps device time into the caller's readback).
last_train_phases: dict = {}

#: One-entry cache of the densified device inputs, keyed by a content
#: fingerprint of the COO (ref: the reference's train path never
#: re-reads what it already staged — CoreWorkflow.scala:42-99). A is
#: constant across iterations AND across trains on the same ratings, so
#: a retrain (deploy-time retrain, hyperparameter sweeps, repeated
#:  bench trains) pays host sort + COO upload + densify exactly once.
#: The entry pins ~bytes(A) of HBM between trains; clear_dense_cache()
#: releases it, and any new fingerprint evicts the old entry.
_A_CACHE: dict = {}


def _evict_a_cache() -> None:
    """Drop every cached entry, releasing its HBM-arena registration
    first so ``pio_device_hbm_bytes{arena="dense_a_cache"}`` tracks the
    eviction (the arrays themselves die with the dict reference)."""
    for entry in _A_CACHE.values():
        _A_ARENA.free(entry.get("arena_alloc"))
    _A_CACHE.clear()


def clear_dense_cache() -> None:
    """Drop the cached densified inputs (frees the device A)."""
    _evict_a_cache()


def _cache_entry(key: str, entry: dict) -> None:
    """Pin one entry (the cache holds exactly one): evict the old A
    before registering the new one under the dense_a_cache arena."""
    _evict_a_cache()
    entry["arena_alloc"] = _A_ARENA.register(
        (entry["blocks"], entry["dup_u"], entry["dup_i"]),
        label=key[:12])
    _A_CACHE[key] = entry


def _cache_enabled() -> bool:
    import os

    return os.environ.get("PIO_DENSE_CACHE", "1") != "0"


def _fingerprint(ui, ii, ratings, n_users: int, n_items: int,
                 kernel: bool) -> str:
    """Content hash of everything the device inputs derive from. blake2b
    streams the 240 MB ML-20M COO at ~760 MB/s on this host — ~0.3 s to
    skip ~7 s of sort + upload + densify on a hit."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for a in (ui, ii, ratings):
        h.update(np.ascontiguousarray(a))
    h.update(repr((n_users, n_items, len(ratings), kernel,
                   jax.default_backend())).encode())
    return h.hexdigest()


def _phase_sync(x) -> None:
    """Tiny readback that orders a phase boundary for timing — only under
    PIO_DENSE_PHASE_TIMING (block_until_ready does not block through
    this environment's TPU tunnel; a 4-element fetch does)."""
    np.asarray(jax.device_get(jnp.ravel(x)[:4]))


def acquire_device_inputs(ui, ii, ratings, n_users: int, n_items: int,
                          phases: dict | None = None) -> dict:
    """Cache-aware densified device inputs: fingerprint + (prepare +
    upload + densify | cache hit). Returns the entry dict
    (blocks/dup_u/dup_i/scale/ub/nb/nd) — shared by train_dense and
    bench.py's steady timer so the bench never rebuilds (or double-pins)
    an A the cache already holds."""
    import os
    import time

    if phases is None:
        phases = {}
    sync_timing = os.environ.get("PIO_DENSE_PHASE_TIMING") == "1"
    kernel = use_kernel()
    entry = None
    key = None
    if _cache_enabled():
        t0 = time.perf_counter()
        key = _fingerprint(ui, ii, ratings, n_users, n_items, kernel)
        phases["fingerprint_s"] = round(time.perf_counter() - t0, 3)
        entry = _A_CACHE.get(key)
    phases["cache_hit"] = entry is not None

    if entry is None and _pipeline_enabled():
        # streamed path: the blocking host work is just the cell sort +
        # correction collapse (prepare); per-block packing and the
        # host→device copies then overlap the device densify inside
        # _stream_device_inputs, so upload_densify_s is pipeline wall
        # time, not a serial sum
        scale = _int8_scale(ratings)
        assert scale, "dense solver requires int8-encodable ratings"
        t0 = time.perf_counter()
        mu, mi, mv, dup_u, dup_i = _sorted_main_and_corrections(
            ui, ii, ratings, n_users, n_items, scale)
        phases["prepare_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        entry = _stream_device_inputs(
            mu, mi, mv, dup_u, dup_i, scale, n_users, n_items, kernel,
            phases)
        if sync_timing:
            _phase_sync(entry["blocks"][0])
        phases["upload_densify_s"] = round(time.perf_counter() - t0, 3)
        if key is not None:
            _cache_entry(key, entry)  # one entry: evicts the old A
    elif entry is None:
        t0 = time.perf_counter()
        plan = _dense_prepare(ui, ii, ratings, n_users, n_items)
        phases["prepare_s"] = round(time.perf_counter() - t0, 3)
        merged = should_merge(plan, kernel)
        t0 = time.perf_counter()
        blocks, dup_u, dup_i = prepare_device_inputs(
            plan, pad_for_kernel=kernel, merge=merged)
        if sync_timing:
            _phase_sync(blocks[0])
        phases["upload_densify_s"] = round(time.perf_counter() - t0, 3)
        nd = 0 if plan.dup_u is None else len(plan.dup_u.seg)
        entry = dict(blocks=blocks, dup_u=dup_u, dup_i=dup_i,
                     scale=plan.scale, ub=merged_ub(plan, merged),
                     nb=plan.nb, nd=nd)
        if key is not None:
            _cache_entry(key, entry)  # one entry: evicts the old A
        logger.info(
            "ALS(dense): %d ratings -> %d x %d int8 cells in %d blocks"
            "%s, %d correction cells, scale %d, dots=%s",
            len(ratings), n_users, n_items, plan.nb,
            " (merged)" if merged else "", nd, plan.scale,
            "pallas" if kernel else "xla")
    else:
        logger.info(
            "ALS(dense): cache hit — reusing densified %d x %d device "
            "inputs (fingerprint %s)", n_users, n_items, key[:12])
    return entry


def train_dense(ctx, params, ui, ii, ratings, n_users, n_items,
                callback=None, resume=None):
    """Driver: fingerprint + (prepare + densify | cache hit) + train.
    Returns (user_f, item_f) as device arrays; models/als.ALS.train
    wraps this. ``resume`` = ``(start_iter, user_f, item_f)`` continues
    a checkpointed solve from iteration ``start_iter`` on the given
    host factors (crash-safe training: the math is iteration-for-
    iteration identical to an uninterrupted run, so a resumed train
    reproduces the uninterrupted factors exactly)."""
    import time

    from predictionio_tpu.models.als import _init_factors

    p = params
    phases: dict = {}
    import os

    sync_timing = os.environ.get("PIO_DENSE_PHASE_TIMING") == "1"
    kernel = use_kernel()
    entry = acquire_device_inputs(ui, ii, ratings, n_users, n_items,
                                  phases=phases)
    from predictionio_tpu.obs import runlog

    # run-ledger phase records (no-ops outside an active run): the host
    # prep + staged upload that precede the solve, so `pio watch` can
    # tell "densifying" from "hung" before the first iteration lands
    for _k, _phase in (("prepare_s", "prepare"),
                       ("upload_densify_s", "upload_densify")):
        if _k in phases:
            runlog.phase(_phase, phases[_k])

    start_iter = 0
    if resume is not None:
        start_iter, uf0, if0 = resume
        user_f = jnp.asarray(np.asarray(uf0, np.float32))
        item_f = jnp.asarray(np.asarray(if0, np.float32))
    else:
        prng = jax.random.PRNGKey(p.seed if p.seed is not None else 0)
        ku, ki = jax.random.split(prng)
        user_f = _init_factors(ku, n_users, p.rank)
        item_f = _init_factors(ki, n_items, p.rank)
    blocks, dup_u, dup_i = entry["blocks"], entry["dup_u"], entry["dup_i"]

    # gather_dtype="float32" is the parity-study mode: every dot at
    # HIGHEST. The default runs the gram-pairs dot f32-faithfully
    # (HIGHEST or explicit split — see _make_dots) and the rhs dot
    # relaxed.
    static = dict(implicit=p.implicit_prefs, rank=p.rank,
                  scale=entry["scale"], ub=entry["ub"],
                  exact=p.gather_dtype == "float32",
                  kernel=kernel)
    t0 = time.perf_counter()
    # factor matrices live in HBM for the whole solve; past the return
    # they belong to the caller (readback) and show as unattributed
    factors_alloc = _FACTORS_ARENA.register(
        (n_users + n_items) * p.rank * 4, label=f"rank{p.rank}")
    # per-iteration dispatch when the iterations must be individually
    # visible: a checkpointed resume (the fused fori_loop cannot start
    # mid-loop), a progress/checkpoint callback, or an active run ledger
    # with step-level observation enabled (PIO_RUNS_STEP_ITERATIONS) —
    # the `pio train` live-watch mode
    per_iter = (resume is not None or callback is not None
                or runlog.want_steps())
    try:
        if per_iter:
            from predictionio_tpu.resilience import faults

            # the crash-safe-training chaos site: an error here is a
            # mid-train kill between checkpoint intervals
            st = runlog.StepTimer("als_dense", total=p.num_iterations,
                                  start=start_iter, phase="solve")
            for it in range(start_iter, p.num_iterations):
                faults.fault_point("train.iteration")
                user_f, item_f = _dense_iteration(
                    user_f, item_f, blocks, dup_u, dup_i, p.lambda_, p.alpha,
                    **static)
                if callback is not None:
                    callback(it, user_f, item_f)
                st.step(it + 1, sync=item_f)
        elif _pipeline_enabled() and p.num_iterations >= 1:
            # the final iteration runs as two half dispatches: once the user
            # half lands, its factors' d2h copy is kicked off and proceeds
            # concurrently with the item half still executing on device —
            # the readback overlap half of the transfer pipeline (the caller
            # collects both arrays via io.transfer.async_readback)
            user_f, item_f = _dense_train(
                user_f, item_f, blocks, dup_u, dup_i, p.lambda_, p.alpha,
                p.num_iterations - 1, **static)

            def start_fetch(x):
                # whole-array d2h copy, started early (pure DMA — overlaps
                # the compute still queued behind it). Only when the caller's
                # async_readback will NOT row-chunk the array: above the
                # chunk threshold it slices and copies per chunk, and a
                # redundant whole-array copy here would double the d2h bytes
                if (hasattr(x, "copy_to_host_async")
                        and x.nbytes <= transfer.transfer_chunk_bytes()):
                    x.copy_to_host_async()

            user_f = _dense_user_half(
                user_f, item_f, blocks, dup_u, p.lambda_, p.alpha, **static)
            start_fetch(user_f)
            item_f = _dense_item_half(
                item_f, user_f, blocks, dup_i, p.lambda_, p.alpha, **static)
            start_fetch(item_f)
        else:
            user_f, item_f = _dense_train(
                user_f, item_f, blocks, dup_u, dup_i, p.lambda_, p.alpha,
                p.num_iterations, **static)
        # sync the solve timing when explicitly asked OR when a ledger
        # run observes a fused solve (honest step telemetry; unobserved
        # pipeline trains keep their readback overlap un-synced)
        fused_synced = sync_timing or (not per_iter
                                       and runlog.active() is not None)
        if fused_synced:
            _phase_sync(item_f)
    finally:
        _FACTORS_ARENA.free(factors_alloc)
    phases["solve_s"] = round(time.perf_counter() - t0, 3)
    if not per_iter:
        # the fused whole-run dispatch: one aggregate ledger/metric
        # record (per-iteration average), marked fused; enqueue-only
        # timings stay out of the step histogram
        runlog.fused_steps("als_dense", p.num_iterations,
                           phases["solve_s"], synced=fused_synced)
    global last_train_phases
    last_train_phases = phases
    return user_f, item_f


# ---------------------------------------------------------------------------
# Stacked multi-candidate training (hyperparameter sweeps)
# ---------------------------------------------------------------------------
#
# A sweep bucket's candidates share EVERYTHING static — the rating matrix,
# rank, iteration count, implicit flag — and differ only in per-candidate
# scalars (lambda, alpha, seed). Training them serially re-dispatches the
# same program N times; instead the whole bucket runs as ONE fused
# program: a leading candidate axis over the factors and a vmap of the
# dense iteration, with the int8 A blocks closed over UNBATCHED (the MXU
# contracts each candidate's payload against the same operand — no A
# duplication in HBM, and the staged upload through acquire_device_inputs'
# ChunkStager/dense-A cache is paid once per ratings fingerprint, not once
# per candidate).


@device_obs.profiled_program(
    lambda *a, **kw: f"als_dense_stacked_rank{kw['rank']}",
    flops=lambda uf_stack, if_stack, blocks, dup_u, dup_i, lambdas,
    alphas, iters, **kw: float(iters) * uf_stack.shape[0]
    * iteration_flops(uf_stack.shape[1], if_stack.shape[1], kw["rank"]),
    bucket=_dense_bucket,
    sync=True,
)
@partial(
    jax.jit,
    static_argnames=("implicit", "rank", "scale", "ub", "exact"),
    donate_argnums=(0, 1),
)
def _dense_train_stacked(
    uf_stack,  # [C, n_users, r] per-candidate factors
    if_stack,  # [C, n_items, r]
    blocks, dup_u, dup_i,
    lambdas,  # [C] per-candidate regularization
    alphas,  # [C] per-candidate implicit confidence weight
    iters,  # traced loop bound (shared across the bucket)
    *, implicit: bool, rank: int, scale: int, ub: int, exact: bool = False,
):
    """The whole bucket's training as one XLA dispatch: fori_loop over a
    vmapped dense iteration. ``blocks``/``dup_*`` are closed over without
    a batch axis — shared operands, per-candidate payloads."""

    def one(uf, itf, lam, al):
        return _iteration_dense(uf, itf, blocks, dup_u, dup_i, lam, al,
                                implicit, rank, scale, ub, exact, False)

    def body(_i, carry):
        u, v = carry
        return jax.vmap(one, in_axes=(0, 0, 0, 0))(u, v, lambdas, alphas)

    return jax.lax.fori_loop(0, iters, body, (uf_stack, if_stack))


#: HBM budget (MiB) for one stacked sweep chunk's per-candidate payload
#: transients (``PIO_SWEEP_HBM_MB``). The A blocks are shared; what scales
#: with the candidate axis is each half-step's payload + gram/rhs
#: temporaries, roughly 4 payload-sized f32 arrays per candidate.
DEFAULT_SWEEP_HBM_MB = 2048


def stacked_candidate_limit(rank: int, n_users: int, n_items: int) -> int:
    """Candidate-axis chunk cap for one stacked solve. Per candidate the
    dominant transients are the [n, pairs+rank+1] f32 payload/gram/rhs
    arrays on both sides (~4 live at a half-step peak); the cap divides
    the ``PIO_SWEEP_HBM_MB`` budget by that footprint (floor 1)."""
    import os

    budget = float(os.environ.get("PIO_SWEEP_HBM_MB",
                                  DEFAULT_SWEEP_HBM_MB)) * 2**20
    cols = rank * (rank + 1) // 2 + rank + 1
    per_cand = 4.0 * (n_users + n_items) * cols * 4.0
    return max(int(budget // max(per_cand, 1.0)), 1)


def stacked_eligible(ctx, n_users: int, n_items: int,
                     ratings: np.ndarray) -> bool:
    """Whether a sweep bucket can take the stacked dense path: a
    SINGLE-device context where the ``solver="auto"`` gate itself
    (:func:`auto_pick` — the single source of truth, so the two routes
    can never drift) would pick dense, on the XLA dot path (the Pallas
    kernel is not vmap-validated). A bucket therefore batches exactly
    when its sequential candidates would have run the same dense
    solver; on a mesh the sequential path routes to the SPMD train and
    the stacked program declines rather than funnel the bucket onto one
    chip."""
    return (
        ctx.mesh.devices.size == 1
        and auto_pick(ctx, n_users, n_items, ratings)
        and not use_kernel()
    )


def train_dense_stacked(ctx, params_list, ui, ii, ratings,
                        n_users: int, n_items: int):
    """Train one sweep bucket's candidates as a single stacked dense solve.

    ``params_list`` (ALSParams) must agree on rank / num_iterations /
    implicit_prefs / gather_dtype (the bucket signature); lambda_, alpha
    and seed vary per candidate. Returns ``(user_stack [C, n_users, r],
    item_stack [C, n_items, r])`` as DEVICE arrays — metric evaluation is
    expected to happen on device before any readback — or None when the
    stacked path does not apply (caller falls back to sequential trains).

    The densified A is acquired through :func:`acquire_device_inputs`:
    one ChunkStager-streamed upload per ratings fingerprint, shared by
    every candidate of every bucket evaluated on the same fold."""
    import time

    from predictionio_tpu.models.als import _init_factors

    p0 = params_list[0]
    for p in params_list[1:]:
        if (p.rank, p.num_iterations, p.implicit_prefs, p.gather_dtype) != (
                p0.rank, p0.num_iterations, p0.implicit_prefs,
                p0.gather_dtype):
            raise ValueError(
                "train_dense_stacked needs a homogeneous bucket: rank/"
                "iterations/implicit/gather_dtype must match across "
                "candidates")
    ui = np.asarray(ui, np.int32)
    ii = np.asarray(ii, np.int32)
    ratings = np.asarray(ratings, np.float32)
    if ratings.size == 0 or not stacked_eligible(ctx, n_users, n_items,
                                                 ratings):
        return None

    phases: dict = {}
    entry = acquire_device_inputs(ui, ii, ratings, n_users, n_items,
                                  phases=phases)
    inits_u, inits_i = [], []
    for p in params_list:
        key = jax.random.PRNGKey(p.seed if p.seed is not None else 0)
        ku, ki = jax.random.split(key)
        # per-candidate seeds reproduce the sequential path's init exactly
        inits_u.append(_init_factors(ku, n_users, p0.rank))
        inits_i.append(_init_factors(ki, n_items, p0.rank))
    uf_stack = jnp.stack(inits_u)
    if_stack = jnp.stack(inits_i)
    lambdas = jnp.asarray([p.lambda_ for p in params_list], jnp.float32)
    alphas = jnp.asarray([p.alpha for p in params_list], jnp.float32)
    logger.info(
        "ALS(dense,stacked): %d candidate(s), rank %d, %d iteration(s), "
        "A %s", len(params_list), p0.rank, p0.num_iterations,
        "cache hit" if phases.get("cache_hit") else "staged")
    t0 = time.perf_counter()
    uf_stack, if_stack = _dense_train_stacked(
        uf_stack, if_stack, entry["blocks"], entry["dup_u"], entry["dup_i"],
        lambdas, alphas, p0.num_iterations,
        implicit=p0.implicit_prefs, rank=p0.rank, scale=entry["scale"],
        ub=entry["ub"], exact=p0.gather_dtype == "float32")
    # sync before returning so the caller's solve timer measures the
    # solve, not just its dispatch — otherwise the whole stacked train
    # would be paid inside the metric stage's first blocking readback and
    # pio_sweep_stage_seconds{stage=solve|score} would invert. A tiny
    # readback, not block_until_ready: the latter does not actually block
    # through the axon tunnel.
    np.asarray(jax.device_get(uf_stack[:, :1, :1]))
    from predictionio_tpu.obs import runlog

    runlog.fused_steps(f"als_dense_stacked_rank{p0.rank}",
                       p0.num_iterations, time.perf_counter() - t0)
    return uf_stack, if_stack


# ---------------------------------------------------------------------------
# SPMD dense training (mesh data axis)
# ---------------------------------------------------------------------------
#
# Each device owns one row-block of A (its shard of the users): the user
# half-step is entirely local (local rows x replicated item payload), the
# item half-step contracts each device's block against its local user
# rows and one psum over ``data`` produces the replicated item normal
# equations — the same collective role MLlib's factor-block shuffle
# plays, riding ICI. Item factors stay replicated; user factors live
# row-sharded for the whole run and only materialize on the host once,
# at the final readback.


def _local_half_inputs(itf, rank, implicit):
    payload = _pairs_payload(itf, rank)
    n_pairs = rank * (rank + 1) // 2
    if implicit:
        return payload[:, n_pairs:], payload[:, : n_pairs + rank]
    return (
        jnp.concatenate([payload[:, :n_pairs], payload[:, -1:]], axis=1),
        payload[:, n_pairs: n_pairs + rank],
    )


def _normal_eq_solve(prev, gi, gv, corr, fixed, lambda_, alpha, implicit,
                     rank, scale, xtx=None):
    """pairs/rhs/counts -> regularized Cholesky solve (the shared tail of
    both half-steps; ``corr`` is an optional [n, P+r+1] f32 addend). The
    gram stays in its packed upper-triangle column layout all the way
    into the solver (_reg_solve_packed) — no [n, r, r] materialization.
    ``xtx`` supplies implicit mode's shared Gram term precomputed as a
    full [r, r] matrix — the sharded path psums per-shard partial grams
    because no device holds the fixed side whole; ``fixed`` may then be
    None."""
    from predictionio_tpu.models.als import _reg_solve_packed

    n_pairs = rank * (rank + 1) // 2
    if implicit:
        pairs = gv[:, :n_pairs] * alpha / scale
        rhs = gi[:, :rank] + alpha * gv[:, n_pairs:] / scale
        counts = gi[:, -1]
    else:
        pairs = gi[:, :n_pairs]
        rhs = gv / scale
        counts = gi[:, -1]
    if corr is not None:
        pairs = pairs + corr[:, :n_pairs]
        rhs = rhs + corr[:, n_pairs: n_pairs + rank]
        counts = counts + corr[:, -1]
    if implicit:
        # Hu-Koren's shared XtX Gram term, packed: one [r, r] added to
        # every entity's upper triangle
        iu, ju = np.triu_indices(rank)
        if xtx is None:
            xtx = jax.lax.dot_general(
                fixed, fixed, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
        pairs = pairs + xtx[iu, ju][None, :]
    reg = lambda_ * jnp.maximum(counts, 1.0) + 1e-8
    sol = _reg_solve_packed(pairs, rhs, reg, rank)
    return jnp.where(counts[:, None] > 0, sol, prev)


def _pow2(n: int, floor: int) -> int:
    """Next power of two >= n (bounded retrace ladder for the sharded
    programs' data-dependent dims — same role as foldin's pad ladder)."""
    p = floor
    while p < n:
        p *= 2
    return p


@dataclass
class _ShardPlan:
    """Host-prepared two-sided sharded layout (see ``_sharded_prepare``).
    Per-shard payloads are built lazily by ``_pack_shard`` so the staging
    pipeline can overlap shard k+1's pack with shard k's upload."""

    ndev: int
    ub: int  # user rows per shard (ceil; ndev*ub >= n_users)
    ib: int  # item rows per shard (ceil; ndev*ib >= n_items)
    w: int  # slice width per (src, dst) shard pair (pow2, uniform)
    m: int  # packed COO cells per shard (pow2, uniform)
    nd: int  # padded correction cells per shard (0: no corrections)
    counts: np.ndarray  # [ndev] real cells per shard
    scale: int
    imbalance: float  # max/mean cells per shard (1.0 = balanced)
    n_users: int
    n_items: int
    starts: np.ndarray  # [ndev+1] cell offsets per user shard
    dstarts: np.ndarray | None  # [ndev+1] correction offsets per shard
    need: list  # need[d][s]: local item rows of shard s that d references
    mu: np.ndarray
    mi: np.ndarray
    mv: np.ndarray
    dup_u: _DupSide | None


def _sharded_prepare(ui, ii, vals, n_users: int, n_items: int, ndev: int,
                     scale: int | None = None) -> _ShardPlan:
    """Host prepare for the fully sharded (ALX-style) layout: the
    cell-sorted COO split into one user-row block per shard, plus each
    shard's dedup'd index of the item rows its cells (and correction
    cells) reference — grouped by owner shard, so the per-iteration
    exchange ships only referenced factor rows via
    ``ops.collectives.gather_slices`` instead of replicating the item
    matrix."""
    if scale is None:
        scale = _int8_scale(vals)
    assert scale, "dense solver requires int8-encodable ratings"
    mu, mi, mv, dup_u, _dup_i = _sorted_main_and_corrections(
        ui, ii, vals, n_users, n_items, scale)
    # the item-side correction is rebuilt per shard in slice-slot space
    # (_pack_shard); the global item-sorted view is unused here
    ub = -(-n_users // ndev)
    ib = -(-n_items // ndev)
    bounds = np.searchsorted(mu, np.arange(1, ndev) * ub)
    starts = np.concatenate([[0], bounds, [len(mu)]]).astype(np.int64)
    dstarts = None
    if dup_u is not None:
        dstarts = np.searchsorted(
            dup_u.seg, np.arange(ndev + 1) * ub).astype(np.int64)
    need: list = []
    wmax = 1
    for d in range(ndev):
        ref = mi[starts[d]:starts[d + 1]]
        if dup_u is not None:
            # correction cells may reference items with no densified cell
            # in this shard (zero-valued cells ride corrections only) —
            # their rows must be in the slice index too
            ref = np.concatenate(
                [ref, dup_u.nbr[dstarts[d]:dstarts[d + 1]]])
        uniq = np.unique(ref)
        ob = np.searchsorted(uniq, np.arange(ndev + 1) * ib)
        per = [uniq[ob[s]:ob[s + 1]].astype(np.int32) - np.int32(s * ib)
               for s in range(ndev)]
        wmax = max(wmax, max((len(r) for r in per), default=0))
        need.append(per)
    w = _pow2(wmax, floor=8)
    counts = np.diff(starts).astype(np.int64)
    m = _pow2(max(int(counts.max()), 1), floor=1024)
    nd = 0
    if dup_u is not None:
        nd = _pow2(max(int(np.diff(dstarts).max()), 1), floor=8)
    imbalance = (float(counts.max() / max(counts.mean(), 1e-9))
                 if counts.sum() else 1.0)
    return _ShardPlan(ndev, ub, ib, w, m, nd, counts, scale, imbalance,
                      n_users, n_items, starts, dstarts, need, mu, mi, mv,
                      dup_u)


def _pack_shard(plan: _ShardPlan, d: int) -> dict:
    """Shard ``d``'s staged payload: the compact COO with item columns
    remapped to slice-slot ids (owner * w + position — ascending within
    each row because the owner is monotone in the item id and positions
    ascend within an owner, so the device scatter's sorted/unique
    contract holds with n_items -> ndev*w), this shard's send table, and
    both correction sides keyed to the cell's user-owner shard (the item
    side in slice-slot space, routed back by the reverse all_to_all)."""
    ndev, w, ib, ub, m = plan.ndev, plan.w, plan.ib, plan.ub, plan.m
    lookup = np.empty(plan.n_items, np.int32)
    for s in range(ndev):
        rows = plan.need[d][s]
        lookup[s * ib + rows] = s * w + np.arange(len(rows), dtype=np.int32)
    lo, hi = plan.starts[d], plan.starts[d + 1]
    k = int(hi - lo)
    items = np.zeros(m, np.int32)
    vals8 = np.zeros(m, np.int8)
    items[:k] = lookup[plan.mi[lo:hi]]
    vals8[:k] = plan.mv[lo:hi]
    row_starts = np.searchsorted(
        plan.mu[lo:hi], d * ub + np.arange(ub + 1)).astype(np.int32)
    # send table: row dst lists the LOCAL item rows shard dst needs from
    # this shard; pad = ib (the gather clamps it to a row the receiver
    # never references, the reverse scatter drops it)
    send = np.full((ndev, w), ib, np.int32)
    for dst in range(ndev):
        rows = plan.need[dst][d]
        send[dst, :len(rows)] = rows
    out = dict(items=items, vals=vals8, row_starts=row_starts,
               k=np.asarray(k, np.int32), send=send)
    if plan.nd:
        du = plan.dup_u
        dlo, dhi = plan.dstarts[d], plan.dstarts[d + 1]
        kd = int(dhi - dlo)
        seg = np.zeros(plan.nd, np.int32)
        nbr = np.zeros(plan.nd, np.int32)
        cnt = np.zeros(plan.nd, np.float32)
        val = np.zeros(plan.nd, np.float32)
        seg[:kd] = du.seg[dlo:dhi] - d * ub
        nbr[:kd] = lookup[du.nbr[dlo:dhi]]
        cnt[:kd] = du.cnt[dlo:dhi]
        val[:kd] = du.val[dlo:dhi]
        if kd:  # keep segment ids sorted through the padding
            seg[kd:] = seg[kd - 1]
        out.update(du_seg=seg, du_nbr=nbr, du_cnt=cnt, du_val=val)
        # item-side corrections: segment = slice slot (sorted), neighbor
        # = local user row; weights are zero on padding so pad slots
        # contribute nothing before the reverse exchange
        slot = nbr[:kd]
        o = np.argsort(slot, kind="stable")
        iseg = np.zeros(plan.nd, np.int32)
        inbr = np.zeros(plan.nd, np.int32)
        icnt = np.zeros(plan.nd, np.float32)
        ival = np.zeros(plan.nd, np.float32)
        iseg[:kd] = slot[o]
        inbr[:kd] = seg[:kd][o]
        icnt[:kd] = cnt[:kd][o]
        ival[:kd] = val[:kd][o]
        if kd:
            iseg[kd:] = iseg[kd - 1]
        out.update(di_seg=iseg, di_nbr=inbr, di_cnt=icnt, di_val=ival)
    return out


def _stage_sharded_inputs(mesh, plan: _ShardPlan, rank: int,
                          phases: dict):
    """Per-shard pack/upload through the ChunkStager: a background worker
    packs shard k+1's COO + send table while this thread uploads shard
    k's buffers to its own devices — host pack, h2d copies, and arena
    registration all overlap. Each shard's HBM footprint registers in
    its own ``als_shard{k}`` DeviceArena so attribution and leak checks
    stay per-shard truthful (and prove the item matrix is never whole on
    one device). Returns (device arrays dict, [(arena, alloc), ...])."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndev = plan.ndev
    if jax.process_count() > 1:
        # multi-process meshes cannot device_put another process's shard;
        # fall back to bulk sharded puts (identical content everywhere)
        shards = [_pack_shard(plan, d) for d in range(ndev)]
        out = {}
        for nm in shards[0]:
            stacked = np.stack([sh[nm] for sh in shards])
            spec = P("data", *([None] * (stacked.ndim - 1)))
            out[nm] = jax.device_put(stacked, NamedSharding(mesh, spec))
        return out, []

    devices = mesh.devices  # [data, model] grid
    arenas: list = []
    bufs: dict = {}

    def pack(d: int):
        return d, _pack_shard(plan, d)

    def upload(packed):
        d, arrs = packed
        dev_list = list(np.ravel(devices[d]))
        put = {nm: [jax.device_put(a[None], dev) for dev in dev_list]
               for nm, a in arrs.items()}
        arena = device_obs.arena(f"als_shard{d}")
        nbytes = sum(int(a.nbytes) for a in arrs.values())
        # + this shard's live factor rows and its transient slice buffer
        nbytes += (plan.ub + plan.ib + ndev * plan.w) * rank * 4
        arenas.append((arena, arena.register(nbytes, label=f"rank{rank}")))
        return put

    stager = transfer.ChunkStager(name="als_shard_stage")
    for _i, put in stager.stream(range(ndev), pack, upload=upload):
        for nm, arr_list in put.items():
            bufs.setdefault(nm, []).extend(arr_list)
    out = {}
    for nm, arr_list in bufs.items():
        per = arr_list[0]
        spec = P("data", *([None] * (per.ndim - 1)))
        out[nm] = jax.make_array_from_single_device_arrays(
            (ndev,) + per.shape[1:], NamedSharding(mesh, spec), arr_list)
    phases["shard_chunks"] = ndev
    phases["shard_stage_s"] = round(stager.staged_s, 3)
    phases["shard_wait_s"] = round(stager.wait_s, 3)
    phases["shard_overlap_frac"] = round(stager.overlap_frac(), 3)
    return out, arenas


#: Compiled sharded train programs, keyed by every static of the layout.
#: Module-level so warm re-dispatch (a retrain at the same shapes) reuses
#: the compiled executable — the retrace guard's zero-retrace contract.
_SHARDED_PROGRAMS: dict = {}


def _sharded_train_program(mesh, ndev: int, ub: int, ib: int, w: int,
                           rank: int, implicit: bool, scale: int,
                           exact: bool, has_dup: bool, n_users: int,
                           n_items: int):
    """Build (or fetch) the compiled SPMD train program for one sharded
    layout. Profiled as ``als_dense_spmd_rank{rank}`` with the shard
    count riding the bucket key: each (ndev, shapes) bucket compiles
    exactly once, and re-dispatch at a seen bucket must not retrace."""
    key = (mesh, ndev, ub, ib, w, rank, implicit, scale, exact, has_dup,
           n_users, n_items)
    prog = _SHARDED_PROGRAMS.get(key)
    if prog is not None:
        return prog

    from jax.sharding import PartitionSpec as P

    from predictionio_tpu.ops import collectives
    from predictionio_tpu.parallel.mesh import shard_map

    dots = _make_dots(implicit, exact, rank=rank)
    n_pairs = rank * (rank + 1) // 2
    ncols = n_pairs + rank + 1
    ci = (rank + 1) if implicit else (n_pairs + 1)
    cv = (n_pairs + rank) if implicit else rank
    nw = ndev * w
    hi = jax.lax.Precision.HIGHEST

    def gram(f):
        return jax.lax.dot_general(
            f, f, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=hi)

    def spmd_train(iters, items_l, vals_l, starts_l, k_l, send_l, uf_l,
                   itf_l, du, di, lambda_, alpha):
        # items_l/vals_l/starts_l/k_l/send_l/du/di: this shard's [1, ...]
        # slice — squeeze it. uf_l/itf_l partition their row dim directly
        # ([ub, r] / [ib, r]). ``iters`` is a traced scalar so the SAME
        # program serves the fused run and the per-iteration path.
        a = _scatter_block(items_l[0], vals_l[0], starts_l[0], k_l[0],
                           ub=ub, n_items=nw)
        send = send_l[0]
        du_sq = tuple(x[0] for x in du) if has_dup else None
        di_sq = tuple(x[0] for x in di) if has_dup else None

        def body(_i, carry):
            uf_l, itf_l = carry
            # ---- user half: gather only the item-factor slices this
            # shard's cells reference (the ALX slice exchange — never the
            # whole item matrix). Pad slots hold clamped garbage rows the
            # A block's zero cells and the corrections never touch.
            ys = collectives.gather_slices(itf_l, send, "data")
            ip, vp = _local_half_inputs(ys, rank, implicit)
            gi, gv = dots(a, ip, vp, ((1,), (0,)))
            corr = (_dup_correction(du_sq, ys, rank, ub, alpha, implicit)
                    if has_dup else None)
            # implicit XtX over a sharded fixed side: psum of per-shard
            # partial grams (zero-padded rows contribute nothing)
            xtx = (jax.lax.psum(gram(itf_l), "data") if implicit
                   else None)
            uf_l = _normal_eq_solve(uf_l, gi, gv, corr, None, lambda_,
                                    alpha, implicit, rank, scale, xtx=xtx)
            # ---- item half: contract this shard's cells into per-slice-
            # slot partial grams (+ slot-space corrections), route every
            # slot back to the shard owning its item row, scatter-add,
            # and solve locally — the gram accumulation never leaves the
            # owner shard un-reduced.
            ip2, vp2 = _local_half_inputs(uf_l, rank, implicit)
            d_gi, d_gv = dots(a, ip2, vp2, ((0,), (0,)))
            buf = jnp.concatenate([d_gi, d_gv], axis=1)
            if has_dup:
                buf = jnp.concatenate(
                    [buf, _dup_correction(di_sq, uf_l, rank, nw, alpha,
                                          implicit)], axis=1)
            acc = collectives.scatter_slices_add(buf, send, ib, "data")
            corr2 = acc[:, ci + cv:] if has_dup else None
            xtx2 = (jax.lax.psum(gram(uf_l), "data") if implicit
                    else None)
            itf_l = _normal_eq_solve(
                itf_l, acc[:, :ci], acc[:, ci:ci + cv], corr2, None,
                lambda_, alpha, implicit, rank, scale, xtx=xtx2)
            return uf_l, itf_l

        return jax.lax.fori_loop(0, iters, body, (uf_l, itf_l))

    dup_spec = (P("data", None),) * 4 if has_dup else P()
    fn = jax.jit(shard_map(
        spmd_train, mesh=mesh,
        in_specs=(P(), P("data", None), P("data", None), P("data", None),
                  P("data"), P("data", None, None), P("data", None),
                  P("data", None), dup_spec, dup_spec, P(), P()),
        out_specs=(P("data", None), P("data", None)),
        check_vma=False,
    ))
    prog = device_obs.profiled_program(
        f"als_dense_spmd_rank{rank}",
        flops=lambda iters, *a, **kw: float(iters) * iteration_flops(
            n_users, n_items, rank),
        # shard count rides the bucket key: each mesh size is its own
        # expected-compile bucket, and pio_device_dispatch_seconds stays
        # retrace-free across them
        bucket=lambda *a, **kw: (ndev, rank,
                                 device_obs.shape_bucket(*a)),
        sync=True,
    )(fn)
    if len(_SHARDED_PROGRAMS) >= 8:
        _SHARDED_PROGRAMS.pop(next(iter(_SHARDED_PROGRAMS)))
    _SHARDED_PROGRAMS[key] = prog
    return prog


#: Layout manifest magic for sharded checkpoints ("ALX").
_SHARDED_LAYOUT_MAGIC = 0x414C58


def _factor_slabs(arr, ndev: int, rows: int) -> list:
    """Per-shard host slabs of a row-sharded factor array, in shard
    order, fetched shard-by-shard (never materializing the matrix whole
    on any device)."""
    slabs: list = [None] * ndev
    try:
        for s in arr.addressable_shards:
            i0 = s.index[0].start or 0
            d = int(i0) // rows
            if slabs[d] is None:
                slabs[d] = np.asarray(s.data).reshape(rows, -1)
    except Exception:
        logger.debug("per-shard fetch failed; falling back to device_get",
                     exc_info=True)
    if any(s is None for s in slabs):
        full = np.asarray(jax.device_get(arr))
        slabs = [full[d * rows:(d + 1) * rows] for d in range(ndev)]
    return slabs


def load_sharded_resume(checkpointer, fingerprint: str, n_users: int,
                        n_items: int, rank: int):
    """(start_iter, user_f [n_users, r], item_f [n_items, r]) from the
    newest valid sharded checkpoint, or None. The per-shard slabs are
    concatenated and re-split for the CURRENT device count — resume
    across a different shard count is re-sharding, not a format
    mismatch."""
    got = checkpointer.load_latest(None, fingerprint=fingerprint)
    if got is None:
        return None
    step, state = got
    try:
        layout = np.asarray(state["layout"]).ravel()
        if (int(layout[0]) != _SHARDED_LAYOUT_MAGIC
                or [int(x) for x in layout[2:5]]
                != [n_users, n_items, rank]):
            logger.warning(
                "sharded ALS checkpoint layout %s does not match this "
                "run (%d users x %d items, rank %d) — starting fresh",
                layout.tolist(), n_users, n_items, rank)
            return None
        uf = np.concatenate(
            [np.asarray(s, np.float32) for s in state["user_shards"]]
        )[:n_users]
        itf = np.concatenate(
            [np.asarray(s, np.float32) for s in state["item_shards"]]
        )[:n_items]
    except Exception:
        logger.warning("unreadable sharded ALS checkpoint — starting "
                       "fresh", exc_info=True)
        return None
    if uf.shape != (n_users, rank) or itf.shape != (n_items, rank):
        return None
    return int(step) + 1, uf, itf


def _fetch_rows(arr, n: int, rows: int, ndev: int) -> np.ndarray:
    """Host [n, r] view of a row-sharded factor array via per-shard
    fetches (pad rows trimmed)."""
    return np.concatenate(_factor_slabs(arr, ndev, rows))[:n]


#: Layout/traffic stats of the most recent train_dense_sharded call:
#: ndev, w, slice_slots, ub, ib, gather_bytes_per_iter, imbalance,
#: replicated_item_bytes (what the old replicated layout would pin per
#: device), per_shard_hbm_bytes. Read by bench.py and the parity tests.
last_sharded_stats: dict = {}


def train_dense_sharded(ctx, params, ui, ii, ratings, n_users, n_items,
                        scale: int | None = None, callback=None,
                        resume=None, checkpoint=None):
    """Fully sharded SPMD dense training over the mesh ``data`` axis
    (ALX layout): users AND items row-shard across the axis, gram
    accumulation stays shard-local, and each iteration exchanges only
    the dedup'd opposite-side factor *slices* a shard's cells reference
    (ops/collectives.gather_slices / scatter_slices_add) — no device
    ever holds the item matrix whole. Returns (user_f [n_users, r],
    item_f [n_items, r]) as HOST arrays assembled from per-shard
    fetches.

    ``callback`` (it, user_f, item_f) runs per iteration on host views.
    ``resume`` = (start_iter, user_f, item_f) continues from global host
    factors. ``checkpoint`` (utils.checkpoint.TrainCheckpointSpec) saves
    per-shard factor slabs + a layout manifest every ``every``
    iterations and resumes from the newest valid one — re-sharding
    across a different device count on load."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from predictionio_tpu.models.als import _init_factors
    from predictionio_tpu.obs import runlog
    from predictionio_tpu.resilience import faults

    p = params
    mesh = ctx.mesh
    ndev = mesh.shape["data"]
    if not sharded_block_fits(ctx, n_users, n_items, len(ratings)):
        # the flat-cell scatter ids are int32; unlike the single-device
        # path (whose _BLOCK_BYTES split bounds ub*n_items), one-block-
        # per-device has no second split — wrap-around would silently
        # DROP ratings via the scatter's mode="drop"
        raise ValueError(
            "dense SPMD row-block out of bounds "
            f"({-(-n_users // ndev)} rows x {n_items} items per device); "
            "use solver='bucket' or more devices"
        )
    phases: dict = {}
    t0 = time.perf_counter()
    plan = _sharded_prepare(ui, ii, ratings, n_users, n_items, ndev,
                            scale=scale)
    phases["prepare_s"] = round(time.perf_counter() - t0, 3)
    runlog.phase("prepare", phases["prepare_s"])
    nw = ndev * plan.w
    if plan.ub * nw + plan.m >= 2**31:
        raise ValueError(
            "dense SPMD slice block out of bounds "
            f"({plan.ub} rows x {nw} slice slots per device); "
            "use solver='bucket' or more devices")

    rank, implicit = p.rank, p.implicit_prefs
    exact = p.gather_dtype == "float32"
    n_pairs = rank * (rank + 1) // 2
    ncols = n_pairs + rank + 1
    ci = (rank + 1) if implicit else (n_pairs + 1)
    cv = (n_pairs + rank) if implicit else rank
    # per-iteration cross-shard traffic: every shard sends [ndev, w, r]
    # f32 factor slices forward and [ndev, w, ci+cv(+ncols)] partial
    # grams back
    width_back = ci + cv + (ncols if plan.nd else 0)
    gather_bytes = 4 * ndev * ndev * plan.w * (rank + width_back)
    SHARD_GATHER_BYTES.observe(float(gather_bytes))
    SHARD_IMBALANCE.set(plan.imbalance)
    runlog.note("shard_imbalance", round(plan.imbalance, 3))
    runlog.note("shard_gather_bytes", int(gather_bytes))
    logger.info(
        "ALS(dense,SPMD): %d ratings -> %d x %d cells over %d shards "
        "(%d user rows x %d slice slots each, slice width %d, imbalance "
        "%.2fx), scale %d, rank %d",
        len(ratings), n_users, n_items, ndev, plan.ub, nw, plan.w,
        plan.imbalance, plan.scale, rank)

    t0 = time.perf_counter()
    dev_in, arenas = _stage_sharded_inputs(mesh, plan, rank, phases)
    phases["upload_densify_s"] = round(time.perf_counter() - t0, 3)
    runlog.phase("upload_densify", phases["upload_densify_s"])

    global last_sharded_stats
    last_sharded_stats = dict(
        ndev=ndev, w=plan.w, slice_slots=nw, ub=plan.ub, ib=plan.ib,
        gather_bytes_per_iter=int(gather_bytes),
        imbalance=round(plan.imbalance, 4),
        replicated_item_bytes=int(n_items) * rank * 4,
        per_shard_hbm_bytes=[int(a.bytes()) for a, _ in arenas],
    )

    ck = fp = None
    if checkpoint is not None:
        ck = checkpoint.checkpointer
        fp = checkpoint.fingerprint
        if resume is None and checkpoint.resume:
            got = load_sharded_resume(ck, fp, n_users, n_items, rank)
            if got is not None:
                resume = got
                logger.info(
                    "ALS(dense,SPMD): resuming from sharded checkpoint "
                    "at iteration %d (re-sharded to %d shards)",
                    got[0], ndev)

    data_ax = NamedSharding(mesh, P("data", None))
    up, ip_tot = ndev * plan.ub, ndev * plan.ib
    start_iter = 0
    # padding rows must be ZERO: they are never solved (count 0 keeps
    # them) and the psum'd XtX Gram term must not see garbage in them;
    # the PRNG stream matches the single-device path row for row
    uf_host = np.zeros((up, rank), np.float32)
    if_host = np.zeros((ip_tot, rank), np.float32)
    if resume is not None:
        start_iter, uf0, if0 = resume
        uf_host[:n_users] = np.asarray(uf0, np.float32)
        if_host[:n_items] = np.asarray(if0, np.float32)
    else:
        key = jax.random.PRNGKey(p.seed if p.seed is not None else 0)
        ku, ki = jax.random.split(key)
        uf_host[:n_users] = np.asarray(_init_factors(ku, n_users, rank))
        if_host[:n_items] = np.asarray(_init_factors(ki, n_items, rank))
    uf = jax.device_put(uf_host, data_ax)
    itf = jax.device_put(if_host, data_ax)

    prog = _sharded_train_program(
        mesh, ndev, plan.ub, plan.ib, plan.w, rank, implicit, plan.scale,
        exact, plan.nd > 0, n_users, n_items)
    if plan.nd:
        du = (dev_in["du_seg"], dev_in["du_nbr"], dev_in["du_cnt"],
              dev_in["du_val"])
        di = (dev_in["di_seg"], dev_in["di_nbr"], dev_in["di_cnt"],
              dev_in["di_val"])
    else:
        du = di = None
    args = (dev_in["items"], dev_in["vals"], dev_in["row_starts"],
            dev_in["k"], dev_in["send"])
    lam, al = float(p.lambda_), float(p.alpha)

    per_iter = (resume is not None or callback is not None
                or ck is not None or runlog.want_steps())
    # shard observatory (obs/shards.py): per-shard cell loads + the
    # dispatch metadata the byte replay scales by (a fused run is ONE
    # dispatch executing num_iterations loop steps)
    from predictionio_tpu.obs import shards as shard_obs

    spmd_name = f"als_dense_spmd_rank{rank}"
    shard_obs.OBSERVATORY.program_meta(
        spmd_name, shards=ndev, arena_prefix="als_shard",
        steps_per_dispatch=(1 if per_iter
                            else max(int(p.num_iterations) - start_iter,
                                     1)))
    shard_obs.OBSERVATORY.record_shard_load(
        spmd_name, [int(c) for c in plan.counts], kind="rating cells")
    t0 = time.perf_counter()
    try:
        if not per_iter:
            uf, itf = prog(int(p.num_iterations), *args, uf, itf, du, di,
                           lam, al)
        else:
            st = runlog.StepTimer("als_dense_spmd",
                                  total=p.num_iterations,
                                  start=start_iter, phase="solve")
            for it in range(start_iter, p.num_iterations):
                # the crash-safe-training chaos site: an error here is a
                # mid-train kill between checkpoint intervals
                faults.fault_point("train.iteration")
                uf, itf = prog(1, *args, uf, itf, du, di, lam, al)
                if callback is not None:
                    callback(it, _fetch_rows(uf, n_users, plan.ub, ndev),
                             _fetch_rows(itf, n_items, plan.ib, ndev))
                if ck is not None and ck.should_save(it):
                    state = {
                        "layout": np.asarray(
                            [_SHARDED_LAYOUT_MAGIC, ndev, n_users,
                             n_items, rank], np.int64),
                        "user_shards": _factor_slabs(uf, ndev, plan.ub),
                        "item_shards": _factor_slabs(itf, ndev, plan.ib),
                    }
                    ck.save(it, state, fingerprint=fp)
                st.step(it + 1, sync=itf)
    finally:
        for arena, alloc in arenas:
            arena.free(alloc)
    phases["solve_s"] = round(time.perf_counter() - t0, 3)
    if not per_iter:
        runlog.fused_steps("als_dense_spmd", p.num_iterations,
                           phases["solve_s"], synced=True)
    ex_frac = shard_obs.OBSERVATORY.exchange_frac(spmd_name)
    if ex_frac is not None:
        runlog.note("exchange_frac", round(ex_frac, 4))
        last_sharded_stats["exchange_frac"] = round(ex_frac, 4)
    snap = shard_obs.OBSERVATORY.snapshot(spmd_name)
    if snap is not None:
        last_sharded_stats["collective_bytes_per_iter"] = snap[
            "bytesPerStep"]
    global last_train_phases
    last_train_phases = phases
    return (_fetch_rows(uf, n_users, plan.ub, ndev),
            _fetch_rows(itf, n_items, plan.ib, ndev))
