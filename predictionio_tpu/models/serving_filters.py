"""Shared serve-time filter helpers for recommendation-style templates.

The similarproduct and ecommerce templates apply the same white/black-list +
category filters before their top-k kernels (ref:
examples/scala-parallel-ecommercerecommendation/.../ALSAlgorithm.scala:
148-267 and examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala);
all filters fold into ONE boolean exclusion mask handed to the XLA kernel,
keeping the device path a single masked matmul + top_k.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

from predictionio_tpu.data.bimap import BiMap


def build_exclusion_mask(
    item_ids: BiMap,
    banned: Iterable[str] = (),
    black_list: Sequence[str] | None = None,
    white_list: Sequence[str] | None = None,
    categories: Sequence[str] | None = None,
    item_categories: Mapping[str, tuple[str, ...]] | None = None,
) -> np.ndarray:
    """[1, n_items] bool mask; True → excluded from recommendation."""
    n_items = len(item_ids)
    exclude = np.zeros((1, n_items), bool)

    def ban(item: str) -> None:
        idx = item_ids.get(item)
        if idx is not None:
            exclude[0, idx] = True

    for item in banned:
        ban(item)
    if black_list:
        for item in black_list:
            ban(item)
    if white_list is not None:
        allowed = {item_ids(i) for i in white_list if i in item_ids}
        mask = np.ones(n_items, bool)
        if allowed:
            mask[list(allowed)] = False
        exclude[0] |= mask
    if categories is not None:
        want = set(categories)
        cats_by_item = item_categories or {}
        for item, idx in item_ids.to_dict().items():
            if not (set(cats_by_item.get(item, ())) & want):
                exclude[0, idx] = True
    return exclude


def topk_to_item_scores(scores_row, idx_row, item_ids: BiMap, num: int,
                        make_item_score):
    """Decode a top-k kernel row into template ItemScore objects, dropping
    -inf (fully-excluded) entries."""
    out = []
    for s, i in zip(np.asarray(scores_row), np.asarray(idx_row)):
        if np.isfinite(s):
            out.append(make_item_score(item_ids.inverse(int(i)), float(s)))
    return tuple(out[:num])
