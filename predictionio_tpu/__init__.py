"""predictionio_tpu — a TPU-native machine learning server.

A from-scratch re-design of the capabilities of PredictionIO
(reference: methodmill/PredictionIO): REST event collection with pluggable
storage, engines composed from pluggable DASE components (DataSource,
Preparator, Algorithm(s), Serving, Evaluation), a ``pio``-style CLI, a
deployed REST query server, and metric-driven evaluation/tuning — with the
Spark/MLlib compute substrate replaced by a JAX/XLA runtime: training runs
as pjit-sharded XLA programs over a `jax.sharding.Mesh` with ICI collectives
in place of Spark shuffles, and trained parameters live in HBM behind a
batched XLA predict path.

Layer map (mirrors SURVEY.md §1 of the reference):

  L0  parallel/   device mesh + collectives        (ref: Apache Spark)
  L1  data/storage/  event + metadata storage      (ref: data/.../storage)
  L2  data/api/   REST event server                (ref: data/.../api)
  L3  core/       DASE controller API              (ref: core/.../controller)
  L4  workflow/   train/eval/deploy runtime        (ref: core/.../workflow)
  L5  tools/      CLI + ops                        (ref: tools/)
  L6  templates/  engine templates                 (ref: examples/)
  L7  models/     algorithm library                (ref: e2/)
"""

__version__ = "0.1.0"
