"""Overlapped host↔device transfer pipeline.

Round-5 phase accounting (BENCH_r05) showed a cold ML-20M ALS train spends
38.5 s uploading+densifying and 3.8 s preparing strictly *before* the
36.3 s solve starts, plus 1.7 s of serialized readback after it — over
half the cold wall-clock is transfer that never overlaps compute. ALX
(arxiv 2112.02194) and Google's ads-training infrastructure paper (arxiv
2501.10546) both identify overlapped input staging as the difference
between transfer-bound and compute-bound TPU matrix-factorization
training. This module is the reusable half of that fix:

:class:`ChunkStager`
    A chunked, double-buffered host→device stager: a background producer
    thread walks the chunk stream and a small worker pool packs (and
    optionally uploads) chunk ``k+1`` while the caller consumes chunk
    ``k`` — e.g. enqueues its device densify. In-flight chunks are
    bounded by a slot semaphore (``PIO_TRANSFER_SLOTS``), so host staging
    buffers and un-consumed device uploads can never pile up unbounded.
    Chunks are yielded strictly in order; a worker exception propagates
    to the consumer (never a hang, never a silent partial result), and a
    consumer that stops early (error or ``break``) drains every in-flight
    slot before the generator closes.

:func:`async_readback`
    Chunked device→host readback: every row-chunk's ``copy_to_host_async``
    is started before the first blocking fetch, so the copies run behind
    whatever device work is still queued (e.g. the final solve half-step)
    and behind each other.

Chunk sizing rides ``PIO_TRANSFER_CHUNK_MB`` (MiB of payload per chunk);
both tunables are read at call time so tests and operators can adjust a
live process. The ``pio_transfer_*`` metrics (chunk bytes, per-stage
seconds, consumer queue-wait seconds, in-flight slots) land in the
process-global obs registry, labelled by pipeline name.
"""

from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

from predictionio_tpu.obs import REGISTRY, device as device_obs, trace

logger = logging.getLogger(__name__)

#: HBM arena for staged-but-not-yet-consumed upload chunks: the slot
#: semaphore bounds them, and this makes the bound's actual byte cost
#: visible next to the other device-memory owners
#: (``pio_device_hbm_bytes{arena="transfer_staging"}``).
_STAGING_ARENA = device_obs.arena("transfer_staging")


def _free_staged_alloc(fut) -> None:
    """Future done-callback for abandoned chunks whose worker outlived
    the cancellation drain's deadline: release the arena registration
    whenever the upload finally lands (no-op for failed stages)."""
    try:
        if fut.cancelled() or fut.exception() is not None:
            return
        _STAGING_ARENA.free(fut.result()[1])
    except Exception:
        logger.debug("abandoned-chunk arena free failed", exc_info=True)

__all__ = [
    "ChunkStager",
    "async_readback",
    "begin_readback",
    "iter_chunks",
    "stage_training_arrays",
    "transfer_chunk_bytes",
    "transfer_slots",
]

#: Default MiB per staged chunk (``PIO_TRANSFER_CHUNK_MB``). 512 MiB of
#: densified A-cells splits ML-20M (~3.7 GB) into ~8 chunks — enough
#: granularity that pack/upload of chunk k+1 hides behind the device
#: densify of chunk k, while each scatter stays far above the TPU
#: scatter-strategy cliff (docs/perf.md §3).
DEFAULT_CHUNK_MB = 512

#: Default in-flight chunk slots (``PIO_TRANSFER_SLOTS``): 2 = classic
#: double buffering (one chunk being consumed, one being staged).
DEFAULT_SLOTS = 2

#: Byte-size buckets: 1 KiB → 4 GiB, ×2 per bucket.
BYTES_BUCKETS: tuple[float, ...] = tuple(1024.0 * 2.0**i for i in range(23))

#: Host seconds per chunk, by pipeline and stage (pack/upload/readback).
STAGE_SECONDS = REGISTRY.histogram(
    "pio_transfer_stage_seconds",
    "Host seconds spent per transfer-pipeline chunk, by stage",
    labels=("pipeline", "stage"),
)

#: Seconds the consumer blocked waiting for the next staged chunk — the
#: un-overlapped remainder of the pipeline (0 on a perfectly hidden
#: stage; equals the full stage time when nothing overlaps).
QUEUE_WAIT_SECONDS = REGISTRY.histogram(
    "pio_transfer_queue_wait_seconds",
    "Seconds the transfer-pipeline consumer blocked awaiting a chunk",
    labels=("pipeline",),
)

#: Payload bytes per staged chunk.
CHUNK_BYTES = REGISTRY.histogram(
    "pio_transfer_chunk_bytes",
    "Host payload bytes per transfer-pipeline chunk",
    labels=("pipeline",),
    buckets=BYTES_BUCKETS,
)

#: Currently-held in-flight chunk slots per pipeline.
INFLIGHT_SLOTS = REGISTRY.gauge(
    "pio_transfer_inflight_slots",
    "Transfer-pipeline chunk slots currently in flight",
    labels=("pipeline",),
)


def transfer_chunk_bytes() -> int:
    """Target payload bytes per chunk (``PIO_TRANSFER_CHUNK_MB``), read
    at call time so a live process can be retuned."""
    mb = float(os.environ.get("PIO_TRANSFER_CHUNK_MB", DEFAULT_CHUNK_MB))
    return max(int(mb * 2**20), 1)


def transfer_slots() -> int:
    """In-flight chunk bound (``PIO_TRANSFER_SLOTS``), floor 1."""
    return max(int(os.environ.get("PIO_TRANSFER_SLOTS", DEFAULT_SLOTS)), 1)


def iter_chunks(items: Iterable, n: int) -> Iterator[list]:
    """Lists of up to ``n`` consecutive items — the stager's unit for
    record streams (event scans). Pulls lazily: inside a stager stream
    the pulls happen on the producer thread, off the consumer's path."""
    if n < 1:
        raise ValueError("chunk size must be >= 1")
    it = iter(items)
    while True:
        chunk = list(itertools.islice(it, n))
        if not chunk:
            return
        yield chunk


def _nbytes(staged: Any) -> int:
    """Payload bytes of a packed chunk: any nesting of sequences/dicts of
    objects with ``nbytes`` (numpy or device arrays)."""
    if staged is None:
        return 0
    nb = getattr(staged, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(staged, dict):
        return sum(_nbytes(v) for v in staged.values())
    if isinstance(staged, (tuple, list)):
        return sum(_nbytes(v) for v in staged)
    return 0


class _Cancelled(Exception):
    """Raised inside a worker when the stream was closed under it — never
    surfaces to the consumer (the drain swallows it)."""


_DONE = object()


class ChunkStager:
    """Ordered, slot-bounded background staging of a chunk stream.

    One stager instance carries the counters for one pipeline run
    (``staged_s``/``wait_s``/``chunks``/``bytes``/``max_inflight``), so a
    caller can compute its overlap after the stream completes; the
    process-global ``pio_transfer_*`` metrics are recorded as well,
    labelled with ``name``.

    Slot semantics: a slot is held from just before a chunk's pack starts
    until the consumer finishes the loop body that received it (i.e. has
    *dispatched* whatever consumes the chunk). With device uploads the
    bound therefore covers every chunk whose host staging buffers are
    alive or whose device consumption has not yet been enqueued — the
    quantity that must stay bounded for host RAM and HBM staging alike.
    """

    def __init__(self, slots: int | None = None, workers: int | None = None,
                 name: str = "stager"):
        self.slots = int(slots) if slots is not None else transfer_slots()
        if self.slots < 1:
            raise ValueError("ChunkStager needs at least one slot")
        # pack/upload are usually GIL-dropping (numpy slicing, device
        # puts); more workers than slots can never run, so cap there
        self.workers = (int(workers) if workers is not None
                        else min(self.slots, 2))
        self.name = name
        self.staged_s = 0.0  # summed worker seconds packing + uploading
        self.busy_s = 0.0  # WALL seconds with >= 1 worker staging (the
        # interval union — overlap_frac's denominator; summed worker
        # seconds would overstate hidden time whenever workers run
        # concurrently with each other instead of with the consumer)
        self.wait_s = 0.0  # consumer seconds blocked on the queue
        self.chunks = 0
        self.bytes = 0
        self.max_inflight = 0
        self._inflight = 0
        self._busy_depth = 0
        self._busy_since = 0.0
        self._lock = threading.Lock()

    # -- slot bookkeeping (counter + gauge + high-water mark) ---------------

    def _slot_taken(self) -> None:
        with self._lock:
            self._inflight += 1
            self.max_inflight = max(self.max_inflight, self._inflight)
        INFLIGHT_SLOTS.inc(pipeline=self.name)

    def _slot_freed(self, sem: threading.Semaphore) -> None:
        with self._lock:
            self._inflight -= 1
        INFLIGHT_SLOTS.dec(pipeline=self.name)
        sem.release()

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _busy_enter(self) -> None:
        with self._lock:
            if self._busy_depth == 0:
                self._busy_since = time.perf_counter()
            self._busy_depth += 1

    def _busy_exit(self) -> None:
        with self._lock:
            self._busy_depth -= 1
            if self._busy_depth == 0:
                self.busy_s += time.perf_counter() - self._busy_since

    def overlap_frac(self) -> float:
        """Fraction of staging WALL time hidden behind the consumer:
        ``(busy_s - wait_s) / busy_s`` clamped to [0, 1] (0 with no
        staging at all). ``busy_s`` is the interval union over workers,
        so concurrent workers hiding only each other do not inflate the
        figure; consumer queue/future waits are exactly the staging
        seconds that could NOT be overlapped — the first chunk's wait is
        inherent pipeline fill and correctly counts against it."""
        if self.busy_s <= 0.0:
            return 0.0
        return max(0.0, min(1.0, (self.busy_s - self.wait_s)
                            / self.busy_s))

    # -- the stream ---------------------------------------------------------

    def stream(self, items: Iterable, pack: Callable[[Any], Any],
               upload: Callable[[Any], Any] | None = None):
        """Yield ``(index, staged)`` for every item, in order.

        ``pack(item)`` runs on a worker thread (host-side chunk build);
        ``upload(packed)``, when given, runs on the same worker right
        after (device puts — async in jax, so the worker returns once the
        transfer is enqueued). The producer thread advances ``items``
        itself, so an expensive source iterator (an event-store scan) is
        also off the consumer's thread.

        Error contract: an exception from ``items``, ``pack`` or
        ``upload`` re-raises at the consumer's next iteration — after the
        failing chunk's slot is returned, so nothing leaks. Closing the
        generator early (consumer ``break``/exception) stops the
        producer, waits out in-flight workers, and drains every held
        slot.
        """
        sem = threading.Semaphore(self.slots)
        stop = threading.Event()
        q: queue.Queue = queue.Queue()
        # trace handle of the CONSUMER (the traced request/train, if
        # any): worker threads retro-record their pack/upload spans
        # against it, so a transfer stall shows up on the waterfall
        tr_handle = trace.capture()

        def stage(item):
            from predictionio_tpu.resilience import faults

            if stop.is_set():
                raise _Cancelled()
            self._busy_enter()
            try:
                t0 = time.perf_counter()
                # payload-bearing chaos site: error/delay fire here, and
                # corrupt-shape truncates the packed chunk so downstream
                # shape validation gets exercised for real
                staged = faults.fault_point("transfer.pack", pack(item))
                t1 = time.perf_counter()
                STAGE_SECONDS.observe(t1 - t0, pipeline=self.name,
                                      stage="pack")
                nb = _nbytes(staged)
                if nb > 0:  # opaque payloads (event batches) have no
                    # byte size — all-zero samples would be histogram noise
                    CHUNK_BYTES.observe(float(nb), pipeline=self.name)
                trace.record_span(tr_handle, "transfer_pack", t0, t1 - t0,
                                  pipeline=self.name, bytes=nb)
                did_upload = False
                if upload is not None and not stop.is_set():
                    faults.fault_point("transfer.upload")
                    staged = upload(staged)
                    did_upload = True
                    t2 = time.perf_counter()
                    STAGE_SECONDS.observe(t2 - t1,
                                          pipeline=self.name,
                                          stage="upload")
                    trace.record_span(tr_handle, "transfer_upload", t1,
                                      t2 - t1, pipeline=self.name)
                dt = time.perf_counter() - t0
                with self._lock:
                    self.staged_s += dt
                    self.chunks += 1
                    self.bytes += nb
                # device memory is held from upload completion — a chunk
                # queued ahead of a busy consumer must show as attributed
                # staging bytes, not unattributed residual. Registered
                # LAST: an exception past this point would orphan the
                # registration (no free path ever sees the alloc)
                alloc = (_STAGING_ARENA.register(staged, label=self.name)
                         if did_upload else None)
                return staged, alloc
            finally:
                self._busy_exit()

        # stage workers are hand-rolled DAEMON threads, not a
        # ThreadPoolExecutor: executor workers are non-daemon and joined
        # by an atexit hook, so a worker wedged in a dead device link
        # would hang interpreter exit even after the drain below
        # abandoned it — exactly the hang the deadline exists to prevent
        tasks: queue.Queue = queue.Queue()

        def work():
            while True:
                task = tasks.get()
                if task is None:
                    return
                fut, item = task
                try:
                    fut.set_result(stage(item))
                except BaseException as e:
                    fut.set_exception(e)

        workers = [
            threading.Thread(
                target=work, daemon=True,
                name=f"pio-stager-{self.name}-{w}")
            for w in range(self.workers)
        ]
        for w in workers:
            w.start()

        def produce():
            try:
                for idx, item in enumerate(items):
                    while not sem.acquire(timeout=0.05):
                        if stop.is_set():
                            q.put(_DONE)
                            return
                    if stop.is_set():
                        sem.release()
                        q.put(_DONE)
                        return
                    self._slot_taken()
                    fut: Future = Future()
                    tasks.put((fut, item))
                    q.put((idx, fut))
                q.put(_DONE)
            except BaseException as e:  # the source iterator itself raised
                q.put(e)

        producer = threading.Thread(
            target=produce, daemon=True,
            name=f"pio-stager-{self.name}-producer")
        producer.start()
        def note_wait(t0: float) -> None:
            # consumer-blocked seconds: the queue get AND the wait for
            # the chunk's future — both are staging time the consumer
            # could not overlap (fut.result() on an unfinished chunk is
            # exactly the pipeline running dry)
            dt = time.perf_counter() - t0
            with self._lock:
                self.wait_s += dt
            QUEUE_WAIT_SECONDS.observe(dt, pipeline=self.name)
            if dt > 1e-3:  # only waits that could matter on a
                # waterfall; sub-ms polls would be span spam
                trace.record_span(tr_handle, "transfer_wait", t0, dt,
                                  pipeline=self.name)

        try:
            while True:
                t0 = time.perf_counter()
                msg = q.get()
                if msg is _DONE:
                    note_wait(t0)
                    return
                if isinstance(msg, BaseException):
                    note_wait(t0)
                    raise msg
                idx, fut = msg
                try:
                    # worker exceptions surface here
                    staged, alloc = fut.result()
                except BaseException:
                    note_wait(t0)
                    self._slot_freed(sem)
                    raise
                note_wait(t0)
                try:
                    yield idx, staged
                finally:
                    _STAGING_ARENA.free(alloc)
                    self._slot_freed(sem)
        finally:
            stop.set()
            # drain: slots of staged-but-unconsumed chunks must come back
            # even when the consumer bailed mid-stream. The whole drain
            # is deadline-bounded: a source iterator or worker stage
            # wedged in a blocking call must not convert a consumer
            # error into an indefinite hang — past the deadline the
            # daemon threads are abandoned (and said so), because
            # surfacing the caller's exception beats a perfect cleanup
            deadline = time.monotonic() + 10.0
            while True:
                # aliveness BEFORE the poll: an Empty seen after the
                # producer was already dead is conclusive (nothing can
                # enqueue anymore) — checking after would race a final
                # put-then-exit and leak that chunk's slot
                alive = producer.is_alive()
                try:
                    msg = q.get_nowait()
                except queue.Empty:
                    if alive and time.monotonic() < deadline:
                        producer.join(timeout=0.05)
                        continue
                    break
                if msg is _DONE or isinstance(msg, BaseException):
                    continue
                _idx, fut = msg
                try:
                    _staged, alloc = fut.result(
                        timeout=max(deadline - time.monotonic(), 0.05))
                    # abandoned chunk: its arrays die with the future,
                    # so the attribution must come down with them
                    _STAGING_ARENA.free(alloc)
                except BaseException:
                    # cancellation path: result is irrelevant — but a
                    # worker slow in upload() can still REGISTER after
                    # this timeout, so the free must chase the future
                    # (Allocation.free is idempotent; an exception
                    # result makes this a no-op)
                    fut.add_done_callback(_free_staged_alloc)
                self._slot_freed(sem)
            producer.join(timeout=max(deadline - time.monotonic(), 0.0))
            for _w in workers:
                tasks.put(None)
            if producer.is_alive():
                logger.warning(
                    "transfer stager %r: source/stage still blocked %.0fs "
                    "after cancellation; abandoning its daemon threads",
                    self.name, 10.0)
            # gauge reconciliation: any slot still held here belongs to
            # an abandoned chunk (the stream is over, nothing can free it
            # later) — a process-global gauge must not report phantom
            # in-flight slots for the rest of the process lifetime
            with self._lock:
                leaked, self._inflight = self._inflight, 0
            if leaked:
                INFLIGHT_SLOTS.dec(float(leaked), pipeline=self.name)
                logger.warning(
                    "transfer stager %r: reconciled %d abandoned "
                    "in-flight slot(s)", self.name, leaked)


def _row_chunks(a, chunk_bytes: int) -> list:
    """Row-major chunks of a device/host array, each ≲ ``chunk_bytes``
    (whole array when small, not row-splittable, or of unknown size)."""
    shape = getattr(a, "shape", None)
    nbytes = getattr(a, "nbytes", None)
    if not shape or nbytes is None or nbytes <= chunk_bytes:
        return [a]
    rows = int(shape[0])
    n_chunks = min(rows, -(-int(nbytes) // chunk_bytes))
    if n_chunks <= 1:
        return [a]
    per = -(-rows // n_chunks)
    return [a[i: i + per] for i in range(0, rows, per)]


def _stage_sharded_slabs(a: np.ndarray, sharding, name: str,
                         chunk_bytes: int) -> "jax.Array":
    """Per-shard slab staging for a DEVICE-SHARDED target: each shard's
    host slab packs and uploads straight to its owner device through the
    :class:`ChunkStager` (pack of shard ``d+1`` overlaps shard ``d``'s
    in-flight put — the ALS ``als_shard_stage`` pattern), then the
    single-device pieces assemble into one global array. The full host
    array is never resident on ANY device — the staging path for
    embedding tables bigger than one HBM (docs/perf.md §19)."""
    import jax

    if a.nbytes <= chunk_bytes:  # nothing to overlap
        return jax.device_put(a, sharding)
    items = list(sharding.addressable_devices_indices_map(a.shape).items())

    def pack(item):
        dev, idx = item
        return dev, np.ascontiguousarray(a[idx])

    def upload(packed):
        dev, slab = packed
        return jax.device_put(slab, dev)

    singles = [None] * len(items)
    stager = ChunkStager(name=name)
    for i, dev_arr in stager.stream(items, pack=pack, upload=upload):
        singles[i] = dev_arr
    return jax.make_array_from_single_device_arrays(
        a.shape, sharding, singles)


def stage_training_arrays(arrays: Sequence, sharding=None,
                          name: str = "train_inputs",
                          chunk_bytes: int | None = None) -> list:
    """Upload host training arrays through the :class:`ChunkStager`.

    The neural trainers' input-streaming path (ROADMAP item 3): each
    array is split into row chunks of ``PIO_TRANSFER_CHUNK_MB``, a
    worker packs (ascontiguousarray slice) and ``device_put``s chunk
    ``k+1`` while the consumer enqueues chunk ``k``'s device concat —
    the same pack/upload-overlaps-consume contract the ALS densify
    stream rides, with ``pio_transfer_*`` telemetry under ``name``.
    Arrays at or under one chunk skip the pipeline (a single put has
    nothing to overlap). Returns one device array per input, placed on
    ``sharding`` (None = default device). A ``sharding`` that actually
    splits the array (e.g. row-sharded embedding tables) takes the
    per-shard SLAB path instead: each shard streams straight to its
    owner device and the host array never lands whole on one device."""
    import jax
    import jax.numpy as jnp

    chunk_bytes = chunk_bytes or transfer_chunk_bytes()

    def put(a):
        return jax.device_put(a, sharding) if sharding is not None \
            else jnp.asarray(a)

    out = []
    for a in arrays:
        a = np.asarray(a)
        if (sharding is not None
                and not getattr(sharding, "is_fully_replicated", True)):
            out.append(_stage_sharded_slabs(a, sharding, name, chunk_bytes))
            continue
        parts = _row_chunks(a, chunk_bytes)
        if len(parts) <= 1:
            out.append(put(a))
            continue
        stager = ChunkStager(name=name)
        staged = [None] * len(parts)
        for idx, dev in stager.stream(
                parts, pack=np.ascontiguousarray, upload=put):
            staged[idx] = dev
        out.append(jnp.concatenate(staged, axis=0))
    return out


def begin_readback(arrays: Sequence, chunk_bytes: int | None = None,
                   name: str = "readback") -> Callable[[], list[np.ndarray]]:
    """Start an overlapped device→host fetch NOW; block for it later.

    Every row-chunk's ``copy_to_host_async`` is issued before this
    function returns, so the d2h copies run behind whatever device work
    is still queued — and behind whatever the CALLER does next. Returns a
    zero-arg resolver that performs the blocking gather and returns one
    ``np.ndarray`` per input, in order.

    This is the serving tick pipeline's half of the transfer layer: the
    micro-batcher dispatches tick N, begins its readback, and goes
    straight back to draining tick N+1 — the resolver runs on the
    batcher's finalizer thread, so tick N's copy wall-time overlaps tick
    N+1's dispatch instead of serializing the consumer.
    """
    chunk_bytes = chunk_bytes or transfer_chunk_bytes()
    staged: list[list] = []
    for a in arrays:
        parts = _row_chunks(a, chunk_bytes)
        for p in parts:
            start = getattr(p, "copy_to_host_async", None)
            if start is not None:
                start()
            CHUNK_BYTES.observe(float(getattr(p, "nbytes", 0) or 0),
                                pipeline=name)
        staged.append(parts)

    def resolve() -> list[np.ndarray]:
        from predictionio_tpu.resilience import faults

        faults.fault_point("transfer.readback")
        out: list[np.ndarray] = []
        t0 = time.perf_counter()
        for parts in staged:
            if len(parts) == 1:
                out.append(np.asarray(parts[0]))
            else:
                out.append(np.concatenate([np.asarray(p) for p in parts]))
        wait_s = time.perf_counter() - t0
        STAGE_SECONDS.observe(wait_s, pipeline=name, stage="readback")
        # the blocking tail of the d2h fetch, on the caller's trace (the
        # un-overlapped remainder the async copies could not hide)
        trace.record("transfer_readback", t0, wait_s, pipeline=name,
                     arrays=len(staged))
        return out

    return resolve


def async_readback(arrays: Sequence, chunk_bytes: int | None = None,
                   name: str = "readback") -> list[np.ndarray]:
    """Fetch device arrays to host numpy with overlapped, chunked copies.

    Every row-chunk's ``copy_to_host_async`` is issued before the first
    blocking ``np.asarray``, so the device→host copies run concurrently
    with each other AND with any device work still queued behind the
    arrays (jax only starts a copy once its array is ready — which is
    exactly what lets a user-factor fetch overlap the final item-factor
    half-step). Plain numpy arrays pass through untouched. Returns one
    ``np.ndarray`` per input, in order. (:func:`begin_readback` is the
    split form for callers that dispatch more device work between the
    issue and the blocking wait.)
    """
    return begin_readback(arrays, chunk_bytes, name)()
