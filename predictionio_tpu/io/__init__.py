"""Host↔device transfer pipeline (see :mod:`predictionio_tpu.io.transfer`).

The package exists because round-5 phase accounting (BENCH_r05) showed
over half of a cold ML-20M train was host↔device transfer that never
overlapped compute; the stager/readback primitives here are shared by the
dense ALS staging path and the data/view scan ETL.
"""

from predictionio_tpu.io.transfer import (  # noqa: F401
    ChunkStager,
    async_readback,
    iter_chunks,
    transfer_chunk_bytes,
    transfer_slots,
)

__all__ = [
    "ChunkStager",
    "async_readback",
    "iter_chunks",
    "transfer_chunk_bytes",
    "transfer_slots",
]
