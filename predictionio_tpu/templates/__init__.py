"""Built-in engine templates (L6).

Python re-designs of the reference's stock templates
(ref: examples/scala-parallel-{recommendation,classification,similarproduct,
ecommercerecommendation}) plus the new two-tower retrieval engine. Each
template module exposes ``engine_factory()`` and a default ``ENGINE_JSON``;
``pio template scaffold <name> <dir>`` copies a user-editable engine.py +
engine.json into place.
"""

# names listed here must have a module in this package; `pio template
# list/scaffold` trusts this tuple
TEMPLATE_NAMES = (
    "recommendation",
    "classification",
    "similarproduct",
    "ecommercerecommendation",
    "twotower",
    "sequentialrecommendation",
)
