"""Two-tower deep retrieval engine template (BASELINE.json configs[4]).

New engine family with no reference counterpart: trains the two-tower model
of :mod:`predictionio_tpu.models.two_tower` on view/buy interaction events
and serves top-N retrieval queries like the recommendation template. The
DASE surface is identical to the stock templates, so the whole workflow
(train/deploy/eval CLI, REST serving) applies unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.core import Engine, FirstServing, P2LAlgorithm, PDataSource, PPreparator
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.als import top_k_scores
from predictionio_tpu.models.serving_filters import topk_to_item_scores
from predictionio_tpu.models.two_tower import (
    TwoTowerModel,
    TwoTowerParams,
    embed_users,
    train_two_tower,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple[ItemScore, ...] = ()


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "twotower"
    event_names: tuple[str, ...] = ("view", "buy")


@dataclass
class TrainingData(SanityCheck):
    users: list[str]
    items: list[str]

    def sanity_check(self) -> None:
        if not self.users:
            raise ValueError("TrainingData is empty; ingest interaction events")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        users, items, _ratings, _names, _ = PEventStore.interaction_arrays(
            self.params.app_name,
            event_names=list(self.params.event_names),
            rating_property=None,
        )
        return TrainingData(users, items)


@dataclass
class PreparedData:
    user_ids: BiMap
    item_ids: BiMap
    user_idx: np.ndarray
    item_idx: np.ndarray


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        user_ids = BiMap.string_int(td.users)
        item_ids = BiMap.string_int(td.items)
        return PreparedData(
            user_ids, item_ids,
            user_ids.encode(td.users), item_ids.encode(td.items),
        )


@dataclass(frozen=True)
class AlgorithmParams(Params):
    embed_dim: int = 64
    hidden_dims: tuple[int, ...] = (128,)
    out_dim: int = 32
    batch_size: int = 1024
    steps: int = 1000
    learning_rate: float = 1e-3
    temperature: float = 0.05
    seed: int = 0
    # "adam" | "rowwise_adam" (per-row second moment on the embedding
    # tables: ~15% faster steps at near-Adam quality — models/two_tower)
    optimizer: str = "adam"


@dataclass
class RetrievalModel:
    tt: TwoTowerModel
    user_ids: BiMap
    item_ids: BiMap


class TwoTowerAlgorithm(P2LAlgorithm):
    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    def train(self, ctx: ComputeContext, pd: PreparedData) -> RetrievalModel:
        p = self.params
        tt = train_two_tower(
            ctx,
            pd.user_idx,
            pd.item_idx,
            n_users=len(pd.user_ids),
            n_items=len(pd.item_ids),
            p=TwoTowerParams(
                embed_dim=p.embed_dim,
                hidden_dims=tuple(p.hidden_dims),
                out_dim=p.out_dim,
                batch_size=p.batch_size,
                steps=p.steps,
                learning_rate=p.learning_rate,
                temperature=p.temperature,
                seed=p.seed,
                optimizer=p.optimizer,
            ),
        )
        return RetrievalModel(tt, pd.user_ids, pd.item_ids)

    def predict(self, model: RetrievalModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: RetrievalModel, queries):
        """Micro-batched serving: ONE top_k_scores call for every known
        user in the drained batch (the query server coalesces concurrent
        requests through this, workflow/batching.py)."""
        out = []
        known = []
        for i, q in queries:
            uidx = model.user_ids.get(q.user)
            if uidx is None:
                out.append((i, PredictedResult(())))
            else:
                known.append((i, q, uidx))
        if known:
            qv = embed_users(
                model.tt, np.array([u for _, _, u in known], np.int32)
            )
            k = min(max(q.num for _, q, _ in known), len(model.item_ids))
            scores, idx = top_k_scores(qv, model.tt.item_embeddings, k)
            for row, (i, q, _u) in enumerate(known):
                out.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )
        return out


class Serving(FirstServing):
    pass


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"twotower": TwoTowerAlgorithm},
        serving_class=Serving,
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Two-tower deep retrieval",
    "engineFactory": "predictionio_tpu.templates.twotower:engine_factory",
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [
        {"name": "twotower",
         "params": {"embed_dim": 64, "out_dim": 32, "steps": 1000,
                    "batch_size": 1024, "seed": 0}}
    ],
}
