"""Two-tower deep retrieval engine template (BASELINE.json configs[4]).

New engine family with no reference counterpart: trains the two-tower model
of :mod:`predictionio_tpu.models.two_tower` on view/buy interaction events
and serves top-N retrieval queries like the recommendation template. The
DASE surface is identical to the stock templates, so the whole workflow
(train/deploy/eval CLI, REST serving) applies unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.core import Engine, FirstServing, P2LAlgorithm, PDataSource, PPreparator
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.als import top_k_scores
from predictionio_tpu.models.serving_filters import topk_to_item_scores
from predictionio_tpu.models.two_tower import (
    TwoTowerModel,
    TwoTowerParams,
    embed_users,
    fold_in_two_tower,
    train_two_tower,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple[ItemScore, ...] = ()


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "twotower"
    event_names: tuple[str, ...] = ("view", "buy")


@dataclass
class TrainingData(SanityCheck):
    users: list[str]
    items: list[str]

    def sanity_check(self) -> None:
        if not self.users:
            raise ValueError("TrainingData is empty; ingest interaction events")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        users, items, _ratings, _names, _ = PEventStore.interaction_arrays(
            self.params.app_name,
            event_names=list(self.params.event_names),
            rating_property=None,
        )
        return TrainingData(users, items)

    def delta_source(self):
        """Continuous-training protocol (train/continuous.py): the same
        event names the training scan reads; interactions are implicit
        (no rating property), so every delta row carries weight 1.0 —
        exactly what ``interaction_arrays(rating_property=None)``
        produces."""
        from predictionio_tpu.train.continuous import DeltaSpec

        return DeltaSpec(
            app_name=self.params.app_name,
            event_names=tuple(self.params.event_names),
            rating_property=None,
            default_rating=1.0,
        )


@dataclass
class PreparedData:
    user_ids: BiMap
    item_ids: BiMap
    user_idx: np.ndarray
    item_idx: np.ndarray


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        user_ids = BiMap.string_int(td.users)
        item_ids = BiMap.string_int(td.items)
        return PreparedData(
            user_ids, item_ids,
            user_ids.encode(td.users), item_ids.encode(td.items),
        )


@dataclass(frozen=True)
class AlgorithmParams(Params):
    embed_dim: int = 64
    hidden_dims: tuple[int, ...] = (128,)
    out_dim: int = 32
    batch_size: int = 1024
    steps: int = 1000
    learning_rate: float = 1e-3
    temperature: float = 0.05
    seed: int = 0
    # "adam" | "rowwise_adam" (per-row second moment on the embedding
    # tables: ~15% faster steps at near-Adam quality — models/two_tower)
    optimizer: str = "adam"
    # sparse embedding updates: optimizer traffic O(batch) touched rows
    # instead of the full [n, d] tables (models/two_tower, perf.md §17)
    sparse_update: bool = True


@dataclass
class RetrievalModel:
    tt: TwoTowerModel
    user_ids: BiMap
    item_ids: BiMap


class TwoTowerAlgorithm(P2LAlgorithm):
    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    def train(self, ctx: ComputeContext, pd: PreparedData) -> RetrievalModel:
        p = self.params
        tt = train_two_tower(
            ctx,
            pd.user_idx,
            pd.item_idx,
            n_users=len(pd.user_ids),
            n_items=len(pd.item_ids),
            p=TwoTowerParams(
                embed_dim=p.embed_dim,
                hidden_dims=tuple(p.hidden_dims),
                out_dim=p.out_dim,
                batch_size=p.batch_size,
                steps=p.steps,
                learning_rate=p.learning_rate,
                temperature=p.temperature,
                seed=p.seed,
                optimizer=p.optimizer,
                sparse_update=p.sparse_update,
            ),
        )
        return RetrievalModel(tt, pd.user_ids, pd.item_ids)

    def predict(self, model: RetrievalModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: RetrievalModel, queries):
        """Micro-batched serving: ONE top_k_scores call for every known
        user in the drained batch (the query server coalesces concurrent
        requests through this, workflow/batching.py)."""
        out = []
        known = []
        for i, q in queries:
            uidx = model.user_ids.get(q.user)
            if uidx is None:
                out.append((i, PredictedResult(())))
            else:
                known.append((i, q, uidx))
        if known:
            qv = embed_users(
                model.tt, np.array([u for _, _, u in known], np.int32)
            )
            k = min(max(q.num for _, q, _ in known), len(model.item_ids))
            scores, idx = top_k_scores(qv, model.tt.item_embeddings, k)
            for row, (i, q, _u) in enumerate(known):
                out.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )
        return out

    # -- device-resident serving protocol (ROADMAP item 3) -------------------

    def pin_serving_state(self, model: RetrievalModel,
                          max_batch: int = 64) -> int:
        """Deploy-time HBM promotion: the precomputed user-query and
        item-corpus embedding matrices pin device-resident
        (``serving_models`` arena) — the two-tower serving tick is then
        exactly the ALS fused tick shape (gather→MIPS→mask→top-k over
        pinned catalogs). Returns pinned bytes (0 = host placement)."""
        from predictionio_tpu.models.als import pin_serving_factors

        return pin_serving_factors(
            model.tt.user_embeddings, model.tt.item_embeddings,
            max_batch=max_batch)

    def batch_predict_deferred(self, model: RetrievalModel, queries):
        """Device-resident serving tick for the item tower: the user-row
        gather, MIPS against the pinned corpus and top-k run as ONE
        fused device program (models/als.serve_top_k_batched — the
        precomputed towers make the two-tower tick ALS-shaped), with the
        blocking readback deferred to the server's finalizer thread.
        Returns None when the fused route does not apply (host
        placement, no known users) — the server falls back to
        :meth:`batch_predict`; resolved results are exactly the host
        route's (parity pinned in tests/test_two_tower.py)."""
        from predictionio_tpu.models.als import (
            serve_top_k_batched,
            serving_tick_on_device,
        )

        known = [(i, q) for i, q in queries if q.user in model.user_ids]
        if not known:
            return None
        n_items = len(model.item_ids)
        if not serving_tick_on_device(
                len(known), n_items, model.tt.item_embeddings.shape[1]):
            return None
        uidx = np.array([model.user_ids(q.user) for _, q in known],
                        np.int32)
        k = min(max(q.num for _, q in known), n_items)
        finalize = serve_top_k_batched(
            model.tt.user_embeddings, model.tt.item_embeddings, uidx, k)
        if finalize is None:
            return None
        out = [(i, PredictedResult(())) for i, q in queries
               if q.user not in model.user_ids]

        def resolve():
            scores, idx = finalize()
            res = list(out)
            for row, (i, q) in enumerate(known):
                res.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )
            return res

        return resolve

    # -- continuous-training fold-in (ROADMAP item 2, neural analog) ---------

    @staticmethod
    def _extended_ids(ids: BiMap, delta) -> BiMap:
        """First-appearance-order extension — the ONE shared rule
        (train/foldin.extended_ids) the trainer's encoded snapshot
        mirrors."""
        from predictionio_tpu.train.foldin import extended_ids

        return extended_ids(ids, delta)

    def fold_in_ready(self, model: RetrievalModel, data) -> bool:
        """Cheap pre-check: a delta minting more than
        ``PIO_FOLDIN_MAX_FRACTION`` new entities of either catalog is
        not "incremental" — the exact full retrain wins."""
        from predictionio_tpu.train import foldin as foldin_mod

        delta_users = set(data.delta_users)
        delta_items = set(data.delta_items)
        if not delta_users:
            return False
        new_u = sum(1 for u in delta_users if u not in model.user_ids)
        new_i = sum(1 for i in delta_items if i not in model.item_ids)
        frac = foldin_mod.max_fraction()
        if new_u > frac * (len(model.user_ids) + new_u) \
                or new_i > frac * (len(model.item_ids) + new_i):
            return False
        return True

    def fold_in(self, ctx: ComputeContext, model: RetrievalModel,
                data) -> RetrievalModel:
        """One neural fold-in generation: extend the id maps with the
        delta's unseen entities, warm-start their embedding rows
        (mean-of-neighbors init + a few sparse-update steps over the
        delta — models/two_tower.fold_in_two_tower) and recompute ONLY
        the new entities' serving-corpus rows. Existing embedding rows,
        the MLP, and existing corpus rows are byte-identical to the
        parent's (pinned in tests/test_foldin.py) — so
        ``fold_in_ready()`` stops being ALS-only."""
        user_ids = self._extended_ids(model.user_ids, data.delta_users)
        item_ids = self._extended_ids(model.item_ids, data.delta_items)
        delta_u = user_ids.encode(data.delta_users).astype(np.int32)
        delta_i = item_ids.encode(data.delta_items).astype(np.int32)
        tt = fold_in_two_tower(
            model.tt, delta_u, delta_i, len(user_ids), len(item_ids))
        return RetrievalModel(tt, user_ids, item_ids)


class Serving(FirstServing):
    pass


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"twotower": TwoTowerAlgorithm},
        serving_class=Serving,
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Two-tower deep retrieval",
    "engineFactory": "predictionio_tpu.templates.twotower:engine_factory",
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [
        {"name": "twotower",
         "params": {"embed_dim": 64, "out_dim": 32, "steps": 1000,
                    "batch_size": 1024, "seed": 0}}
    ],
}
