"""E-commerce recommendation engine template.

Re-design of the reference's scala-parallel-ecommercerecommendation
template (ref: examples/scala-parallel-ecommercerecommendation/
train-with-rate-event/src/main/scala/ALSAlgorithm.scala:148-299): implicit
ALS on view/buy events with SERVE-TIME business filters — at predict time
the algorithm reads the event store for the latest ``$set`` of the
``constraint`` entity's ``unavailableItems`` (ref :194-221), merges query
white/black lists plus the user's recently seen items into an exclusion
set, and for unknown users falls back to recommending near their recent
views (``predictNewUser``, ref :285).

This is the template that exercises LEventStore on the query path. The
XLA-side design keeps predict a single batched matmul+top_k: all filters
are folded host-side into one boolean exclusion mask passed to the kernel —
no host callbacks inside jit.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass

import numpy as np

from predictionio_tpu.core import Engine, FirstServing, P2LAlgorithm, PDataSource, PPreparator
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import LEventStore, PEventStore
from predictionio_tpu.models.als import ALS, ALSParams, top_k_cosine, top_k_scores
from predictionio_tpu.models.serving_filters import (
    build_exclusion_mask,
    topk_to_item_scores,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    categories: tuple[str, ...] | None = None
    whiteList: tuple[str, ...] | None = None
    blackList: tuple[str, ...] | None = None


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple[ItemScore, ...] = ()


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "ecommerce"


@dataclass
class TrainingData(SanityCheck):
    users: list[str]
    items: list[str]
    events: list[str]  # per-row event name (view / buy)
    item_categories: dict[str, tuple[str, ...]]

    def sanity_check(self) -> None:
        if not self.users:
            raise ValueError("TrainingData is empty; ingest view/buy events first")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        app = self.params.app_name
        users, items, names = [], [], []
        for e in PEventStore.find(app, event_names=["view", "buy"]):
            if e.target_entity_id is not None:
                users.append(e.entity_id)
                items.append(e.target_entity_id)
                names.append(e.event)
        categories = {}
        for item_id, pm in PEventStore.aggregate_properties(app, "item").items():
            cats = pm.get_opt("categories", list)
            if cats:
                categories[item_id] = tuple(str(c) for c in cats)
        return TrainingData(users, items, names, categories)


@dataclass
class PreparedData:
    td: TrainingData


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        return PreparedData(td)


@dataclass(frozen=True)
class AlgorithmParams(Params):
    app_name: str = "ecommerce"
    rank: int = 10
    numIterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = None
    buy_weight: float = 5.0  # buys count more than views
    unseen_only: bool = True  # exclude items the user has seen
    seen_events: tuple[str, ...] = ("view", "buy")
    similar_events: tuple[str, ...] = ("view",)  # cold-start basis
    #: TTL (seconds) for the serve-time read of the GLOBAL
    #: constraint/unavailableItems entity. The default 0 matches the
    #: reference exactly — every query re-reads the constraint, so an
    #: operator's $set takes effect on the very next prediction
    #: (ref :194-221). Setting a small TTL keeps the event store off the
    #: per-query hot path under load (SURVEY §7 hard part (c):
    #: "prefetch/cache constraint entities host-side") at the cost of
    #: constraint changes landing within the TTL instead of instantly.
    constraint_cache_seconds: float = 0.0


@dataclass
class ECommModel:
    user_features: np.ndarray
    item_features: np.ndarray
    user_ids: BiMap
    item_ids: BiMap
    item_categories: dict[str, tuple[str, ...]]


class ECommAlgorithm(P2LAlgorithm):
    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    def train(self, ctx: ComputeContext, pd: PreparedData) -> ECommModel:
        td = pd.td
        weights: dict[tuple[str, str], float] = defaultdict(float)
        for u, i, name in zip(td.users, td.items, td.events):
            weights[(u, i)] += (
                self.params.buy_weight if name == "buy" else 1.0
            )
        users = [u for u, _ in weights]
        items = [i for _, i in weights]
        ratings = np.fromiter(weights.values(), np.float32, count=len(weights))
        user_ids = BiMap.string_int(users)
        item_ids = BiMap.string_int(items)
        als = ALS(
            ctx,
            ALSParams(
                rank=self.params.rank,
                num_iterations=self.params.numIterations,
                lambda_=self.params.lambda_,
                implicit_prefs=True,
                alpha=self.params.alpha,
                seed=self.params.seed,
            ),
        )
        factors = als.train(
            user_ids.encode(users), item_ids.encode(items), ratings,
            n_users=len(user_ids), n_items=len(item_ids),
        )
        return ECommModel(
            factors.user_features, factors.item_features, user_ids, item_ids,
            td.item_categories,
        )

    # -- serve-time filters (ref: ALSAlgorithm.scala:148-267) ---------------
    def _unavailable_items(self) -> set[str]:
        """Latest $set on the 'constraint/unavailableItems' entity
        (ref :194-221), cached for ``constraint_cache_seconds``."""
        ttl = self.params.constraint_cache_seconds
        if ttl > 0:
            import time as _time

            cached = getattr(self, "_unavail_cache", None)
            now = _time.monotonic()
            if cached is not None and now - cached[0] < ttl:
                return cached[1]
            val = self._read_unavailable_items()
            self._unavail_cache = (now, val)
            return val
        return self._read_unavailable_items()

    def _read_unavailable_items(self) -> set[str]:
        try:
            events = list(
                LEventStore.find_by_entity(
                    self.params.app_name,
                    entity_type="constraint",
                    entity_id="unavailableItems",
                    event_names=["$set"],
                    limit=1,
                    latest=True,
                )
            )
        except ValueError:
            return set()
        if not events:
            return set()
        items = events[0].properties.get_opt("items", list) or []
        return {str(i) for i in items}

    def _seen_items(self, user: str) -> set[str]:
        """Items the user has interacted with (ref :154-190 seenItems)."""
        if not self.params.unseen_only:
            return set()
        try:
            events = LEventStore.find_by_entity(
                self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.seen_events),
            )
        except ValueError:
            return set()
        return {e.target_entity_id for e in events if e.target_entity_id}

    def _recent_items(self, user: str, n: int = 10) -> list[str]:
        """Recently viewed items for cold-start (ref predictNewUser :285)."""
        try:
            events = LEventStore.find_by_entity(
                self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.similar_events),
                limit=n,
                latest=True,
            )
        except ValueError:
            return []
        return [e.target_entity_id for e in events if e.target_entity_id]

    def _exclusion_mask(self, model: ECommModel, query: Query,
                        user: str) -> np.ndarray:
        return build_exclusion_mask(
            model.item_ids,
            banned=(*self._unavailable_items(), *self._seen_items(user)),
            black_list=query.blackList,
            white_list=query.whiteList,
            categories=query.categories,
            item_categories=model.item_categories,
        )

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def _prep_batch(self, model: ECommModel, queries):
        """Per-query host work for one drained batch: event-store reads
        and mask builds, memoized by query OBJECT identity (the serving
        layer pads a drained batch by repeating its LAST query object, so
        duplicates are free). Returns ``(out, warm, cold)`` — resolved
        empty results plus the warm/cold row plans."""
        out = []
        warm = []  # (index, query, uidx, mask)
        cold = []  # (index, query, mean-vec, mask)
        prepped: dict[int, tuple] = {}
        for i, q in queries:
            hit = prepped.get(id(q))
            if hit is None:
                exclude = self._exclusion_mask(model, q, q.user)
                uidx = model.user_ids.get(q.user)
                if uidx is not None:
                    hit = ("warm", uidx, exclude)
                else:
                    # cold-start: recommend near recent views (ref :285)
                    recent = [
                        model.item_ids(it)
                        for it in self._recent_items(q.user)
                        if it in model.item_ids
                    ]
                    if not recent:
                        hit = ("empty",)
                    else:
                        vec = model.item_features[
                            np.asarray(recent, np.int32)
                        ].mean(axis=0)
                        hit = ("cold", vec, exclude)
                prepped[id(q)] = hit
            if hit[0] == "warm":
                warm.append((i, q, hit[1], hit[2]))
            elif hit[0] == "cold":
                cold.append((i, q, hit[1], hit[2]))
            else:
                out.append((i, PredictedResult(())))
        return out, warm, cold

    # -- device-resident serving protocol (ROADMAP item 3) -------------------

    def pin_serving_state(self, model: ECommModel,
                          max_batch: int = 64) -> int:
        """Deploy-time HBM promotion of the warm-path catalogs (the
        cold-start cosine route keeps its own identity-cached normalized
        catalog and stays on the legacy path). ``max_batch`` is the
        server's drain ceiling, the tick the placement decision
        amortizes over."""
        from predictionio_tpu.models.als import pin_serving_factors

        return pin_serving_factors(
            model.user_features, model.item_features, max_batch=max_batch)

    def batch_predict_deferred(self, model: ECommModel, queries):
        """Device-resident tick for WARM-only drained batches: the factor
        gather, the per-row seen-item/constraint masks (host event-store
        reads stay per query — only the mask APPLICATION moves on device)
        and the top-k run as one fused dispatch with deferred readback.
        Any cold-start rider in the batch falls back to the legacy
        two-call path (its query vector is a host mean over recent
        views, a different program); such mixed ticks pay the host prep
        twice — once here to discover the cold rider, once on the
        fallback — the deliberate trade for keeping warm-majority
        traffic on the one-dispatch route."""
        from predictionio_tpu.models.als import (
            serve_top_k_batched,
            serving_tick_on_device,
        )

        # pre-gate BEFORE the per-query host prep: host-routed ticks
        # (PIO_SERVING_DEVICE=cpu, high-RTT link) must not pay the
        # event-store reads twice — here and on the legacy fallback
        if not serving_tick_on_device(
                len(queries), len(model.item_ids),
                model.item_features.shape[1]):
            return None
        out, warm, cold = self._prep_batch(model, queries)
        if cold or not warm:
            return None
        uidx = np.array([u for _, _, u, _ in warm], np.int32)
        masks = np.concatenate([m for _, _, _, m in warm], axis=0)
        k = min(max(q.num for _, q, _, _ in warm), len(model.item_ids))
        finalize = serve_top_k_batched(
            model.user_features, model.item_features, uidx, k, masks)
        if finalize is None:
            return None

        def resolve():
            scores, idx = finalize()
            res = list(out)
            for row, (i, q, _u, _m) in enumerate(warm):
                res.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )
            return res

        return resolve

    def batch_predict(self, model: ECommModel, queries):
        """Micro-batched serving. The serve-time event-store reads
        (unavailable items, seen items, recent views — host I/O) stay
        per-query like the reference's predict (ref ALSAlgorithm.scala
        :194-221); the device work batches into at most two calls per
        drained batch: one top_k_scores for warm users, one top_k_cosine
        for cold-start users."""
        out, warm, cold = self._prep_batch(model, queries)

        def emit(rows, scores, idx):
            for row, (i, q, _x, _m) in enumerate(rows):
                out.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )

        if warm:
            uidx = np.array([u for _, _, u, _ in warm], np.int32)
            masks = np.concatenate([m for _, _, _, m in warm], axis=0)
            k = min(max(q.num for _, q, _, _ in warm), len(model.item_ids))
            scores, idx = top_k_scores(
                model.user_features[uidx], model.item_features, k, masks
            )
            emit(warm, scores, idx)
        if cold:
            qs = np.stack([v for _, _, v, _ in cold])
            masks = np.concatenate([m for _, _, _, m in cold], axis=0)
            k = min(max(q.num for _, q, _, _ in cold), len(model.item_ids))
            scores, idx = top_k_cosine(qs, model.item_features, k, masks)
            emit(cold, scores, idx)
        return out


class Serving(FirstServing):
    pass


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"ecomm": ECommAlgorithm},
        serving_class=Serving,
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Default settings",
    "engineFactory": (
        "predictionio_tpu.templates.ecommercerecommendation:engine_factory"
    ),
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [
        {"name": "ecomm",
         "params": {"app_name": "MyApp1", "rank": 10, "numIterations": 20,
                    "lambda_": 0.01, "alpha": 1.0, "seed": 3}}
    ],
}
