"""Sequential recommendation engine template (SASRec transformer).

No counterpart exists in the reference (its four stock templates are all
matrix-factorization/classification era — SURVEY.md §2.6); this template is
the TPU build's long-context model family made product: next-item
recommendation from each user's interaction *sequence*, served through the
same DASE / engine.json / train / deploy surfaces as the stock templates.

Query/result shapes mirror the recommendation template:
``{"user": ..., "num": N}`` → ``{"itemScores": [{"item", "score"}]}``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.core import Engine, FirstServing, P2LAlgorithm, PDataSource, PPreparator
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.sasrec import (
    SASRec,
    SASRecParams,
    predict_top_k,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple[ItemScore, ...] = ()


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "MyApp1"
    event_names: tuple[str, ...] = ("view", "buy")


@dataclass
class TrainingData(SanityCheck):
    user_sequences: dict[str, list[str]]  # user → item ids in time order

    def sanity_check(self) -> None:
        if not self.user_sequences:
            raise ValueError(
                "TrainingData has no user sequences; ingest interaction events"
            )


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        sequences: dict[str, list[str]] = {}
        for e in PEventStore.find(
            self.params.app_name, event_names=list(self.params.event_names)
        ):
            if e.target_entity_id is None:
                continue
            sequences.setdefault(e.entity_id, []).append(e.target_entity_id)
        # PEventStore.find returns event-time order, so per-user lists are
        # already chronological
        return TrainingData(sequences)


@dataclass
class PreparedData:
    item_ids: BiMap  # item → 1-based index (0 = padding)
    sequences: list[list[int]]  # per-user encoded sequences
    users: list[str]
    popular: list[str]  # cold-start fallback ranking


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        all_items: list[str] = []
        for seq in td.user_sequences.values():
            all_items.extend(seq)
        # 1-based ids: reserve 0 for padding
        distinct = list(dict.fromkeys(all_items))
        item_ids = BiMap({it: i + 1 for i, it in enumerate(distinct)})
        users = list(td.user_sequences)
        sequences = [
            [item_ids(it) for it in td.user_sequences[u]] for u in users
        ]
        counts: dict[str, int] = {}
        for it in all_items:
            counts[it] = counts.get(it, 0) + 1
        popular = sorted(counts, key=counts.get, reverse=True)
        return PreparedData(item_ids, sequences, users, popular)


@dataclass(frozen=True)
class AlgorithmParams(Params):
    max_len: int = 50
    embed_dim: int = 64
    num_blocks: int = 2
    num_heads: int = 2
    ffn_dim: int = 128
    dropout: float = 0.2
    learning_rate: float = 1e-3
    batch_size: int = 128
    num_epochs: int = 20
    seed: int = 0
    exclude_seen: bool = True  # drop items already in the user's history
    # serving attention path: auto | mha | flash (pallas kernel) | ring
    # (sequence-parallel over the mesh; histories beyond one device)
    attn_impl: str = "auto"
    # sparse item-table updates (models/sasrec.SASRecParams.sparse_update)
    sparse_update: bool = True
    # mid-training checkpointing (utils.checkpoint.TrainCheckpointer):
    # empty = off; a crashed/killed train resumes from the newest epoch
    # checkpoint in this directory instead of restarting from zero
    checkpoint_dir: str = ""
    checkpoint_every: int = 1  # epochs between checkpoints


@dataclass
class SASRecModel:
    params: dict  # trained parameter pytree (host arrays)
    item_ids: BiMap
    user_sequences: dict[str, list[int]]  # encoded, for serve-time context
    popular: list[str]
    hp: SASRecParams
    exclude_seen: bool = True


class SASRecAlgorithm(P2LAlgorithm):
    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    def _hp(self) -> SASRecParams:
        a = self.params
        return SASRecParams(
            max_len=a.max_len, embed_dim=a.embed_dim,
            num_blocks=a.num_blocks, num_heads=a.num_heads,
            ffn_dim=a.ffn_dim, dropout=a.dropout,
            learning_rate=a.learning_rate, batch_size=a.batch_size,
            num_epochs=a.num_epochs, seed=a.seed, attn_impl=a.attn_impl,
            sparse_update=a.sparse_update,
        )

    def train(self, ctx: ComputeContext, pd: PreparedData) -> SASRecModel:
        hp = self._hp()
        checkpointer = None
        if self.params.checkpoint_dir:
            from predictionio_tpu.utils.checkpoint import TrainCheckpointer

            checkpointer = TrainCheckpointer(
                self.params.checkpoint_dir,
                every=self.params.checkpoint_every,
            )
        trained = SASRec(ctx, hp).train(
            pd.sequences, n_items=len(pd.item_ids), checkpointer=checkpointer
        )
        return SASRecModel(
            params=trained,
            item_ids=pd.item_ids,
            user_sequences=dict(zip(pd.users, pd.sequences)),
            popular=pd.popular,
            hp=hp,
            exclude_seen=self.params.exclude_seen,
        )

    def predict(self, model: SASRecModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def _prep_batch(self, model: SASRecModel, queries):
        """Shared tick prep for the host AND device routes: cold-start
        answers for history-less users, bucket-padded histories for the
        rest (pow2 sequence-length ladder — models/sasrec.seq_bucket_len;
        the tail-aligned position table makes the bucketed forward score
        identically to a max_len pad), per-user seen masks, and k.
        Returns (cold_results, rows, padded, exclude, k)."""
        hp = model.hp
        n_rows = model.params["item_emb"].shape[0]
        out = []
        rows = []  # (index, query, history)
        for i, q in queries:
            seq = model.user_sequences.get(q.user)
            if not seq:
                # cold start: most popular items (the ecommerce template's
                # predictNewUser spirit)
                out.append(
                    (i, PredictedResult(tuple(
                        ItemScore(item=it, score=0.0)
                        for it in model.popular[: q.num]
                    )))
                )
                continue
            rows.append((i, q, seq))
        if not rows:
            return out, rows, None, None, 0
        from predictionio_tpu.models.sasrec import seq_bucket_len

        longest = max(min(len(seq), hp.max_len) for _, _, seq in rows)
        l = seq_bucket_len(longest, hp.max_len)
        padded = np.zeros((len(rows), l), dtype=np.int32)
        for r, (_i, _q, seq) in enumerate(rows):
            tail = seq[-l:]
            padded[r, -len(tail):] = tail
        exclude = None
        if model.exclude_seen:  # full history, not the model window
            exclude = np.zeros((len(rows), n_rows), dtype=bool)
            for r, (_i, _q, seq) in enumerate(rows):
                exclude[r, np.asarray(seq, dtype=np.int64)] = True
        k = max(q.num for _, q, _ in rows)
        return out, rows, padded, exclude, k

    @staticmethod
    def _assemble(model: SASRecModel, out, rows, scores, idx):
        scores = np.asarray(scores)
        idx = np.asarray(idx)
        res = list(out)
        for r, (i, q, _seq) in enumerate(rows):
            items = []
            for s, j in zip(scores[r][: q.num], idx[r][: q.num]):
                if not np.isfinite(s) or j == 0:
                    continue
                items.append(
                    ItemScore(
                        item=model.item_ids.inverse(int(j)),
                        score=float(s),
                    )
                )
            res.append((i, PredictedResult(tuple(items))))
        return res

    def batch_predict(self, model: SASRecModel, queries):
        """Micro-batched serving: padded histories and per-user seen
        masks stack into ONE transformer forward + catalog score for the
        drained batch."""
        out, rows, padded, exclude, k = self._prep_batch(model, queries)
        if rows:
            scores, idx = predict_top_k(
                model.params, padded, k, model.hp, exclude_mask=exclude
            )
            out = self._assemble(model, out, rows, scores, idx)
        return out

    # -- device-resident serving protocol (ROADMAP item 3) -------------------

    def pin_serving_state(self, model: SASRecModel,
                          max_batch: int = 64) -> int:
        """Deploy-time HBM promotion: pin the whole SASRec parameter
        pytree (transformer blocks + item table) device-resident
        (``serving_models`` arena) so the first serving tick finds it
        warm. Returns the pinned byte count (0 = host placement)."""
        from predictionio_tpu.models.sasrec import pin_sasrec_serving_state

        return pin_sasrec_serving_state(model.params, model.hp,
                                        max_batch=max_batch)

    def batch_predict_deferred(self, model: SASRecModel, queries):
        """Device-resident serving tick: the padded-history transformer
        forward, catalog score, seen-item exclusion mask and top-k for
        the whole drained batch run as ONE fused device program against
        the HBM-pinned parameters, with the blocking readback deferred
        to the server's finalizer thread (overlapped with the next
        tick's dispatch). Returns None whenever the fused route does not
        apply — host placement, no known users — and the server falls
        back to :meth:`batch_predict`; resolved results are exactly the
        host route's (parity pinned in tests/test_sasrec_serving.py)."""
        from predictionio_tpu.models.sasrec import (
            seq_bucket_len,
            serve_sasrec_topk_batched,
            serving_tick_on_device,
        )

        hp = model.hp
        n_rows = model.params["item_emb"].shape[0]
        with_hist = [q for _, q in queries
                     if model.user_sequences.get(q.user)]
        if not with_hist:
            return None  # nothing to dispatch: the legacy path is free
        # pre-gate BEFORE the per-query host prep (mask builds): a
        # host-routed tick must not pay them twice
        longest = max(
            min(len(model.user_sequences[q.user]), hp.max_len)
            for q in with_hist)
        if not serving_tick_on_device(
                hp, n_rows, len(with_hist),
                seq_bucket_len(longest, hp.max_len)):
            return None
        out, rows, padded, exclude, k = self._prep_batch(model, queries)
        finalize = serve_sasrec_topk_batched(
            model.params, padded, k, hp, exclude_mask=exclude)
        if finalize is None:
            return None

        def resolve():
            scores, idx = finalize()
            return self._assemble(model, out, rows, scores, idx)

        return resolve


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"sasrec": SASRecAlgorithm},
        serving_class=FirstServing,
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Sequential recommendation (SASRec transformer)",
    "engineFactory": (
        "predictionio_tpu.templates.sequentialrecommendation:engine_factory"
    ),
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [
        {
            "name": "sasrec",
            "params": {
                "max_len": 50, "embed_dim": 64, "num_blocks": 2,
                "num_heads": 2, "dropout": 0.2, "num_epochs": 20,
                "seed": 3,
            },
        }
    ],
}
