"""Similar-product engine template.

Re-design of the reference's scala-parallel-similarproduct template
(ref: examples/scala-parallel-similarproduct/multi/src/main/scala/
{Engine,DataSource,Preparator,ALSAlgorithm,LikeAlgorithm,Serving}.scala):
implicit-feedback ALS on ``view`` events; queries name a set of liked items
and get cosine-similar items back, excluding the query items and honoring
white/black lists. The ``multi`` variant's second algorithm trains on
like/dislike events as ±1 implicit ratings (LikeAlgorithm.scala:16-60);
Serving sums scores across algorithms (Serving.scala).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.core import (
    Engine,
    LServing,
    P2LAlgorithm,
    PDataSource,
    PPreparator,
)
from predictionio_tpu.core.dase import LAlgorithm
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.als import ALS, ALSParams, top_k_cosine
from predictionio_tpu.models.serving_filters import (
    build_exclusion_mask,
    topk_to_item_scores,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass(frozen=True)
class Query:
    items: tuple[str, ...]
    num: int = 10
    categories: tuple[str, ...] | None = None
    whiteList: tuple[str, ...] | None = None
    blackList: tuple[str, ...] | None = None


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple[ItemScore, ...] = ()


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "similarproduct"


@dataclass
class TrainingData(SanityCheck):
    view_users: list[str]
    view_items: list[str]
    like_users: list[str] = field(default_factory=list)
    like_items: list[str] = field(default_factory=list)
    like_signs: list[float] = field(default_factory=list)  # +1 like / -1 dislike
    item_categories: dict[str, tuple[str, ...]] = field(default_factory=dict)

    def sanity_check(self) -> None:
        if not self.view_users:
            raise ValueError("TrainingData is empty; ingest view events first")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        app = self.params.app_name
        view_users, view_items = [], []
        for e in PEventStore.find(app, event_names=["view"]):
            if e.target_entity_id is not None:
                view_users.append(e.entity_id)
                view_items.append(e.target_entity_id)
        like_users, like_items, like_signs = [], [], []
        for e in PEventStore.find(app, event_names=["like", "dislike"]):
            if e.target_entity_id is not None:
                like_users.append(e.entity_id)
                like_items.append(e.target_entity_id)
                like_signs.append(1.0 if e.event == "like" else -1.0)
        categories = {}
        for item_id, pm in PEventStore.aggregate_properties(app, "item").items():
            cats = pm.get_opt("categories", list)
            if cats:
                categories[item_id] = tuple(str(c) for c in cats)
        return TrainingData(
            view_users, view_items, like_users, like_items, like_signs, categories
        )


@dataclass
class PreparedData:
    td: TrainingData


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        return PreparedData(td)


@dataclass(frozen=True)
class AlgorithmParams(Params):
    rank: int = 10
    numIterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = None


@dataclass
class SimilarModel:
    item_features: np.ndarray  # [n_items, rank]
    item_ids: BiMap
    item_categories: dict[str, tuple[str, ...]]


def _train_implicit_item_factors(
    ctx: ComputeContext,
    users: list[str],
    items: list[str],
    ratings: np.ndarray,
    params: AlgorithmParams,
    item_categories: dict[str, tuple[str, ...]],
) -> SimilarModel:
    if not users:
        raise ValueError("no interaction events to train on")
    user_ids = BiMap.string_int(users)
    item_ids = BiMap.string_int(items)
    als = ALS(
        ctx,
        ALSParams(
            rank=params.rank,
            num_iterations=params.numIterations,
            lambda_=params.lambda_,
            implicit_prefs=True,
            alpha=params.alpha,
            seed=params.seed,
        ),
    )
    factors = als.train(
        user_ids.encode(users),
        item_ids.encode(items),
        ratings,
        n_users=len(user_ids),
        n_items=len(item_ids),
    )
    return SimilarModel(factors.item_features, item_ids, item_categories)


def _similar_items_batch(model: SimilarModel, queries):
    """Cosine top-k over each query's mean item factor, with the
    reference's filters (drop query items, white/black lists, categories
    — ref: ALSAlgorithm.predict in the similarproduct template), batched:
    query vectors and per-query exclusion masks stack into ONE
    top_k_cosine call for the whole drained micro-batch."""
    out = []
    rows = []  # (index, query, q_vec [d], mask [1, n_items])
    for i, q in queries:
        known = [model.item_ids(it) for it in q.items if it in model.item_ids]
        if not known:
            out.append((i, PredictedResult(())))
            continue
        vec = model.item_features[np.asarray(known, np.int32)].mean(axis=0)
        mask = build_exclusion_mask(
            model.item_ids,
            banned=(it for it in q.items if it in model.item_ids),
            black_list=q.blackList,
            white_list=q.whiteList,
            categories=q.categories,
            item_categories=model.item_categories,
        )
        rows.append((i, q, vec, mask))
    if rows:
        qs = np.stack([v for _, _, v, _ in rows])
        masks = np.concatenate([m for _, _, _, m in rows], axis=0)
        k = min(max(q.num for _, q, _, _ in rows), len(model.item_ids))
        scores, idx = top_k_cosine(qs, model.item_features, k, masks)
        for row, (i, q, _v, _m) in enumerate(rows):
            out.append(
                (i, PredictedResult(topk_to_item_scores(
                    scores[row], idx[row], model.item_ids, q.num, ItemScore
                )))
            )
    return out


def _view_counts(td) -> tuple[list[str], list[str], np.ndarray]:
    """Collapse duplicate views to counts (implicit strength)."""
    counts: dict[tuple[str, str], float] = defaultdict(float)
    for u, i in zip(td.view_users, td.view_items):
        counts[(u, i)] += 1.0
    users = [u for u, _ in counts]
    items = [i for _, i in counts]
    ratings = np.fromiter(counts.values(), np.float32, count=len(counts))
    return users, items, ratings


class ALSAlgorithm(P2LAlgorithm):
    """Implicit ALS on view counts (ref: multi/.../ALSAlgorithm.scala)."""

    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    def train(self, ctx: ComputeContext, pd: PreparedData) -> SimilarModel:
        td = pd.td
        users, items, ratings = _view_counts(td)
        return _train_implicit_item_factors(
            ctx, users, items, ratings, self.params, td.item_categories
        )

    def predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        return _similar_items_batch(model, [(0, query)])[0][1]

    def batch_predict(self, model: SimilarModel, queries):
        """Micro-batched serving: one device call per drained batch."""
        return _similar_items_batch(model, queries)


class LocalALSAlgorithm(LAlgorithm):
    """The similarproduct-localmodel variant (ref: examples/experimental/
    scala-parallel-similarproduct-localmodel/src/main/scala/
    ALSAlgorithm.scala:26-96): the same implicit-ALS item factors as
    :class:`ALSAlgorithm`, but as an L-flavor algorithm — ``train_local``
    sees only local prepared data and runs ALS on a single-device
    context, and the model is plain host arrays (the shape the reference
    collects its ``productFeatures`` Map into). Serving shares the
    batched cosine path, so the two flavors are batch-predict
    interchangeable."""

    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    def train_local(self, pd: PreparedData) -> SimilarModel:
        import jax
        from jax.sharding import Mesh

        td = pd.td
        users, items, ratings = _view_counts(td)
        local = ComputeContext(Mesh(
            np.asarray(jax.devices()[:1]).reshape(1, 1), ("data", "model")))
        return _train_implicit_item_factors(
            local, users, items, ratings, self.params, td.item_categories
        )

    def predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        return _similar_items_batch(model, [(0, query)])[0][1]


class LikeAlgorithm(ALSAlgorithm):
    """like/dislike → ±1 implicit ratings (ref: LikeAlgorithm.scala:16-60);
    latest event per (user, item) wins."""

    def train(self, ctx: ComputeContext, pd: PreparedData) -> SimilarModel:
        td = pd.td
        last: dict[tuple[str, str], float] = {}
        for u, i, s in zip(td.like_users, td.like_items, td.like_signs):
            last[(u, i)] = s  # events are time-ordered from the store
        users = [u for u, _ in last]
        items = [i for _, i in last]
        ratings = np.fromiter(last.values(), np.float32, count=len(last))
        return _train_implicit_item_factors(
            ctx, users, items, ratings, self.params, td.item_categories
        )


class Serving(LServing):
    """Sum scores across algorithms per item (ref: multi Serving.scala)."""

    def __init__(self, params=None):
        pass

    def serve(self, query: Query, predictions) -> PredictedResult:
        combined: dict[str, float] = defaultdict(float)
        for p in predictions:
            for s in p.itemScores:
                combined[s.item] += s.score
        top = sorted(combined.items(), key=lambda kv: -kv[1])[: query.num]
        return PredictedResult(
            tuple(ItemScore(i, s) for i, s in top)
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"als": ALSAlgorithm, "likealgo": LikeAlgorithm,
                             "localals": LocalALSAlgorithm},
        serving_class=Serving,
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Default settings",
    "engineFactory": "predictionio_tpu.templates.similarproduct:engine_factory",
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [
        {"name": "als",
         "params": {"rank": 10, "numIterations": 20, "lambda_": 0.01,
                    "alpha": 1.0, "seed": 3}}
    ],
}
