"""Recommendation engine template (MovieLens-class).

Re-design of the reference's scala-parallel-recommendation template
(ref: examples/scala-parallel-recommendation/custom-serving/src/main/scala/
{Engine,DataSource,Preparator,ALSAlgorithm,Serving}.scala): explicit-rating
ALS on ``rate``/``buy`` events (a ``buy`` counts as rating 4.0, ref:
DataSource.scala:40-47), queries ask for the top-N items for a user.

The MLlib ``ALS.train`` call (ALSAlgorithm.scala:27-67) is replaced by the
TPU-native ALS of :mod:`predictionio_tpu.models.als`; predict-time
``model.recommendProducts`` becomes one jitted matmul + top_k in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.core import (
    Engine,
    EngineParams,
    LServing,
    PAlgorithm,
    PDataSource,
    PPreparator,
)
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.evaluation import Evaluation
from predictionio_tpu.core.metrics import OptionAverageMetric
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.als import (
    ALS,
    ALSFactors,
    ALSParams,
    pin_serving_factors,
    serve_top_k_batched,
    top_k_scores,
)
from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.parallel.mesh import ComputeContext

import logging

logger = logging.getLogger(__name__)

#: HBM arena for stacked sweep-bucket factors (BatchedALSModels): the
#: sweep executor frees each chunk's stack at metric readback, and
#: core/sweep.py leak-checks the arena when a sweep finishes.
_SWEEP_ARENA = device_obs.arena("sweep_factors")


# -- queries / results (ref: Engine.scala Query/PredictedResult) ------------


@dataclass(frozen=True)
class Query:
    """The stock query plus the reference's variant extensions: category
    filtering (ref: filter-by-category variant ALSAlgorithm.scala:67) and
    a per-query blacklist (custom-query variant HOWTO)."""

    user: str
    num: int = 10
    categories: tuple[str, ...] | None = None
    blackList: tuple[str, ...] | None = None


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple[ItemScore, ...] = ()


# -- data source ------------------------------------------------------------


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "recommendation"
    eval_k: int | None = None  # k-fold eval split count (None = no eval)
    buy_rating: float = 4.0  # implicit "buy" → rating (ref: DataSource.scala:44)
    seed: int = 3


@dataclass
class TrainingData(SanityCheck):
    users: list[str]
    items: list[str]
    ratings: np.ndarray  # [n] float32
    #: item → categories from $set properties (the filter-by-category
    #: variant's movie metadata)
    item_categories: dict[str, tuple[str, ...]] = field(default_factory=dict)

    def sanity_check(self) -> None:
        # ref: DataSource readTraining sanity — empty data fails fast
        if len(self.users) == 0:
            raise ValueError("TrainingData is empty; ingest rate/buy events first")
        if not np.isfinite(self.ratings).all():
            raise ValueError("TrainingData has non-finite ratings")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def _read(self) -> TrainingData:
        users, items, ratings, names, _ = PEventStore.interaction_arrays(
            self.params.app_name,
            event_names=["rate", "buy"],
            rating_property="rating",
            default_rating=self.params.buy_rating,
        )
        # "buy" events carry no rating property → buy_rating default applies
        categories = {}
        for item_id, pm in PEventStore.aggregate_properties(
            self.params.app_name, "item"
        ).items():
            cats = pm.get_opt("categories", list)
            if cats:
                categories[item_id] = tuple(str(c) for c in cats)
        return TrainingData(users, items, ratings, categories)

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        """k-fold split for `pio eval` via the shared splitter
        (ref: evaluation variants of the template; e2 CrossValidation)."""
        k = self.params.eval_k
        if not k:
            raise NotImplementedError("set eval_k in datasource params to evaluate")
        return _kfold_read_eval(self._read(), k, self.params.seed)

    # -- continuous-training protocol (train/continuous.py) ------------------

    def delta_source(self):
        """What the ContinuousTrainer tails for this engine: the same
        event names / rating-property rules :meth:`_read`'s
        ``interaction_arrays`` scan applies, so an incrementally folded
        row is exactly the row a full retrain would read."""
        from predictionio_tpu.train.continuous import DeltaSpec

        return DeltaSpec(
            app_name=self.params.app_name,
            event_names=("rate", "buy"),
            rating_property="rating",
            default_rating=self.params.buy_rating,
        )


def _kfold_read_eval(td: "TrainingData", k: int, seed: int):
    """k-fold eval folds from one TrainingData — shared by the event-store
    DataSource above and the in-memory ArrayDataSource below."""
    from predictionio_tpu.models.cross_validation import split_data

    rows = list(zip(td.users, td.items, td.ratings.tolist()))
    return split_data(
        k,
        rows,
        make_training_data=lambda rs: TrainingData(
            [u for u, _, _ in rs],
            [i for _, i, _ in rs],
            np.asarray([r for _, _, r in rs], np.float32),
        ),
        make_eval_info=lambda rs: {"n_train": len(rs)},
        make_query_actual=lambda row: (
            Query(user=row[0], num=10),
            ActualRating(item=row[1], rating=float(row[2])),
        ),
        seed=seed,
    )


#: In-memory datasets for ArrayDataSource, by name. Sweep benches and
#: tests register (users, items, ratings) triples here so an Evaluation
#: can run without an event store behind it.
_DATASETS: dict[str, tuple] = {}


def register_dataset(name: str, users, items, ratings) -> None:
    """Register an in-memory (users, items, ratings) triple for
    :class:`ArrayDataSource`. ``users``/``items`` are id sequences,
    ``ratings`` a float sequence of the same length."""
    _DATASETS[name] = (list(users), list(items),
                       np.asarray(ratings, np.float32))


@dataclass(frozen=True)
class ArrayDataSourceParams(Params):
    dataset: str = ""  # register_dataset name
    eval_k: int = 2
    seed: int = 7


class ArrayDataSource(PDataSource):
    """DataSource over a registered in-memory dataset — the sweep bench /
    test path that skips event-store ingestion. Params stay JSON-able
    (the dataset rides by name), so the FastEval prefix caches key it
    like any other DataSource."""

    params_class = ArrayDataSourceParams

    def __init__(self, params: ArrayDataSourceParams):
        self.params = params

    def _read(self) -> TrainingData:
        if self.params.dataset not in _DATASETS:
            raise KeyError(
                f"ArrayDataSource dataset {self.params.dataset!r} is not "
                "registered; call recommendation.register_dataset first")
        users, items, ratings = _DATASETS[self.params.dataset]
        return TrainingData(list(users), list(items),
                            np.asarray(ratings, np.float32))

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        return _kfold_read_eval(self._read(), self.params.eval_k,
                                self.params.seed)


@dataclass(frozen=True)
class ActualRating:
    item: str
    rating: float


# -- preparator -------------------------------------------------------------


@dataclass
class PreparedData:
    user_ids: BiMap
    item_ids: BiMap
    user_idx: np.ndarray
    item_idx: np.ndarray
    ratings: np.ndarray
    item_categories: dict[str, tuple[str, ...]]


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        # BiMap.stringInt indexing (ref: ALSAlgorithm.scala:33-38)
        user_ids = BiMap.string_int(td.users)
        item_ids = BiMap.string_int(td.items)
        return PreparedData(
            user_ids=user_ids,
            item_ids=item_ids,
            user_idx=user_ids.encode(td.users),
            item_idx=item_ids.encode(td.items),
            ratings=td.ratings,
            item_categories=td.item_categories,
        )


# -- ALS algorithm ----------------------------------------------------------


@dataclass(frozen=True)
class AlgorithmParams(Params):
    rank: int = 10
    numIterations: int = 20
    lambda_: float = 0.01
    seed: int | None = None
    implicitPrefs: bool = False
    alpha: float = 1.0
    # crash-safe training (utils.checkpoint.TrainCheckpointer): empty =
    # off unless `pio train --checkpoint-dir` published a workflow-level
    # scope. With a directory set, factors snapshot every
    # checkpointEvery iterations (atomic rename + content hash) and a
    # killed train resumes from the newest VALID snapshot — a truncated
    # latest falls back to the previous one.
    checkpointDir: str = ""
    checkpointEvery: int = 1


@dataclass
class ALSModel:
    factors: ALSFactors
    user_ids: BiMap
    item_ids: BiMap
    item_categories: dict[str, tuple[str, ...]] = field(default_factory=dict)


@dataclass
class BatchedALSModels:
    """One sweep bucket's stacked candidate factors, DEVICE-resident:
    ``user_stack`` [C, n_users, r] / ``item_stack`` [C, n_items, r].
    Metrics score against the stacks on device (one dispatch for the
    whole bucket); :meth:`free` drops the device references once the
    metric vector is read back so a sweep never pins more than one
    bucket chunk's factors in HBM."""

    user_stack: object
    item_stack: object
    user_ids: BiMap
    item_ids: BiMap
    n_candidates: int
    arena_alloc: object = None  # sweep_factors HBM-arena registration

    def free(self) -> None:
        _SWEEP_ARENA.free(self.arena_alloc)
        self.arena_alloc = None
        self.user_stack = None
        self.item_stack = None


class ALSAlgorithm(PAlgorithm):
    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams):
        self.params = params

    @staticmethod
    def _als_params(p: AlgorithmParams) -> ALSParams:
        """The ONE AlgorithmParams → ALSParams mapping — shared by train
        and batch_train so the batched-vs-sequential parity contract can
        never drift on a field added to only one path."""
        return ALSParams(
            rank=p.rank,
            num_iterations=p.numIterations,
            lambda_=p.lambda_,
            implicit_prefs=p.implicitPrefs,
            alpha=p.alpha,
            seed=p.seed,
        )

    def _train_checkpointer(self):
        """(TrainCheckpointer, resume_allowed) — the algorithm's own
        checkpointDir wins (and auto-resumes, the SASRec idiom: the
        fingerprint makes that safe); otherwise the workflow scope
        published by `pio train --checkpoint-dir` applies, resuming only
        under --resume. (None, False) = checkpointing off."""
        from predictionio_tpu.utils.checkpoint import (
            TrainCheckpointer,
            current_train_checkpoint,
        )

        if self.params.checkpointDir:
            return TrainCheckpointer(
                self.params.checkpointDir,
                every=max(self.params.checkpointEvery, 1)), True
        cfg = current_train_checkpoint()
        if cfg is not None and cfg.directory:
            return TrainCheckpointer(cfg.directory, every=cfg.every), \
                cfg.resume
        return None, False

    def train(self, ctx: ComputeContext, pd: PreparedData) -> ALSModel:
        als_p = self._als_params(self.params)
        als = ALS(ctx, als_p)
        ck, resume_allowed = self._train_checkpointer()
        checkpoint = None
        if ck is not None:
            from predictionio_tpu.utils.checkpoint import (
                TrainCheckpointSpec,
                fingerprint_arrays,
            )

            # bind checkpoints to the data + per-iteration math; the
            # iteration COUNT is deliberately excluded so a resumed run
            # can complete (or extend) the interrupted one — each
            # iteration's update is identical regardless of how many
            # follow it. The solver owns save/resume from here: the
            # sharded SPMD path writes per-shard slabs whose layout this
            # template cannot know.
            fp = fingerprint_arrays(
                pd.user_idx, pd.item_idx, pd.ratings,
                ("als-dense", als_p.rank, als_p.lambda_, als_p.alpha,
                 als_p.implicit_prefs, als_p.seed),
            )
            checkpoint = TrainCheckpointSpec(ck, fp, resume_allowed)
        factors = als.train(
            pd.user_idx,
            pd.item_idx,
            pd.ratings,
            n_users=len(pd.user_ids),
            n_items=len(pd.item_ids),
            checkpoint=checkpoint,
        )
        return ALSModel(factors, pd.user_ids, pd.item_ids, pd.item_categories)

    # -- device-batched sweep protocol (core/sweep.py) -----------------------

    def batch_signature(self) -> tuple:
        """What must be STATIC across a stacked sweep bucket: rank sets
        every array shape in the solve, iteration count the loop bound,
        implicit the program branch. lambda_/alpha/seed are per-candidate
        operands and deliberately absent — they ride the candidate axis."""
        p = self.params
        return ("als-dense", p.rank, p.numIterations, p.implicitPrefs)

    def batch_limit(self, ctx: ComputeContext, pd: PreparedData) -> int:
        """Candidate-axis chunk cap from the sweep HBM budget
        (``PIO_SWEEP_HBM_MB``; see als_dense.stacked_candidate_limit)."""
        from predictionio_tpu.models import als_dense

        return als_dense.stacked_candidate_limit(
            self.params.rank, len(pd.user_ids), len(pd.item_ids))

    def batch_train(self, ctx: ComputeContext, pd: PreparedData,
                    params_list) -> BatchedALSModels | None:
        """Train a whole sweep bucket as ONE stacked dense solve (shared
        staged A, vmapped candidate axis — als_dense.train_dense_stacked).
        Returns None when the stacked dense path does not apply (the sweep
        executor then falls back to sequential per-candidate trains)."""
        from predictionio_tpu.models import als_dense

        als_params = [self._als_params(p) for p in params_list]
        stacks = als_dense.train_dense_stacked(
            ctx, als_params, pd.user_idx, pd.item_idx, pd.ratings,
            len(pd.user_ids), len(pd.item_ids))
        if stacks is None:
            return None
        return BatchedALSModels(
            user_stack=stacks[0], item_stack=stacks[1],
            user_ids=pd.user_ids, item_ids=pd.item_ids,
            n_candidates=len(als_params),
            arena_alloc=_SWEEP_ARENA.register(
                stacks, label=f"c{len(als_params)}"))

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    @staticmethod
    def _query_mask(model: ALSModel, q: Query):
        """[1, n_items] exclusion mask for the variant filters (category
        filter — ref filter-by-category ALSAlgorithm.scala:67 — and
        per-query blacklist), or None when the query uses neither."""
        if q.categories is None and not q.blackList:
            return None
        from predictionio_tpu.models.serving_filters import (
            build_exclusion_mask,
        )

        return build_exclusion_mask(
            model.item_ids,
            black_list=q.blackList,
            categories=q.categories,
            # getattr: models pickled before this field existed restore
            # without it (pickle bypasses dataclass defaults)
            item_categories=getattr(model, "item_categories", {}),
        )

    def _stacked_masks(self, model: ALSModel, queries_seq):
        """[b, n_items] exclusion mask stack for a batch's queries, or
        None when no query filters. Memoized per query OBJECT: the
        serving layer pads drained batches by repeating the LAST query,
        and mask building is a catalog-sized host loop."""
        mask_memo: dict[int, object] = {}
        masks = []
        for q in queries_seq:
            if id(q) not in mask_memo:
                mask_memo[id(q)] = self._query_mask(model, q)
            masks.append(mask_memo[id(q)])
        if not any(m is not None for m in masks):
            return None
        n = len(model.item_ids)
        return np.concatenate(
            [m if m is not None else np.zeros((1, n), bool)
             for m in masks],
            axis=0,
        )

    def batch_predict(self, model: ALSModel, queries):
        """Batched serving/eval path: one matmul for all known users,
        with per-query variant filters stacked into one mask."""
        known = [(i, q) for i, q in queries if q.user in model.user_ids]
        out = [(i, PredictedResult(())) for i, q in queries
               if q.user not in model.user_ids]
        if known:
            uidx = np.array([model.user_ids(q.user) for _, q in known], np.int32)
            k = min(max(q.num for _, q in known), len(model.item_ids))
            exclude = self._stacked_masks(model, [q for _, q in known])
            scores, idx = top_k_scores(
                model.factors.user_features[uidx],
                model.factors.item_features, k, exclude,
            )
            from predictionio_tpu.models.serving_filters import (
                topk_to_item_scores,
            )

            for row, (i, q) in enumerate(known):
                out.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )
        return out

    # -- prediction-quality observatory (obs/quality.py) ---------------------

    def quality_probe_queries(self, model: ALSModel, n: int = 64,
                              k: int = 10) -> list[Query]:
        """Held-out query sample for the train-time quality baseline: an
        even stride over the trained user catalog (deterministic, so two
        trains on the same data sketch the same population)."""
        users = list(model.user_ids.keys())
        if not users:
            return []
        step = max(len(users) // max(n, 1), 1)
        return [Query(user=u, num=k) for u in users[::step][:n]]

    # -- incremental fold-in protocol (train/foldin.py, ROADMAP item 2) ------

    @staticmethod
    def _extended_ids(ids: BiMap, delta) -> BiMap:
        """First-appearance-order extension — the ONE shared rule
        (train/foldin.extended_ids) the continuous trainer's encoded
        snapshot mirrors, which is what makes its O(delta) maps
        verifiably extend this model's."""
        from predictionio_tpu.train.foldin import extended_ids

        return extended_ids(ids, delta)

    def fold_in_ready(self, model: ALSModel, data) -> bool:
        """Cheap pre-check: a delta touching more than
        ``PIO_FOLDIN_MAX_FRACTION`` of either catalog is not
        "incremental" — the exact full retrain wins (and re-anchors any
        accumulated fold-in drift)."""
        from predictionio_tpu.train import foldin as foldin_mod

        delta_users = set(data.delta_users)
        delta_items = set(data.delta_items)
        if not delta_users:
            return False
        n_users = sum(1 for u in delta_users
                      if u not in model.user_ids) + len(model.user_ids)
        n_items = sum(1 for i in delta_items
                      if i not in model.item_ids) + len(model.item_ids)
        frac = foldin_mod.max_fraction()
        if len(delta_users) > frac * n_users \
                or len(delta_items) > frac * n_items:
            logger.info(
                "fold-in declined: delta touches %d/%d users, %d/%d "
                "items (> %.0f%% of a catalog) — full retrain",
                len(delta_users), n_users, len(delta_items), n_items,
                100 * frac)
            return False
        return True

    def fold_in(self, ctx: ComputeContext, model: ALSModel,
                data) -> ALSModel | None:
        """One fold-in generation: re-solve ONLY the users/items with
        delta evidence against frozen opposite-side factors
        (train/foldin.solve_entities — the dense solver's half-step
        restricted to the touched rows). Brand-new users/items append
        zero-initialized rows and get their first least-squares solve
        here. Untouched rows are byte-identical copies of the parent's
        factors. Returns None when the dense formulation does not apply
        (non-int8-encodable ratings) — the trainer falls back to a full
        retrain."""
        from predictionio_tpu.train import foldin as foldin_mod

        p = self._als_params(self.params)
        if data.encoded() \
                and foldin_mod.maps_extend(model.user_ids, data.user_ids) \
                and foldin_mod.maps_extend(model.item_ids, data.item_ids):
            # O(delta) path: the trainer's persistent encoded snapshot
            # verifiably extends this model's maps — no re-encode of the
            # full history (the map check is O(entities), constant per
            # cycle regardless of event count)
            user_ids, item_ids = data.user_ids, data.item_ids
            ui = np.asarray(data.uidx, np.int32)
            ii = np.asarray(data.iidx, np.int32)
            touched_u = np.unique(ui[data.delta_start:]).astype(np.int32)
            touched_i = np.unique(ii[data.delta_start:]).astype(np.int32)
        else:
            user_ids = self._extended_ids(model.user_ids, data.delta_users)
            item_ids = self._extended_ids(model.item_ids, data.delta_items)
            touched_u = np.unique(
                user_ids.encode(data.delta_users)).astype(np.int32)
            touched_i = np.unique(
                item_ids.encode(data.delta_items)).astype(np.int32)
            ui = user_ids.encode(data.users).astype(np.int32)
            ii = item_ids.encode(data.items).astype(np.int32)
        n_users, n_items = len(user_ids), len(item_ids)
        rr = np.asarray(data.ratings, np.float32)
        uf = np.asarray(model.factors.user_features, np.float32)
        uf = np.vstack([uf, np.zeros(
            (n_users - uf.shape[0], p.rank), np.float32)]) \
            if n_users > uf.shape[0] else uf.copy()
        itf = np.asarray(model.factors.item_features, np.float32)
        itf = np.vstack([itf, np.zeros(
            (n_items - itf.shape[0], p.rank), np.float32)]) \
            if n_items > itf.shape[0] else itf.copy()
        # user half against the FROZEN parent item factors, then item
        # half against the updated users — the ordering a full
        # _iteration_dense runs, restricted to the touched rows
        rows = foldin_mod.solve_entities(
            p, touched_u, ui, ii, rr, itf, uf[touched_u], n_users,
            n_items, ctx=ctx)
        if rows is None:
            return None
        uf[touched_u] = rows
        rows = foldin_mod.solve_entities(
            p, touched_i, ii, ui, rr, uf, itf[touched_i], n_items,
            n_users, ctx=ctx)
        if rows is None:
            return None
        itf[touched_i] = rows
        return ALSModel(
            ALSFactors(uf, itf), user_ids, item_ids,
            getattr(model, "item_categories", {}))

    # -- device-resident serving protocol (ROADMAP item 3) -------------------

    def pin_serving_state(self, model: ALSModel, max_batch: int = 64) -> int:
        """Deploy-time HBM promotion: pin both factor matrices device-
        resident (``serving_models`` arena) so the first serving tick
        finds its catalogs warm. ``max_batch`` is the server's configured
        drain ceiling — the representative tick the placement decision
        amortizes over. Returns the pinned byte count (0 = the placement
        decision keeps serving on the host)."""
        return pin_serving_factors(
            model.factors.user_features, model.factors.item_features,
            max_batch=max_batch)

    def batch_predict_deferred(self, model: ALSModel, queries):
        """Device-resident serving tick: the factor gather, MIPS, per-row
        masks and top-k for the whole drained batch run as ONE fused
        device program against the HBM-pinned catalogs, and the blocking
        readback is deferred (the server's finalizer thread overlaps it
        with the next tick's dispatch). Returns None whenever the fused
        route does not apply — host placement, no known users — and the
        server falls back to :meth:`batch_predict`; the resolved results
        are exactly the host route's (parity pinned in test_query_server).
        """
        from predictionio_tpu.models.als import serving_tick_on_device
        from predictionio_tpu.ops.topk import ShardedCatalog

        known = [(i, q) for i, q in queries if q.user in model.user_ids]
        if not known:
            return None  # nothing to dispatch: the legacy path is free
        # pre-gate BEFORE the per-query host prep: a host-routed tick
        # (PIO_SERVING_DEVICE=cpu, high-RTT link at this tick size) must
        # not pay the mask builds twice — here and again in the
        # batch_predict fallback. A mesh-sharded catalog skips the gate:
        # its mesh IS the placement and there is no host copy to prefer.
        if not isinstance(model.factors.item_features, ShardedCatalog) \
                and not serving_tick_on_device(
                    len(known), len(model.item_ids),
                    model.factors.item_features.shape[1]):
            return None
        uidx = np.array([model.user_ids(q.user) for _, q in known], np.int32)
        k = min(max(q.num for _, q in known), len(model.item_ids))
        exclude = self._stacked_masks(model, [q for _, q in known])
        finalize = serve_top_k_batched(
            model.factors.user_features, model.factors.item_features,
            uidx, k, exclude,
        )
        if finalize is None:
            return None
        out = [(i, PredictedResult(())) for i, q in queries
               if q.user not in model.user_ids]

        def resolve():
            scores, idx = finalize()
            from predictionio_tpu.models.serving_filters import (
                topk_to_item_scores,
            )

            res = list(out)
            for row, (i, q) in enumerate(known):
                res.append(
                    (i, PredictedResult(topk_to_item_scores(
                        scores[row], idx[row], model.item_ids, q.num,
                        ItemScore,
                    )))
                )
            return res

        return resolve


# -- serving ----------------------------------------------------------------


@dataclass(frozen=True)
class ServingParams(Params):
    """The custom-serving variant's blacklist file (ref:
    custom-serving/src/main/scala/Serving.scala — re-read per request so
    operators edit the file without redeploying)."""

    filepath: str = ""


class FileBlacklistServing(LServing):
    """Drop disabled products listed one-per-line in ``filepath``
    (the reference's custom-serving variant)."""

    params_class = ServingParams

    def __init__(self, params: ServingParams | None = None):
        self.params = params or ServingParams()

    def serve(self, query: Query, predictions) -> PredictedResult:
        result = predictions[0]
        if not self.params.filepath:
            return result
        try:
            with open(self.params.filepath) as f:
                disabled = {line.strip() for line in f if line.strip()}
        except OSError:
            return result
        return PredictedResult(tuple(
            s for s in result.itemScores if s.item not in disabled
        ))


class Serving(LServing):
    #: identity supplement + first-prediction serve: the device-batched
    #: sweep may bypass serve() entirely (core/sweep.py eligibility)
    batch_passthrough = True

    def __init__(self, params=None):
        pass

    def serve(self, query: Query, predictions) -> PredictedResult:
        return predictions[0]


# -- factory (ref: Engine.scala:20-27 EngineFactory) ------------------------


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class=Serving,
    )


# -- evaluation (ref: the template's evaluation variant — Evaluation.scala
# with PrecisionAtK over k-fold readEval) ----------------------------------


class PrecisionAtK(OptionAverageMetric):
    """Fraction of queries whose held-out item appears in the top-k,
    counting only positively-rated actuals (rating >= threshold)."""

    def __init__(self, k: int = 10, rating_threshold: float = 4.0):
        self.k = k
        self.rating_threshold = rating_threshold

    @property
    def header(self) -> str:
        return f"PrecisionAtK(k={self.k}, threshold={self.rating_threshold})"

    def calculate_qpa(self, q: Query, p: PredictedResult, a: ActualRating):
        if a.rating < self.rating_threshold:
            return None  # excluded from the average (OptionAverageMetric)
        top = [s.item for s in p.itemScores[: self.k]]
        return 1.0 if a.item in top else 0.0

    def batched_fold_stats(self, trained, qa_pairs):
        """Score a whole sweep bucket's fold in ONE batched top-k dispatch
        (models/als.batched_topk_hit_counts), reading back a single
        [n_candidates] hit vector instead of running Q×C calculate_qpa
        calls. Semantics mirror the sequential path exactly: threshold-
        excluded actuals leave the denominator, unknown users and unseen
        held-out items score 0.0, the effective cutoff per query is
        min(query.num, k). Returns None (→ sequential fallback) for
        models this metric does not understand or queries carrying
        serve-time filters the kernel does not reproduce."""
        if not isinstance(trained, BatchedALSModels) \
                or trained.user_stack is None:
            return None
        if any(q.categories is not None or q.blackList
               for q, _a in qa_pairs):
            return None
        from predictionio_tpu.models.als import batched_topk_hit_counts

        c = trained.n_candidates
        n_items = len(trained.item_ids)
        valid = np.array([a.rating >= self.rating_threshold
                          for _q, a in qa_pairs], bool)
        count = float(valid.sum())
        stats = np.zeros((c, 3))
        stats[:, 2] = count
        if count == 0.0 or n_items == 0:
            # count == 0 is the empty-scores NaN path; an empty catalog
            # instead leaves hits at 0 with count intact — every valid
            # query scores 0.0, the sequential empty-prediction behavior
            return stats
        known = np.array([q.user in trained.user_ids
                          for q, _a in qa_pairs], bool)
        uidx = np.array([trained.user_ids(q.user) if ok else 0
                         for ok, (q, _a) in zip(known, qa_pairs)], np.int32)
        target = np.array(
            [trained.item_ids(a.item) if a.item in trained.item_ids else -1
             for _q, a in qa_pairs], np.int32)
        kq = np.array([min(q.num, self.k) for q, _a in qa_pairs], np.int32)
        k = int(min(max(int(kq.max()), 1), n_items))
        hits = np.asarray(batched_topk_hit_counts(
            trained.user_stack, trained.item_stack, uidx, target, kq,
            valid & known, k=k), np.float64)
        stats[:, 0] = hits
        stats[:, 1] = hits  # scores are 0/1: sumsq == sum
        return stats


def evaluation(
    app_name: str = "MyApp1", eval_k: int = 3,
    ranks=(8, 16), lambdas=(0.01, 0.1),
) -> Evaluation:
    """Parameter-sweep evaluation over rank × lambda (ref: the template's
    EngineParamsList generator)."""
    candidates = [
        EngineParams(
            data_source_params=DataSourceParams(app_name=app_name, eval_k=eval_k),
            algorithms_params=(
                ("als", AlgorithmParams(rank=r, numIterations=10, lambda_=l,
                                        seed=3)),
            ),
        )
        for r in ranks
        for l in lambdas
    ]
    return Evaluation(
        engine=engine_factory(),
        engine_params_list=candidates,
        metric=PrecisionAtK(k=10, rating_threshold=4.0),
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Default settings",
    "engineFactory": "predictionio_tpu.templates.recommendation:engine_factory",
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [
        {
            "name": "als",
            "params": {
                "rank": 10,
                "numIterations": 20,
                "lambda_": 0.01,
                "seed": 3,
            },
        }
    ],
}
