"""Classification engine template.

Re-design of the reference's scala-parallel-classification template
(ref: examples/scala-parallel-classification/add-algorithm/src/main/scala/
{Engine,DataSource,Preparator,NaiveBayesAlgorithm,RandomForestAlgorithm,
Serving}.scala): user entities carry ``$set`` attributes (attr0/attr1/attr2)
plus a ``plan`` label; training aggregates current properties and fits a
classifier; queries supply the attributes and get the predicted label.

Like the reference's add-algorithm variant, the engine registers TWO named
algorithms — ``naive`` (multinomial NB, the MLlib NaiveBayes analog) and
``logistic`` (an optax-trained softmax regression; the variant's second
algorithm slot — the reference uses RandomForest there, which is not a
TPU-shaped model, so the second algorithm is a gradient-trained linear
classifier instead). Serving returns the first prediction.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.core import (
    Engine,
    FirstServing,
    P2LAlgorithm,
    PDataSource,
    PPreparator,
)
from predictionio_tpu.core.base import SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.naive_bayes import (
    NaiveBayesModel,
    predict_naive_bayes,
    train_naive_bayes,
)
from predictionio_tpu.parallel.mesh import ComputeContext


@dataclass(frozen=True)
class Query:
    attr0: float = 0.0
    attr1: float = 0.0
    attr2: float = 0.0


@dataclass(frozen=True)
class PredictedResult:
    label: float


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "classification"
    attrs: tuple[str, ...] = ("attr0", "attr1", "attr2")
    label: str = "plan"
    eval_k: int | None = None
    seed: int = 3


@dataclass
class TrainingData(SanityCheck):
    features: np.ndarray  # [n, F]
    labels: np.ndarray  # [n]

    def sanity_check(self) -> None:
        if len(self.labels) == 0:
            raise ValueError(
                "TrainingData is empty; ingest $set events with attributes first"
            )


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        # the Query surface is fixed at attr0/attr1/attr2 (reference parity:
        # the template hardcodes three attributes); attrs only renames which
        # entity properties feed those three slots
        if len(params.attrs) != 3:
            raise ValueError(
                "classification template requires exactly 3 attrs "
                f"(Query has attr0/attr1/attr2); got {params.attrs}"
            )
        self.params = params

    def _read(self) -> TrainingData:
        # aggregated current properties per user (ref: DataSource.scala
        # aggregateProperties over "user" entities)
        props = PEventStore.aggregate_properties(
            self.params.app_name, "user",
            required=[*self.params.attrs, self.params.label],
        )
        features = []
        labels = []
        for pm in props.values():
            features.append([float(pm.get(a, float)) for a in self.params.attrs])
            labels.append(float(pm.get(self.params.label, float)))
        return TrainingData(
            np.asarray(features, np.float32).reshape(-1, len(self.params.attrs)),
            np.asarray(labels, np.float32),
        )

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        from predictionio_tpu.models.cross_validation import split_data

        k = self.params.eval_k
        if not k:
            raise NotImplementedError("set eval_k in datasource params to evaluate")
        td = self._read()
        rows = [(td.features[i], float(td.labels[i]))
                for i in range(len(td.labels))]
        return split_data(
            k,
            rows,
            make_training_data=lambda rs: TrainingData(
                np.asarray([f for f, _ in rs], np.float32).reshape(
                    -1, len(self.params.attrs)
                ),
                np.asarray([l for _, l in rs], np.float32),
            ),
            make_eval_info=lambda rs: {"n_train": len(rs)},
            make_query_actual=lambda row: (
                Query(*[float(v) for v in row[0]]), row[1]
            ),
            seed=self.params.seed,
        )


class Preparator(PPreparator):
    def __init__(self, params=None):
        pass

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> TrainingData:
        return td


# -- naive bayes (ref: NaiveBayesAlgorithm.scala:16-28) ---------------------


@dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0


class NaiveBayesAlgorithm(P2LAlgorithm):
    params_class = NaiveBayesParams
    query_class = Query

    def __init__(self, params: NaiveBayesParams):
        self.params = params

    def train(self, ctx: ComputeContext, td: TrainingData) -> NaiveBayesModel:
        return train_naive_bayes(ctx, td.features, td.labels, self.params.lambda_)

    def predict(self, model: NaiveBayesModel, query: Query) -> PredictedResult:
        labels, _ = predict_naive_bayes(
            model, [query.attr0, query.attr1, query.attr2]
        )
        return PredictedResult(label=float(labels[0]))

    def batch_predict(self, model: NaiveBayesModel, queries):
        """Micro-batched serving: one score matmul for the drained batch
        (predict_naive_bayes is row-batched already)."""
        x = np.array(
            [[q.attr0, q.attr1, q.attr2] for _, q in queries], np.float32
        )
        labels, _ = predict_naive_bayes(model, x)
        return [
            (i, PredictedResult(label=float(lbl)))
            for (i, _q), lbl in zip(queries, labels)
        ]


# -- softmax regression (the add-algorithm second slot) ---------------------


@dataclass(frozen=True)
class LogisticParams(Params):
    learning_rate: float = 0.1
    epochs: int = 200
    l2: float = 1e-4
    seed: int = 0


@dataclass
class LogisticModel:
    w: np.ndarray  # [F, C]
    b: np.ndarray  # [C]
    labels: list


class LogisticAlgorithm(P2LAlgorithm):
    params_class = LogisticParams
    query_class = Query

    def __init__(self, params: LogisticParams):
        self.params = params

    def train(self, ctx: ComputeContext, td: TrainingData) -> LogisticModel:
        import jax
        import jax.numpy as jnp
        import optax

        label_list = sorted(set(td.labels.tolist()))
        label_to_idx = {v: i for i, v in enumerate(label_list)}
        y_host = np.fromiter(
            (label_to_idx[v] for v in td.labels.tolist()), np.int32,
            count=len(td.labels),
        )
        x, n_valid = ctx.device_put_sharded_rows(td.features.astype(np.float32))
        y, _ = ctx.device_put_sharded_rows(y_host)
        wmask = np.zeros(x.shape[0], np.float32)
        wmask[:n_valid] = 1.0
        wmask = jax.device_put(wmask, ctx.batch_sharding())

        n_features = td.features.shape[1]
        n_classes = len(label_list)
        key = jax.random.PRNGKey(self.params.seed)
        params = {
            "w": jax.random.normal(key, (n_features, n_classes)) * 0.01,
            "b": jnp.zeros((n_classes,)),
        }
        tx = optax.adam(self.params.learning_rate)
        opt_state = tx.init(params)
        l2 = self.params.l2

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                logits = x @ p["w"] + p["b"]
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                )
                loss = (losses * wmask).sum() / wmask.sum()
                return loss + l2 * (p["w"] ** 2).sum()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        for _ in range(self.params.epochs):
            params, opt_state, loss = step(params, opt_state)
        return LogisticModel(
            np.asarray(params["w"]), np.asarray(params["b"]), label_list
        )

    def predict(self, model: LogisticModel, query: Query) -> PredictedResult:
        x = np.array([[query.attr0, query.attr1, query.attr2]], np.float32)
        scores = x @ model.w + model.b
        return PredictedResult(label=float(model.labels[int(scores.argmax())]))

    def batch_predict(self, model: LogisticModel, queries):
        """Micro-batched serving: one [b, F] @ [F, C] score for the batch."""
        x = np.array(
            [[q.attr0, q.attr1, q.attr2] for _, q in queries], np.float32
        )
        scores = x @ model.w + model.b
        return [
            (i, PredictedResult(label=float(model.labels[int(row.argmax())])))
            for (i, _q), row in zip(queries, scores)
        ]


class Serving(FirstServing):
    pass


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={
            "naive": NaiveBayesAlgorithm,
            "logistic": LogisticAlgorithm,
        },
        serving_class=Serving,
    )


# -- evaluation: accuracy (ref: the template's evaluation variant) ----------

from predictionio_tpu.core.evaluation import Evaluation  # noqa: E402
from predictionio_tpu.core.metrics import AverageMetric  # noqa: E402
from predictionio_tpu.core import EngineParams  # noqa: E402


class Accuracy(AverageMetric):
    def calculate_qpa(self, q, p: PredictedResult, a: float) -> float:
        return 1.0 if p.label == a else 0.0


def evaluation(app_name: str = "MyApp1", eval_k: int = 3,
               lambdas=(0.1, 1.0, 10.0)) -> Evaluation:
    candidates = [
        EngineParams(
            data_source_params=DataSourceParams(app_name=app_name, eval_k=eval_k),
            algorithms_params=(("naive", NaiveBayesParams(lambda_=l)),),
        )
        for l in lambdas
    ]
    return Evaluation(
        engine=engine_factory(),
        engine_params_list=candidates,
        metric=Accuracy(),
    )


ENGINE_JSON = {
    "id": "default",
    "description": "Default settings",
    "engineFactory": "predictionio_tpu.templates.classification:engine_factory",
    "datasource": {"params": {"app_name": "MyApp1"}},
    "algorithms": [{"name": "naive", "params": {"lambda_": 1.0}}],
}
