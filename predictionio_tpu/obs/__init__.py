"""Process-wide observability layer (L0, no deps on other layers).

The reference leans on per-query bookkeeping and the Spark UI for
visibility (ref: CreateServer.scala:418-420,603-610); this port serves
heavy traffic from long-lived Python processes, where the prerequisite
for every perf PR is quantified hot paths. This package provides:

  * :class:`MetricsRegistry` — thread-safe process registry of
    :class:`Counter` / :class:`Gauge` / :class:`Histogram` metrics.
    Histograms are log-bucketed (fixed exponential bounds, no per-sample
    storage) and answer p50/p90/p99 queries by in-bucket interpolation.
  * Prometheus text exposition (:meth:`MetricsRegistry.expose`), mounted
    as ``GET /metrics`` on every server via
    :func:`predictionio_tpu.utils.http.add_metrics_route`.
  * A request-id context (:mod:`predictionio_tpu.obs.context`): honor an
    incoming ``X-Request-ID``, else generate one; the id flows through
    log records and the feedback loop (query server → event server).
  * JAX compile hooks (:mod:`predictionio_tpu.obs.jax_hooks`): compile
    count and cumulative compile seconds as registry metrics, plus an
    ``xla_compile`` event on the active trace span.
  * Request tracing (:mod:`predictionio_tpu.obs.trace`): sampled span
    timelines riding the request id across gateway → replica →
    batcher → device, kept in a bounded ring + slowest-N reservoir and
    served as ``GET /debug/traces`` / ``pio trace``; histograms carry
    OpenMetrics trace-id exemplars while a sampled span is active.
  * The training-run observatory (:mod:`predictionio_tpu.obs.runlog`,
    the fourth pillar): an append-only per-run JSONL ledger + atomic
    heartbeat under ``PIO_RUNS_DIR``, fed by the training loops'
    step/phase telemetry and read from OUTSIDE the trainer by
    ``pio runs`` / ``pio watch`` / ``pio doctor`` (STALLED-RUN
    judgment). Imported lazily by the training paths; library users of
    obs pay nothing for it.
  * The prediction-quality observatory (:mod:`predictionio_tpu.obs.quality`,
    the fifth pillar): score-drift detection against a trained baseline,
    the feedback-joined online hit-rate ledger behind the
    ``online_quality`` SLO, and the ``/reload`` shadow scorer — surfaced
    as ``GET /debug/quality`` / ``pio quality``. Imported eagerly (it is
    pure stdlib) so its counters predate the first history tick.
  * The fleet layer: metrics federation over a multi-process deploy
    (:mod:`predictionio_tpu.obs.fleet`, ``GET /metrics/fleet`` on the
    gateway), local time-series history rings
    (:mod:`predictionio_tpu.obs.history`, ``GET /debug/history``), and
    declarative SLOs with multi-window burn-rate evaluation
    (:mod:`predictionio_tpu.obs.slo`, ``GET /debug/slo``, the
    ``pio doctor`` triage report). These import lazily (history starts
    its sampler only when a server mounts the scrape surface and
    ``PIO_HISTORY_INTERVAL_S`` > 0), so library users of obs pay
    nothing for the fleet machinery.

Naming convention (enforced at registration): ``pio_`` prefix +
snake_case, so metric names stay scrape-stable across PRs
(tests/test_obs.py guards it).
"""

from predictionio_tpu.obs.context import (  # noqa: F401
    REQUEST_ID_HEADER,
    current_request_id,
    ensure_request_id,
    new_request_id,
    request_id_var,
)
from predictionio_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    validate_metric_name,
)
# Imported last: trace rides metrics (exemplar hook) and context
# (trace id = request id). Importing the package activates the span
# layer everywhere the registry is already active.
from predictionio_tpu.obs import trace  # noqa: E402,F401
# Device-runtime pillar (ISSUE 6): HBM arenas + per-program MFU/retrace
# accounting, and the on-demand profiler capture. Importing here
# registers their gauges and the unattributed-HBM collect hook in the
# same breath as the rest of the scrape surface.
from predictionio_tpu.obs import device, profile  # noqa: E402,F401
# Prediction-quality pillar: imported eagerly so its counters exist
# from the process's FIRST history tick — a family born mid-burst costs
# the rings that burst (the sampler's first sighting of a counter
# establishes a baseline, it can't compute a rate).
from predictionio_tpu.obs import quality  # noqa: E402,F401
# Structured-log pillar (ISSUE 16): imported eagerly for the same
# first-tick reason (its counters feed the error_log_rate series), and
# so obs.logs.warn_once exists before any subsystem's first suppressed
# warning. Ring handler installation stays explicit (logs.install()),
# mirroring the history sampler's ensure_started().
from predictionio_tpu.obs import logs  # noqa: E402,F401
