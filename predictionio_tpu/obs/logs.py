"""Structured log pillar (the sixth): correlated, queryable, bounded.

The other five pillars (metrics, traces, device, fleet/SLO, run ledger,
quality) each made one kind of process state externally visible; plain
stdlib ``logging`` remained write-only — unstructured lines on stderr,
uncorrelated with the ``X-Request-ID`` that already rides every other
surface, and gone the moment the process dies. This module installs ONE
:class:`logging.Handler` on the ``predictionio_tpu`` namespace logger
(every module already logs under it — tools/check_log_hygiene.py
enforces that), so all ~54 existing ``getLogger`` call sites feed it
without a single call-site rewrite. Each record becomes a JSON dict
carrying ts, level, logger, ``server`` (which AppServer handled the
request — a process can host several), the active request id
(:mod:`obs.context`), and the active training-run id
(:mod:`obs.runlog`), and lands in a bounded process-global ring
(``PIO_LOG_RING`` records, default 2048).

Guard rails, in the registry's own idiom:

  * ``pio_log_records_total{level,logger}`` counts every record the
    handler sees (ring-dropped or not), so log volume is a scrapeable
    series even after the ring wraps;
  * storm suppression: a record repeating the same ``(logger, level,
    template)`` more than ``PIO_LOG_STORM_MAX`` times per
    ``PIO_LOG_STORM_WINDOW_S`` stops entering the ring — drops are
    counted (``pio_log_suppressed_total{logger}``) and summarized with
    one synthetic record per window, the cardinality-guard stance
    (bound + counted drop + warn-once, never unbounded growth);
  * :func:`warn_once` — THE process warn-once (trace.py, device.py and
    metrics.py each grew a private one before this module existed) —
    logs the first occurrence per key and counts every suppressed one
    in ``pio_warn_once_total{key}`` so silence stays measurable;
  * every message and traceback is passed through :func:`redact` before
    it is stored, so access keys, ``PIO_*`` secrets and JDBC-style
    connection-string credentials never reach ``/debug/logs`` or a
    post-mortem bundle even when a call site logs them verbatim.

Surfaces: ``GET /debug/logs`` on every server (utils/http.py, 404 when
``PIO_LOGS=0``), the gateway fan-out merge (serve/gateway.py),
``pio logs`` / the ``pio trace`` waterfall interleave (tools/cli.py),
the ``error_log_rate`` history series (obs/history.py) judged by
``pio doctor`` LOG-STORM findings, and the flight recorder
(obs/postmortem.py) that freezes the ring into a bundle on crash.
"""

from __future__ import annotations

import contextvars
import logging
import os
import re
import threading
import time
import traceback as _tb
from collections import deque

from predictionio_tpu.obs.context import request_id_var
from predictionio_tpu.obs.metrics import REGISTRY

__all__ = [
    "LOG_NAMESPACE",
    "current_server_name",
    "diagnose_history_doc",
    "install",
    "logs_enabled",
    "merge_docs",
    "records",
    "redact",
    "redact_env",
    "reset",
    "ring_capacity",
    "server_name_var",
    "set_server_name",
    "to_json",
    "warn_once",
]

#: Every module logger in the package lives under this namespace (the
#: hygiene checker enforces it), so ONE handler here sees them all.
LOG_NAMESPACE = "predictionio_tpu"

_RECORDS_TOTAL = REGISTRY.counter(
    "pio_log_records_total",
    "Log records seen by the structured log handler, by level and logger",
    labels=("level", "logger"),
)
_SUPPRESSED_TOTAL = REGISTRY.counter(
    "pio_log_suppressed_total",
    "Log records dropped from the ring by storm suppression "
    "(PIO_LOG_STORM_MAX repeats per PIO_LOG_STORM_WINDOW_S)",
    labels=("logger",),
)
_WARN_ONCE_TOTAL = REGISTRY.counter(
    "pio_warn_once_total",
    "Invocations of each warn-once key (first one logs, the rest only "
    "count here — suppression stays measurable)",
    labels=("key",),
)
#: Exempt from the series bound (the pio_metrics_dropped_series_total
#: treatment): the bound's own drop path warns THROUGH warn_once, so a
#: bounded warn-once family would re-enter its own counter lock —
#: deadlock. Keys stay bounded by the warn_once contract instead.
_WARN_ONCE_TOTAL._exempt = True

#: Which AppServer (gateway / query_r0 / events / dashboard) is handling
#: the current request — set per-request by utils/http.py next to the
#: request id, because one process hosts several servers and a ring
#: filtered by process alone can't attribute a record to one of them.
server_name_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_server_name", default=None
)

#: Process-level fallback when no request is in flight (a trainer, the
#: CLI, a background thread): ``pio deploy`` sets it to its role.
_default_server: str = "-"


def set_server_name(name: str) -> None:
    """Set the process-default ``server`` attribution for records logged
    outside any request (background threads, startup, trainers)."""
    global _default_server
    _default_server = name or "-"


def current_server_name() -> str:
    return server_name_var.get() or _default_server


def logs_enabled() -> bool:
    """``PIO_LOGS`` (default on; ``0``/``off``/``false`` disables the
    ring and 404s ``/debug/logs``). Read per call so a live process can
    be retuned."""
    return os.environ.get("PIO_LOGS", "1").lower() not in (
        "0", "off", "false", "no")


def ring_capacity() -> int:
    """``PIO_LOG_RING`` records kept (default 2048, floor 16)."""
    try:
        return max(int(os.environ.get("PIO_LOG_RING", "2048")), 16)
    except ValueError:
        return 2048


def _storm_window_s() -> float:
    try:
        return float(os.environ.get("PIO_LOG_STORM_WINDOW_S", "10"))
    except ValueError:
        return 10.0


def _storm_max() -> int:
    """Identical records admitted to the ring per storm window
    (``PIO_LOG_STORM_MAX``, default 20; <= 0 disables suppression)."""
    try:
        return int(os.environ.get("PIO_LOG_STORM_MAX", "20"))
    except ValueError:
        return 20


# ---------------------------------------------------------------------------
# Redaction (shared with obs/postmortem.py)
# ---------------------------------------------------------------------------

#: Patterns applied to every stored message/traceback. Values after
#: secret-shaped key names, secret-shaped PIO_* env assignments, and
#: credentials embedded in URL/JDBC authorities are replaced; the key
#: names themselves survive so the record stays diagnosable.
_REDACTIONS: list[tuple[re.Pattern, str]] = [
    (re.compile(r"(?i)\b(accessKey|access_key|api_?key|secret|token|"
                r"password|passwd|credential)\b(\s*[=:]\s*)"
                r"([^\s&\"',;]+)"),
     r"\1\2[REDACTED]"),
    (re.compile(r"\b(PIO_[A-Z0-9_]*(?:KEY|SECRET|TOKEN|PASSWORD|"
                r"CREDENTIAL)[A-Z0-9_]*)(\s*[=:]\s*)(\S+)"),
     r"\1\2[REDACTED]"),
    # user:password@host in any URL authority, jdbc: prefixed or not
    (re.compile(r"(://[^/\s:@]+:)([^\s@/]+)(@)"), r"\1[REDACTED]\3"),
]

#: Env var NAMES whose values are secrets wholesale (redact_env).
_SECRET_NAME_RE = re.compile(
    r"(?i)(key|secret|token|password|passwd|credential)")


def redact(text: str) -> str:
    """Strip credential material from free text. Applied to every ring
    record and every post-mortem bundle section before storage — a call
    site logging a hostile access key on purpose must not leak it
    through the observability surfaces."""
    for pattern, repl in _REDACTIONS:
        text = pattern.sub(repl, text)
    return text


def redact_env(environ: dict | None = None) -> dict[str, str]:
    """A redacted copy of the environment for post-mortem bundles:
    secret-named variables are replaced wholesale, every other value is
    passed through :func:`redact`."""
    environ = dict(os.environ) if environ is None else dict(environ)
    out: dict[str, str] = {}
    for name in sorted(environ):
        if _SECRET_NAME_RE.search(name):
            out[name] = "[REDACTED]"
        else:
            out[name] = redact(str(environ[name]))
    return out


# ---------------------------------------------------------------------------
# The ring handler
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_RING: deque = deque(maxlen=2048)
_SEQ = 0

#: Per-(logger, level, template) storm windows: key -> [window_start,
#: admitted, dropped]. Bounded like http.py's target cache — wiped
#: wholesale when full, which at worst re-admits one burst per wipe.
_storm: dict[tuple, list] = {}
_STORM_KEYS_MAX = 512

#: Re-entrancy guard: emitting a record increments counters, which can
#: trip the cardinality guard, which warn_once-logs, which would re-enter
#: this handler. One level is enough; deeper is a cycle.
_in_emit = threading.local()


def _trim(text: str, limit: int = 4000) -> str:
    if len(text) <= limit:
        return text
    return text[:limit] + f"... [{len(text) - limit} chars trimmed]"


class _RingHandler(logging.Handler):
    """The one structured handler: JSON-ify, redact, count, suppress,
    ring. Fail-soft end to end — a logging bug must never take down the
    code that logged."""

    def emit(self, record: logging.LogRecord) -> None:
        if getattr(_in_emit, "active", False):
            return
        _in_emit.active = True
        try:
            self._emit(record)
        except Exception:
            pass  # observability never kills the caller
        finally:
            _in_emit.active = False

    def _emit(self, record: logging.LogRecord) -> None:
        global _SEQ
        if not logs_enabled():
            return
        level = record.levelname
        _RECORDS_TOTAL.inc(level=level, logger=record.name)
        # storm suppression keyed on the UNformatted template: a loop
        # logging the same line with varying args is one storm
        now = record.created
        limit = _storm_max()
        summary: dict | None = None
        if limit > 0:
            key = (record.name, record.levelno, record.msg)
            window = _storm_window_s()
            with _LOCK:
                if len(_storm) >= _STORM_KEYS_MAX and key not in _storm:
                    _storm.clear()
                st = _storm.get(key)
                if st is None or now - st[0] >= window:
                    if st is not None and st[2] > 0:
                        summary = self._summary(record, st[2])
                    _storm[key] = st = [now, 0, 0]
                if st[1] >= limit:
                    st[2] += 1
                    _SUPPRESSED_TOTAL.inc(logger=record.name)
                    if summary is not None:
                        self._append(summary)
                    return
                st[1] += 1
        doc = {
            "ts": round(record.created, 3),
            "level": level,
            "logger": record.name,
            "server": current_server_name(),
            "request_id": getattr(record, "request_id", None)
            or request_id_var.get() or "-",
            "run_id": self._run_id(),
            "msg": redact(_trim(self._message(record))),
        }
        if record.exc_info and record.exc_info[0] is not None:
            doc["exc"] = redact(_trim("".join(
                _tb.format_exception(*record.exc_info))))
        if summary is not None:
            self._append(summary)
        self._append(doc)

    @staticmethod
    def _message(record: logging.LogRecord) -> str:
        try:
            return record.getMessage()
        except Exception:
            return str(record.msg)

    @staticmethod
    def _run_id() -> str | None:
        try:
            from predictionio_tpu.obs import runlog

            w = runlog.active()
            return w.run_id if w is not None else None
        except Exception:
            return None

    def _summary(self, record: logging.LogRecord, dropped: int) -> dict:
        """Synthetic once-per-window record so the ring shows THAT a
        storm happened even though its records were dropped."""
        return {
            "ts": round(record.created, 3),
            "level": "WARNING",
            "logger": record.name,
            "server": current_server_name(),
            "request_id": "-",
            "run_id": None,
            "msg": (f"storm suppression dropped {dropped} repeat(s) of: "
                    + redact(_trim(str(record.msg), 200))),
            "suppressed": dropped,
        }

    @staticmethod
    def _append(doc: dict) -> None:
        global _SEQ, _RING
        with _LOCK:
            _SEQ += 1
            doc["seq"] = _SEQ
            cap = ring_capacity()
            if _RING.maxlen != cap:  # retuned live: rebuild, keep tail
                _RING = deque(_RING, maxlen=cap)
            _RING.append(doc)


_HANDLER: _RingHandler | None = None
_INSTALL_LOCK = threading.Lock()


def install(server_name: str | None = None) -> None:
    """Attach the ring handler to the ``predictionio_tpu`` namespace
    logger (idempotent; every server mounts it via
    utils/http.add_metrics_route, trainers/CLI via their entrypoints).
    Sets the namespace logger's level to ``PIO_LOG_LEVEL`` (default
    INFO) when unset, so INFO-level records reach the ring; stderr
    output is unchanged (the stdlib lastResort handler still gates at
    WARNING)."""
    global _HANDLER
    if server_name:
        set_server_name(server_name)
    with _INSTALL_LOCK:
        if _HANDLER is None:
            _HANDLER = _RingHandler(level=logging.NOTSET)
        ns = logging.getLogger(LOG_NAMESPACE)
        if _HANDLER not in ns.handlers:
            ns.addHandler(_HANDLER)
        if ns.level == logging.NOTSET:
            wanted = os.environ.get("PIO_LOG_LEVEL", "INFO").upper()
            ns.setLevel(getattr(logging, wanted, logging.INFO))


def reset() -> None:
    """Detach the handler and clear the ring/storm state (tests)."""
    global _HANDLER, _SEQ
    with _INSTALL_LOCK:
        if _HANDLER is not None:
            logging.getLogger(LOG_NAMESPACE).removeHandler(_HANDLER)
            _HANDLER = None
    with _LOCK:
        _RING.clear()
        _storm.clear()
        _SEQ = 0
    with _WARNED_LOCK:
        _WARNED.clear()


# ---------------------------------------------------------------------------
# Reads
# ---------------------------------------------------------------------------

_LEVELS = {"DEBUG": 10, "INFO": 20, "WARNING": 30, "ERROR": 40,
           "CRITICAL": 50}


def records(level: str | None = None, logger: str | None = None,
            since: int | None = None, request_id: str | None = None,
            limit: int | None = None) -> list[dict]:
    """Ring records oldest→newest after filters: ``level`` is a minimum
    severity, ``logger`` a name prefix, ``since`` a ``seq`` watermark
    (records AFTER it — the ``pio logs --follow`` cursor), and
    ``request_id`` an exact match for cross-server correlation."""
    with _LOCK:
        out = list(_RING)
    if level:
        floor = _LEVELS.get(level.upper())
        if floor is None:
            raise ValueError(f"unknown level {level!r}")
        out = [r for r in out if _LEVELS.get(r["level"], 0) >= floor]
    if logger:
        out = [r for r in out if r["logger"].startswith(logger)]
    if since is not None:
        out = [r for r in out if r["seq"] > since]
    if request_id:
        out = [r for r in out if r.get("request_id") == request_id]
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def to_json(level: str | None = None, logger: str | None = None,
            since: int | None = None, request_id: str | None = None,
            limit: int | None = None) -> dict:
    """The ``/debug/logs`` document."""
    recs = records(level=level, logger=logger, since=since,
                   request_id=request_id, limit=limit)
    with _LOCK:
        last_seq = _SEQ
    return {
        "capacity": ring_capacity(),
        "lastSeq": last_seq,
        "count": len(recs),
        "records": recs,
    }


def merge_docs(docs: list[dict], limit: int = 500) -> dict:
    """Fleet merge for the gateway's ``/debug/logs`` fan-out: concat
    every member's records, dedupe (an in-process ``--replicas N``
    deploy shares ONE ring, so the same record comes back once per
    member), order by time then sequence, keep the newest ``limit``."""
    seen: set = set()
    merged: list[dict] = []
    for doc in docs:
        for rec in (doc or {}).get("records") or []:
            key = (rec.get("seq"), rec.get("ts"), rec.get("logger"),
                   rec.get("msg"))
            if key in seen:
                continue
            seen.add(key)
            merged.append(rec)
    merged.sort(key=lambda r: (r.get("ts") or 0, r.get("seq") or 0))
    if limit and limit > 0:
        merged = merged[-limit:]
    return {"count": len(merged), "records": merged}


# ---------------------------------------------------------------------------
# warn_once — the one process-wide suppressed-warning helper
# ---------------------------------------------------------------------------

_WARNED: set[str] = set()
_WARNED_LOCK = threading.Lock()


def warn_once(key: str, msg: str, *args,
              logger: logging.Logger | None = None,
              exc_info: bool = False) -> bool:
    """Log ``msg`` at WARNING exactly once per ``key`` for the process
    lifetime; EVERY call (logged or suppressed) increments
    ``pio_warn_once_total{key}`` so repetition stays visible on
    /metrics after the one log line scrolled away. Keys must be
    bounded (a family name, a program name — never a request id).
    Returns True when this call emitted the log line."""
    _WARN_ONCE_TOTAL.inc(key=key)
    with _WARNED_LOCK:
        if key in _WARNED:
            return False
        _WARNED.add(key)
    (logger or logging.getLogger(__name__)).warning(
        msg, *args, exc_info=exc_info)
    return True


# ---------------------------------------------------------------------------
# LOG-STORM judgment (pio doctor)
# ---------------------------------------------------------------------------


def storm_errors_per_s() -> float:
    """Sustained error-record rate that reads as a LOG-STORM
    (``PIO_LOG_STORM_ERRORS_PER_S``, default 5/s)."""
    try:
        return float(os.environ.get("PIO_LOG_STORM_ERRORS_PER_S", "5"))
    except ValueError:
        return 5.0


def diagnose_history_doc(doc: dict | None, now: float | None = None,
                         window_s: float = 120.0) -> list[dict]:
    """LOG-STORM findings from a fetched ``/debug/history`` document
    (the doctor runs OUTSIDE the server process, so it judges the
    series the server already recorded): critical when the
    ``error_log_rate`` series burned past the threshold on >= 2 points
    in the trailing window. Finding shape matches
    obs.runlog.diagnose_runs."""
    series = ((doc or {}).get("series") or {}).get("error_log_rate") or {}
    pts = series.get("points") or []
    now = time.time() if now is None else now
    threshold = storm_errors_per_s()
    burning = [v for t, v in pts
               if v is not None and now - t <= window_s and v >= threshold]
    if len(burning) < 2:
        return []
    return [{
        "severity": "critical",
        "subject": "log volume",
        "detail": (
            f"LOG-STORM: error_log_rate peaked at {max(burning):.1f}/s "
            f"({len(burning)} samples >= {threshold:g}/s in the last "
            f"{window_s:.0f}s) — something is failing repeatedly; "
            "inspect `pio logs --level ERROR` and the suppression "
            "counters (pio_log_suppressed_total), then capture "
            "`pio postmortem` before restarting"),
    }]
