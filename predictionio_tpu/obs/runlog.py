"""Training-run observatory: the run ledger (fourth obs pillar).

Serving has been watchable end-to-end since the fleet layer landed, but
training was a black box: BENCH_r06 burned two 7200 s walls at ~70% CPU
with no way to tell hung from slow. This module gives every training
run an on-disk, append-only JSONL ledger — one record per step/phase —
plus a monotonic heartbeat file rewritten atomically, both under a runs
directory (``PIO_RUNS_DIR``), so an *external* process (``pio watch``,
``pio runs``, ``pio doctor``) can answer "is it making progress?"
without touching the trainer.

Writer side (the trainer process):

  * :func:`run_scope` — opened by ``workflow.core_workflow.run_train``
    around the whole train; one ``<run-id>.jsonl`` ledger per run, with
    ``start`` / ``step`` / ``phase`` / ``end`` records and a
    ``<run-id>.hb`` heartbeat (tmp + ``os.replace``, so a reader never
    sees a torn beat). The heartbeat is a PROCESS-LIVENESS signal: a
    background keepalive thread rewrites it every couple of seconds, so
    a minutes-long XLA compile or fused device dispatch reads as alive
    (slow), while a killed trainer goes stale within one beat interval
    — progress lives in the step records, liveness in the beat. The
    runs dir is bounded by a retention cap (``PIO_RUNS_RETAIN``
    ledgers, oldest pruned at run start).
  * :func:`step` / :class:`StepTimer` — called from the training loops
    that already carry the ``train.iteration`` fault points (dense /
    stacked / bucketed ALS, two-tower steps, SASRec epochs). Each step
    feeds ``pio_train_step_seconds{program}``,
    ``pio_train_progress_ratio`` and (via a collect hook)
    ``pio_train_heartbeat_age_seconds`` — the same registry the history
    rings sample — and, when a run is active, appends a ledger record
    with throughput, loss (when the algorithm reports one), the HBM
    peak from the :class:`~predictionio_tpu.obs.device.DeviceArena`
    gauges, and an ETA from the rolling median step time. Ledger
    emission is thinned to ~:data:`_MAX_LEDGER_STEPS` records per run
    so a 100k-step trainer cannot grow its ledger unboundedly; the
    metrics observe every step.
  * Steps always update the metrics; the ledger only grows inside an
    active :func:`run_scope` — benches and tests stay ledger-silent
    unless they opt in.

Reader side (any process):

  * :func:`read_run` tolerates a killed writer: a torn final line (the
    crash window of an append) is skipped, never fatal.
  * :func:`summarize` derives status (RUNNING / COMPLETED / FAILED —
    plus STALLED, judged from the heartbeat), progress, median step
    seconds, throughput and ETA.
  * :func:`diagnose_runs` turns a stale heartbeat on a RUNNING run into
    the ``pio doctor`` STALLED-RUN finding: age >
    max(``PIO_RUNS_STALL_FACTOR`` x the run's own median step time,
    ``PIO_RUNS_STALL_GRACE``) — a hung trainer is flagged within one
    heartbeat window, a merely-slow one is not.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import statistics
import threading
import time
from collections import deque
from contextlib import contextmanager
from pathlib import Path

from predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

__all__ = [
    "RunWriter",
    "StepTimer",
    "active",
    "diagnose_runs",
    "fused_steps",
    "list_runs",
    "note",
    "phase",
    "read_run",
    "run_scope",
    "runs_dir",
    "stall_threshold",
    "step",
    "step_iterations_enabled",
    "summarize",
    "want_steps",
]

STEP_SECONDS = REGISTRY.histogram(
    "pio_train_step_seconds",
    "Wall seconds per training step/iteration, by profiled program",
    labels=("program",),
)
PROGRESS_RATIO = REGISTRY.gauge(
    "pio_train_progress_ratio",
    "iteration/total of the active training run's most recent step",
)
HEARTBEAT_AGE = REGISTRY.gauge(
    "pio_train_heartbeat_age_seconds",
    "Seconds since the active training run's last heartbeat "
    "(refreshed at scrape; absent outside a run)",
)

#: Ledger step records are thinned to at most ~this many per run (the
#: metrics still observe every step).
_MAX_LEDGER_STEPS = 400

#: Minimum seconds between heartbeat rewrites (an atomic rename each) —
#: sub-millisecond training steps must not turn the beat into fsync load.
_HB_MIN_INTERVAL = 0.25

#: Keepalive beat period (seconds): the background thread's liveness
#: signal between step records (long compiles, fused dispatches).
_HB_KEEPALIVE_INTERVAL = 2.0

_SAFE_ID = re.compile(r"[^A-Za-z0-9_.-]")


def runs_dir() -> Path:
    """``PIO_RUNS_DIR``, else ``$PIO_TPU_HOME/runs``, else
    ``~/.predictionio_tpu/runs`` (the pidfile convention's home)."""
    env = os.environ.get("PIO_RUNS_DIR")
    if env:
        return Path(env)
    home = os.environ.get("PIO_TPU_HOME")
    base = Path(home) if home else Path.home() / ".predictionio_tpu"
    return base / "runs"


def _retention_cap() -> int:
    """``PIO_RUNS_RETAIN`` ledgers kept (default 32, floor 1)."""
    try:
        return max(int(os.environ.get("PIO_RUNS_RETAIN", "32")), 1)
    except ValueError:
        return 32


def _stall_factor() -> float:
    try:
        return float(os.environ.get("PIO_RUNS_STALL_FACTOR", "8"))
    except ValueError:
        return 8.0


def _stall_grace() -> float:
    try:
        return float(os.environ.get("PIO_RUNS_STALL_GRACE", "10"))
    except ValueError:
        return 10.0


def stall_threshold(median_step_s: float | None) -> float:
    """Heartbeat age beyond which a RUNNING run reads as STALLED: N x
    the run's OWN median step time (``PIO_RUNS_STALL_FACTOR``, default
    8), floored at ``PIO_RUNS_STALL_GRACE`` seconds (default 10) so
    sub-second steppers aren't flagged on scheduler noise."""
    med = median_step_s or 0.0
    return max(_stall_factor() * med, _stall_grace())


def step_iterations_enabled() -> bool:
    """``PIO_RUNS_STEP_ITERATIONS`` (default on): whether fused
    whole-run training dispatches switch to per-iteration dispatch while
    a ledger run is active, trading some dispatch overhead for live
    step-level progress. 0 restores the fused paths under ``pio
    train``."""
    return os.environ.get("PIO_RUNS_STEP_ITERATIONS", "1") != "0"


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


def _prune(directory: Path, keep: int, exclude: set[str]) -> None:
    """Drop the oldest ledgers (and their heartbeats) beyond the
    retention cap. Count-based, oldest-mtime first; the just-created
    ledger is excluded so a cap of 1 keeps exactly the new run."""
    try:
        ledgers = [p for p in directory.glob("*.jsonl")
                   if p.name not in exclude]
        ledgers.sort(key=lambda p: p.stat().st_mtime)
        for p in ledgers[: max(len(ledgers) - (keep - 1), 0)]:
            p.unlink(missing_ok=True)
            p.with_suffix(".hb").unlink(missing_ok=True)
    except OSError:
        logger.warning("run-ledger retention prune failed", exc_info=True)


class RunWriter:
    """One training run's ledger + heartbeat. All methods are fail-soft
    (a full disk degrades observability, never the train) and
    thread-safe (two-tower's trainer threads may step concurrently)."""

    def __init__(self, run_id: str, directory: Path,
                 engine: str = "", params_hash: str = ""):
        self.run_id = _SAFE_ID.sub("_", str(run_id)) or "run"
        self.directory = directory
        directory.mkdir(parents=True, exist_ok=True)
        self.path = directory / f"{self.run_id}.jsonl"
        self.hb_path = self.path.with_suffix(".hb")
        self._lock = threading.Lock()
        self._recent: deque[float] = deque(maxlen=64)
        self._last_hb = 0.0
        self._hb_progress: dict = {}
        self.last_beat_t = time.time()
        self._closed = False
        _prune(directory, _retention_cap(), exclude={self.path.name})
        self._f = open(self.path, "a", encoding="utf-8")
        self._append({
            "kind": "start", "t": round(time.time(), 3),
            "runId": self.run_id, "engine": engine,
            "paramsHash": params_hash, "pid": os.getpid(),
        })
        self.heartbeat(force=True)
        # The keepalive thread: the heartbeat is a PROCESS-LIVENESS
        # signal, not a progress signal (step records carry progress).
        # Without it, the first iteration's minutes-long XLA compile —
        # or a fused multi-minute device dispatch — would read as
        # STALLED from outside; with it, only a dead (or entirely
        # wedged) trainer goes stale, which is exactly the judgment the
        # doctor needs. A SIGKILL kills the daemon thread with the
        # process, so the beat stops within one interval.
        self._stop = threading.Event()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name=f"runlog-hb-{self.run_id}",
            daemon=True)
        self._beat_thread.start()

    def _beat_loop(self) -> None:
        while not self._stop.wait(_HB_KEEPALIVE_INTERVAL):
            self.heartbeat()

    def abandon(self) -> None:
        """Stop beating and close WITHOUT an end record — the state a
        killed trainer leaves behind (tests simulate kills with this;
        a real SIGKILL needs no cooperation)."""
        self._stop.set()
        self._beat_thread.join(timeout=2.0)
        with self._lock:
            self._closed = True
            try:
                self._f.close()
            except OSError:
                pass

    # -- records ------------------------------------------------------------
    def _append(self, rec: dict) -> None:
        # one line per write() call: the crash window is a torn final
        # line, which readers skip — earlier records stay intact
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            if self._closed:
                return
            try:
                self._f.write(line)
                self._f.flush()
            except (OSError, ValueError):
                logger.warning("run ledger append failed", exc_info=True)

    def _ledger_every(self, total: int) -> int:
        return max(int(total) // _MAX_LEDGER_STEPS, 1)

    def step(self, program: str, *, iteration: int, total: int,
             seconds: float, phase: str = "train",
             loss: float | None = None,
             examples_per_sec: float | None = None,
             fused: int | None = None) -> None:
        with self._lock:
            self._recent.append(seconds)
            med = statistics.median(self._recent)
        every = self._ledger_every(total)
        if iteration % every == 0 or iteration >= total or iteration <= 1:
            rec: dict = {
                "kind": "step", "t": round(time.time(), 3),
                "program": program, "phase": phase,
                "iteration": int(iteration), "total": int(total),
                "stepSeconds": round(seconds, 6),
            }
            if seconds > 0:
                rec["itPerSec"] = round(1.0 / seconds, 4)
            if loss is not None and math.isfinite(loss):
                rec["loss"] = round(float(loss), 6)
            if examples_per_sec is not None:
                rec["examplesPerSec"] = round(examples_per_sec, 2)
            if fused is not None:
                # one dispatch covered `fused` iterations; stepSeconds
                # is their average
                rec["fusedIterations"] = int(fused)
            hbm = _hbm_peak_bytes()
            if hbm is not None:
                rec["hbmPeakBytes"] = hbm
            if total > iteration:
                rec["etaSeconds"] = round(med * (total - iteration), 3)
            self._append(rec)
        self.heartbeat(iteration=iteration, total=total, phase=phase)

    def phase(self, name: str, seconds: float | None = None) -> None:
        rec: dict = {"kind": "phase", "t": round(time.time(), 3),
                     "phase": name}
        if seconds is not None:
            rec["seconds"] = round(float(seconds), 4)
        self._append(rec)
        self.heartbeat(phase=name, force=True)

    def note(self, key: str, value) -> None:
        """One named fact about the run (shard imbalance, gather bytes,
        layout choices) — a "note" record; the newest value per key wins
        in :func:`read_run`. Values must be JSON scalars."""
        self._append({"kind": "note", "t": round(time.time(), 3),
                      "key": str(key), "value": value})

    def end(self, status: str, error: str | None = None) -> None:
        self._stop.set()
        rec: dict = {"kind": "end", "t": round(time.time(), 3),
                     "status": status}
        if error:
            rec["error"] = error[:500]
        self._append(rec)
        self.heartbeat(force=True)
        with self._lock:
            self._closed = True
            try:
                self._f.close()
            except OSError:
                pass
        self._beat_thread.join(timeout=2.0)

    # -- heartbeat ----------------------------------------------------------
    def heartbeat(self, iteration: int | None = None,
                  total: int | None = None, phase: str | None = None,
                  force: bool = False) -> None:
        """Atomically rewrite the ``.hb`` file (tmp + ``os.replace``) so
        an external reader always sees a complete beat; throttled so
        fast steppers don't turn progress into rename load. Progress
        fields persist across beats: a keepalive beat (no args) re-emits
        the last step's iteration/total/phase instead of erasing them —
        otherwise `pio watch` would flicker back to the thinned ledger's
        older progress whenever a keepalive landed between steps."""
        now = time.monotonic()
        with self._lock:
            # record progress BEFORE the throttle gate: a throttled
            # step's fields must still ride the next beat
            if iteration is not None:
                self._hb_progress["iteration"] = int(iteration)
            if total is not None:
                self._hb_progress["total"] = int(total)
            if phase is not None:
                self._hb_progress["phase"] = phase
            if not force and now - self._last_hb < _HB_MIN_INTERVAL:
                return
            self._last_hb = now
            progress = dict(self._hb_progress)
        doc: dict = {"t": round(time.time(), 3), "pid": os.getpid(),
                     **progress}
        tmp = self.hb_path.with_suffix(f".hb.tmp{os.getpid()}")
        try:
            tmp.write_text(json.dumps(doc), encoding="utf-8")
            os.replace(tmp, self.hb_path)
            self.last_beat_t = doc["t"]
        except OSError:
            logger.warning("run heartbeat write failed", exc_info=True)
            tmp.unlink(missing_ok=True)


def _hbm_peak_bytes() -> int | None:
    try:
        from predictionio_tpu.obs import device as device_obs

        return int(device_obs.peak_total_bytes())
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Process-global active run
# ---------------------------------------------------------------------------

_ACTIVE: RunWriter | None = None
_ACTIVE_LOCK = threading.Lock()


def active() -> RunWriter | None:
    return _ACTIVE


def want_steps() -> bool:
    """True when a fused training dispatch should run per-iteration for
    live progress: a ledger run is active and stepping is enabled."""
    return _ACTIVE is not None and step_iterations_enabled()


@contextmanager
def run_scope(run_id: str | None = None, engine: str = "",
              params_hash: str = "", directory: Path | None = None):
    """Activate a run ledger for the duration of a training run.
    Exceptions mark the run FAILED and propagate; a clean exit marks it
    COMPLETED. Nested scopes (an eval sweep inside ``run_train``) reuse
    the outer run. Yields the writer, or None when the ledger could not
    be opened (training proceeds unobserved, never fails)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        outer = _ACTIVE
    if outer is not None:
        yield outer
        return
    rid = run_id or time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
    writer: RunWriter | None = None
    try:
        writer = RunWriter(rid, directory or runs_dir(), engine=engine,
                           params_hash=params_hash)
    except OSError:
        logger.warning("run ledger unavailable; training unobserved",
                       exc_info=True)
    if writer is None:
        yield None
        return
    with _ACTIVE_LOCK:
        _ACTIVE = writer
    try:
        yield writer
    except BaseException as e:
        writer.end("FAILED", error=repr(e))
        raise
    else:
        writer.end("COMPLETED")
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
        # absent-outside-a-run gauges: a frozen last value would read as
        # a forever-fresh heartbeat / stuck progress on /metrics
        try:
            HEARTBEAT_AGE.remove()
            PROGRESS_RATIO.remove()
        except Exception:
            pass


def step(program: str, *, iteration: int, total: int, seconds: float,
         phase: str = "train", loss: float | None = None,
         examples_per_sec: float | None = None) -> None:
    """One training step's telemetry: metrics always (histogram +
    progress gauge feed the history rings whether or not a run is
    active), ledger when inside a :func:`run_scope`. Never raises."""
    try:
        STEP_SECONDS.observe(max(float(seconds), 0.0), program=program)
        if total > 0:
            PROGRESS_RATIO.set(min(iteration / total, 1.0))
        w = _ACTIVE
        if w is not None:
            w.step(program, iteration=iteration, total=total,
                   seconds=seconds, phase=phase, loss=loss,
                   examples_per_sec=examples_per_sec)
    except Exception:
        logger.warning("run-ledger step emission failed", exc_info=True)


def fused_steps(program: str, total: int, seconds: float,
                phase: str = "solve", loss: float | None = None,
                synced: bool = True) -> None:
    """Telemetry for a whole-run fused dispatch (``total`` iterations in
    one XLA call): the per-iteration average lands once in the step
    histogram and once in the ledger, marked ``fusedIterations`` so
    readers don't mistake it for a single slow step. ``synced=False``
    says the caller timed only the async ENQUEUE (a deliberately
    unsynchronized pipeline path): the ledger record still lands for
    progress, but the histogram is skipped — an enqueue-time "step"
    would poison the windowed ``train_step_p50_ms`` series."""
    try:
        avg = float(seconds) / max(int(total), 1)
        if synced:
            STEP_SECONDS.observe(max(avg, 0.0), program=program)
        PROGRESS_RATIO.set(1.0)
        w = _ACTIVE
        if w is not None:
            w.step(program, iteration=total, total=total, seconds=avg,
                   phase=phase, loss=loss, fused=total)
    except Exception:
        logger.warning("run-ledger fused emission failed", exc_info=True)


def phase(name: str, seconds: float | None = None) -> None:
    """Record a named phase (ledger only; no-op outside a run)."""
    w = _ACTIVE
    if w is not None:
        w.phase(name, seconds)


def note(key: str, value) -> None:
    """Record a named run fact (ledger only; no-op outside a run).
    Never raises — telemetry must not fail training."""
    w = _ACTIVE
    if w is not None:
        try:
            w.note(key, value)
        except Exception:
            logger.warning("run-ledger note emission failed",
                           exc_info=True)


class StepTimer:
    """Per-iteration wall clock for a training loop. ``step(i)`` times
    the interval since the previous call and emits through
    :func:`step`; ``sync`` (a device array) is blocked on first so the
    histogram records compute time, not enqueue time — the per-iteration
    loops this timer instruments are already dispatch-per-step, so the
    sync costs at most one in-flight step of overlap."""

    def __init__(self, program: str, total: int, start: int = 0,
                 phase: str = "train",
                 examples_per_step: float | None = None):
        self.program = program
        self.total = int(total)
        self.phase = phase
        self.examples_per_step = examples_per_step
        self._t = time.perf_counter()
        _ = start  # documented anchor; the timer is interval-based

    def step(self, iteration: int, sync=None,
             loss: float | None = None) -> None:
        if sync is not None:
            try:
                import jax

                jax.block_until_ready(sync)
            except Exception:
                pass
        now = time.perf_counter()
        dt = now - self._t
        self._t = now
        eps = (self.examples_per_step / dt
               if self.examples_per_step and dt > 0 else None)
        step(self.program, iteration=iteration, total=self.total,
             seconds=dt, phase=self.phase, loss=loss,
             examples_per_sec=eps)


def _refresh_heartbeat_age() -> None:
    w = _ACTIVE
    if w is not None:
        HEARTBEAT_AGE.set(max(time.time() - w.last_beat_t, 0.0))


REGISTRY.add_collect_hook(_refresh_heartbeat_age)


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


def read_run(path: Path | str) -> dict:
    """Parse one run ledger (+ its heartbeat). A killed writer's torn
    final line — the only partial state an append can leave — is
    skipped; a missing heartbeat file degrades to the ledger's newest
    record time."""
    path = Path(path)
    meta: dict = {}
    steps: list[dict] = []
    phases: list[dict] = []
    notes: dict = {}
    end: dict | None = None
    try:
        text = path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        text = ""
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail from a killed writer
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind == "start":
            meta = rec
        elif kind == "step":
            steps.append(rec)
        elif kind == "phase":
            phases.append(rec)
        elif kind == "note":
            if rec.get("key"):
                notes[rec["key"]] = rec.get("value")
        elif kind == "end":
            end = rec
    hb = None
    try:
        hb = json.loads(path.with_suffix(".hb").read_text(encoding="utf-8"))
        if not isinstance(hb, dict):
            hb = None
    except (OSError, ValueError):
        pass
    return {
        "runId": meta.get("runId") or path.stem,
        "path": str(path),
        "meta": meta,
        "steps": steps,
        "phases": phases,
        "notes": notes,
        "end": end,
        "heartbeat": hb,
    }


def summarize(run: dict, now: float | None = None) -> dict:
    """Status + progress + rates derived from one :func:`read_run` doc.
    Pure function of (run, now) so the STALLED judgment unit-tests with
    synthetic clocks."""
    now = time.time() if now is None else now
    end = run.get("end")
    steps = run.get("steps") or []
    last = steps[-1] if steps else None
    step_secs = [s["stepSeconds"] for s in steps
                 if isinstance(s.get("stepSeconds"), (int, float))]
    median_step = statistics.median(step_secs) if step_secs else None
    hb = run.get("heartbeat") or {}
    # the heartbeat file is THE liveness signal (rewritten atomically on
    # every step); ledger record times are a fallback for a run whose
    # .hb never landed or was swept, and the ledger file's mtime is the
    # last resort — a trainer killed before flushing ANY record must
    # still age into STALLED, not float as forever-RUNNING
    last_beat = hb.get("t")
    if last_beat is None:
        times = [t for t in ((last or {}).get("t"),
                             run.get("meta", {}).get("t"))
                 if t is not None]
        last_beat = max(times) if times else None
    if last_beat is None and run.get("path"):
        try:
            last_beat = os.path.getmtime(run["path"])
        except OSError:
            pass
    age = max(now - last_beat, 0.0) if last_beat is not None else None
    status = (end or {}).get("status") or "RUNNING"
    stalled = (end is None and age is not None
               and age > stall_threshold(median_step))
    if stalled:
        status = "STALLED"
    iteration = (last or {}).get("iteration")
    total = (last or {}).get("total")
    # the heartbeat may be ahead of the (thinned) ledger steps
    if hb.get("iteration") is not None and (
            iteration is None or hb["iteration"] >= iteration):
        iteration, total = hb.get("iteration"), hb.get("total", total)
    progress = (iteration / total if iteration is not None and total
                else None)
    started = run.get("meta", {}).get("t")
    ended = (end or {}).get("t")
    duration = None
    if started is not None:
        duration = ((ended if ended is not None else
                     (last_beat if end is None else started)) - started)
    return {
        "runId": run.get("runId"),
        "path": run.get("path"),
        "engine": run.get("meta", {}).get("engine", ""),
        "paramsHash": run.get("meta", {}).get("paramsHash", ""),
        "pid": hb.get("pid") or run.get("meta", {}).get("pid"),
        "status": status,
        "stalled": bool(stalled),
        "phase": hb.get("phase") or (last or {}).get("phase"),
        "program": (last or {}).get("program"),
        "iteration": iteration,
        "total": total,
        "progress": progress,
        "medianStepSeconds": median_step,
        "lastStepSeconds": (last or {}).get("stepSeconds"),
        "itPerSec": (last or {}).get("itPerSec"),
        "loss": next((s.get("loss") for s in reversed(steps)
                      if s.get("loss") is not None), None),
        "etaSeconds": (last or {}).get("etaSeconds") if end is None else 0.0,
        "hbmPeakBytes": (last or {}).get("hbmPeakBytes"),
        "heartbeatAgeSeconds": round(age, 3) if age is not None else None,
        "stallThresholdSeconds": round(stall_threshold(median_step), 3),
        "startedAt": started,
        "endedAt": ended,
        "durationSeconds": (round(duration, 3) if duration is not None
                            else None),
        "error": (end or {}).get("error"),
        "steps": len(steps),
        "notes": run.get("notes") or {},
    }


def list_runs(directory: Path | str | None = None,
              limit: int | None = None,
              now: float | None = None) -> list[dict]:
    """Summaries of the ledgers in the runs dir, newest first."""
    directory = Path(directory) if directory else runs_dir()
    try:
        ledgers = sorted(directory.glob("*.jsonl"),
                         key=lambda p: p.stat().st_mtime, reverse=True)
    except OSError:
        return []
    if limit is not None:
        ledgers = ledgers[:limit]
    return [summarize(read_run(p), now=now) for p in ledgers]


def throughput_series(run: dict, n: int = 40) -> list[float | None]:
    """The last ``n`` ledger steps' it/s, for the watch sparkline."""
    out = [s.get("itPerSec") for s in (run.get("steps") or [])[-n:]]
    return [v for v in out if v is not None] or []


def diagnose_runs(directory: Path | str | None = None,
                  now: float | None = None,
                  limit: int = 50) -> list[dict]:
    """``pio doctor`` findings from the local run ledger: a critical
    STALLED-RUN per RUNNING run whose heartbeat age exceeds its stall
    threshold, and a SHARD-IMBALANCE (sharded ALS) or EMB-SHARD-IMBALANCE
    (row-sharded embedding tables) warn per run whose noted load skew
    exceeds ``PIO_SHARD_IMBALANCE_WARN`` (default 2.0). Same finding
    shape as obs.fleet.diagnose."""
    findings: list[dict] = []
    from predictionio_tpu.obs import shards as _shards

    warn_at = _shards.shard_imbalance_warn()
    # one code path for every shard-skew note: (note key, finding name,
    # what the skew is measured over, why waiting on the heavy shard
    # hurts, what to turn). Stragglers are the classic sharded failure
    # mode — every collective waits for the heaviest shard, so a
    # 3x-loaded shard makes the whole mesh run at 1/3 throughput.
    imbalance_rules = (
        ("shard_imbalance",
         "SHARD-IMBALANCE: heaviest data shard carries {imb:.2f}x the "
         "mean rating cells (threshold {warn_at:g}x) — every sharded-ALS "
         "collective waits on that straggler; re-index entity ids toward "
         "a uniform spread or change the shard count"),
        # row-sharded embedding trainers (PIO_EMB_SHARDS): skewed id
        # ownership loads one shard's all_to_all segment and its
        # touched-row adam heavier than the rest — surfaced from
        # pio_emb_shard_touched_rows' per-shard counts noted at start
        ("emb_shard_imbalance",
         "EMB-SHARD-IMBALANCE: heaviest embedding shard owns {imb:.2f}x "
         "the mean touched rows (threshold {warn_at:g}x) — the id "
         "exchange and the touched-row adam both wait on that shard; "
         "re-index toward a uniform id spread or change PIO_EMB_SHARDS"),
    )
    for s in list_runs(directory, limit=limit, now=now):
        notes = s.get("notes") or {}
        for note_key, template in imbalance_rules:
            imb = notes.get(note_key)
            if isinstance(imb, (int, float)) and imb > warn_at:
                findings.append({
                    "severity": "warn",
                    "subject": f"run {s['runId']}",
                    "detail": template.format(imb=imb, warn_at=warn_at),
                })
        if not s["stalled"]:
            continue
        prog = (f"{s['iteration']}/{s['total']}"
                if s.get("iteration") is not None else "no steps yet")
        findings.append({
            "severity": "critical",
            "subject": f"run {s['runId']}",
            "detail": (
                f"STALLED: heartbeat {s['heartbeatAgeSeconds']:.1f}s old "
                f"(threshold {s['stallThresholdSeconds']:.1f}s = "
                f"{_stall_factor():g}x median step "
                f"{(s['medianStepSeconds'] or 0):.3g}s, floor "
                f"{_stall_grace():g}s) at {prog}"
                f"{' in ' + s['phase'] if s.get('phase') else ''} — the "
                f"trainer (pid {s.get('pid') or '?'}) is hung or dead, "
                "not slow; inspect with `pio runs "
                + str(s['runId']) + "`"),
        })
    return findings
