"""Request-id context: one id per request, honored end to end.

The id rides a ``contextvars.ContextVar`` so it follows the request
through handler code without threading a parameter everywhere (each
HTTP connection is served on its own thread, and contextvars are
per-thread by default — no cross-request bleed).

Flow: :meth:`utils.http.AppServer` sets the var from the incoming
``X-Request-ID`` header (generating one when absent), echoes it on the
response, and resets it after the response is written. The query server
forwards it on the feedback POST to the event server and attaches it to
the feedback event, so one user query is traceable across both services
and the event store.

Log records grow a ``request_id`` attribute (``-`` outside a request)
via a record factory installed on first import, so any format string
can include ``%(request_id)s``.
"""

from __future__ import annotations

import contextvars
import logging
import uuid

__all__ = [
    "REQUEST_ID_HEADER",
    "request_id_var",
    "new_request_id",
    "ensure_request_id",
    "current_request_id",
]

REQUEST_ID_HEADER = "X-Request-ID"

#: Caps a client-supplied id; longer ids are truncated, not rejected —
#: an oversized tracing header should never fail the request itself.
MAX_REQUEST_ID_LEN = 128

request_id_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_request_id", default=None
)


def new_request_id() -> str:
    """16 hex chars — short enough for logs, unique enough per process
    fleet (64 random bits)."""
    return uuid.uuid4().hex[:16]


def _sanitize(raw: str) -> str | None:
    """Printable ASCII, header-safe, bounded; None when nothing survives.
    ASCII-only is load-bearing: the id is written back into a response
    header block encoded as iso-8859-1, so wider characters would crash
    the response write after the handler already succeeded."""
    cleaned = "".join(
        ch for ch in raw.strip()
        if " " <= ch <= "~" and ch not in '",\\'
    )
    return cleaned[:MAX_REQUEST_ID_LEN] or None


def ensure_request_id(incoming: str | None = None) -> str:
    """The id for this request: a sanitized incoming ``X-Request-ID``
    when the client sent one, else a fresh id. Does NOT set the
    contextvar — callers hold the reset token (utils/http.py)."""
    if incoming:
        cleaned = _sanitize(incoming)
        if cleaned:
            return cleaned
    return new_request_id()


def current_request_id() -> str | None:
    """The id of the request being served on this thread, or None."""
    return request_id_var.get()


def _install_record_factory() -> None:
    """Give every LogRecord a ``request_id`` attribute (idempotent)."""
    old = logging.getLogRecordFactory()
    if getattr(old, "_pio_request_id_factory", False):
        return

    def factory(*args, **kwargs):
        record = old(*args, **kwargs)
        record.request_id = request_id_var.get() or "-"
        return record

    factory._pio_request_id_factory = True
    logging.setLogRecordFactory(factory)


_install_record_factory()
