"""Sampled per-request span tracing: the "why was THIS query slow" layer.

PR 1 gave every server aggregate ``pio_*`` histograms; those answer
"how slow is the fleet" but not "why was this one request slow — hedge,
breaker, cache miss, queue wait, compile, or transfer stall?".  This
module is the Dapper-style answer, sized for a single long-lived Python
process:

  * :func:`span` — a context manager recording name, monotonic
    start/duration, a bounded attribute dict, and point events
    (:meth:`_Span.add_event`).  Parent linkage rides a
    ``contextvars.ContextVar``, so nesting needs no plumbing; the trace
    id IS the request id (:mod:`predictionio_tpu.obs.context`), so one
    trace spans gateway → replica → batcher → device inside a process,
    and the id in a log line, a histogram exemplar, and ``pio trace``
    all mean the same request.
  * :class:`Tracer` — a process-global bounded ring buffer of finished
    traces plus an always-keep reservoir of the slowest N, surfaced as
    ``GET /debug/traces`` on every server (utils/http.py), the
    dashboard's slow-traces panel, and the ``pio trace`` CLI.
  * Cross-server propagation: outbound HTTP calls carry
    ``X-Trace-Sampled`` (so the callee joins the caller's sampling
    decision) and ``X-Parent-Span`` next to the existing
    ``X-Request-ID``; the HTTP layer opens a server span per request
    with those as the remote parent.
  * Histogram exemplars: while a sampled span is active, every
    histogram observation stamps its bucket with the trace id
    (obs/metrics.py), exposed as OpenMetrics ``# {trace_id=...}``
    exemplar comments — the p99 bucket links straight back to a
    concrete trace.

Sampling rides ``PIO_TRACE`` (read per request, so a live process can
be retuned): ``off`` | ``slow`` (default — trace everything, keep the
recent ring only for traces ≥ ``PIO_TRACE_SLOW_MS``; the slowest-N
reservoir always competes) | a probability in (0, 1) | ``all``.  The
``off`` path is a true no-op: :func:`span` returns one shared
:data:`NOOP` object — no span allocation, no dict churn, no lock
(guarded by the identity test in tests/test_trace.py and the
``trace_overhead_frac`` bench guard in bench_serving.py).
"""

from __future__ import annotations

import contextvars
import heapq
import itertools
import logging
import os
import random
import threading
import time
from collections import deque

from predictionio_tpu.obs import metrics as _metrics
from predictionio_tpu.obs.context import current_request_id, new_request_id
from predictionio_tpu.obs.metrics import REGISTRY

__all__ = [
    "NOOP",
    "PARENT_SPAN_HEADER",
    "SAMPLED_HEADER",
    "TRACER",
    "Tracer",
    "add_event",
    "capture",
    "child_span",
    "current_trace_id",
    "hold",
    "inject_headers",
    "record_span",
    "release",
    "render_waterfall_text",
    "server_span",
    "span",
    "trace_enabled",
    "trace_mode",
]

logger = logging.getLogger(__name__)

TRACE_ENV = "PIO_TRACE"
SAMPLED_HEADER = "X-Trace-Sampled"
PARENT_SPAN_HEADER = "X-Parent-Span"

#: ``slow`` mode: traces at least this slow enter the recent ring.
SLOW_MS_ENV = "PIO_TRACE_SLOW_MS"
DEFAULT_SLOW_MS = 25.0

#: Hard bounds — tracing must never grow without limit on a hot server.
MAX_SPANS_PER_TRACE = 256
MAX_ATTRS_PER_SPAN = 16
MAX_EVENTS_PER_SPAN = 32
MAX_ATTR_CHARS = 200
MAX_ACTIVE_TRACES = 1024

_SPANS_TOTAL = REGISTRY.counter(
    "pio_trace_spans_total", "Finished spans recorded into traces")
_TRACES_TOTAL = REGISTRY.counter(
    "pio_trace_traces_total",
    "Finished traces by retention outcome (recent ring / slowest "
    "reservoir only / dropped)",
    labels=("outcome",),
)
_RING_ENTRIES = REGISTRY.gauge(
    "pio_trace_ring_entries", "Finished traces currently in the ring")


#: (last raw env value, parsed mode) — parsing is memoized on the raw
#: string (re-read every call, so a live retune still lands on the next
#: request) because this runs at EVERY span site on the serving hot path.
_mode_cache: tuple[str | None, str] = (None, "slow")


def trace_mode() -> str:
    """Effective ``PIO_TRACE`` mode: ``off`` | ``slow`` | ``all`` | a
    probability string. Read per call so a live process can be retuned
    (the bench's A/B toggle relies on this)."""
    global _mode_cache
    env = os.environ.get(TRACE_ENV)
    cached_env, cached_mode = _mode_cache
    if env == cached_env:
        return cached_mode
    raw = (env if env is not None else "slow").strip().lower()
    if raw in ("off", "0", "false", "none", ""):
        mode = "off"
    elif raw in ("all", "1", "true"):
        mode = "all"
    elif raw == "slow" or _as_prob(raw) is not None:
        mode = raw
    else:
        try:
            # numeric but outside (0, 1): the operator's intent is
            # plain — ≤ 0 disables, ≥ 1 traces everything — so honor it
            # instead of silently tracing under the "slow" default
            mode = "off" if float(raw) <= 0.0 else "all"
        except ValueError:
            # lazy import: logs rides metrics/context only, so trace may
            # call into it at warn time without an import cycle
            from predictionio_tpu.obs.logs import warn_once

            warn_once(
                "trace-bad-mode",
                "unrecognized %s=%r; falling back to 'slow' "
                "(valid: off | slow | all | probability in (0,1))",
                TRACE_ENV, env, logger=logger)
            mode = "slow"
    _mode_cache = (env, mode)
    return mode


def _as_prob(raw: str) -> float | None:
    try:
        p = float(raw)
    except ValueError:
        return None
    return p if 0.0 < p < 1.0 else None


def trace_enabled() -> bool:
    return trace_mode() != "off"


def _slow_threshold_s() -> float:
    try:
        return float(os.environ.get(SLOW_MS_ENV, DEFAULT_SLOW_MS)) / 1e3
    except ValueError:
        return DEFAULT_SLOW_MS / 1e3


def _sample(mode: str) -> bool:
    """Head sampling decision for a NEW trace under ``mode`` (callers
    handle ``off``)."""
    if mode in ("all", "slow"):
        return True
    p = _as_prob(mode)
    if p is None:
        return True
    return random.random() < p


def _clip(value: object) -> object:
    """Attribute/event values: JSON scalars pass, everything else is a
    bounded str() — a trace must serialize no matter what rode in."""
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, (int, float)):
        return value if value == value else None  # NaN is invalid JSON
    s = str(value)
    return s if len(s) <= MAX_ATTR_CHARS else s[:MAX_ATTR_CHARS] + "…"


class _TraceState:
    """Mutable collection point for one trace id's spans. Shared by
    every span of the trace (across threads: gateway handler, hedge
    threads, the micro-batcher consumer), so all mutation happens under
    the tracer lock."""

    __slots__ = ("trace_id", "t0_wall", "t0_mono", "spans", "open",
                 "dropped", "committed")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.t0_wall = time.time()
        self.t0_mono = time.perf_counter()
        self.spans: list[dict] = []
        self.open = 0
        self.dropped = 0
        self.committed = False


class _NoopSpan:
    """The disabled path: one shared instance, every method a constant
    no-op. ``span()`` must return THIS object (identity-tested) when
    tracing is off or the request is unsampled."""

    __slots__ = ()
    sampled = False
    trace_id = None
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add_event(self, name, **attrs):
        pass

    def set_attr(self, key, value):
        pass


NOOP = _NoopSpan()


class _SuppressedScope:
    """Request-scope "not sampled" marker. :func:`server_span` returns
    one (instead of the bare :data:`NOOP`) when the request is
    explicitly suppressed (``X-Trace-Sampled: 0``), loses the
    probability coin, or is load-shed: nested :func:`span` calls then
    see the REQUEST's head decision instead of re-sampling per stage
    (which would fragment one unsampled request into single-span
    traces), and :func:`inject_headers` propagates the ``0``
    downstream. One tiny allocation per unsampled request — never on
    the ``off`` path, which keeps returning :data:`NOOP` itself."""

    __slots__ = ("_token",)
    sampled = False
    trace_id = None
    span_id = None
    state = None

    def __enter__(self):
        self._token = _span_var.set(self)
        return self

    def __exit__(self, *exc):
        _span_var.reset(self._token)
        return False

    def add_event(self, name, **attrs):
        pass

    def set_attr(self, key, value):
        pass

#: Span-id source: a counter on a random epoch. ``uuid.uuid4`` costs an
#: entropy syscall (~30 µs in sandboxed environments — measured 8 ids ≈
#: 0.25 ms per traced request); span ids only need uniqueness within a
#: retained trace, and CPython's ``itertools.count.__next__`` is atomic,
#: so this is both thread-safe and ~300x cheaper.
_span_ids = itertools.count(random.getrandbits(31))


def _new_span_id() -> str:
    return f"{next(_span_ids) & 0xFFFFFFFF:08x}"

#: The innermost active span on this thread/context (None = untraced).
_span_var: contextvars.ContextVar["_Span | None"] = contextvars.ContextVar(
    "pio_trace_span", default=None
)


class _Span:
    """A live span: collects attrs/events locally (no lock — a span is
    used by the thread that opened it) and hands one finished record to
    the tracer on exit."""

    __slots__ = ("state", "name", "span_id", "parent_id", "_attrs",
                 "_events", "_t0", "_token")

    sampled = True

    def __init__(self, state: _TraceState, name: str,
                 parent_id: str | None, attrs: dict | None = None):
        self.state = state
        self.name = name
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self._attrs = {}
        if attrs:
            for k, v in attrs.items():
                self.set_attr(k, v)
        self._events: list[tuple[str, float, dict | None]] = []
        self._t0 = 0.0
        self._token = None

    @property
    def trace_id(self) -> str:
        return self.state.trace_id

    def set_attr(self, key: str, value: object) -> None:
        if len(self._attrs) < MAX_ATTRS_PER_SPAN or key in self._attrs:
            self._attrs[key] = _clip(value)

    def add_event(self, name: str, **attrs) -> None:
        """Point annotation at now (hedge_fired, cache_hit,
        xla_compile, ...)."""
        if len(self._events) < MAX_EVENTS_PER_SPAN:
            self._events.append((
                name, time.perf_counter(),
                {k: _clip(v) for k, v in attrs.items()} or None,
            ))

    def __enter__(self):
        self._t0 = time.perf_counter()
        TRACER._span_opened(self.state)
        self._token = _span_var.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        if self._token is not None:
            _span_var.reset(self._token)
        if exc_type is not None:
            self.set_attr("error", f"{exc_type.__name__}: {exc}")
        TRACER._span_closed(self.state, self._record(self._t0, end))
        return False

    def _record(self, start: float, end: float) -> dict:
        return {
            "name": self.name,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": start,
            "duration": end - start,
            "attrs": self._attrs or None,
            "events": self._events or None,
        }


class Tracer:
    """Finished-trace retention: a recent ring (``deque``) plus a
    slowest-N min-heap reservoir, behind one lock (touched only on the
    sampled path)."""

    def __init__(self, ring_size: int = 128, slowest_size: int = 16):
        self.ring_size = ring_size
        self.slowest_size = slowest_size
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_size)
        self._slowest: list[tuple[float, int, dict]] = []
        self._active: dict[str, _TraceState] = {}
        self._seq = 0

    # -- span bookkeeping ---------------------------------------------------

    def _state_for(self, trace_id: str) -> _TraceState | None:
        """Get-or-create the collection state for ``trace_id``; None
        when the active table is full (load-shed: tracing must degrade,
        never grow unbounded)."""
        with self._lock:
            state = self._active.get(trace_id)
            if state is not None:
                return state
            if len(self._active) >= MAX_ACTIVE_TRACES:
                return None
            state = _TraceState(trace_id)
            self._active[trace_id] = state
            return state

    def _span_opened(self, state: _TraceState) -> None:
        with self._lock:
            state.open += 1

    def _span_closed(self, state: _TraceState,
                     record: dict | None) -> None:
        """Drop the open count by one, appending ``record`` when this
        is a real span exit (None = a :func:`hold` being released)."""
        commit = None
        with self._lock:
            state.open -= 1
            if not state.committed:
                if record is None:
                    pass
                elif len(state.spans) < MAX_SPANS_PER_TRACE:
                    state.spans.append(record)
                    _SPANS_TOTAL.inc()
                else:
                    state.dropped += 1
                if state.open <= 0:
                    # the outermost span closed: the trace is done (a
                    # hedge loser still in flight holds open > 0, so its
                    # span lands before commit)
                    state.committed = True
                    self._active.pop(state.trace_id, None)
                    commit = state
        if commit is not None:
            self._commit(commit)

    def _record_finished(self, state: _TraceState, record: dict) -> None:
        """A retroactive span (timed elsewhere, e.g. per micro-batch
        rider on the consumer thread) — appended without touching the
        open count."""
        with self._lock:
            if state.committed:
                return  # the trace already shipped; drop, never resurrect
            if len(state.spans) < MAX_SPANS_PER_TRACE:
                state.spans.append(record)
                _SPANS_TOTAL.inc()
            else:
                state.dropped += 1

    # -- retention ----------------------------------------------------------

    def _commit(self, state: _TraceState) -> None:
        doc = self._doc(state)
        duration_s = doc["durationMs"] / 1e3
        keep_recent = (trace_mode() != "slow"
                       or duration_s >= _slow_threshold_s())
        with self._lock:
            self._seq += 1
            entry = (duration_s, self._seq, doc)
            in_reservoir = False
            if len(self._slowest) < self.slowest_size:
                heapq.heappush(self._slowest, entry)
                in_reservoir = True
            elif self._slowest and duration_s > self._slowest[0][0]:
                heapq.heappushpop(self._slowest, entry)
                in_reservoir = True
            if keep_recent:
                self._ring.append(doc)
            _RING_ENTRIES.set(len(self._ring))
        outcome = ("recent" if keep_recent
                   else "reservoir" if in_reservoir else "dropped")
        _TRACES_TOTAL.inc(outcome=outcome)

    def _doc(self, state: _TraceState) -> dict:
        t0 = state.t0_mono
        spans = sorted(state.spans, key=lambda r: r["start"])
        start = spans[0]["start"] if spans else t0
        end = max((r["start"] + r["duration"] for r in spans), default=t0)
        out_spans = []
        for r in spans:
            s = {
                "name": r["name"],
                "spanId": r["spanId"],
                "parentId": r["parentId"],
                "offsetMs": round((r["start"] - t0) * 1e3, 3),
                "durationMs": round(r["duration"] * 1e3, 3),
            }
            if r["attrs"]:
                s["attrs"] = r["attrs"]
            if r["events"]:
                s["events"] = [
                    {"name": n, "offsetMs": round((t - t0) * 1e3, 3),
                     **({"attrs": a} if a else {})}
                    for n, t, a in r["events"]
                ]
            out_spans.append(s)
        return {
            "traceId": state.trace_id,
            "startTime": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(state.t0_wall)) + "Z",
            "durationMs": round(max(end - start, 0.0) * 1e3, 3),
            "spans": out_spans,
            "droppedSpans": state.dropped,
        }

    # -- query surface (/debug/traces, dashboard, pio trace) ----------------

    def traces(self, min_duration_ms: float = 0.0,
               trace_id: str | None = None, limit: int = 50) -> dict:
        """Snapshot for ``GET /debug/traces``: recent (newest first) and
        slowest (slowest first), optionally filtered."""
        with self._lock:
            recent = list(self._ring)
            slowest = [doc for _, _, doc in
                       sorted(self._slowest, reverse=True)]

        def keep(doc: dict) -> bool:
            if trace_id is not None and doc["traceId"] != trace_id:
                return False
            return doc["durationMs"] >= min_duration_ms

        limit = max(int(limit), 1)
        return {
            "mode": trace_mode(),
            "slowMs": round(_slow_threshold_s() * 1e3, 3),
            "recent": [d for d in reversed(recent) if keep(d)][:limit],
            "slowest": [d for d in slowest if keep(d)][:limit],
        }

    def find(self, trace_id: str) -> dict | None:
        got = self.traces(trace_id=trace_id, limit=1)
        hits = got["recent"] or got["slowest"]
        return hits[0] if hits else None

    def reset(self) -> None:
        """Drop everything (tests)."""
        with self._lock:
            self._ring.clear()
            self._slowest.clear()
            self._active.clear()
            _RING_ENTRIES.set(0)


#: The process-global tracer every server surfaces.
TRACER = Tracer()


# -- public span API ---------------------------------------------------------


def span(name: str, **attrs):
    """Open a span under the current one, or start a new sampled trace
    when none is active. Returns :data:`NOOP` (shared, lock-free,
    allocation-free) when tracing is off or the trace is unsampled."""
    mode = trace_mode()
    if mode == "off":
        return NOOP
    parent = _span_var.get()
    if parent is not None:
        if not parent.sampled:  # the request's head decision wins
            return NOOP
        return _Span(parent.state, name, parent.span_id, attrs or None)
    if not _sample(mode):
        return NOOP
    state = TRACER._state_for(current_request_id() or new_request_id())
    if state is None:
        return NOOP
    return _Span(state, name, None, attrs or None)


def server_span(name: str, trace_id: str, sampled_header: str | None,
                parent_id: str | None):
    """The HTTP layer's per-request root: joins the caller's sampling
    decision when ``X-Trace-Sampled`` rode in (``"1"`` forces sampling,
    ``"0"`` suppresses it), else samples per ``PIO_TRACE``. The trace id
    is the request id, so gateway and replica spans of one user query
    land in one trace."""
    mode = trace_mode()
    if mode == "off":
        return NOOP
    if sampled_header == "0":
        return _SuppressedScope()
    if sampled_header != "1" and not _sample(mode):
        return _SuppressedScope()
    state = TRACER._state_for(trace_id)
    if state is None:
        return _SuppressedScope()
    return _Span(state, name, parent_id)


def capture():
    """Handle for cross-thread span creation: ``(state, span_id)`` of
    the current span, or None. Pass to :func:`child_span` /
    :func:`record_span` on another thread."""
    sp = _span_var.get()
    return (sp.state, sp.span_id) \
        if sp is not None and sp.sampled else None


def child_span(handle, name: str, **attrs):
    """A span parented on a :func:`capture` handle — for work that hops
    threads (the gateway's hedge/retry attempt threads)."""
    if handle is None or trace_mode() == "off":
        return NOOP
    state, parent_id = handle
    return _Span(state, name, parent_id, attrs or None)


def hold(handle):
    """Keep a trace uncommitted across a thread handoff: call on the
    LAUNCHING thread (before ``Thread.start``) with a :func:`capture`
    handle, and pair with :func:`release` in the worker's ``finally``.
    Without the hold, the root span can close — and the trace commit —
    in the scheduling gap before the worker's :func:`child_span`
    enters, silently dropping the worker's span (a hedge attempt's
    ``upstream``, for example). Returns None (a no-op to release) for
    an untraced handle."""
    if handle is None:
        return None
    state, _ = handle
    TRACER._span_opened(state)
    return state


def release(held) -> None:
    """Release a :func:`hold` (None-safe). Runs the same
    commit-on-last-close logic as a span exit, without a record."""
    if held is not None:
        TRACER._span_closed(held, None)


def record_span(handle, name: str, start: float, duration: float,
                **attrs) -> None:
    """Retroactively record a completed span (perf_counter ``start`` +
    ``duration``) under a handle — the micro-batcher uses this to give
    every rider its own queue_wait/predict/serve spans even though the
    timing happened once on the consumer thread."""
    if handle is None:
        return
    state, parent_id = handle
    record = {
        "name": name,
        "spanId": _new_span_id(),
        "parentId": parent_id,
        "start": start,
        "duration": max(duration, 0.0),
        "attrs": {k: _clip(v) for k, v in attrs.items()} or None,
        "events": None,
    }
    TRACER._record_finished(state, record)


def record(name: str, start: float, duration: float, **attrs) -> None:
    """:func:`record_span` under the CURRENT span (same thread)."""
    record_span(capture(), name, start, duration, **attrs)


def add_event(name: str, **attrs) -> None:
    """Annotate the current span (no-op when untraced)."""
    sp = _span_var.get()
    if sp is not None:
        sp.add_event(name, **attrs)


def current_trace_id() -> str | None:
    sp = _span_var.get()
    return sp.state.trace_id if sp is not None and sp.sampled else None


def inject_headers(headers: dict) -> None:
    """Stamp outbound-call headers with the active trace's sampling
    decision and parent span (callers already send ``X-Request-ID``).
    A request whose head decision was "don't sample" propagates the
    suppression (``0``) so the callee doesn't re-sample its half of an
    unsampled request; contexts with no request at all (background
    work, ``off`` mode) send nothing — the callee decides for
    itself."""
    sp = _span_var.get()
    if sp is None:
        return
    if sp.sampled:
        headers[SAMPLED_HEADER] = "1"
        headers[PARENT_SPAN_HEADER] = sp.span_id
    else:
        headers[SAMPLED_HEADER] = "0"


# -- histogram exemplars ------------------------------------------------------

def _exemplar() -> str | None:
    sp = _span_var.get()
    return sp.state.trace_id if sp is not None and sp.sampled else None


# Installed at import: every Histogram.observe made under a sampled span
# stamps its bucket with the trace id (obs/metrics.py emits them as
# OpenMetrics exemplar comments). With tracing off the hook returns None
# and the exposition stays byte-identical.
_metrics.set_exemplar_hook(_exemplar)


# -- rendering (pio trace / dashboard share the layout math) ------------------

def waterfall_rows(doc: dict) -> list[dict]:
    """Depth-annotated spans in start order: adds ``depth`` (parent
    chain length, remote/unknown parents count as roots) to each span
    dict — the shared layout pass for text and HTML waterfalls."""
    by_id = {s["spanId"]: s for s in doc.get("spans", ())}
    rows = []
    for s in doc.get("spans", ()):
        depth, seen, cur = 0, set(), s
        while cur.get("parentId") in by_id and cur["spanId"] not in seen:
            seen.add(cur["spanId"])
            cur = by_id[cur["parentId"]]
            depth += 1
        rows.append({**s, "depth": depth})
    return rows


def render_waterfall_text(doc: dict, width: int = 40) -> str:
    """One trace as an aligned text waterfall (the ``pio trace``
    output)."""
    total = max(doc.get("durationMs", 0.0), 1e-6)
    lines = [
        f"trace {doc['traceId']}  {doc.get('startTime', '?')}  "
        f"{doc['durationMs']:.2f} ms  ({len(doc.get('spans', ()))} spans)"
    ]
    for s in waterfall_rows(doc):
        left = int(width * s["offsetMs"] / total)
        bar = max(int(width * s["durationMs"] / total), 1)
        bar = min(bar, width - min(left, width - 1))
        label = "  " * s["depth"] + s["name"]
        attrs = s.get("attrs") or {}
        suffix = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  {label:<28} {s['offsetMs']:>9.2f}ms "
            f"|{' ' * min(left, width - 1)}{'#' * bar}"
            f"{' ' * max(width - left - bar, 0)}| "
            f"{s['durationMs']:>8.2f}ms{('  ' + suffix) if suffix else ''}"
        )
        for ev in s.get("events", ()) or ():
            ev_attrs = ev.get("attrs") or {}
            ev_suffix = " ".join(f"{k}={v}" for k, v in ev_attrs.items())
            lines.append(
                f"  {'  ' * s['depth']}  * {ev['name']} "
                f"@{ev['offsetMs']:.2f}ms"
                f"{('  ' + ev_suffix) if ev_suffix else ''}"
            )
    if doc.get("droppedSpans"):
        lines.append(f"  ({doc['droppedSpans']} span(s) dropped: "
                     f"per-trace cap {MAX_SPANS_PER_TRACE})")
    return "\n".join(lines)
