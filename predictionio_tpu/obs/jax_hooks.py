"""JAX compile-time telemetry → registry metrics.

XLA compiles are the dominant cold-path cost on a TPU deploy (the
post-deploy batch-shape warmup exists because of them). ``jax.monitoring``
emits a duration event per backend compile; this hook folds them into:

  * ``pio_jax_compiles_total{program=...}`` — backend compiles since
    install, labelled with the profiled device program active on the
    compiling thread (obs/device.py), ``unattributed`` otherwise
  * ``pio_jax_compile_seconds_total{program=...}`` — cumulative backend
    compile time, same labels

The training workflow snapshots the cross-program totals around a train
run and publishes the deltas into the engine-instance record (keys
unchanged — :func:`jax_compile_stats` sums over programs); the query
server's warmup compiles show up on ``/metrics`` under the warmed
programs. The default-registry listener also streams each compile into
the device layer's per-(program, bucket) accounting, which is what the
retrace-regression guard asserts over.

Everything is best-effort: jax versions move the monitoring surface, and
observability must never sink a train or a deploy.
"""

from __future__ import annotations

import logging
import threading

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger(__name__)

#: The duration event one XLA backend compile emits (jax >= 0.4.x).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: Label value for compiles outside any profiled program (module-init
#: jits, helper ops, un-wrapped entry points).
_UNATTRIBUTED = "unattributed"

_install_lock = threading.Lock()
#: Registries a listener already feeds — idempotent PER REGISTRY, so a
#: private registry installed after the global one still gets events.
#: Strong refs on purpose: an id()-keyed set could collide after GC.
_installed: list[MetricsRegistry] = []


def install_jax_compile_hook(registry: MetricsRegistry = REGISTRY) -> bool:
    """Register a monitoring listener feeding ``registry`` (idempotent
    per registry). Returns whether the hook is active for it."""
    with _install_lock:
        if any(r is registry for r in _installed):
            return True
        try:
            from jax import monitoring
        except Exception:  # jax absent/stripped: run unobserved
            logger.debug("jax.monitoring unavailable", exc_info=True)
            return False
        compiles = registry.counter(
            "pio_jax_compiles_total", "XLA backend compiles, by the "
            "profiled device program active on the compiling thread",
            labels=("program",))
        seconds = registry.counter(
            "pio_jax_compile_seconds_total",
            "Cumulative XLA backend compile seconds, by profiled program",
            labels=("program",))

        # only the default-registry listener drives the per-program
        # device accounting and stamps trace events: a second
        # (private-registry) listener firing for the same compile would
        # double-count retrace detection and duplicate every xla_compile
        # annotation on the span
        is_primary = registry is REGISTRY

        def on_duration(event: str, duration: float, **kw) -> None:
            if event == _COMPILE_EVENT:
                from predictionio_tpu.obs import device as device_obs

                dur = max(duration, 0.0)
                if is_primary:
                    # feeds per-(program, bucket) compile counts + the
                    # active call's compile-second accumulator (MFU
                    # subtracts one-time compile cost from program rate)
                    program = device_obs.note_compile(dur)
                else:
                    program = device_obs.current_program_name()
                label = program or _UNATTRIBUTED
                compiles.inc(program=label)
                seconds.inc(dur, program=label)
                if is_primary:
                    # a compile inside a traced request is exactly the
                    # "why was this one slow" answer: stamp the span
                    from predictionio_tpu.obs.trace import add_event

                    add_event("xla_compile", seconds=round(duration, 4))

        try:
            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception:
            logger.debug("jax monitoring listener rejected", exc_info=True)
            return False
        _installed.append(registry)
        return True


def jax_compile_stats(registry: MetricsRegistry = REGISTRY) -> dict:
    """Current totals summed across program labels:
    ``{"compiles": int, "compile_seconds": float}`` (zeros when the hook
    never installed). The engine-instance ``env`` parity keys
    (``pio_train_jax_compiles*``) derive from these totals, so the
    per-program label split changes nothing downstream."""
    compiles = registry.get("pio_jax_compiles_total")
    seconds = registry.get("pio_jax_compile_seconds_total")
    return {
        "compiles": int(compiles.total()) if compiles is not None else 0,
        "compile_seconds": (
            round(seconds.total(), 4) if seconds is not None else 0.0
        ),
    }
