"""JAX compile-time telemetry → registry metrics.

XLA compiles are the dominant cold-path cost on a TPU deploy (the
post-deploy batch-shape warmup exists because of them). ``jax.monitoring``
emits a duration event per backend compile; this hook folds them into:

  * ``pio_jax_compiles_total`` — backend compiles since install
  * ``pio_jax_compile_seconds_total`` — cumulative backend compile time

The training workflow snapshots these around a train run and publishes
the deltas into the engine-instance record; the query server's warmup
compiles show up on ``/metrics`` the same way.

Everything is best-effort: jax versions move the monitoring surface, and
observability must never sink a train or a deploy.
"""

from __future__ import annotations

import logging
import threading

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger(__name__)

#: The duration event one XLA backend compile emits (jax >= 0.4.x).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_install_lock = threading.Lock()
#: Registries a listener already feeds — idempotent PER REGISTRY, so a
#: private registry installed after the global one still gets events.
#: Strong refs on purpose: an id()-keyed set could collide after GC.
_installed: list[MetricsRegistry] = []


def install_jax_compile_hook(registry: MetricsRegistry = REGISTRY) -> bool:
    """Register a monitoring listener feeding ``registry`` (idempotent
    per registry). Returns whether the hook is active for it."""
    with _install_lock:
        if any(r is registry for r in _installed):
            return True
        try:
            from jax import monitoring
        except Exception:  # jax absent/stripped: run unobserved
            logger.debug("jax.monitoring unavailable", exc_info=True)
            return False
        compiles = registry.counter(
            "pio_jax_compiles_total", "XLA backend compiles")
        seconds = registry.counter(
            "pio_jax_compile_seconds_total",
            "Cumulative XLA backend compile seconds")

        # only the default-registry listener stamps trace events: a
        # second (private-registry) listener firing for the same compile
        # would duplicate every xla_compile annotation on the span
        emit_trace_event = registry is REGISTRY

        def on_duration(event: str, duration: float, **kw) -> None:
            if event == _COMPILE_EVENT:
                compiles.inc()
                seconds.inc(max(duration, 0.0))
                if emit_trace_event:
                    # a compile inside a traced request is exactly the
                    # "why was this one slow" answer: stamp the span
                    from predictionio_tpu.obs.trace import add_event

                    add_event("xla_compile", seconds=round(duration, 4))

        try:
            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception:
            logger.debug("jax monitoring listener rejected", exc_info=True)
            return False
        _installed.append(registry)
        return True


def jax_compile_stats(registry: MetricsRegistry = REGISTRY) -> dict:
    """Current totals: ``{"compiles": int, "compile_seconds": float}``
    (zeros when the hook never installed)."""
    compiles = registry.get("pio_jax_compiles_total")
    seconds = registry.get("pio_jax_compile_seconds_total")
    return {
        "compiles": int(compiles.total()) if compiles is not None else 0,
        "compile_seconds": (
            round(seconds.total(), 4) if seconds is not None else 0.0
        ),
    }
