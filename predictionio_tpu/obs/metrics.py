"""Thread-safe metrics primitives + Prometheus text exposition.

Design notes:

  * One process-global default registry (:data:`REGISTRY`); every server
    in the process exposes the same registry on its ``/metrics``. The
    registry is also instantiable for isolated counter sets (the event
    server's per-instance ``/stats.json`` bookkeeping uses a private
    one so "since server start" semantics survive in a process that
    creates several servers).
  * Registration is get-or-create: module-level metric definitions in
    different files share one object by name (name/type/label mismatch
    raises — silent divergence would corrupt the scrape).
  * Histograms keep ONLY per-bucket counts + sum + count: fixed
    exponential bounds, so the hot-path cost is a bisect + two adds and
    memory is O(buckets), never O(samples). Quantiles interpolate
    linearly inside the containing bucket — the standard Prometheus
    ``histogram_quantile`` estimate, computed server-side for status
    pages.
  * Metric names must match ``pio_`` + snake_case (scrape stability;
    guarded by tests/test_obs.py).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from bisect import bisect_left
from typing import Callable, Iterable, Iterator

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "max_series_per_family",
    "set_exemplar_hook",
    "validate_metric_name",
    "DEFAULT_SECONDS_BUCKETS",
    "DEFAULT_SIZE_BUCKETS",
]

_NAME_RE = re.compile(r"^pio(_[a-z0-9]+)+$")
_LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: Latency bounds: 50 µs → ~105 s, ×2 per bucket (22 buckets + +Inf).
#: Covers a 0.1 ms HTTP parse and a multi-second cold XLA compile alike
#: with ≤ ~41% worst-case quantile error (half a log2 step).
DEFAULT_SECONDS_BUCKETS: tuple[float, ...] = tuple(
    5e-05 * 2.0**i for i in range(22)
)

#: Size/count bounds: 1 → 4096, ×2 (batch sizes, queue depths).
DEFAULT_SIZE_BUCKETS: tuple[float, ...] = tuple(float(2**i) for i in range(13))


def validate_metric_name(name: str) -> str:
    """Return ``name`` or raise: ``pio_`` prefix + snake_case only."""
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} violates the naming convention: "
            "'pio_' prefix + snake_case ([a-z0-9_], no leading/trailing/"
            "double underscores)"
        )
    return name


def _validate_labels(label_names: Iterable[str]) -> tuple[str, ...]:
    names = tuple(label_names)
    for n in names:
        if not _LABEL_RE.match(n):
            raise ValueError(f"label name {n!r} must be snake_case")
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate label names in {names}")
    return names


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a trailing ``.0``."""
    if v != v or v in (float("inf"), float("-inf")):
        return {float("inf"): "+Inf", float("-inf"): "-Inf"}.get(v, "NaN")
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    """Label-value escaping per the exposition format: backslash first,
    then double-quote and newline — a hostile value (a ``server_name``
    carrying any of the three) must never break a scrape."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping (backslash and newline only, per the format —
    quotes are legal in help text)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def max_series_per_family() -> int:
    """Label-set (child) bound per metric family
    (``PIO_METRICS_MAX_SERIES``, default 1000; <= 0 disables). Read at
    observation time so a live process can be retuned. Federation
    multiplies cardinality (every scraped instance contributes its label
    sets), so the registry needs a backstop: past the bound a NEW label
    set is dropped — counted in ``pio_metrics_dropped_series_total`` with
    a warn-once — instead of growing the scrape unboundedly. Existing
    children keep updating."""
    try:
        return int(os.environ.get("PIO_METRICS_MAX_SERIES", "1000"))
    except ValueError:
        return 1000


#: Created lazily against REGISTRY (defined at module bottom); exempt
#: from the bound itself so the drop accounting can never recurse into
#: another drop.
_dropped_series: "Counter | None" = None


def _note_dropped_series(family: str) -> None:
    global _dropped_series
    if _dropped_series is None:
        c = REGISTRY.counter(
            "pio_metrics_dropped_series_total",
            "Observations dropped because the family hit the "
            "PIO_METRICS_MAX_SERIES label-set bound",
            labels=("family",),
        )
        c._exempt = True
        _dropped_series = c
    _dropped_series.inc(family=family)
    # lazy import: logs.py imports this module for its counters, so the
    # dependency must point one way at import time and loop only at call
    # time (warn_once's own counter is an ordinary family, bounded by
    # its callers using bounded keys)
    from predictionio_tpu.obs.logs import warn_once

    warn_once(
        f"metrics-series-bound:{family}",
        "metric family %s hit the label-set bound (%d); new label sets "
        "are dropped (PIO_METRICS_MAX_SERIES raises the bound)",
        family, max_series_per_family(),
        logger=logging.getLogger(__name__))


#: Trace-exemplar hook (installed by obs/trace.py): returns the active
#: sampled trace id, or None. Kept as a module global read per
#: observation so metrics has no import dependency on the trace layer
#: and the un-traced path costs one None-check.
_exemplar_fn: Callable[[], "str | None"] | None = None


def set_exemplar_hook(fn: Callable[[], "str | None"] | None) -> None:
    global _exemplar_fn
    _exemplar_fn = fn


class _Metric:
    """Base: name/help/labels + one lock guarding the children dict and
    every value mutation (uncontended CPython lock ≈ 100 ns — noise next
    to the request path's JSON work)."""

    kind = "untyped"
    #: True exempts the family from the label-set bound (only the drop
    #: counter itself — bounding the bound's own accounting would lose
    #: exactly the signal it exists to give).
    _exempt = False

    def __init__(self, name: str, help: str = "", labels: Iterable[str] = ()):
        self.name = validate_metric_name(name)
        self.help = help
        self.label_names = _validate_labels(labels)
        self._lock = threading.Lock()

    def _admit_child(self, n_children: int) -> bool:
        """Gate a label set seen for the first time (call under
        ``self._lock``): False = at the cardinality bound, drop it."""
        if self._exempt:
            return True
        limit = max_series_per_family()
        if limit <= 0 or n_children < limit:
            return True
        _note_dropped_series(self.name)
        return False

    def _key(self, labels: dict[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def _labelstr(self, key: tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{n}="{_escape_label(v)}"' for n, v in zip(self.label_names, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class _ScalarMetric(_Metric):
    """Shared store + snapshot + exposition for the single-value kinds
    (Counter/Gauge): one copy of the locking and formatting rules."""

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._values: dict[tuple[str, ...], float] = {}

    def _add(self, amount: float, labels: dict[str, str]) -> None:
        key = self._key(labels)
        with self._lock:
            cur = self._values.get(key)
            if cur is None and not self._admit_child(len(self._values)):
                return
            self._values[key] = (cur or 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def items(self) -> list[tuple[tuple[str, ...], float]]:
        """Snapshot of (label-values, value) pairs."""
        with self._lock:
            return list(self._values.items())

    def total(self) -> float:
        """Sum over every label combination."""
        with self._lock:
            return sum(self._values.values())

    def sample_lines(self, openmetrics: bool = False) -> Iterator[str]:
        samples = self.items()
        for key, v in sorted(samples):
            yield f"{self.name}{self._labelstr(key)} {_fmt(v)}"
        if not self.label_names and not samples and self.kind == "counter":
            # a never-incremented counter truthfully reads 0; a never-SET
            # gauge must stay absent — "pio_ingest_last_event_age_seconds
            # 0" on a server that has ingested nothing would read as a
            # perpetually-fresh pipeline
            yield f"{self.name} 0"


class Counter(_ScalarMetric):
    """Monotonic counter. Name by convention ends in ``_total``."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        self._add(amount, labels)


class Gauge(_ScalarMetric):
    """Last-written value (set/inc/dec)."""

    kind = "gauge"

    def remove(self, **labels: str) -> None:
        """Drop a child so the series goes ABSENT from the exposition —
        for gauges whose absence is the signal (a heartbeat age after
        the run ended would otherwise export a frozen, forever-fresh
        value)."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            if key not in self._values and \
                    not self._admit_child(len(self._values)):
                return
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        self._add(amount, labels)

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self._add(-amount, labels)


class _HistData:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 = the +Inf bucket
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, value): the LAST sampled-trace
        # observation per bucket, exposed as an OpenMetrics exemplar.
        # None until the first exemplar, so un-traced processes pay and
        # store nothing.
        self.exemplars: dict[int, tuple[str, float]] | None = None


class Histogram(_Metric):
    """Log-bucketed histogram: fixed exponential bounds, cumulative
    Prometheus exposition, server-side quantile estimates."""

    kind = "histogram"

    def __init__(self, name, help="", labels=(),
                 buckets: Iterable[float] | None = None):
        super().__init__(name, help, labels)
        bounds = tuple(sorted(buckets or DEFAULT_SECONDS_BUCKETS))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self._data: dict[tuple[str, ...], _HistData] = {}

    def observe(self, value: float, times: int = 1, **labels: str) -> None:
        """Record ``value`` (``times`` repetitions share one lock
        round-trip — the per-request accounting of a coalesced batch)."""
        key = self._key(labels)
        idx = bisect_left(self.bounds, value)  # bounds are upper edges
        ex = _exemplar_fn
        trace_id = ex() if ex is not None else None
        with self._lock:
            d = self._data.get(key)
            if d is None:
                if not self._admit_child(len(self._data)):
                    return
                d = self._data[key] = _HistData(len(self.bounds))
            d.counts[idx] += times
            d.sum += value * times
            d.count += times
            if trace_id is not None:
                if d.exemplars is None:
                    d.exemplars = {}
                d.exemplars[idx] = (trace_id, value)

    class _Timer:
        __slots__ = ("_hist", "_labels", "_t0")

        def __init__(self, hist, labels):
            self._hist = hist
            self._labels = labels

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._hist.observe(
                time.perf_counter() - self._t0, **self._labels)
            return False

    def time(self, **labels: str) -> "Histogram._Timer":
        """``with hist.time(stage="parse"): ...`` — observe the elapsed
        wall seconds on exit (exceptions included: error paths are
        exactly the latencies worth recording)."""
        return Histogram._Timer(self, labels)

    def _merged(self, labels: dict[str, str] | None):
        """One _HistData view: a specific child, or all children merged
        (process-wide quantiles for status pages)."""
        with self._lock:
            if labels is not None:
                d = self._data.get(self._key(labels))
                if d is None:
                    return None
                out = _HistData(len(self.bounds))
                out.counts = list(d.counts)
                out.sum, out.count = d.sum, d.count
                return out
            if not self._data:
                return None
            out = _HistData(len(self.bounds))
            for d in self._data.values():
                for i, c in enumerate(d.counts):
                    out.counts[i] += c
                out.sum += d.sum
                out.count += d.count
            return out

    def _quantile_of(self, q: float, counts, count: int) -> float | None:
        if count <= 0:
            return None
        rank = q * count
        cum = 0
        for i, c in enumerate(counts):
            if cum + c >= rank and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
                frac = (rank - cum) / c
                return lo + frac * (hi - lo)
            cum += c
        return self.bounds[-1]

    def quantile(self, q: float, **labels: str) -> float | None:
        """Estimated q-quantile (0 < q < 1) from the bucket counts, or
        None with no observations. Labels select one child; with no
        labels given on a labelled histogram, children are merged."""
        d = self._merged(labels if (labels or not self.label_names) else None)
        if d is None:
            return None
        return self._quantile_of(q, d.counts, d.count)

    def state(self, **labels: str) -> _HistData:
        """Frozen copy of the (merged) bucket counts — a baseline for
        :meth:`quantile_since`, so a consumer created mid-process (a
        fresh QueryService in a long-lived test process) can report
        quantiles over ONLY its own lifetime's observations."""
        d = self._merged(labels if (labels or not self.label_names) else None)
        return d if d is not None else _HistData(len(self.bounds))

    def quantile_since(self, q: float, baseline: _HistData,
                       **labels: str) -> float | None:
        """Quantile of the observations made AFTER ``baseline`` was
        captured with :meth:`state` (bucket-count subtraction — counts
        only grow, so the delta is itself a valid histogram)."""
        d = self._merged(labels if (labels or not self.label_names) else None)
        if d is None:
            return None
        delta = [c - b for c, b in zip(d.counts, baseline.counts)]
        return self._quantile_of(q, delta, d.count - baseline.count)

    def count(self, **labels: str) -> int:
        d = self._merged(labels if (labels or not self.label_names) else None)
        return 0 if d is None else d.count

    def sum(self, **labels: str) -> float:
        d = self._merged(labels if (labels or not self.label_names) else None)
        return 0.0 if d is None else d.sum

    def items(self) -> list[tuple[tuple[str, ...], _HistData]]:
        with self._lock:
            out = []
            for key, d in self._data.items():
                copy = _HistData(len(self.bounds))
                copy.counts = list(d.counts)
                copy.sum, copy.count = d.sum, d.count
                if d.exemplars:
                    copy.exemplars = dict(d.exemplars)
                out.append((key, copy))
            return out

    @staticmethod
    def _exemplar_suffix(d: _HistData, idx: int) -> str:
        """OpenMetrics exemplar comment for one bucket line (empty when
        the bucket never saw a sampled-trace observation — exposition is
        byte-identical to the pre-exemplar format then)."""
        if not d.exemplars or idx not in d.exemplars:
            return ""
        trace_id, value = d.exemplars[idx]
        return (f' # {{trace_id="{_escape_label(trace_id)}"}}'
                f" {_fmt(value)}")

    def sample_lines(self, openmetrics: bool = False) -> Iterator[str]:
        for key, d in sorted(self.items()):
            cum = 0
            for i, (bound, c) in enumerate(zip(self.bounds, d.counts)):
                cum += c
                le = f'le="{_fmt(bound)}"'
                yield (f"{self.name}_bucket"
                       f"{self._labelstr(key, le)} {cum}"
                       f"{self._exemplar_suffix(d, i) if openmetrics else ''}")
            cum += d.counts[-1]
            inf_labels = self._labelstr(key, 'le="+Inf"')
            yield (f"{self.name}_bucket{inf_labels} {cum}"
                   f"{self._exemplar_suffix(d, len(self.bounds)) if openmetrics else ''}")
            yield f"{self.name}_sum{self._labelstr(key)} {_fmt(d.sum)}"
            yield f"{self.name}_count{self._labelstr(key)} {d.count}"


class MetricsRegistry:
    """Named metrics with get-or-create registration and Prometheus
    text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collect_hooks: list[Callable[[], None]] = []

    def add_collect_hook(self, fn: Callable[[], None]) -> None:
        """Register a refresher run before every exposition/snapshot —
        for gauges whose truth is computed on demand rather than pushed
        (the device-HBM "unattributed" residual walks ``jax.live_arrays``
        and must be current at scrape time, not at last-mutation time).
        Idempotent per function object; hook failures never sink a
        scrape."""
        with self._lock:
            if all(h is not fn for h in self._collect_hooks):
                self._collect_hooks.append(fn)

    def _run_collect_hooks(self) -> None:
        with self._lock:
            hooks = list(self._collect_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception:  # a broken refresher must not fail /metrics
                import logging

                logging.getLogger(__name__).debug(
                    "metrics collect hook failed", exc_info=True)

    def _get_or_create(self, cls, name, help, labels, **kw) -> _Metric:
        labels = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"{name} already registered as {existing.kind}, "
                        f"not {cls.kind}"
                    )
                if existing.label_names != labels:
                    raise ValueError(
                        f"{name} already registered with labels "
                        f"{existing.label_names}, not {labels}"
                    )
                if cls is Histogram:
                    want = tuple(sorted(
                        kw.get("buckets") or DEFAULT_SECONDS_BUCKETS))
                    if existing.bounds != want:
                        # silent divergence here would bucket one
                        # registrant's samples against the other's bounds
                        raise ValueError(
                            f"{name} already registered with different "
                            "buckets"
                        )
                return existing
            metric = cls(name, help, labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Iterable[float] | None = None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def expose(self, openmetrics: bool = False) -> str:
        """Prometheus text format 0.0.4, or (``openmetrics=True``) the
        OpenMetrics variant with histogram trace-id exemplars and the
        ``# EOF`` terminator. Exemplar comments are a hard parse error
        for the classic 0.0.4 parser — a stock Prometheus scraping the
        default content type would fail the WHOLE scrape — so they are
        emitted only under the negotiated OpenMetrics content type
        (utils/http.py checks the Accept header)."""
        self._run_collect_hooks()
        lines: list[str] = []
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        for m in metrics:
            family = m.name
            if openmetrics and m.kind == "counter" \
                    and family.endswith("_total"):
                # OpenMetrics names a counter FAMILY without the
                # ``_total`` suffix; the sample keeps it (family +
                # "_total"). Announcing the family AS ``pio_x_total``
                # is a "clashing name" hard error in the reference
                # parser — it would fail the whole negotiated scrape,
                # the only one that carries exemplars.
                family = family[: -len("_total")]
            if m.help:
                lines.append(f"# HELP {family} {_escape_help(m.help)}")
            lines.append(f"# TYPE {family} {m.kind}")
            lines.extend(m.sample_lines(openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-friendly dump: counters/gauges as {labels: value} maps,
        histograms as count/sum/p50/p90/p99 (bench captures, status
        pages)."""
        self._run_collect_hooks()
        out: dict = {}
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        for m in metrics:
            if isinstance(m, Histogram):
                entry: dict = {}
                for key, d in sorted(m.items()):
                    labels = dict(zip(m.label_names, key))
                    child = {
                        "count": d.count,
                        "sum": round(d.sum, 6),
                    }
                    for q, tag in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                        v = m.quantile(q, **labels) if m.label_names else \
                            m.quantile(q)
                        if v is not None:
                            child[tag] = round(v, 6)
                    entry[",".join(key) or "_"] = child
                out[m.name] = entry
            else:
                out[m.name] = {
                    ",".join(key) or "_": v for key, v in sorted(m.items())
                }
        return out


#: The process-global default registry every server exposes.
REGISTRY = MetricsRegistry()
