"""Prediction-quality observatory: the fifth observability pillar.

The first four pillars (tracing, device profiling, fleet SLOs, the
training-run ledger) say how fast and how reliably the system answers;
this module says whether the answers are any GOOD — the online
model-quality monitoring the ads-infra line of work (PAPERS.md) treats
as production table stakes. Three capabilities, one process-global
:class:`QualityMonitor`:

  * **Score/output drift.** ``run_train`` persists a per-instance
    baseline into the engine-instance ``env`` (``quality_baseline``:
    a score-distribution histogram sketch plus a top-k popularity/
    coverage profile from a held-out query sample, built by
    :func:`baseline_env`). The query server samples live predictions
    (``PIO_QUALITY_SAMPLE`` — ``off`` | ``all`` | a probability, the
    trace-sampling grammar) into a windowed per-instance sketch and the
    monitor's collect hook publishes ``pio_prediction_score_*``,
    ``pio_prediction_drift_score{instance}`` (population-stability index
    vs the baseline), and item-coverage / popularity-skew gauges, all
    riding the obs/history rings.
  * **Feedback-joined online accuracy.** Sampled served top-k sets wait
    in a bounded TTL join buffer keyed by request id; the event server
    feeds ingested events through :func:`observe_event`, and an event
    carrying the ``requestId`` the feedback loop stamps
    (workflow/create_server.py) joins its serving record — a hit when
    the acted-on item was in the served set — attributed to the engine
    instance (and model age) THAT REQUEST was served by, even if a
    hot-swap landed in between. Windowed hit rate lands in
    ``pio_online_hit_rate`` and the ``online_quality`` SLO (obs/slo.py).
  * **Shadow-scored hot swaps.** The monitor keeps the last N sampled
    queries; ``/reload`` replays them against the candidate instance on
    the host path before committing the swap and reports score shift +
    top-k overlap (the ``shadow`` block; ``PIO_RELOAD_SHADOW_GATE``
    optionally refuses swaps below an overlap floor).

Everything is fail-soft and bounded: sampling off costs a memoized env
read per query, the join buffer is capacity- and TTL-evicted, and a
broken baseline never sinks a train or a deploy.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import random
import threading
import time
from collections import Counter as _TallyCounter, OrderedDict, deque

from predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

__all__ = [
    "MONITOR",
    "QualityMonitor",
    "baseline_env",
    "build_baseline",
    "extract_item_scores",
    "merge_docs",
    "observe_event",
    "population_stability_index",
    "quality_enabled",
    "quality_findings",
    "sample",
    "sample_mode",
    "shadow_gate_floor",
]

#: Engine-instance env key the trained baseline sketch persists under.
#: Deliberately NOT ``pio_``-prefixed: that namespace is the metric
#: scrape contract (tools/check_metrics.py enforces it against the
#: docs), and this is stored state, not a metric.
BASELINE_ENV_KEY = "quality_baseline"

_SAMPLED = REGISTRY.counter(
    "pio_quality_sampled_total",
    "Live predictions sampled into the quality window and join buffer",
    labels=("instance",),
)
_FEEDBACK = REGISTRY.counter(
    "pio_quality_feedback_total",
    "Feedback events processed against the join buffer: hit (acted-on "
    "item was in the served top-k), miss, unknown (no buffered request "
    "id — never sampled, expired, or another process served it), "
    "duplicate (request id already consumed)",
    labels=("result",),
)
_JOIN_EVICTIONS = REGISTRY.counter(
    "pio_quality_join_evictions_total",
    "Join-buffer entries dropped before any feedback arrived, by "
    "reason (ttl = outlived PIO_QUALITY_JOIN_TTL_S, capacity = pushed "
    "out by PIO_QUALITY_JOIN_CAP)",
    labels=("reason",),
)
_JOIN_ENTRIES = REGISTRY.gauge(
    "pio_quality_join_buffer_entries",
    "Served top-k sets currently waiting in the feedback join buffer",
)
_HIT_RATE = REGISTRY.gauge(
    "pio_online_hit_rate",
    "Windowed online accuracy per engine instance: feedback-joined "
    "requests whose acted-on item was in the served top-k, over the "
    "trailing PIO_QUALITY_WINDOW_S",
    labels=("instance",),
)
_SCORE_MEAN = REGISTRY.gauge(
    "pio_prediction_score_mean",
    "Mean top-k prediction score over the sampled live window, per "
    "serving engine instance",
    labels=("instance",),
)
_SCORE_P50 = REGISTRY.gauge(
    "pio_prediction_score_p50",
    "Median top-k prediction score over the sampled live window",
    labels=("instance",),
)
_DRIFT = REGISTRY.gauge(
    "pio_prediction_drift_score",
    "Population-stability index of the live score distribution vs the "
    "instance's trained baseline sketch (rule of thumb: <0.1 stable, "
    "0.1-0.25 drifting, >0.25 major shift)",
    labels=("instance",),
)
_COVERAGE = REGISTRY.gauge(
    "pio_prediction_item_coverage",
    "Distinct items served in the sampled window as a fraction of the "
    "trained catalog (needs a baseline for the catalog size)",
    labels=("instance",),
)
_POP_SKEW = REGISTRY.gauge(
    "pio_prediction_popularity_skew",
    "Share of sampled top-k slots taken by the single most-served item "
    "(1.0 = every slot is one item)",
    labels=("instance",),
)
_SHADOW_OVERLAP = REGISTRY.gauge(
    "pio_reload_shadow_overlap",
    "Top-k overlap@k between the serving and candidate instances in "
    "the last /reload shadow replay",
)
_SHADOW_SWAPS = REGISTRY.counter(
    "pio_reload_shadow_swaps_total",
    "Shadow-scored /reload outcomes: ok (committed), blocked (refused "
    "by PIO_RELOAD_SHADOW_GATE), unjudged (no sampled queries to "
    "replay)",
    labels=("result",),
)


# -- env knobs (read per call so live processes retune) ----------------------

from predictionio_tpu.utils.env import (  # noqa: E402
    env_float as _env_float,
    env_int as _env_int,
)


#: (raw env value, parsed mode) memo — the mode check runs per query.
_mode_cache: tuple[str | None, str] = (None, "all")


def sample_mode() -> str:
    """``PIO_QUALITY_SAMPLE``: ``off`` | ``all`` (default) | a
    probability in (0, 1) — the trace-sampling grammar, minus ``slow``
    (quality has no latency to threshold on)."""
    global _mode_cache
    env = os.environ.get("PIO_QUALITY_SAMPLE")
    cached_env, cached_mode = _mode_cache
    if env == cached_env:
        return cached_mode
    raw = (env if env is not None else "all").strip().lower()
    if raw in ("off", "0", "false", "none", ""):
        mode = "off"
    elif raw in ("all", "1", "true"):
        mode = "all"
    else:
        try:
            p = float(raw)
            mode = "off" if p <= 0.0 else "all" if p >= 1.0 else raw
        except ValueError:
            logger.warning("unrecognized PIO_QUALITY_SAMPLE=%r; "
                           "falling back to 'all'", env)
            mode = "all"
    _mode_cache = (env, mode)
    return mode


def quality_enabled() -> bool:
    return sample_mode() != "off"


def sample(request_id: str | None = None) -> bool:
    """Head decision for one served prediction. With a request id the
    decision is a DETERMINISTIC hash of the id, so every process that
    sees the same request (the query server at serve time, the event
    server on the feedback loop's predict event) draws the same coin —
    independent draws would double the effective rate in-process and
    desynchronize the split-deploy join."""
    mode = sample_mode()
    if mode == "off":
        return False
    if mode == "all":
        return True
    p = float(mode)
    if request_id:
        digest = hashlib.sha1(request_id.encode("utf-8", "replace"))
        return int.from_bytes(digest.digest()[:4], "big") / 2**32 < p
    return random.random() < p


def join_ttl_s() -> float:
    return _env_float("PIO_QUALITY_JOIN_TTL_S", 600.0)


def join_capacity() -> int:
    return max(_env_int("PIO_QUALITY_JOIN_CAP", 4096), 1)


def window_size() -> int:
    return max(_env_int("PIO_QUALITY_WINDOW", 256), 8)


def window_s() -> float:
    return _env_float("PIO_QUALITY_WINDOW_S", 600.0)


def replay_size() -> int:
    return max(_env_int("PIO_QUALITY_REPLAY_N", 32), 1)


def baseline_sample_n() -> int:
    return max(_env_int("PIO_QUALITY_BASELINE_N", 64), 4)


def baseline_k() -> int:
    return max(_env_int("PIO_QUALITY_TOPK", 10), 1)


def shadow_gate_floor() -> float | None:
    """``PIO_RELOAD_SHADOW_GATE``: minimum shadow overlap@k a /reload
    candidate must clear before the swap commits; unset/empty = the
    shadow report is advisory only."""
    raw = os.environ.get("PIO_RELOAD_SHADOW_GATE", "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("bad PIO_RELOAD_SHADOW_GATE=%r; gate disabled", raw)
        return None


# -- score extraction --------------------------------------------------------

def extract_item_scores(result) -> list[tuple[str | None, float]]:
    """``(item, score)`` pairs from a prediction in any of the shapes
    the serving path produces: a template ``PredictedResult`` (an
    ``itemScores`` sequence of objects or dicts), the JSON dict the
    server returns, or a bare scalar-``score`` prediction. Unknown
    shapes yield ``[]`` — quality sampling must never fail a query."""
    pairs: list[tuple[str | None, float]] = []
    try:
        item_scores = None
        if isinstance(result, dict):
            item_scores = result.get("itemScores")
        else:
            item_scores = getattr(result, "itemScores", None)
        if item_scores is not None:
            for entry in item_scores:
                if isinstance(entry, dict):
                    item, score = entry.get("item"), entry.get("score")
                else:
                    item = getattr(entry, "item", None)
                    score = getattr(entry, "score", None)
                if isinstance(score, (int, float)) and not isinstance(
                        score, bool) and math.isfinite(float(score)):
                    pairs.append((None if item is None else str(item),
                                  float(score)))
            return pairs
        score = (result.get("score") if isinstance(result, dict)
                 else getattr(result, "score", None))
        if isinstance(score, (int, float)) and not isinstance(score, bool) \
                and math.isfinite(float(score)):
            pairs.append((None, float(score)))
    except Exception:  # noqa: BLE001 — never fail the serving path
        logger.debug("score extraction failed", exc_info=True)
    return pairs


# -- baseline sketch ---------------------------------------------------------

def _score_bins(scores: list[float], edges: list[float]) -> list[float]:
    """Normalized occupancy over the ``len(edges)+1`` bins the edges
    split the real line into."""
    counts = [0] * (len(edges) + 1)
    for s in scores:
        lo, hi = 0, len(edges)
        while lo < hi:  # bisect_right, inlined to avoid float-key import
            mid = (lo + hi) // 2
            if s < edges[mid]:
                hi = mid
            else:
                lo = mid + 1
        counts[lo] += 1
    total = float(sum(counts)) or 1.0
    return [c / total for c in counts]


def population_stability_index(baseline_counts: list[float],
                               live_scores: list[float],
                               edges: list[float]) -> float | None:
    """PSI of the live values against the baseline's binned
    distribution, on the BASELINE's bin edges: ``sum((q-p) * ln(q/p))``.
    Both sides get Laplace smoothing (α=0.5 per bin) so a small live
    window's empty bins read as sampling noise, not as a vanished
    population — raw epsilon smoothing makes PSI explode at the exact
    moment (few samples) a drift monitor must stay quiet."""
    if not live_scores or not baseline_counts or \
            len(baseline_counts) != len(edges) + 1:
        return None
    bins = len(baseline_counts)
    alpha = 0.5
    n_base = float(sum(baseline_counts))
    n_live = float(len(live_scores))
    live_counts = [f * n_live for f in _score_bins(live_scores, edges)]
    psi = 0.0
    for cb, cl in zip(baseline_counts, live_counts):
        p = (cb + alpha) / (n_base + alpha * bins)
        q = (cl + alpha) / (n_live + alpha * bins)
        psi += (q - p) * math.log(q / p)
    return psi


def build_baseline(scored: list[list[tuple[str | None, float]]],
                   n_items: int | None = None,
                   k: int | None = None) -> dict | None:
    """The persisted per-instance baseline: decile bin edges + counts of
    the held-out sample's TOP score per query (the top score is
    invariant to how many items a live query asks for, so a ``num: 5``
    request drifts only when the model does), plus the popularity/
    coverage profile of its served items. ``scored`` is one
    ``(item, score)`` list per probe query."""
    scores = [s for pairs in scored for _, s in pairs]
    tops = [max(s for _, s in pairs) for pairs in scored if pairs]
    if not scores or not tops:
        return None
    ordered = sorted(tops)
    n = len(ordered)
    edges = []
    for decile in range(1, 10):
        edges.append(ordered[min(int(n * decile / 10), n - 1)])
    counts = [c * n for c in _score_bins(tops, edges)]
    tally = _TallyCounter(i for pairs in scored for i, _ in pairs
                          if i is not None)
    slots = sum(tally.values())
    doc = {
        "v": 1,
        "queries": len(scored),
        "k": k if k is not None else max(len(p) for p in scored),
        "scoreMean": sum(scores) / len(scores),
        "edges": [round(e, 6) for e in edges],
        "counts": [round(c, 3) for c in counts],
        "topShare": (max(tally.values()) / slots) if slots else None,
        "distinctItems": len(tally),
    }
    if n_items:
        doc["nItems"] = int(n_items)
        doc["coverage"] = len(tally) / n_items
    return doc


def baseline_env(engine, engine_params, models) -> dict[str, str]:
    """The train-time half of drift detection: probe each algorithm that
    exposes ``quality_probe_queries(model, n, k)`` with a held-out query
    sample, score the answers on the host path, and return the sketch as
    the ``{BASELINE_ENV_KEY: json}`` fragment ``run_train`` merges into
    the engine-instance env. ``{}`` when no algorithm opts in or the
    probe fails — a baseline must never sink a train."""
    try:
        algorithms = engine._algorithms(engine_params)
        for algo, model in zip(algorithms, models):
            probe = getattr(algo, "quality_probe_queries", None)
            if probe is None:
                continue
            queries = probe(model, n=baseline_sample_n(), k=baseline_k())
            scored = [pairs for pairs in
                      (extract_item_scores(p)
                       for p in batch_predictions(algo, model, queries))
                      if pairs]
            if not scored:
                continue
            ids = getattr(model, "item_ids", None)
            n_items = len(ids) if ids is not None and len(ids) else None
            doc = build_baseline(scored, n_items=n_items, k=baseline_k())
            if doc is not None:
                return {BASELINE_ENV_KEY: json.dumps(doc)}
    except Exception:  # noqa: BLE001
        logger.debug("quality baseline probe failed", exc_info=True)
    return {}


def batch_predictions(algo, model, queries) -> list:
    """Predictions for ``queries`` via ONE ``batch_predict`` call when
    the algorithm has one (one catalog upload/matmul for the whole
    probe or shadow replay, not one per query), falling back to the
    per-query path. A query that fails yields None in its slot."""
    n = len(queries)
    if n == 0:
        return []
    try:
        got = dict(algo.batch_predict(model, list(enumerate(queries))))
        return [got.get(i) for i in range(n)]
    except Exception:  # noqa: BLE001 — per-query fallback isolates one
        out = []       # bad query instead of losing the whole probe
        for q in queries:
            try:
                out.append(algo.predict(model, q))
            except Exception:  # noqa: BLE001
                out.append(None)
        return out


# -- the monitor -------------------------------------------------------------

class _JoinEntry:
    __slots__ = ("t", "instance", "model_age_s", "items")

    def __init__(self, t: float, instance: str, model_age_s: float | None,
                 items: frozenset):
        self.t = t
        self.instance = instance
        self.model_age_s = model_age_s
        self.items = items


class QualityMonitor:
    """Process-global quality state: the sampled-prediction window, the
    feedback join buffer, the shadow replay buffer, and per-instance
    tallies. All methods are thread-safe and bounded."""

    #: per-instance tallies kept for at most this many instances (old
    #: swapped-out instances age out of the doc, newest last)
    MAX_INSTANCES = 8

    def __init__(self):
        self._lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.baseline: dict | None = None
        self.baseline_instance: str | None = None
        #: (t, instance, scores tuple, items tuple) — the live sketch
        self._window: deque = deque(maxlen=window_size())
        #: request id -> _JoinEntry (FIFO, capacity- and TTL-bounded)
        self._join: OrderedDict[str, _JoinEntry] = OrderedDict()
        #: (t, instance, hit, model_age_s) — joined feedback outcomes
        self._results: deque = deque(maxlen=4096)
        #: last-N sampled query objects, for the /reload shadow replay
        self._replay: deque = deque(maxlen=replay_size())
        #: instance -> {"sampled", "joined", "hits", "modelAgeSeconds"}
        self._instances: OrderedDict[str, dict] = OrderedDict()
        #: request ids already joined once — duplicates are recognized,
        #: not re-counted; bounded like everything else here
        self._consumed = _ConsumedSet()
        #: (t, reason) of recent feedback POST failures — the doctor
        #: warns on RECENT failures, not a lifetime counter (one blip
        #: must not read as a dead loop forever)
        self._feedback_errors: deque = deque(maxlen=1024)
        self.last_shadow: dict | None = None

    def reset(self) -> None:
        """Drop all state (tests retuning the env knobs)."""
        with self._lock:
            self._reset_locked()

    # -- baseline ------------------------------------------------------------
    def set_baseline(self, instance_id: str, doc: dict | None) -> None:
        """Adopt the deployed instance's trained baseline (None clears —
        an instance trained before this pillar has no sketch)."""
        with self._lock:
            self.baseline = doc if isinstance(doc, dict) else None
            self.baseline_instance = instance_id

    # -- the serving side ----------------------------------------------------
    def record_prediction(self, request_id: str | None, instance_id: str,
                          model_age_s: float | None, query,
                          result) -> None:
        """One SAMPLED served prediction: into the score window, the
        shadow replay buffer, and (when a request id exists) the
        feedback join buffer."""
        pairs = extract_item_scores(result)
        now = time.time()
        scores = tuple(s for _, s in pairs)
        items = tuple(i for i, _ in pairs if i is not None)
        with self._lock:
            tally = self._tally(instance_id)
            tally["sampled"] += 1
            if model_age_s is not None:
                tally["modelAgeSeconds"] = round(model_age_s, 1)
            self._window.append((now, instance_id, scores, items))
            if query is not None:
                self._replay.append(query)
            if request_id and items:
                self._evict_locked(now)
                if request_id not in self._join:
                    while len(self._join) >= join_capacity():
                        self._join.popitem(last=False)
                        _JOIN_EVICTIONS.inc(reason="capacity")
                    self._join[request_id] = _JoinEntry(
                        now, instance_id, model_age_s, frozenset(items))
        _SAMPLED.inc(instance=instance_id)

    def record_served_set(self, request_id: str, instance_id: str,
                          model_age_s: float | None,
                          items: tuple) -> None:
        """Buffer a served top-k set learned from the SERVING LOG (the
        feedback loop's predict event) rather than from serving itself —
        how a split-process event server joins feedback it alone
        receives. No-op when the request id is already buffered or
        consumed (the in-process topology records at serve time first),
        so one request never tallies twice."""
        if not request_id or not items:
            return
        now = time.time()
        with self._lock:
            self._evict_locked(now)
            if request_id in self._join or request_id in self._consumed:
                return
            while len(self._join) >= join_capacity():
                self._join.popitem(last=False)
                _JOIN_EVICTIONS.inc(reason="capacity")
            self._join[request_id] = _JoinEntry(
                now, instance_id, model_age_s,
                frozenset(str(i) for i in items))
            tally = self._tally(instance_id)
            tally["sampled"] += 1
            if model_age_s is not None:
                tally["modelAgeSeconds"] = round(model_age_s, 1)
        _SAMPLED.inc(instance=instance_id)

    def _tally(self, instance_id: str) -> dict:
        tally = self._instances.get(instance_id)
        if tally is None:
            while len(self._instances) >= self.MAX_INSTANCES:
                self._instances.popitem(last=False)
            tally = self._instances[instance_id] = {
                "sampled": 0, "joined": 0, "hits": 0,
                "modelAgeSeconds": None}
        return tally

    def _evict_locked(self, now: float) -> None:
        ttl = join_ttl_s()
        while self._join:
            rid, entry = next(iter(self._join.items()))
            if now - entry.t <= ttl:
                break
            del self._join[rid]
            _JOIN_EVICTIONS.inc(reason="ttl")

    # -- the feedback side ---------------------------------------------------
    def record_feedback(self, request_id: str | None,
                        item: str | None) -> str:
        """Join one feedback event against the buffered serving record.
        Returns the outcome (``hit``/``miss``/``unknown``/``duplicate``)
        — attribution goes to the instance that SERVED the request, not
        whatever is serving now."""
        now = time.time()
        outcome = "unknown"
        with self._lock:
            self._evict_locked(now)
            if request_id:
                entry = self._join.pop(request_id, None)
                if entry is None:
                    outcome = ("duplicate"
                               if request_id in self._consumed else "unknown")
                else:
                    self._consumed.add(request_id)
                    hit = item is not None and item in entry.items
                    outcome = "hit" if hit else "miss"
                    self._results.append(
                        (now, entry.instance, hit, entry.model_age_s))
                    tally = self._tally(entry.instance)
                    tally["joined"] += 1
                    if hit:
                        tally["hits"] += 1
        _FEEDBACK.inc(result=outcome)
        return outcome

    def note_feedback_error(self, reason: str) -> None:
        """One failed feedback POST (create_server._send_feedback) —
        timestamped so the quality doc (and the doctor's starving-loop
        WARN) reports the trailing window, while the lifetime
        ``pio_feedback_errors_total`` counter rides /metrics."""
        with self._lock:
            self._feedback_errors.append((time.time(), reason))
    def shadow_queries(self) -> list:
        with self._lock:
            return list(self._replay)

    def note_shadow(self, report: dict) -> None:
        with self._lock:
            self.last_shadow = report
        overlap = report.get("overlapAtK")
        if overlap is not None:
            _SHADOW_OVERLAP.set(float(overlap))
        _SHADOW_SWAPS.inc(result=(
            "blocked" if report.get("blocked")
            else "ok" if report.get("replayed") else "unjudged"))

    # -- derived state -------------------------------------------------------
    def _instance_stats_locked(self, now: float) -> dict[str, dict]:
        window_floor = now - window_s()
        per: dict[str, dict] = {}
        for iid, tally in self._instances.items():
            per[iid] = dict(tally)
        # ONE pass over the joined-feedback window for every instance —
        # this runs under the monitor lock at every scrape/history tick,
        # and a per-instance rescan would block the serving hot path for
        # O(instances × results)
        window_joined: dict[str, int] = {}
        window_hits: dict[str, int] = {}
        for t, riid, hit, _age in self._results:
            if t >= window_floor:
                window_joined[riid] = window_joined.get(riid, 0) + 1
                if hit:
                    window_hits[riid] = window_hits.get(riid, 0) + 1
        scores: dict[str, list[float]] = {}
        tops: dict[str, list[float]] = {}
        seen_preds: dict[str, set] = {}
        items: dict[str, _TallyCounter] = {}
        for t, iid, ss, ii in self._window:
            scores.setdefault(iid, []).extend(ss)
            if ss:
                # the drift population is DISTINCT prediction signatures:
                # one hot user asked 500 times is one draw from the
                # model, not 500 — without the dedup, narrow-but-heavy
                # traffic reads as a drifted score distribution
                seen = seen_preds.setdefault(iid, set())
                if ss not in seen:
                    seen.add(ss)
                    tops.setdefault(iid, []).append(max(ss))
            items.setdefault(iid, _TallyCounter()).update(ii)
        base = self.baseline or {}
        for iid, doc in per.items():
            ss = scores.get(iid) or []
            tally = items.get(iid) or _TallyCounter()
            slots = sum(tally.values())
            doc["scoreMean"] = (sum(ss) / len(ss)) if ss else None
            doc["scoreP50"] = (sorted(ss)[len(ss) // 2]) if ss else None
            doc["popularitySkew"] = (max(tally.values()) / slots
                                     if slots else None)
            n_items = base.get("nItems")
            doc["coverage"] = (len(tally) / n_items
                               if n_items and slots else None)
            drift = None
            live_tops = tops.get(iid) or []
            if live_tops and base and iid == self.baseline_instance:
                # drift judges the TOP-score distribution — invariant
                # to the per-query num, unlike the full top-k spread
                drift = population_stability_index(
                    base.get("counts") or [], live_tops,
                    base.get("edges") or [])
            doc["drift"] = None if drift is None else round(drift, 4)
            # distinct signatures — the drift finding's evidence count
            doc["windowPredictions"] = len(live_tops)
            joined = window_joined.get(iid, 0)
            hits = window_hits.get(iid, 0)
            doc["windowJoined"] = joined
            doc["hitRate"] = (hits / joined) if joined else None
            doc["joinRate"] = (doc["joined"] / doc["sampled"]
                               if doc["sampled"] else None)
        return per

    def refresh_gauges(self) -> None:
        """Collect hook: publish the windowed sketch/hit-rate gauges at
        every scrape (and every history tick)."""
        now = time.time()
        with self._lock:
            self._evict_locked(now)
            per = self._instance_stats_locked(now)
            _JOIN_ENTRIES.set(len(self._join))
        for iid, doc in per.items():
            if doc["scoreMean"] is not None:
                _SCORE_MEAN.set(doc["scoreMean"], instance=iid)
            if doc["scoreP50"] is not None:
                _SCORE_P50.set(doc["scoreP50"], instance=iid)
            if doc["drift"] is not None:
                _DRIFT.set(doc["drift"], instance=iid)
            if doc["coverage"] is not None:
                _COVERAGE.set(doc["coverage"], instance=iid)
            if doc["popularitySkew"] is not None:
                _POP_SKEW.set(doc["popularitySkew"], instance=iid)
            if doc["hitRate"] is not None:
                _HIT_RATE.set(doc["hitRate"], instance=iid)

    def join_buffer_len(self) -> int:
        with self._lock:
            return len(self._join)

    def join_snapshot(self) -> list[tuple[str, str]]:
        """(request id, one served item) per buffered entry — the
        public face the serving bench drives deterministic feedback
        through (bench_serving._quality_section)."""
        with self._lock:
            return [(rid, next(iter(e.items)))
                    for rid, e in self._join.items() if e.items]

    def to_json(self) -> dict:
        """The ``GET /debug/quality`` document."""
        now = time.time()
        with self._lock:
            self._evict_locked(now)
            per = self._instance_stats_locked(now)
            doc = {
                "sampleMode": sample_mode(),
                "windowSize": self._window.maxlen,
                "windowS": window_s(),
                "joinTtlS": join_ttl_s(),
                "joinCapacity": join_capacity(),
                "joinEntries": len(self._join),
                "baseline": self.baseline,
                "baselineInstance": self.baseline_instance,
                "instances": per,
                "lastShadow": self.last_shadow,
            }
        doc["feedback"] = {key[0]: v for key, v in _FEEDBACK.items()}
        floor = now - window_s()
        errors: dict[str, int] = {}
        with self._lock:
            for t, reason in self._feedback_errors:
                if t >= floor:
                    errors[reason] = errors.get(reason, 0) + 1
        doc["feedbackErrors"] = errors
        return doc


class _ConsumedSet:
    """Bounded remember-set of already-joined request ids (duplicate
    detection without unbounded growth)."""

    MAX = 8192

    def __init__(self):
        self._d: OrderedDict[str, None] = OrderedDict()

    def add(self, rid: str) -> None:
        self._d[rid] = None
        while len(self._d) > self.MAX:
            self._d.popitem(last=False)

    def __contains__(self, rid: str) -> bool:
        return rid in self._d


#: The process-global monitor (one per process, like the registry).
MONITOR = QualityMonitor()

# Gauges refresh at every scrape/history tick, like the staleness gauges.
REGISTRY.add_collect_hook(MONITOR.refresh_gauges)


def observe_event(event) -> str | None:
    """Event-server hook: classify one ingested event.

    The serving log itself — the feedback loop's ``predict`` event on a
    ``pio_pr`` entity — is not user feedback, but it CARRIES the served
    top-k, the request id, and the serving attribution, so it registers
    the served set in this process's join buffer (the split-deploy
    event server has no other view of what was served; in-process the
    query server already recorded it and the registration no-ops).
    Any OTHER event carrying the ``requestId`` property joins the
    buffer, with the event's target entity (falling back to the entity)
    as the acted-on item. Returns the join outcome, or None for events
    that aren't feedback."""
    if not quality_enabled():
        return None
    try:
        props = getattr(event, "properties", None)
        rid = props.get_opt("requestId") if props is not None else None
        if not rid:
            return None
        if getattr(event, "event", None) == "predict" and \
                getattr(event, "entity_type", None) == "pio_pr":
            # the same PIO_QUALITY_SAMPLE head decision the serving
            # side made — keyed on the request id, so this is the SAME
            # coin, not a second draw: the feedback loop logs every
            # request, and an operator sampling at 1% must see the join
            # path (buffer occupancy, sampled tallies) bounded at 1%
            if not sample(str(rid)):
                return None
            prediction = props.get_opt("prediction")
            items = tuple(
                i for i, _ in extract_item_scores(prediction)
                if i is not None)
            age = props.get_opt("modelAgeSeconds")
            MONITOR.record_served_set(
                str(rid),
                str(props.get_opt("engineInstanceId") or "unknown"),
                float(age) if isinstance(age, (int, float)) else None,
                items)
            return None
        item = getattr(event, "target_entity_id", None) or \
            getattr(event, "entity_id", None)
        return MONITOR.record_feedback(str(rid),
                                       None if item is None else str(item))
    except Exception:  # noqa: BLE001 — quality must never fail ingest
        logger.debug("quality feedback observation failed", exc_info=True)
        return None


# -- doc merging (gateway fleet view) ----------------------------------------

def merge_docs(docs: list[dict]) -> dict:
    """Fleet-merged quality doc from per-replica ``/debug/quality``
    documents: per-instance tallies sum, window stats take the worst
    case (max drift / skew, min coverage / hit rate — the operator
    cares about the sickest replica). Note the in-process ``--replicas
    N`` caveat from obs/fleet.py: replicas sharing one process registry
    each report the same monitor, so sums there overcount by the
    replica factor; per-instance worst-case stats stay meaningful."""
    merged: dict = {"instances": {}, "feedback": {}, "feedbackErrors": {},
                    "joinEntries": 0, "lastShadow": None, "baseline": None,
                    "baselineInstance": None}
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        merged["joinEntries"] += doc.get("joinEntries") or 0
        if merged["baseline"] is None and doc.get("baseline"):
            merged["baseline"] = doc["baseline"]
            merged["baselineInstance"] = doc.get("baselineInstance")
        if doc.get("lastShadow"):
            merged["lastShadow"] = doc["lastShadow"]
        for family in ("feedback", "feedbackErrors"):
            for k, v in (doc.get(family) or {}).items():
                merged[family][k] = merged[family].get(k, 0) + v
        for iid, stats in (doc.get("instances") or {}).items():
            out = merged["instances"].setdefault(iid, {
                "sampled": 0, "joined": 0, "hits": 0, "windowJoined": 0,
                "windowPredictions": 0,
                "modelAgeSeconds": None, "scoreMean": None,
                "scoreP50": None, "drift": None, "coverage": None,
                "popularitySkew": None, "hitRate": None, "joinRate": None,
            })
            for k in ("sampled", "joined", "hits", "windowJoined",
                      "windowPredictions"):
                out[k] += stats.get(k) or 0
            # a replica's JUDGED stats (drift, hitRate) only join the
            # worst-case merge when that replica's OWN window has enough
            # evidence: the merged doc pairs worst-case values with
            # fleet-SUMMED counts, so an unguarded merge would let one
            # replica's 2-sample PSI noise ride the fleet's summed
            # sample count straight past quality_findings' minimum-
            # evidence guards (docs without the count — older peers —
            # are judged as-is, matching quality_findings)
            n_pred = stats.get("windowPredictions")
            n_join = stats.get("windowJoined")
            for k, worst in (("drift", max), ("popularitySkew", max),
                             ("modelAgeSeconds", max),
                             ("coverage", min), ("hitRate", min),
                             ("scoreMean", max), ("scoreP50", max)):
                v = stats.get(k)
                if v is None:
                    continue
                if k == "drift" and n_pred is not None \
                        and n_pred < min_drift_samples():
                    continue
                if k == "hitRate" and n_join is not None \
                        and n_join < min_joins_for_judgment():
                    continue
                out[k] = v if out[k] is None else worst(out[k], v)
            out["joinRate"] = (out["joined"] / out["sampled"]
                               if out["sampled"] else None)
    return merged


# -- triage (`pio doctor`) ----------------------------------------------------

def drift_warn_threshold() -> float:
    return _env_float("PIO_QUALITY_DRIFT_WARN", 0.1)


def drift_crit_threshold() -> float:
    return _env_float("PIO_QUALITY_DRIFT_CRIT", 0.25)


def min_joins_for_judgment() -> int:
    return max(_env_int("PIO_QUALITY_MIN_JOINS", 20), 1)


def min_drift_samples() -> int:
    return max(_env_int("PIO_QUALITY_MIN_SAMPLES", 16), 1)


def hit_rate_floor() -> float:
    return _env_float("PIO_SLO_ONLINE_HIT_RATE_MIN", 0.05)


def quality_findings(doc: dict | None) -> list[dict]:
    """Ranked findings from a quality doc (the single-server shape or a
    gateway merge): QUALITY-DRIFT (PSI past the warn/crit thresholds),
    QUALITY-REGRESSION (windowed hit rate under the online_quality
    floor, with enough joins to judge), and a starving feedback loop
    (nonzero ``pio_feedback_errors_total``) — each naming the engine
    instance and its model age."""
    if not isinstance(doc, dict):
        return []
    doc = doc.get("merged") or doc
    findings: list[dict] = []

    def age_txt(stats: dict) -> str:
        age = stats.get("modelAgeSeconds")
        return f"model age {age:.0f}s" if isinstance(age, (int, float)) \
            else "model age unknown"

    for iid, stats in sorted((doc.get("instances") or {}).items()):
        drift = stats.get("drift")
        # a handful of sampled predictions is sampling noise, not a
        # drifted model: hold the finding until the window has evidence
        # (a doc without the count — an older peer — is judged as-is)
        n_window = stats.get("windowPredictions")
        if n_window is not None and n_window < min_drift_samples():
            drift = None
        if drift is not None and drift > drift_warn_threshold():
            crit = drift > drift_crit_threshold()
            findings.append({
                "severity": "critical" if crit else "warn",
                "subject": f"QUALITY-DRIFT {iid}",
                "detail": (
                    f"live score distribution PSI {drift:.3f} vs trained "
                    f"baseline (warn>{drift_warn_threshold():g}, "
                    f"crit>{drift_crit_threshold():g}), {age_txt(stats)}"),
            })
        hit_rate = stats.get("hitRate")
        joined = stats.get("windowJoined") or 0
        if hit_rate is not None and joined >= min_joins_for_judgment() \
                and hit_rate < hit_rate_floor():
            findings.append({
                "severity": "critical",
                "subject": f"QUALITY-REGRESSION {iid}",
                "detail": (
                    f"online hit rate {hit_rate:.3f} under the "
                    f"online_quality floor {hit_rate_floor():g} over "
                    f"{joined} joined feedback event(s), {age_txt(stats)}"),
            })
    errors = doc.get("feedbackErrors") or {}
    total_errors = sum(errors.values())
    if total_errors:
        by_reason = ", ".join(f"{k}={v}" for k, v in sorted(errors.items()))
        findings.append({
            "severity": "warn",
            "subject": "feedback loop",
            "detail": (
                f"{total_errors} feedback POST failure(s) in the last "
                f"{window_s():g}s ({by_reason}) — a dead feedback loop "
                "starves the online-accuracy join "
                "(pio_feedback_errors_total)"),
        })
    return findings


def reset() -> None:
    """Tests: drop the process monitor's state and the mode memo."""
    global _mode_cache
    _mode_cache = (None, "all")
    MONITOR.reset()
