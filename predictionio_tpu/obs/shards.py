"""Shard & collective observatory — the seventh obs pillar (ISSUE 20).

PRs 18–19 made the hot paths collective-heavy (ALX-layout sharded ALS,
row-sharded embedding tables, sharded top-k) but the obs stack still saw
a sharded run as one opaque dispatch: ``ops/collectives.py`` published
zero metrics and per-shard accounting was scattered across
``last_sharded_stats`` / ``route_stats``. ALX (PAPERS.md) shows the
exchange fraction is THE scaling limiter for this layout; this module is
the process-global ledger every sharded call site reports into so the
owed real-hardware captures are diagnosable.

Three legs:

collective ledger
    The ``ops/collectives.py`` helpers (and the ``sharded_table`` /
    ``topk`` routes) tick analytic mesh-wide bytes at TRACE time —
    tracing happens inside ``device_obs.profiled_program``'s active
    scope, so the tick is program-labelled for free and costs nothing
    per dispatch (a jit body traces once per signature). The dispatch
    side rides a ``device_obs.add_dispatch_listener`` hook: each
    profiled dispatch of a registered program replays the traced
    per-step bytes × ``steps_per_dispatch`` into
    ``pio_collective_bytes_total{op,program}``, observes the host wall
    time into ``pio_collective_dispatch_seconds{program}``, derives an
    exchange-time estimate from the analytic link model
    (``PIO_SHARD_LINK_GBPS``, default 25.0 — a documented constant, not
    a runtime probe, so the accounting is deterministic and adds zero
    compiles), publishes ``pio_shard_exchange_frac{program}`` =
    cumulative exchange seconds / cumulative dispatch seconds, and
    records retroactive ``<program>:exchange`` / ``<program>:solve``
    trace spans so ``pio trace`` waterfalls show the exchange inside a
    sharded iteration.

per-shard skew
    Call sites report per-shard loads (rating cells, touched rows,
    fold-in chunk sizes) into shard-indexed ``pio_shard_load`` gauges
    plus the unified ``pio_shard_imbalance{program}`` (max/mean). The
    history sampler calls :meth:`ShardObservatory.history_tick` each
    tick; a shard whose load exceeds ``PIO_SHARD_IMBALANCE_WARN`` ×
    median in the two most recent ticks is a persistent straggler —
    the SHARD-STRAGGLER doctor finding (:func:`diagnose_shards_doc`).

surfaces
    ``GET /debug/shards`` (utils/http.py, 404 until a sharded program
    reports), ``pio shards`` (tools/cli.py), the dashboard "Sharded
    runtime" panel, history series (``exchange_frac``,
    ``collective_bytes_per_sec``, ``shard_imbalance``), run-ledger
    ``exchange_frac`` notes, and bench.py's sharded sections reading
    ``*_exchange_frac`` from this live ledger.

Everything here is fail-soft and lock-cheap: an un-instrumented process
pays one dict lookup per profiled dispatch (the ``shard_obs_overhead_frac``
bench guard prices the instrumented path at ≤ 1% of a sharded step).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.obs import trace
from predictionio_tpu.obs.metrics import REGISTRY

__all__ = [
    "OBSERVATORY",
    "ShardObservatory",
    "collective_traced",
    "diagnose_shards_doc",
    "link_gbps",
    "shard_imbalance_warn",
]

logger = logging.getLogger(__name__)

#: Analytic interconnect bytes per collective, labelled by op and the
#: profiled program whose trace issued it (``unattributed`` outside any).
#: Ticked at trace time (the regression-pinned floor: the helpers must
#: publish even when a call site bypasses the observatory) and replayed
#: per executed step at dispatch time for registered programs.
COLLECTIVE_BYTES = REGISTRY.counter(
    "pio_collective_bytes_total",
    "Analytic mesh-wide interconnect bytes of sharded collectives "
    "(trace-time model: all_to_all ships every device's send buffer, "
    "all_gather n-1 copies of each local block)",
    labels=("op", "program"),
)

#: Host-side wall time of each profiled dispatch of a collective-bearing
#: program (enqueue→results for sync'd programs — the denominator of the
#: exchange fraction).
COLLECTIVE_DISPATCH = REGISTRY.histogram(
    "pio_collective_dispatch_seconds",
    "Host wall seconds per profiled dispatch of a registered sharded "
    "program",
    labels=("program",),
)

#: Estimated fraction of a sharded program's wall time spent on the
#: interconnect: cumulative analytic exchange seconds (bytes /
#: ``PIO_SHARD_LINK_GBPS``) over cumulative dispatch seconds. The ALX
#: scaling limiter, live.
EXCHANGE_FRAC = REGISTRY.gauge(
    "pio_shard_exchange_frac",
    "Estimated exchange-time fraction of a sharded program's dispatch "
    "wall time (analytic bytes over the PIO_SHARD_LINK_GBPS link model)",
    labels=("program",),
)

#: Per-shard load of the most recent reported sharded plan/batch (rating
#: cells, touched embedding rows, fold-in chunk cells — ``kind`` in the
#: /debug/shards doc says which). Shard-indexed so skew is visible per
#: series, not just as a ratio.
SHARD_LOAD = REGISTRY.gauge(
    "pio_shard_load",
    "Per-shard load units of the most recent reported sharded "
    "plan/batch for a program (see /debug/shards for the unit)",
    labels=("program", "shard"),
)

#: The unified skew gauge (max/mean of ``pio_shard_load``): one family
#: for every sharded program, where the ALS and embedding paths used to
#: keep separate ad-hoc gauges (those remain as legacy aliases).
SHARD_SKEW = REGISTRY.gauge(
    "pio_shard_imbalance",
    "Heaviest-shard / mean per-shard load of the most recent reported "
    "sharded plan/batch (1.0 = balanced)",
    labels=("program",),
)


def shard_imbalance_warn() -> float:
    """THE ``PIO_SHARD_IMBALANCE_WARN`` parse (default 2.0): the shared
    threshold of the SHARD-IMBALANCE / EMB-SHARD-IMBALANCE run-ledger
    findings and the SHARD-STRAGGLER rolling judgment."""
    try:
        return float(os.environ.get("PIO_SHARD_IMBALANCE_WARN", "2.0"))
    except ValueError:
        return 2.0


def link_gbps() -> float:
    """``PIO_SHARD_LINK_GBPS`` (default 25.0): the analytic per-link
    interconnect bandwidth the exchange-time estimate divides bytes by.
    A documented constant rather than a runtime probe — deterministic,
    zero extra compiles; set it to the real fabric (ICI ~100s of GB/s,
    DCN ~25) to calibrate ``pio_shard_exchange_frac``."""
    try:
        v = float(os.environ.get("PIO_SHARD_LINK_GBPS", "25.0"))
        return v if v > 0 else 25.0
    except ValueError:
        return 25.0


#: Straggler judgment window (history ticks). Two consecutive over-
#: threshold ticks trip the finding — "within two history ticks" is the
#: ISSUE acceptance — and the deque keeps a few more for the doc.
_WINDOW = 8


class _ProgramLedger:
    """Everything the observatory knows about one sharded program."""

    __slots__ = ("name", "shards", "arena_prefix", "steps_per_dispatch",
                 "trace_bytes", "trace_marker", "dispatches", "steps",
                 "dispatch_s", "bytes_total", "exchange_s",
                 "exchange_frac", "loads", "load_kind", "imbalance",
                 "load_window", "updated_at")

    def __init__(self, name: str):
        self.name = name
        self.shards: int = 0
        self.arena_prefix: str | None = None
        self.steps_per_dispatch: int = 1
        #: op -> analytic bytes per STEP, captured at trace time (the
        #: collectives sit inside the program's fori/scan body, so one
        #: trace sees exactly one step's worth). Latest trace wins.
        self.trace_bytes: dict[str, float] = {}
        self.trace_marker: object | None = None
        self.dispatches = 0
        self.steps = 0
        self.dispatch_s = 0.0
        self.bytes_total = 0.0
        self.exchange_s = 0.0
        self.exchange_frac: float | None = None
        self.loads: list[float] | None = None
        self.load_kind = ""
        self.imbalance: float | None = None
        #: per-history-tick snapshots of ``loads`` (the straggler window)
        self.load_window: deque = deque(maxlen=_WINDOW)
        self.updated_at = 0.0


def _straggler(window, warn_at: float) -> dict | None:
    """The persistent-straggler rule: one shard whose load exceeds
    ``warn_at`` × median(loads) in BOTH of the two most recent history
    ticks. Returns ``{"shard", "ratio", "ticks"}`` or None."""
    if len(window) < 2:
        return None
    hot: dict[int, float] | None = None
    for loads in list(window)[-2:]:
        if not loads:
            return None
        srt = sorted(loads)
        med = srt[len(srt) // 2]
        if med <= 0:
            return None
        tick_hot = {i: ld / med for i, ld in enumerate(loads)
                    if ld > warn_at * med}
        hot = (tick_hot if hot is None else
               {i: max(r, hot[i]) for i, r in tick_hot.items()
                if i in hot})
        if not hot:
            return None
    shard = max(hot, key=hot.get)
    return {"shard": shard, "ratio": round(hot[shard], 2), "ticks": 2}


class ShardObservatory:
    """Process-global per-shard runtime ledger (see module docstring).
    Instantiable for tests; the process singleton is
    :data:`OBSERVATORY`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: dict[str, _ProgramLedger] = {}
        #: total dispatch-listener invocations that found a registered
        #: program — the bench census numerator (shard_obs_overhead_frac)
        self.dispatch_events = 0

    # -- registration -------------------------------------------------------
    def program_meta(self, program: str, *, shards: int | None = None,
                     steps_per_dispatch: int | None = None,
                     arena_prefix: str | None = None) -> None:
        """Register (or update) a sharded program's static facts. Call
        before dispatching: ``steps_per_dispatch`` is how many loop
        steps one profiled dispatch executes (a fused N-iteration run
        is ONE dispatch), so the byte replay scales correctly."""
        with self._lock:
            led = self._programs.get(program)
            if led is None:
                led = self._programs[program] = _ProgramLedger(program)
            if shards is not None:
                led.shards = int(shards)
            if steps_per_dispatch is not None:
                led.steps_per_dispatch = max(int(steps_per_dispatch), 1)
            if arena_prefix is not None:
                led.arena_prefix = arena_prefix
            led.updated_at = time.time()

    def record_shard_load(self, program: str, loads, kind: str = "load"
                          ) -> None:
        """Report per-shard load units (rating cells, touched rows...).
        Publishes the shard-indexed gauges and the unified imbalance;
        the rolling straggler window samples these at history ticks."""
        loads = [float(v) for v in loads]
        if not loads:
            return
        self.program_meta(program, shards=len(loads))
        with self._lock:
            led = self._programs[program]
            prev_n = len(led.loads) if led.loads else 0
            led.loads = loads
            led.load_kind = kind
            mean = sum(loads) / len(loads)
            led.imbalance = (max(loads) / mean) if mean > 0 else 1.0
            led.updated_at = time.time()
        for d, v in enumerate(loads):
            SHARD_LOAD.set(v, program=program, shard=str(d))
        for d in range(len(loads), prev_n):  # re-shard shrank the mesh
            SHARD_LOAD.remove(program=program, shard=str(d))
        SHARD_SKEW.set(led.imbalance, program=program)

    # -- trace-time byte capture -------------------------------------------
    def collective_traced(self, op: str, nbytes: float) -> None:
        """Called by the ``ops/collectives.py`` helpers (and the
        sharded_table/topk routes) while a jit body TRACES: ticks the
        raw counter unconditionally (the regression-pinned floor) and,
        when the trace runs inside a profiled program, accumulates the
        per-step byte model into that program's ledger. One dispatch =
        one ``_ActiveCall`` marker, so a retrace restarts the
        accumulation instead of double-counting."""
        nbytes = float(nbytes)
        program = device_obs.current_program_name() or "unattributed"
        COLLECTIVE_BYTES.inc(nbytes, op=op, program=program)
        if program == "unattributed":
            return
        marker = device_obs.current_dispatch_marker()
        with self._lock:
            led = self._programs.get(program)
            if led is None:
                led = self._programs[program] = _ProgramLedger(program)
            if led.trace_marker is not marker:
                led.trace_marker = marker
                led.trace_bytes = {}
            led.trace_bytes[op] = led.trace_bytes.get(op, 0.0) + nbytes

    # -- dispatch accounting (device_obs listener) --------------------------
    def on_dispatch(self, program: str, seconds: float) -> None:
        """The ``device_obs.add_dispatch_listener`` hook: account one
        profiled dispatch of a registered program. Unregistered programs
        cost one dict lookup (the overhead-guard fast path)."""
        led = self._programs.get(program)
        if led is None:
            return
        with self._lock:
            self.dispatch_events += 1
            steps = led.steps_per_dispatch
            per_step = sum(led.trace_bytes.values())
            nbytes = per_step * steps
            led.dispatches += 1
            led.steps += steps
            led.dispatch_s += seconds
            led.bytes_total += nbytes
            # analytic exchange time, clamped to the wall it lives in
            ex_s = min(nbytes / (link_gbps() * 1e9), max(seconds, 0.0))
            led.exchange_s += ex_s
            frac = (led.exchange_s / led.dispatch_s
                    if led.dispatch_s > 0 else 0.0)
            led.exchange_frac = frac
            ops = dict(led.trace_bytes)
            led.updated_at = time.time()
        for op, b in ops.items():
            COLLECTIVE_BYTES.inc(b * steps, op=op, program=program)
        COLLECTIVE_DISPATCH.observe(seconds, program=program)
        EXCHANGE_FRAC.set(frac, program=program)
        if nbytes > 0:
            # retroactive spans under the caller's span (no-op when the
            # trace layer is off or unsampled): the exchange share at
            # the head of the dispatch window, the solve share after —
            # an attribution model, not a measured interleaving, but it
            # puts the exchange inside `pio trace` waterfalls
            t_end = time.time()
            trace.record(f"{program}:exchange", t_end - seconds, ex_s,
                         bytes=int(nbytes), steps=steps)
            trace.record(f"{program}:solve", t_end - seconds + ex_s,
                         max(seconds - ex_s, 0.0))

    # -- history / straggler window ----------------------------------------
    def history_tick(self) -> None:
        """Called by the history sampler each tick: snapshot every
        program's current per-shard loads into its straggler window."""
        with self._lock:
            for led in self._programs.values():
                if led.loads:
                    led.load_window.append(list(led.loads))

    # -- readers ------------------------------------------------------------
    def active(self) -> bool:
        """Whether any sharded program has reported (the /debug/shards
        404 gate: absent must look exactly like not-built)."""
        with self._lock:
            return any(led.dispatches > 0 or led.loads
                       for led in self._programs.values())

    def exchange_frac(self, program_prefix: str) -> float | None:
        """Live exchange fraction of the most recently updated program
        whose name starts with ``program_prefix`` (bench sections read
        their ``*_exchange_frac`` keys here)."""
        with self._lock:
            leds = [led for name, led in self._programs.items()
                    if name.startswith(program_prefix)
                    and led.exchange_frac is not None]
            if not leds:
                return None
            return max(leds, key=lambda led: led.updated_at).exchange_frac

    def snapshot(self, program_prefix: str) -> dict | None:
        """The report doc of the most recently updated matching program
        (None when nothing matches)."""
        doc = self.report()
        matches = {name: d for name, d in doc["programs"].items()
                   if name.startswith(program_prefix)}
        if not matches:
            return None
        name = max(matches, key=lambda n: matches[n]["updatedAt"])
        return {"program": name, **matches[name]}

    def report(self) -> dict:
        """The merged /debug/shards document."""
        warn_at = shard_imbalance_warn()
        with self._lock:
            leds = [(name, led, list(led.load_window))
                    for name, led in self._programs.items()]
        programs = {}
        for name, led, window in leds:
            per_shard = []
            for d in range(led.shards):
                row: dict = {"shard": d}
                if led.loads and d < len(led.loads):
                    row["load"] = led.loads[d]
                if led.arena_prefix:
                    row["arenaBytes"] = int(device_obs.arena(
                        f"{led.arena_prefix}{d}").bytes())
                per_shard.append(row)
            per_step = sum(led.trace_bytes.values())
            programs[name] = {
                "shards": led.shards,
                "loadKind": led.load_kind,
                "dispatches": led.dispatches,
                "steps": led.steps,
                "stepsPerDispatch": led.steps_per_dispatch,
                "dispatchSeconds": round(led.dispatch_s, 6),
                "collectiveBytes": int(led.bytes_total),
                "bytesPerStep": int(per_step),
                "collectiveOps": {op: int(b)
                                  for op, b in led.trace_bytes.items()},
                "exchangeSeconds": round(led.exchange_s, 6),
                "exchangeFrac": (None if led.exchange_frac is None
                                 else round(led.exchange_frac, 4)),
                "imbalance": (None if led.imbalance is None
                              else round(led.imbalance, 3)),
                "straggler": _straggler(window, warn_at),
                "windowTicks": len(window),
                "perShard": per_shard,
                "updatedAt": led.updated_at,
            }
        return {"programs": programs, "linkGbps": link_gbps(),
                "warnAt": warn_at}

    # -- bench guard helpers -------------------------------------------------
    def listener_cost_s(self, iters: int = 5000) -> float:
        """Unit cost of one registered-program :meth:`on_dispatch` pass
        (min of 3 tight-loop rounds against a scratch ledger — the
        EXPENSIVE path: metrics ticks included, trace spans no-op'd by
        zero bytes... so a one-op byte model is installed to price the
        counter replay too). The ``shard_obs_overhead_frac`` bench guard
        multiplies this by the dispatch census."""
        probe = "shard_obs_overhead_probe"
        self.program_meta(probe, shards=2, steps_per_dispatch=1)
        with self._lock:
            self._programs[probe].trace_bytes = {"probe": 1024.0}
        best = float("inf")
        try:
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    self.on_dispatch(probe, 1e-6)
                best = min(best, time.perf_counter() - t0)
        finally:
            self.reset_program(probe)
        return best / iters

    def reset_program(self, program: str) -> None:
        """Drop one program's ledger and gauge children (tests, the
        overhead probe)."""
        with self._lock:
            led = self._programs.pop(program, None)
        if led is None:
            return
        EXCHANGE_FRAC.remove(program=program)
        SHARD_SKEW.remove(program=program)
        for d in range(len(led.loads) if led.loads else 0):
            SHARD_LOAD.remove(program=program, shard=str(d))

    def reset(self) -> None:
        """Drop every ledger (tests)."""
        with self._lock:
            names = list(self._programs)
        for name in names:
            self.reset_program(name)
        with self._lock:
            self.dispatch_events = 0


#: The process singleton every call site reports into, wired into the
#: profiled-dispatch path at import (utils/http.py, the trainers, and
#: the CLI all import this module, so any process that runs a sharded
#: program has the listener installed).
OBSERVATORY = ShardObservatory()
device_obs.add_dispatch_listener(OBSERVATORY.on_dispatch)


def collective_traced(op: str, nbytes: float) -> None:
    """Module-level convenience for the ops-layer call sites."""
    OBSERVATORY.collective_traced(op, nbytes)


def diagnose_shards_doc(doc: dict | None) -> list[dict]:
    """SHARD-STRAGGLER findings from a fetched ``/debug/shards``
    document (``pio doctor``'s client-side judge, same finding shape as
    obs.fleet.diagnose). None / empty docs judge clean — an unreachable
    or 404 surface is not a straggler."""
    findings: list[dict] = []
    if not isinstance(doc, dict):
        return findings
    warn_at = doc.get("warnAt", shard_imbalance_warn())
    for name, prog in sorted((doc.get("programs") or {}).items()):
        st = prog.get("straggler") if isinstance(prog, dict) else None
        if not st:
            continue
        kind = prog.get("loadKind") or "load"
        findings.append({
            "severity": "warn",
            "subject": f"program {name}",
            "detail": (
                f"SHARD-STRAGGLER: shard {st.get('shard')} has carried "
                f"{st.get('ratio'):.2f}x the median {kind} for "
                f"{st.get('ticks')} consecutive history ticks (threshold "
                f"{warn_at:g}x, PIO_SHARD_IMBALANCE_WARN) — every "
                "collective waits on that shard; re-index ids toward a "
                "uniform spread or change the shard count"),
        })
    return findings
