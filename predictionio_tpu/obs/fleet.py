"""Metrics federation: one merged view over a fleet of processes.

The per-process pillars (metrics, traces, device profiling) each answer
questions about ONE process; a ``pio deploy --replicas N`` topology plus
an event server is several. This module scrapes every member's
``GET /metrics`` (Prometheus text format — our own exposition, but any
conformant one parses) and ``GET /``, and merges the families into a
single fleet exposition served by the gateway at ``GET /metrics/fleet``:

  * every sample gains an ``instance`` label (the member's ``host:port``,
    or its role name for the local process); a family that already
    carries an ``instance`` label has it relabelled to
    ``exported_instance`` — the standard Prometheus federation collision
    rule;
  * **counters** additionally emit a fleet-summed series per remaining
    label set under ``instance="fleet"`` (query totals across replicas);
  * **gauges** stay strictly per-instance (summing two replicas' breaker
    flags or HBM gauges would manufacture a number no process reports);
  * **histograms** bucket-merge into an ``instance="fleet"`` series only
    when every member's ``le`` ladder for that label set is identical —
    cumulative buckets sum correctly then, and silently merging
    misaligned ladders would corrupt every fleet quantile;
  * members that fail to answer within the scrape timeout are omitted
    (their absence shows in ``pio_fleet_instances{state="down"}``) —
    a dead replica must not stall or sink the fleet scrape.

Note for the in-process ``--replicas N`` topology: the gateway and its
replicas share one process-wide registry, so each replica's scrape
returns the same process text and fleet sums count it once per member.
The per-instance series are still the point there (the ``server`` label
separates replica traffic); the sums become meaningful the moment
replicas run as their own processes (``Gateway.add_replica`` at remote
ports), which is the deployment this layer exists for.

No imports from serve/ — the gateway supplies targets; this module only
scrapes, parses, merges, and (for ``pio doctor``) diagnoses.
"""

from __future__ import annotations

import http.client
import json
import re
import threading
import time
from dataclasses import dataclass, field

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

__all__ = [
    "FleetTarget",
    "collect",
    "diagnose",
    "fetch_json",
    "merge_expositions",
    "parse_exposition",
    "post_json",
]

_SCRAPES = REGISTRY.counter(
    "pio_fleet_scrapes_total",
    "Per-member federation scrape outcomes",
    labels=("result",),
)
_SCRAPE_SECONDS = REGISTRY.histogram(
    "pio_fleet_scrape_seconds",
    "Wall seconds for one whole-fleet federation collect (all members, "
    "concurrent)",
)
_INSTANCES = REGISTRY.gauge(
    "pio_fleet_instances",
    "Fleet members by reachability after the last collect",
    labels=("state",),
)


# -- exposition parsing -------------------------------------------------------

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SUFFIXES = ("_bucket", "_sum", "_count", "_total")


def _unescape(v: str) -> str:
    return (v.replace("\\\\", "\x00").replace('\\"', '"')
            .replace("\\n", "\n").replace("\x00", "\\"))


@dataclass
class Family:
    name: str
    kind: str = "untyped"
    help: str = ""
    #: (sample metric name, labels, value) — the sample name keeps its
    #: _bucket/_sum/_count suffix
    samples: list = field(default_factory=list)


def parse_exposition(text: str) -> dict[str, Family]:
    """Prometheus text format 0.0.4 → families by name. Tolerant: lines
    it can't parse are skipped (a fleet scrape must survive one member's
    odd line), samples before any TYPE get an untyped family keyed by
    their base name."""
    families: dict[str, Family] = {}
    current: Family | None = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                current = families.setdefault(parts[2], Family(parts[2]))
                current.kind = parts[3].strip() if len(parts) > 3 else \
                    "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam = families.setdefault(parts[2], Family(parts[2]))
                fam.help = parts[3] if len(parts) > 3 else ""
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                continue
            name = line[:brace]
            labels = {k: _unescape(v)
                      for k, v in _LABEL_RE.findall(line[brace + 1:close])}
            rest = line[close + 1:].strip()
        else:
            bits = line.split()
            if len(bits) < 2:
                continue
            name, rest = bits[0], " ".join(bits[1:])
            labels = {}
        try:
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        fam = current
        if fam is None or not _belongs(name, fam.name):
            base = _base_name(name, families)
            fam = families.setdefault(base, Family(base))
        fam.samples.append((name, labels, value))
    return families


def _belongs(sample_name: str, family: str) -> bool:
    return sample_name == family or (
        sample_name.startswith(family)
        and sample_name[len(family):] in _SUFFIXES)


def _base_name(sample_name: str, families: dict) -> str:
    for sfx in _SUFFIXES:
        if sample_name.endswith(sfx) and sample_name[: -len(sfx)] in families:
            return sample_name[: -len(sfx)]
    return sample_name


# -- merge --------------------------------------------------------------------

def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _relabel(labels: dict[str, str], instance: str) -> dict[str, str]:
    out = dict(labels)
    if "instance" in out:  # relabel-on-collision, never clobber
        out["exported_instance"] = out.pop("instance")
    out["instance"] = instance
    return out


def _groupkey(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _merge_histogram_fleet(per_instance: list[tuple[str, Family]],
                           lines: list[str], family: str) -> None:
    """Fleet-summed histogram series, emitted only for label sets whose
    ``le`` ladder is identical across every contributing member."""
    groups: dict[tuple, dict] = {}
    for instance, fam in per_instance:
        for name, labels, value in fam.samples:
            suffix = name[len(family):]
            base = {k: v for k, v in labels.items() if k != "le"}
            g = groups.setdefault(_groupkey(base), {
                "labels": base, "buckets": {}, "ladders": [],
                "sum": 0.0, "count": 0.0, "seen": set()})
            if suffix == "_bucket":
                le = labels.get("le", "")
                g["buckets"][le] = g["buckets"].get(le, 0.0) + value
                g["seen"].add(instance)
                g.setdefault("ladder_by_instance", {}).setdefault(
                    instance, []).append(le)
            elif suffix == "_sum":
                g["sum"] += value
            elif suffix == "_count":
                g["count"] += value
    for key in sorted(groups):
        g = groups[key]
        ladders = {tuple(v) for v in
                   g.get("ladder_by_instance", {}).values()}
        if len(ladders) != 1:
            continue  # misaligned le sets: per-instance series only
        labels = _relabel(g["labels"], "fleet")
        (ladder,) = ladders
        for le in ladder:
            le_labels = dict(labels)
            le_labels["le"] = le
            lines.append(f"{family}_bucket{_fmt_labels(le_labels)} "
                         f"{_fmt_value(g['buckets'][le])}")
        lines.append(f"{family}_sum{_fmt_labels(labels)} "
                     f"{_fmt_value(g['sum'])}")
        lines.append(f"{family}_count{_fmt_labels(labels)} "
                     f"{_fmt_value(g['count'])}")


def merge_expositions(per_instance: list[tuple[str, str]]) -> str:
    """Merge (instance_name, exposition_text) pairs into one fleet
    exposition (see the module docstring for the per-kind rules)."""
    parsed = [(inst, parse_exposition(text)) for inst, text in per_instance]
    names = sorted({name for _, fams in parsed for name in fams})
    lines: list[str] = []
    for family in names:
        members = [(inst, fams[family]) for inst, fams in parsed
                   if family in fams]
        kind = next((f.kind for _, f in members if f.kind != "untyped"),
                    "untyped")
        help_text = next((f.help for _, f in members if f.help), "")
        if help_text:
            lines.append(f"# HELP {family} {help_text}")
        lines.append(f"# TYPE {family} {kind}")
        # per-instance samples, instance-labelled, in SOURCE order — a
        # lexical re-sort would put le="+Inf" before le="0.1" and break
        # parsers that expect ascending histogram buckets
        for instance, fam in members:
            for name, labels, value in fam.samples:
                relabelled = _relabel(labels, instance)
                lines.append(f"{name}{_fmt_labels(relabelled)} "
                             f"{_fmt_value(value)}")
        # fleet aggregates
        if kind == "counter":
            sums: dict[tuple, tuple[dict, float, str]] = {}
            for instance, fam in members:
                for name, labels, value in fam.samples:
                    key = (name, _groupkey(labels))
                    prev = sums.get(key)
                    sums[key] = (labels, (prev[1] if prev else 0.0) + value,
                                 name)
            for key in sorted(sums, key=str):
                labels, total, name = sums[key]
                lines.append(f"{name}{_fmt_labels(_relabel(labels, 'fleet'))}"
                             f" {_fmt_value(total)}")
        elif kind == "histogram":
            _merge_histogram_fleet(members, lines, family)
    return "\n".join(lines) + "\n"


# -- scraping -----------------------------------------------------------------

@dataclass
class FleetTarget:
    """One fleet member. ``registry`` set = read the local process
    registry directly (the gateway itself); else scrape host:port.
    ``status_only`` skips the /metrics fetch (consumers that want just
    the concurrent bounded status sweep — the dashboard fleet panel);
    status-only members are naturally absent from the federated merge."""

    instance: str
    host: str = ""
    port: int = 0
    role: str = "replica"
    registry: MetricsRegistry | None = None
    status_only: bool = False


def fetch_json(url: str, timeout: float = 10.0):
    """GET ``url`` → parsed JSON, or None on HTTP error (body drained so
    keep-alive connections stay usable), unreachable host, or a non-JSON
    body. The one fail-soft JSON-GET used by ``pio doctor``,
    ``pio status --fleet``, and the dashboard panels — the surfaces it
    reads are each optional, so "missing" is an answer, not a crash."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        e.read()
        return None
    except (urllib.error.URLError, OSError, ValueError):
        return None


def post_json(url: str, body: dict, timeout: float = 10.0
              ) -> tuple[int, dict] | None:
    """POST ``body`` as JSON → (status, parsed body) — HTTP error
    statuses still return their parsed body (a remediation endpoint
    answers 501/502 WITH a structured result the doctor must report).
    None only when the host is unreachable or answers non-JSON."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw, status = resp.read(), resp.status
        try:
            doc = json.loads(raw or b"{}")
        except ValueError:
            doc = {}  # a 2xx with a non-JSON body still ANSWERED
        return status, doc if isinstance(doc, dict) else {}
    except urllib.error.HTTPError as e:
        try:
            doc = json.loads(e.read() or b"{}")
        except ValueError:
            # the server ANSWERED, just not with JSON (plain-HTML 404,
            # intermediary error page): keep the status visible —
            # None is reserved for hosts that never answered
            doc = {}
        return e.code, doc if isinstance(doc, dict) else {}
    except (urllib.error.URLError, OSError, ValueError):
        return None


def _http_get(host: str, port: int, path: str,
              timeout: float) -> tuple[int, bytes]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def scrape_member(target: FleetTarget, timeout: float = 2.0) -> dict:
    """One member's /metrics text + / status JSON (fail-soft: ``ok``
    False with the error string when unreachable)."""
    out: dict = {"instance": target.instance, "role": target.role,
                 "ok": False, "metricsText": None, "status": None,
                 "error": None}
    if target.registry is not None:
        out["ok"] = True
        out["metricsText"] = target.registry.expose()
        return out
    try:
        if not target.status_only:
            code, body = _http_get(target.host, target.port, "/metrics",
                                   timeout)
            if code != 200:
                raise OSError(f"/metrics answered HTTP {code}")
            out["metricsText"] = body.decode("utf-8", "replace")
        try:
            scode, sbody = _http_get(target.host, target.port, "/", timeout)
            if scode == 200:
                status = json.loads(sbody or b"{}")
                out["status"] = status if isinstance(status, dict) else None
        except (OSError, ValueError):
            if target.status_only:
                raise  # the status IS the contract then
            # else: status is garnish; the scrape is the contract
        out["ok"] = True
    except (OSError, ValueError) as e:
        out["error"] = str(e)
    return out


def collect(targets: list[FleetTarget], timeout: float = 2.0) -> list[dict]:
    """Scrape every member concurrently: one straggler costs the fleet
    scrape a bounded wait (scrape_member makes up to TWO sequential
    GETs — /metrics then / — each budgeted ``timeout``, so the join
    waits for both), never ``N *`` anything."""
    t0 = time.perf_counter()
    results: list[dict | None] = [None] * len(targets)

    def one(i: int, t: FleetTarget) -> None:
        results[i] = scrape_member(t, timeout)

    threads = [threading.Thread(target=one, args=(i, t), daemon=True)
               for i, t in enumerate(targets)]
    for th in threads:
        th.start()
    deadline = time.monotonic() + 2.0 * timeout + 0.5
    for th in threads:
        th.join(max(deadline - time.monotonic(), 0.0))
    out = [r if r is not None else
           {"instance": t.instance, "role": t.role, "ok": False,
            "metricsText": None, "status": None, "error": "scrape hung"}
           for r, t in zip(results, targets)]
    up = sum(1 for r in out if r["ok"])
    _INSTANCES.set(up, state="up")
    _INSTANCES.set(len(out) - up, state="down")
    for r in out:
        _SCRAPES.inc(result="ok" if r["ok"] else "error")
    _SCRAPE_SECONDS.observe(time.perf_counter() - t0)
    return out


def federated_exposition(results: list[dict]) -> str:
    """Merged fleet text from collect() results (dead members omitted)."""
    return merge_expositions([
        (r["instance"], r["metricsText"]) for r in results
        if r["ok"] and r["metricsText"]])


# -- triage (`pio doctor`) ----------------------------------------------------

_SEVERITY_RANK = {"critical": 0, "warn": 1, "info": 2}


def _finding(severity: str, subject: str, detail: str,
             action: dict | None = None) -> dict:
    doc = {"severity": severity, "subject": subject, "detail": detail}
    if action is not None:
        # the machine-actionable half of a finding: what `pio doctor
        # --fix` would POST to the gateway's /fleet/actions
        doc["action"] = action
    return doc


def diagnose(gateway_status: dict | None,
             members: list[dict],
             slo_state: dict | None,
             traces: list[dict] | None = None,
             quality: dict | None = None) -> list[dict]:
    """Rank what's wrong, most actionable first. Pure function of the
    fetched surfaces so the heuristics unit-test without a deploy:

      * breached SLOs (and fast-window burns over threshold);
      * unreachable / down / suspect replicas and open breakers;
      * per-replica outliers vs the fleet median p99 and error ratio;
      * tripped device routes and stale models;
      * prediction-quality judgment (``quality`` = a ``/debug/quality``
        doc): QUALITY-DRIFT / QUALITY-REGRESSION naming the engine
        instance and its model age, plus a starving feedback loop. A
        breached ``model_staleness`` SLO FOLDS INTO the quality finding
        for one ranked story — "the model is old AND its answers
        degraded" is one problem, not two rows;
      * the slowest retained traces, as leads.

    Findings with a mechanical fix carry an ``action`` hint
    (``{"kind", "replica"}``) — the exact payload ``pio doctor --fix``
    POSTs to the gateway's ``/fleet/actions``.
    """
    findings: list[dict] = []
    # -- SLO judgment
    staleness_rows: list[dict] = []
    for slo in (slo_state or {}).get("slos", []):
        burns = slo.get("burnRates") or {}
        fast, slow = burns.get("fast"), burns.get("slow")
        burn_txt = (f"burn {fast if fast is not None else 'n/a'}x fast / "
                    f"{slow if slow is not None else 'n/a'}x slow "
                    f"(threshold {slo.get('burnThreshold')}x)")
        if slo.get("breached"):
            row = _finding(
                "critical", f"SLO {slo['name']}",
                f"BREACHED: {burn_txt} — {slo.get('description', '')}")
        elif fast is not None and fast > slo.get("burnThreshold", 14.4):
            row = _finding(
                "warn", f"SLO {slo['name']}",
                f"fast-window burn over threshold: {burn_txt}")
        else:
            continue
        findings.append(row)
        if slo.get("name") == "model_staleness":
            staleness_rows.append(row)
    # -- prediction quality (obs/quality.py findings, staleness folded)
    from predictionio_tpu.obs import quality as quality_mod

    quality_rows = quality_mod.quality_findings(quality)
    fold_target = next(
        (row for row in quality_rows
         if row["subject"].startswith("QUALITY-")), None)
    if fold_target is not None and staleness_rows:
        # one ranked story: the model-related quality row carries the
        # staleness burn and the standalone SLO row leaves the report.
        # The folded row keeps the WORST severity of the two — folding
        # a critical breach into a warn-band drift must not downgrade
        # the doctor's exit code
        stale = staleness_rows[0]
        if _SEVERITY_RANK.get(stale["severity"], 3) < \
                _SEVERITY_RANK.get(fold_target["severity"], 3):
            fold_target["severity"] = stale["severity"]
        fold_target["detail"] += (f"; meanwhile {stale['subject']} "
                                  f"{stale['detail']}")
        for row in staleness_rows:
            findings.remove(row)
    findings.extend(quality_rows)
    # -- replica state from the gateway's view
    breakers_open = []
    for rep in (gateway_status or {}).get("replicas", []):
        rid = rep.get("replica", "?")
        if rep.get("state") == "down":
            findings.append(_finding(
                "critical", f"replica {rid}",
                f"DOWN after {rep.get('consecutiveFailures', '?')} failed "
                "health probes — routing skips it",
                action={"kind": "restart_replica", "replica": rid}))
        elif rep.get("state") == "suspect":
            findings.append(_finding(
                "warn", f"replica {rid}",
                "suspect (failed its last health probe; still routable)"))
        if rep.get("breaker") == "open":
            breakers_open.append(rid)
            findings.append(_finding(
                "critical", f"replica {rid}",
                "circuit breaker OPEN — transport failures shed its "
                "traffic to the rest of the fleet",
                action={"kind": "reset_breaker", "replica": rid}))
    # -- per-member statuses: outliers vs the fleet
    statuses = {m["instance"]: m.get("status") for m in members
                if m.get("role") == "replica"}
    for m in members:
        if not m["ok"]:
            findings.append(_finding(
                "critical", f"{m['role']} {m['instance']}",
                f"unreachable: {m.get('error')}"))
    p99s = {inst: s["p99ServingSec"] for inst, s in statuses.items()
            if isinstance(s, dict) and s.get("p99ServingSec")}
    if len(p99s) >= 2:
        ordered = sorted(p99s.values())
        mid = len(ordered) // 2
        median = (ordered[mid] if len(ordered) % 2
                  else (ordered[mid - 1] + ordered[mid]) / 2.0)
        if median > 0:
            for inst, p99 in sorted(p99s.items()):
                if p99 >= 2.0 * median:
                    findings.append(_finding(
                        "warn", f"replica {inst}",
                        f"p99 {p99 * 1e3:.1f} ms is "
                        f"{p99 / median:.1f}x the fleet median "
                        f"({median * 1e3:.1f} ms)"))
    for inst, s in sorted(statuses.items()):
        if not isinstance(s, dict):
            continue
        reqs = s.get("requestCount") or 0
        errs = s.get("errorCount") or 0
        if reqs >= 20 and errs / reqs > 0.05:
            findings.append(_finding(
                "warn", f"replica {inst}",
                f"error ratio {errs}/{reqs} "
                f"({errs / reqs:.1%}) over the last lifetime window"))
        batching = s.get("batching") or {}
        if batching.get("deviceRouteBreaker") == "open":
            findings.append(_finding(
                "warn", f"replica {inst}",
                "device serving route tripped to host (awaiting a "
                "successful synthetic probe)",
                action={"kind": "reset_device_route", "replica": inst}))
    # -- leads from the trace reservoir (the caller already bounds how
    # many it wants folded in — `pio doctor --traces K`)
    for doc in traces or []:
        findings.append(_finding(
            "info", f"trace {doc.get('traceId', '?')}",
            f"slowest retained: {doc.get('durationMs', 0):.1f} ms, "
            f"{len(doc.get('spans', []))} span(s) — "
            f"`pio trace {doc.get('traceId', '')}`"))
    findings.sort(key=lambda f: _SEVERITY_RANK.get(f["severity"], 3))
    return findings
