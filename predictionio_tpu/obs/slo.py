"""Declarative SLOs with multi-window burn-rate evaluation.

Metrics say what the fleet is doing; history says what it has been
doing; this module renders the *judgment*: is the deploy inside its
service-level objectives, and how fast is it spending error budget. The
formulation is the SRE multi-window burn-rate alert that the ads-infra
continuous-training loop (PAPERS.md) runs in production: for each SLO,
the burn rate is

    burn = (observed bad fraction over a window) / (1 - target)

so burn 1.0 spends the error budget exactly at the sustainable rate and
burn 14.4 exhausts a 30-day budget in ~2 days. An SLO is **breached**
when BOTH the fast window (``PIO_SLO_FAST_WINDOW_S``, default 300 s)
and the slow window (``PIO_SLO_SLOW_WINDOW_S``, default 3600 s) burn
above the SLO's threshold (default 14.4) — fast-only spikes are noise,
slow-only burns are old news; both together mean "paging-worthy now"
(Google SRE workbook ch. 5).

Windows are evaluated over the obs/history.py rings on every sample
tick, and judged state lands in three places: the
``pio_slo_burn_rate{slo,window}`` / ``pio_slo_breached{slo}`` gauges,
``GET /debug/slo`` (mounted on every server; 404 when history is off),
and the dashboard banner. ``pio doctor`` folds the same state into its
triage report.

Built-in SLOs (each retunable by env, replaceable wholesale by
``PIO_SLO_CONFIG`` — inline JSON or ``@path`` to a file):

  * ``query_availability`` — ratio: gateway failure outcomes over
    gateway traffic (falls back to replica query errors over query
    traffic in a gateway-less deploy); target
    ``PIO_SLO_AVAILABILITY_TARGET`` (0.999).
  * ``query_latency_p99`` — threshold: the windowed serving p99 must
    stay under ``PIO_SLO_QUERY_P99_MS`` (250 ms); target 0.99 of
    intervals.
  * ``ingest_success`` — ratio: ingest error rate over all ingest
    attempts; target ``PIO_SLO_INGEST_TARGET`` (0.999).
  * ``model_staleness`` — threshold: the serving model's age must stay
    under ``PIO_SLO_MODEL_MAX_AGE_S`` (86400 s); target 0.99.
  * ``online_quality`` — threshold (inverted, ``bad_below``): the
    windowed feedback-joined online hit rate (obs/quality.py) must stay
    ABOVE ``PIO_SLO_ONLINE_HIT_RATE_MIN`` (0.05); intervals with no
    joined feedback are no evidence, not a breach; target 0.99.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass

from predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

__all__ = [
    "SLO",
    "SLOEngine",
    "attach",
    "default_slos",
    "engine",
    "ratio_burn",
    "threshold_burn",
]

_BURN_RATE = REGISTRY.gauge(
    "pio_slo_burn_rate",
    "Error-budget burn rate per SLO and window (1.0 = spending budget "
    "exactly at the sustainable rate)",
    labels=("slo", "window"),
)
_BREACHED = REGISTRY.gauge(
    "pio_slo_breached",
    "1 while the SLO's fast AND slow burn rates both exceed its "
    "threshold",
    labels=("slo",),
)


@dataclass
class SLO:
    """One objective. ``kind="ratio"`` judges a bad-event rate against a
    traffic rate (series are per-second rates from the history rings);
    ``kind="threshold"`` judges a value series against a bound, where a
    sample over the bound is one bad interval."""

    name: str
    description: str
    kind: str  # "ratio" | "threshold"
    target: float  # good-fraction objective, e.g. 0.999
    #: ratio: series names (history rings)
    bad: str = ""
    base: str = ""
    #: True when ``base`` already counts bad events (gateway_qps counts
    #: failures); False adds bad to base for the denominator
    base_includes_bad: bool = True
    fallback_bad: str = ""
    fallback_base: str = ""
    fallback_base_includes_bad: bool = True
    #: threshold: value series + bound. ``bad_below`` inverts the
    #: direction for higher-is-better series (online hit rate): a sample
    #: UNDER the bound is the bad interval then
    series: str = ""
    bound: float = 0.0
    bad_below: bool = False
    burn_threshold: float = 14.4

    def __post_init__(self):
        if self.kind not in ("ratio", "threshold"):
            raise ValueError(f"SLO {self.name}: unknown kind {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO {self.name}: target must be in (0, 1)")


def ratio_burn(bad_sum: float, total_sum: float,
               target: float) -> float | None:
    """Burn rate of a ratio SLO over one window: bad fraction divided by
    the error budget (1 - target). None without traffic — no traffic is
    no evidence, not a breach."""
    if total_sum <= 0:
        return None
    return (bad_sum / total_sum) / (1.0 - target)


def threshold_burn(values: list[float], bound: float,
                   target: float, bad_below: bool = False) -> float | None:
    """Burn rate of a threshold SLO over one window: the fraction of
    samples beyond the bound (under it with ``bad_below``), divided by
    the budgeted fraction."""
    if not values:
        return None
    bad = sum(1 for v in values if (v < bound if bad_below else v > bound))
    return (bad / len(values)) / (1.0 - target)


from predictionio_tpu.utils.env import env_float as _env_float  # noqa: E402


def fast_window_s() -> float:
    return _env_float("PIO_SLO_FAST_WINDOW_S", 300.0)


def slow_window_s() -> float:
    return _env_float("PIO_SLO_SLOW_WINDOW_S", 3600.0)


def default_slos() -> list[SLO]:
    return [
        SLO(
            name="query_availability",
            description="queries answered without a gateway-side failure "
                        "(replica error rate in a gateway-less deploy)",
            kind="ratio",
            target=_env_float("PIO_SLO_AVAILABILITY_TARGET", 0.999),
            bad="gateway_failure_rate", base="gateway_qps",
            base_includes_bad=True,
            fallback_bad="query_error_rate", fallback_base="query_qps",
            fallback_base_includes_bad=True,
        ),
        SLO(
            name="query_latency_p99",
            description="windowed serving p99 under the latency bound",
            kind="threshold",
            target=0.99,
            series="query_p99_ms",
            bound=_env_float("PIO_SLO_QUERY_P99_MS", 250.0),
        ),
        SLO(
            name="ingest_success",
            description="events committed without an ingest error",
            kind="ratio",
            target=_env_float("PIO_SLO_INGEST_TARGET", 0.999),
            bad="ingest_error_rate", base="ingest_events_per_sec",
            base_includes_bad=False,
        ),
        SLO(
            name="bulk_ingest_success",
            description="bulk-path events (batch + ndjson) committed "
                        "without a store-side failure",
            kind="ratio",
            target=_env_float("PIO_SLO_BULK_INGEST_TARGET", 0.999),
            bad="bulk_ingest_error_rate",
            base="bulk_ingest_events_per_sec",
            base_includes_bad=False,
        ),
        SLO(
            name="model_staleness",
            description="serving model age under the freshness bound",
            kind="threshold",
            target=0.99,
            series="model_age_seconds",
            bound=_env_float("PIO_SLO_MODEL_MAX_AGE_S", 86400.0),
        ),
        SLO(
            name="online_quality",
            description="feedback-joined online hit rate above the "
                        "quality floor (no joined feedback = no "
                        "evidence, not a breach)",
            kind="threshold",
            target=0.99,
            series="online_hit_rate",
            bound=_env_float("PIO_SLO_ONLINE_HIT_RATE_MIN", 0.05),
            bad_below=True,
        ),
    ]


def _configured_slos() -> list[SLO]:
    """``PIO_SLO_CONFIG`` replaces the default set: inline JSON list or
    ``@path`` to a JSON file; entries are SLO fields by name. A broken
    config falls back to the defaults with a warning — a typo must not
    silently disable judgment."""
    raw = os.environ.get("PIO_SLO_CONFIG", "").strip()
    if not raw:
        return default_slos()
    try:
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as f:
                raw = f.read()
        entries = json.loads(raw)
        if not isinstance(entries, list):
            raise ValueError("PIO_SLO_CONFIG must be a JSON list")
        return [SLO(**e) for e in entries]
    except (OSError, ValueError, TypeError) as e:
        logger.warning("bad PIO_SLO_CONFIG (%s); using default SLOs", e)
        return default_slos()


class SLOEngine:
    """Evaluates every SLO's fast/slow windows over a HistorySampler's
    rings; holds the last judged state for /debug/slo."""

    def __init__(self, slos: list[SLO] | None = None):
        self.slos = _configured_slos() if slos is None else slos
        self._lock = threading.Lock()
        self._state: list[dict] = []
        self._evaluated_at: float | None = None

    # -- window reads -------------------------------------------------------
    @staticmethod
    def _ratio_window(sampler, slo: SLO, seconds: float, now_ts: float,
                      fallback: bool) -> float | None:
        bad_name = slo.fallback_bad if fallback else slo.bad
        base_name = slo.fallback_base if fallback else slo.base
        includes = (slo.fallback_base_includes_bad if fallback
                    else slo.base_includes_bad)
        since = now_ts - seconds
        bad_pts = dict(sampler.points(bad_name, since=since))
        base_pts = dict(sampler.points(base_name, since=since))
        bad_sum = total_sum = 0.0
        seen = False
        for t, base in base_pts.items():
            if base is None:
                continue
            seen = True
            bad = bad_pts.get(t) or 0.0
            bad_sum += bad
            total_sum += base if includes else base + bad
        if not seen:
            return None
        return ratio_burn(bad_sum, total_sum, slo.target)

    def _burn(self, sampler, slo: SLO, seconds: float,
              now_ts: float) -> float | None:
        if slo.kind == "threshold":
            return threshold_burn(
                sampler.window_values(slo.series, seconds, now_ts),
                slo.bound, slo.target, slo.bad_below)
        burn = self._ratio_window(sampler, slo, seconds, now_ts,
                                  fallback=False)
        if burn is None and slo.fallback_base:
            burn = self._ratio_window(sampler, slo, seconds, now_ts,
                                      fallback=True)
        return burn

    # -- the tick -----------------------------------------------------------
    def evaluate(self, sampler, now_ts: float | None = None) -> list[dict]:
        now_ts = time.time() if now_ts is None else now_ts
        fast_s, slow_s = fast_window_s(), slow_window_s()
        state: list[dict] = []
        for slo in self.slos:
            fast = self._burn(sampler, slo, fast_s, now_ts)
            slow = self._burn(sampler, slo, slow_s, now_ts)
            breached = (fast is not None and slow is not None
                        and fast > slo.burn_threshold
                        and slow > slo.burn_threshold)
            # no-data windows write 0, not "keep the last value": a
            # frozen 310x burn after an outage drains to zero traffic
            # would page forever on the gauge while the JSON surface
            # says null (the registry has no per-child remove)
            _BURN_RATE.set(fast if fast is not None else 0.0,
                           slo=slo.name, window="fast")
            _BURN_RATE.set(slow if slow is not None else 0.0,
                           slo=slo.name, window="slow")
            _BREACHED.set(1.0 if breached else 0.0, slo=slo.name)
            doc = {
                "name": slo.name,
                "description": slo.description,
                "kind": slo.kind,
                "target": slo.target,
                "burnThreshold": slo.burn_threshold,
                "burnRates": {
                    "fast": None if fast is None else round(fast, 4),
                    "slow": None if slow is None else round(slow, 4),
                },
                "windows": {"fastS": fast_s, "slowS": slow_s},
                "breached": breached,
            }
            if slo.kind == "threshold":
                doc["series"] = slo.series
                doc["bound"] = slo.bound
                doc["badBelow"] = slo.bad_below
                latest = sampler.window_values(
                    slo.series, fast_s, now_ts)
                doc["latest"] = latest[-1] if latest else None
            state.append(doc)
        with self._lock:
            self._state = state
            self._evaluated_at = now_ts
        return state

    def state(self) -> dict:
        with self._lock:
            return {
                "evaluatedAt": self._evaluated_at,
                "fastWindowS": fast_window_s(),
                "slowWindowS": slow_window_s(),
                "slos": list(self._state),
                "breached": [s["name"] for s in self._state
                             if s["breached"]],
            }

    def config(self) -> list[dict]:
        return [asdict(s) for s in self.slos]


#: process-global engine, created when history attaches it
_ENGINE: SLOEngine | None = None
_ENGINE_LOCK = threading.Lock()


def engine() -> SLOEngine | None:
    return _ENGINE


def attach(sampler) -> SLOEngine:
    """Wire the process SLO engine onto a history sampler's tick (called
    by history.ensure_started). Idempotent per process."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = SLOEngine()
        eng = _ENGINE

    def on_tick(s, t):
        eng.evaluate(s, t)

    # one listener per sampler (history.reset() builds a fresh sampler)
    if not any(getattr(f, "_slo_listener", False)
               for f in sampler.listeners):
        on_tick._slo_listener = True
        sampler.listeners.append(on_tick)
    return eng


def reset() -> None:
    """Drop the process engine (tests retuning SLO env knobs)."""
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = None
