"""Flight recorder: freeze the process's observability state on death.

PR 12's run ledger exists because BENCH_r06 burned two 7200 s walls with
nothing recording what the process was doing when it stalled; this
module closes the same gap for *crashes*. Every pillar keeps bounded
in-memory state (log ring, history rings, trace reservoir, HBM arenas,
SLO judgments, run ledger) — all of it gone the instant the process
dies, which is exactly when an operator needs it. The flight recorder
snapshots them into one on-disk *bundle* under ``PIO_POSTMORTEM_DIR``:

  * on unhandled exceptions (``sys.excepthook`` + ``threading.excepthook``,
    chained onto whatever was installed before);
  * on SIGTERM before graceful stop (``pio deploy`` wires it into its
    signal handler);
  * on demand: ``POST /debug/postmortem`` and ``pio postmortem``;
  * automatically when ``pio doctor --fix`` hits a critical finding.

Bundle discipline mirrors the checkpoint/heartbeat atomicity rules:
each bundle is written into a dot-prefixed temp directory and
``os.rename``-d into place, so a process SIGKILLed mid-capture leaves
only an invisible temp dir, never a torn bundle readers would trust.
Bundles are size-bounded per section, newest-``PIO_POSTMORTEM_KEEP``
retained (oldest pruned, the run-ledger pattern), and every section is
passed through :func:`obs.logs.redact` / :func:`obs.logs.redact_env`
before it touches disk. ``pio postmortem --list/--show`` renders them.
"""

from __future__ import annotations

import faulthandler
import json
import logging
import os
import sys
import threading
import time
import traceback as _tb
from pathlib import Path

from predictionio_tpu.obs import logs as _logs

logger = logging.getLogger(__name__)

__all__ = [
    "bundles_dir",
    "capture_bundle",
    "install",
    "list_bundles",
    "load_bundle",
    "postmortem_enabled",
]

#: Per-section byte cap: a runaway section is truncated to a stub, not
#: allowed to fill the disk the operator is about to debug on.
_SECTION_MAX_BYTES = 4 * 2**20

#: Automatic (hook-driven) captures are rate-limited so a crash loop
#: can't churn the retention window; explicit captures bypass this.
_AUTO_MIN_INTERVAL_S = 30.0
_last_auto = 0.0
_capture_lock = threading.Lock()


def postmortem_enabled() -> bool:
    """``PIO_POSTMORTEM`` (default on; ``0``/``off`` disables capture
    and 404s ``POST /debug/postmortem``)."""
    return os.environ.get("PIO_POSTMORTEM", "1").lower() not in (
        "0", "off", "false", "no")


def bundles_dir() -> Path:
    """``PIO_POSTMORTEM_DIR``, else ``$PIO_TPU_HOME/postmortem``, else
    ``~/.predictionio_tpu/postmortem`` (the runs-dir convention)."""
    env = os.environ.get("PIO_POSTMORTEM_DIR")
    if env:
        return Path(env)
    home = os.environ.get("PIO_TPU_HOME")
    base = Path(home) if home else Path.home() / ".predictionio_tpu"
    return base / "postmortem"


def _keep() -> int:
    """``PIO_POSTMORTEM_KEEP`` newest bundles retained (default 8)."""
    try:
        return max(int(os.environ.get("PIO_POSTMORTEM_KEEP", "8")), 1)
    except ValueError:
        return 8


# ---------------------------------------------------------------------------
# Section collectors — each independent and fail-soft: a broken pillar
# costs its own section, never the bundle.
# ---------------------------------------------------------------------------


def _section_logs() -> dict:
    return _logs.to_json()


def _section_history() -> dict | None:
    from predictionio_tpu.obs import history

    sampler = history.get_sampler()
    return sampler.to_json() if sampler is not None else None


def _section_traces() -> dict | None:
    from predictionio_tpu.obs import trace

    if not trace.trace_enabled():
        return None
    return trace.TRACER.traces(limit=16)


def _section_device() -> dict:
    from predictionio_tpu.obs import device

    return device.hbm_snapshot()


def _section_slo() -> dict | None:
    from predictionio_tpu.obs import slo

    eng = slo.engine()
    return eng.state() if eng is not None else None


def _section_runs() -> list[dict]:
    from predictionio_tpu.obs import runlog

    return runlog.list_runs(limit=4)


def _write_stacks(path: Path) -> None:
    """faulthandler writes through the OS file descriptor (it is
    async-signal-safe, not io-module aware), so dump to the real file,
    then re-read and redact in place like every other section."""
    with open(path, "w", encoding="utf-8") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
    path.write_text(_logs.redact(path.read_text(encoding="utf-8")),
                    encoding="utf-8")


_SECTIONS = {
    "logs.json": _section_logs,
    "history.json": _section_history,
    "traces.json": _section_traces,
    "device.json": _section_device,
    "slo.json": _section_slo,
    "runs.json": _section_runs,
}


def _dump_section(payload) -> str:
    text = json.dumps(payload, indent=1, default=str)
    if len(text) > _SECTION_MAX_BYTES:
        return json.dumps({"truncated": True, "bytes": len(text)})
    return _logs.redact(text)


def capture_bundle(reason: str, exc: BaseException | None = None,
                   auto: bool = False) -> Path | None:
    """Snapshot every pillar into a new bundle; returns its path, or
    None when disabled, rate-limited (``auto=True`` hooks only), or the
    filesystem refused. Never raises — this runs inside excepthooks."""
    global _last_auto
    if not postmortem_enabled():
        return None
    with _capture_lock:
        now = time.time()
        if auto:
            if now - _last_auto < _AUTO_MIN_INTERVAL_S:
                return None
            _last_auto = now
        try:
            return _capture_locked(reason, exc, now)
        except Exception:
            logger.warning("post-mortem capture failed", exc_info=True)
            return None


def _capture_locked(reason: str, exc: BaseException | None,
                    now: float) -> Path:
    root = bundles_dir()
    root.mkdir(parents=True, exist_ok=True)
    slug = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                   for ch in reason)[:40] or "manual"
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(now))
    name = f"pm-{stamp}-{os.getpid()}-{slug}"
    final = root / name
    if final.exists():  # two captures in the same second
        name += f"-{int((now % 1) * 1000):03d}"
        final = root / name
    # dot-prefixed temp dir: a SIGKILL mid-write leaves an invisible
    # partial, never a torn bundle (list_bundles skips dot-dirs); the
    # rename at the end is the atomic commit, same as checkpoints
    tmp = root / f".tmp-{name}"
    tmp.mkdir(parents=True, exist_ok=True)
    meta: dict = {
        "reason": reason,
        "capturedAt": round(now, 3),
        "pid": os.getpid(),
        "server": _logs.current_server_name(),
        "argv": [_logs.redact(a) for a in sys.argv],
    }
    if exc is not None:
        meta["exception"] = {
            "type": type(exc).__name__,
            "message": _logs.redact(str(exc)),
            "traceback": _logs.redact("".join(_tb.format_exception(
                type(exc), exc, exc.__traceback__))),
        }
    sections_written = []
    for fname, collect in _SECTIONS.items():
        try:
            payload = collect()
        except Exception as e:
            payload = {"error": f"{type(e).__name__}: {e}"}
        if payload is None:
            continue
        (tmp / fname).write_text(_dump_section(payload), encoding="utf-8")
        sections_written.append(fname)
    try:
        _write_stacks(tmp / "stacks.txt")
        sections_written.append("stacks.txt")
    except Exception:
        logger.debug("stack dump failed", exc_info=True)
    (tmp / "env.json").write_text(
        json.dumps(_logs.redact_env(), indent=1), encoding="utf-8")
    sections_written.append("env.json")
    meta["sections"] = sections_written
    (tmp / "meta.json").write_text(json.dumps(meta, indent=1),
                                   encoding="utf-8")
    os.rename(tmp, final)  # the commit point
    _prune(root)
    logger.warning("post-mortem bundle captured: %s (%s)", final, reason)
    return final


def _prune(root: Path) -> None:
    """Newest-K retention over committed bundles, plus sweep of stale
    temp dirs older than an hour (a crashed capture's leavings)."""
    try:
        committed = sorted((p for p in root.iterdir()
                            if p.is_dir() and not p.name.startswith(".")),
                           key=lambda p: p.stat().st_mtime)
        for p in committed[: max(len(committed) - _keep(), 0)]:
            _rmtree(p)
        cutoff = time.time() - 3600
        for p in root.iterdir():
            if (p.is_dir() and p.name.startswith(".tmp-")
                    and p.stat().st_mtime < cutoff):
                _rmtree(p)
    except OSError:
        logger.warning("post-mortem retention prune failed", exc_info=True)


def _rmtree(path: Path) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


# ---------------------------------------------------------------------------
# Reads (pio postmortem --list/--show)
# ---------------------------------------------------------------------------


def list_bundles(root: Path | str | None = None) -> list[dict]:
    """Committed bundles newest first: name, path, capture metadata."""
    root = Path(root) if root else bundles_dir()
    out: list[dict] = []
    try:
        dirs = sorted((p for p in root.iterdir()
                       if p.is_dir() and not p.name.startswith(".")),
                      key=lambda p: p.stat().st_mtime, reverse=True)
    except OSError:
        return []
    for p in dirs:
        meta: dict = {}
        try:
            meta = json.loads((p / "meta.json").read_text(encoding="utf-8"))
        except (OSError, ValueError):
            pass
        out.append({
            "name": p.name,
            "path": str(p),
            "reason": meta.get("reason"),
            "capturedAt": meta.get("capturedAt"),
            "pid": meta.get("pid"),
            "server": meta.get("server"),
            "sections": meta.get("sections", []),
            "sizeBytes": sum(f.stat().st_size for f in p.iterdir()
                             if f.is_file()),
        })
    return out


def load_bundle(name: str, root: Path | str | None = None) -> dict:
    """Every section of one bundle, parsed where JSON. Raises
    FileNotFoundError for an unknown name."""
    root = Path(root) if root else bundles_dir()
    path = root / name
    if not path.is_dir() or name.startswith("."):
        raise FileNotFoundError(f"no post-mortem bundle named {name!r} "
                                f"under {root}")
    doc: dict = {"name": name, "path": str(path)}
    for f in sorted(path.iterdir()):
        if not f.is_file():
            continue
        text = f.read_text(encoding="utf-8")
        if f.suffix == ".json":
            try:
                doc[f.stem] = json.loads(text)
            except ValueError:
                doc[f.stem] = text
        else:
            doc[f.stem] = text
    return doc


# ---------------------------------------------------------------------------
# Crash hooks
# ---------------------------------------------------------------------------

_installed = False
_install_lock = threading.Lock()


def install() -> None:
    """Chain bundle capture onto ``sys.excepthook`` and
    ``threading.excepthook`` (idempotent). The prior hooks still run —
    the crash still prints — capture happens first, while the process
    state is intact."""
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True
    prev_sys = sys.excepthook
    prev_thread = threading.excepthook

    def _sys_hook(exc_type, exc, tb):
        if exc_type not in (KeyboardInterrupt, SystemExit):
            capture_bundle("unhandled-exception", exc, auto=True)
        prev_sys(exc_type, exc, tb)

    def _thread_hook(args):
        if args.exc_type not in (KeyboardInterrupt, SystemExit):
            capture_bundle(
                f"thread-crash-{args.thread.name if args.thread else '?'}",
                args.exc_value, auto=True)
        prev_thread(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _thread_hook
