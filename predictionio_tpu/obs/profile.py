"""On-demand device profiler capture (``POST /debug/profile``).

``pio train --profile DIR`` already wraps a whole train in
``jax.profiler.trace``; production questions arrive differently — a
serving replica is slow NOW and the operator wants a bounded device
trace of live traffic without redeploying. This module is that capture:
a duration-bounded ``jax.profiler`` trace started over HTTP (every
server mounts the route via utils/http.add_metrics_route) or the
``pio profile`` CLI, returning the artifact directory for
TensorBoard's profile plugin / xprof.

Semantics:

  * One capture at a time per process (the profiler is a process-global
    singleton; a second request gets 409).
  * Duration is clamped to [0.05, 60] seconds — the capture thread
    sleeps while the profiler records every other thread's device
    activity, so an unbounded duration would pin an HTTP worker and an
    ever-growing trace buffer.
  * ``PIO_PROFILE=0`` disables the surface entirely; the route then
    404s exactly like a feature that is not there (the same contract as
    ``/debug/traces`` under ``PIO_TRACE=off``).
  * Artifacts land under ``PIO_PROFILE_DIR`` (default
    ``<tmpdir>/pio-profiles``), one timestamped directory per capture.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time

from predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

__all__ = [
    "CaptureBusy",
    "MAX_SECONDS",
    "capture",
    "profile_dir",
    "profiling_enabled",
]

#: Capture outcomes (ok / busy / error) — a quiet failure in a feature
#: operators reach for under incident pressure would be the worst kind.
CAPTURES_TOTAL = REGISTRY.counter(
    "pio_profile_captures_total",
    "On-demand device profiler captures by outcome",
    labels=("outcome",),
)

MAX_SECONDS = 60.0
MIN_SECONDS = 0.05

_capture_lock = threading.Lock()
_capture_seq = 0  # disambiguates captures within one wall-clock second


class CaptureBusy(RuntimeError):
    """A capture is already running in this process."""


def profiling_enabled() -> bool:
    """``PIO_PROFILE`` gate (default on), read at call time like the
    other obs toggles."""
    return os.environ.get("PIO_PROFILE", "1").lower() not in ("0", "off")


def profile_dir() -> str:
    return os.environ.get("PIO_PROFILE_DIR") or os.path.join(
        tempfile.gettempdir(), "pio-profiles")


def _artifact_files(path: str) -> list[str]:
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            out.append(os.path.relpath(os.path.join(root, f), path))
    return sorted(out)


def capture(seconds: float = 1.0) -> dict:
    """Record a ``seconds``-bounded ``jax.profiler`` trace and return
    ``{"artifact": dir, "seconds": s, "files": [...]}``. Raises
    :class:`CaptureBusy` when a capture is already in flight, ValueError
    on a non-finite duration; any profiler failure (e.g. a ``pio train
    --profile`` trace already active in-process) propagates after being
    counted."""
    try:
        seconds = float(seconds)
    except (TypeError, ValueError) as e:
        raise ValueError(f"bad duration: {seconds!r}") from e
    if seconds != seconds:  # NaN
        raise ValueError("bad duration: NaN")
    seconds = min(max(seconds, MIN_SECONDS), MAX_SECONDS)
    if not _capture_lock.acquire(blocking=False):
        CAPTURES_TOTAL.inc(outcome="busy")
        raise CaptureBusy("a profiler capture is already running")
    try:
        import jax

        global _capture_seq
        _capture_seq += 1  # under _capture_lock: two sub-second captures
        # must not share one artifact directory (interleaved traces
        # would load as a single garbled timeline)
        stamp = (time.strftime("%Y%m%d-%H%M%S")
                 + f"-{os.getpid()}-{_capture_seq}")
        path = os.path.join(profile_dir(), stamp)
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        try:
            # the capture thread only keeps time; the profiler records
            # every OTHER thread's dispatches for the window
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        files = _artifact_files(path)
        CAPTURES_TOTAL.inc(outcome="ok")
        logger.info("profiler capture: %.2fs -> %s (%d file(s))",
                    seconds, path, len(files))
        return {"artifact": path, "seconds": seconds, "files": files}
    except Exception:
        CAPTURES_TOTAL.inc(outcome="error")
        raise
    finally:
        _capture_lock.release()
