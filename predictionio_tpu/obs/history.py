"""Local time-series history: bounded rings over ~20 key series.

Prometheus answers fleet-wide questions *if* a scraper is running; this
module answers "what did the last hour look like" from inside the
process, with no external infrastructure — the co-located-observation
stance of the serverless-dataflow line of work (PAPERS.md). A background
sampler reads the process metrics registry every
``PIO_HISTORY_INTERVAL_S`` seconds (default 10; 0 disables) and records
each derived series — qps and error rates as counter deltas, latency
p50/p99 as *windowed* histogram quantiles (``quantile_since`` against
the previous tick's bucket state, so each point covers exactly one
interval), plus gauge snapshots (HBM, breakers, admission, staleness) —
into fixed-size ring buffers (``PIO_HISTORY_CAPACITY`` points, default
360 = one hour at the default interval).

Surfaces:

  * ``GET /debug/history`` on every server (mounted by
    utils/http.add_metrics_route; 404 when disabled) — JSON
    ``{intervalS, capacity, series: {name: {latest, points: [[t, v]]}}}``;
  * dashboard sparklines (tools/dashboard.py);
  * the SLO burn-rate engine (obs/slo.py) evaluates its windows over
    these rings on every sample tick;
  * optional JSONL spill for post-mortems: ``PIO_HISTORY_SPILL=<path>``
    appends one ``{"t": ..., "values": {...}}`` line per tick, so a
    crashed process leaves its last hour on disk.

The sampler is process-global (one per process, like the registry) and
fail-soft: a broken series samples None, never kills the thread.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Callable

from predictionio_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

logger = logging.getLogger(__name__)

__all__ = [
    "HistorySampler",
    "ensure_started",
    "get_sampler",
    "history_enabled",
    "history_interval_s",
    "reset",
    "sparkline",
]

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: list) -> str:
    """Unicode sparkline over the series' own min..max (gaps for None).
    Character cells instead of an image/JS chart: zero dependencies and
    it renders in any terminal. The one renderer shared by the dashboard
    panels and ``pio watch``."""
    nums = [v for v in values if v is not None]
    if not nums:
        return ""
    lo, hi = min(nums), max(nums)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        else:
            idx = int((v - lo) / span * (len(_SPARK_CHARS) - 1))
            out.append(_SPARK_CHARS[idx])
    return "".join(out)

_SAMPLES = REGISTRY.counter(
    "pio_history_samples_total",
    "History sampler ticks recorded into the local rings",
)


def history_interval_s() -> float:
    """``PIO_HISTORY_INTERVAL_S`` (default 10 s; 0 disables history,
    /debug/history, and the SLO engine). Read per call so tests and
    operators can retune before (re)starting the sampler."""
    try:
        return float(os.environ.get("PIO_HISTORY_INTERVAL_S", "10"))
    except ValueError:
        return 10.0


def history_enabled() -> bool:
    return history_interval_s() > 0


def _capacity() -> int:
    try:
        return max(int(os.environ.get("PIO_HISTORY_CAPACITY", "360")), 2)
    except ValueError:
        return 360


def _counter_total(registry: MetricsRegistry, name: str,
                   label: str | None = None,
                   values: tuple[str, ...] | None = None) -> float | None:
    """Cumulative sum over a counter's children, optionally restricted to
    ``label in values``; None when the metric has never observed."""
    m = registry.get(name)
    if not isinstance(m, (Counter, Gauge)):
        return None
    # a registered family with no children yet reads 0, not None: the
    # subsystem is loaded, it just hasn't observed — so the tick BEFORE
    # a burst still records a baseline and the burst's first rate lands
    # one interval sooner (the SLO acceptance window depends on it)
    items = m.items()
    if label is None:
        return sum(v for _, v in items)
    try:
        idx = m.label_names.index(label)
    except ValueError:
        return None
    return sum(v for key, v in items
               if values is None or key[idx] in values)


def _gauge_sum(registry: MetricsRegistry, name: str) -> float | None:
    m = registry.get(name)
    if not isinstance(m, Gauge):
        return None
    items = m.items()
    if not items:
        return None
    return sum(v for _, v in items)


def _gauge_max(registry: MetricsRegistry, name: str) -> float | None:
    m = registry.get(name)
    if not isinstance(m, Gauge):
        return None
    items = m.items()
    if not items:
        return None
    return max(v for _, v in items)


class HistorySampler:
    """Ring-buffered sampler over the process metrics registry.

    ``sample_once()`` is the whole engine — the background thread just
    calls it on the interval — so tests (and the SLO unit suite) drive
    ticks synthetically without threads or sleeps."""

    def __init__(self, interval_s: float | None = None,
                 capacity: int | None = None,
                 registry: MetricsRegistry = REGISTRY):
        self.interval_s = (history_interval_s() if interval_s is None
                           else float(interval_s))
        self.capacity = _capacity() if capacity is None else int(capacity)
        self.registry = registry
        self._lock = threading.Lock()
        self._rings: dict[str, deque] = {}
        #: previous cumulative counter values, for per-interval rates
        self._prev_totals: dict[str, float] = {}
        #: previous histogram bucket states, for windowed quantiles
        self._prev_hist: dict[str, object] = {}
        self._last_sample_t: float | None = None
        #: called after every tick with (sampler, unix_ts) — the SLO
        #: engine evaluates its windows here
        self.listeners: list[Callable[["HistorySampler", float], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- derivation helpers -------------------------------------------------
    def _rate(self, key: str, total: float | None,
              dt: float) -> float | None:
        """Per-second rate from a cumulative total vs the previous tick
        (None on the first sighting — a rate needs two points; a total
        that went BACKWARDS, i.e. a restarted private registry, re-bases
        instead of reporting a negative rate)."""
        if total is None:
            return None
        prev = self._prev_totals.get(key)
        self._prev_totals[key] = total
        if prev is None or dt <= 0 or total < prev:
            return None
        return (total - prev) / dt

    def _windowed_quantile(self, name: str, q: float,
                           **labels) -> float | None:
        """Histogram quantile over ONLY the last interval's observations
        (bucket-state delta vs the previous tick)."""
        m = self.registry.get(name)
        if not isinstance(m, Histogram):
            return None
        key = f"{name}:{','.join(f'{k}={v}' for k, v in sorted(labels.items()))}:{q}"
        state = m.state(**labels)
        prev = self._prev_hist.get(key)
        self._prev_hist[key] = state
        if prev is None:
            return None
        try:
            return m.quantile_since(q, prev, **labels)
        except Exception:  # bucket-shape change mid-process
            return None

    def _collect(self, dt: float) -> dict[str, float | None]:
        """One tick's values for every series. Each entry is independent
        and fail-soft; a series the process never exercises (no gateway
        in a bare replica, no event server in a query process) samples
        None and renders as a gap, not a zero."""
        reg = self.registry
        ct = _counter_total
        ms = lambda v: None if v is None else v * 1e3  # noqa: E731

        values: dict[str, float | None] = {}
        # serving (replica side)
        values["query_qps"] = self._rate(
            "query", ct(reg, "pio_query_requests_total"), dt)
        values["query_error_rate"] = self._rate(
            "query_err", ct(reg, "pio_query_errors_total"), dt)
        values["query_p50_ms"] = ms(
            self._windowed_quantile("pio_query_seconds", 0.5))
        values["query_p99_ms"] = ms(
            self._windowed_quantile("pio_query_seconds", 0.99))
        values["stage_predict_p99_ms"] = ms(self._windowed_quantile(
            "pio_query_stage_seconds", 0.99, stage="predict"))
        values["stage_queue_wait_p99_ms"] = ms(self._windowed_quantile(
            "pio_query_stage_seconds", 0.99, stage="queue_wait"))
        # serving (gateway side)
        values["gateway_qps"] = self._rate(
            "gw", ct(reg, "pio_gateway_requests_total"), dt)
        values["gateway_failure_rate"] = self._rate(
            "gw_fail", ct(reg, "pio_gateway_requests_total", "outcome",
                          ("error", "upstream_error", "no_replica",
                           "all_down", "deadline")), dt)
        values["gateway_p99_ms"] = ms(
            self._windowed_quantile("pio_gateway_seconds", 0.99))
        values["gateway_cache_hit_rate"] = self._ratio_rate(
            "gw_cache", ct(reg, "pio_gateway_cache_hits_total"),
            ct(reg, "pio_gateway_cache_misses_total"), dt)
        values["gateway_breakers_open"] = _gauge_sum(
            reg, "pio_gateway_breaker_open")
        # ingest
        values["ingest_events_per_sec"] = self._rate(
            "ingest", ct(reg, "pio_events_ingested_total", "status",
                         ("200", "201")), dt)
        values["ingest_error_rate"] = self._rate(
            "ingest_err", ct(reg, "pio_events_ingested_total", "status",
                             ("400", "401", "404", "500", "503")), dt)
        values["ingest_p99_ms"] = ms(
            self._windowed_quantile("pio_ingest_seconds", 0.99))
        # bulk ingest (batch + ndjson routes; data/api/event_server.py):
        # per-event accept/reject rates plus the event-time age of the
        # newest committed bulk event — the staleness guardrail pio
        # doctor's ingest finding and the bulk_ingest_success SLO ride
        values["bulk_ingest_events_per_sec"] = self._rate(
            "bulk_ingest", ct(reg, "pio_ingest_bulk_events_total",
                              "status", ("201",)), dt)
        values["bulk_ingest_error_rate"] = self._rate(
            "bulk_ingest_err", ct(reg, "pio_ingest_bulk_events_total",
                                  "status", ("500",)), dt)
        values["bulk_ingest_lag_seconds"] = _gauge_max(
            reg, "pio_ingest_lag_seconds")
        # device / resilience
        values["hbm_live_bytes"] = _gauge_sum(reg, "pio_device_hbm_bytes")
        values["retraces_per_sec"] = self._rate(
            "retrace", ct(reg, "pio_jax_retraces_total"), dt)
        values["serving_route_breaker_open"] = _gauge_sum(
            reg, "pio_serving_route_breaker_open")
        values["admission_rejected_per_sec"] = self._rate(
            "admission", ct(reg, "pio_admission_rejected_total"), dt)
        values["admission_inflight"] = _gauge_sum(
            reg, "pio_admission_inflight")
        values["microbatch_queue_depth"] = _gauge_sum(
            reg, "pio_microbatch_queue_depth")
        # staleness (the gauges refresh via collect hooks; run them so
        # the sample reads current ages, not last-scrape ages)
        reg._run_collect_hooks()
        values["model_age_seconds"] = _gauge_max(
            reg, "pio_serving_model_age_seconds")
        values["ingest_last_event_age_seconds"] = _gauge_max(
            reg, "pio_ingest_last_event_age_seconds")
        # prediction quality (obs/quality.py; the drift gauge refreshes
        # via the collect-hook run above). The hit rate is an interval
        # ratio of JOINED feedback — hits over hits+misses — so the
        # online_quality SLO judges accuracy, not join coverage; the
        # join rate separately says how much evidence each interval had
        values["prediction_drift_score"] = _gauge_max(
            reg, "pio_prediction_drift_score")
        values["online_hit_rate"] = self._ratio_rate(
            "qual_hit",
            ct(reg, "pio_quality_feedback_total", "result", ("hit",)),
            ct(reg, "pio_quality_feedback_total", "result", ("miss",)),
            dt)
        values["quality_join_rate"] = self._div_rate(
            "qual_join",
            ct(reg, "pio_quality_feedback_total", "result",
               ("hit", "miss")),
            ct(reg, "pio_quality_sampled_total"), dt)
        values["feedback_error_rate"] = self._rate(
            "feedback_err", ct(reg, "pio_feedback_errors_total"), dt)
        # training (the run-ledger pillar, obs/runlog.py): step latency,
        # progress and heartbeat age ride the same rings so a trainer
        # process's /debug/history answers "is it moving?" — the
        # heartbeat gauge is refreshed by the collect-hook run above
        values["train_step_p50_ms"] = ms(
            self._windowed_quantile("pio_train_step_seconds", 0.5))
        values["train_progress_ratio"] = _gauge_max(
            reg, "pio_train_progress_ratio")
        values["train_heartbeat_age_seconds"] = _gauge_max(
            reg, "pio_train_heartbeat_age_seconds")
        # continuous training (train/continuous.py): generation progress,
        # how fresh the fold-in loop keeps the serving model, and how far
        # behind the ingest stream it is running
        values["foldin_generation"] = _gauge_max(
            reg, "pio_foldin_generation")
        values["foldin_events_to_servable_s"] = self._windowed_quantile(
            "pio_foldin_events_to_servable_seconds", 0.5)
        values["foldin_watermark_lag_s"] = _gauge_max(
            reg, "pio_foldin_watermark_lag_seconds")
        # structured logs (obs/logs.py): overall record volume and the
        # ERROR+ slice — the series pio doctor's LOG-STORM judgment
        # (obs.logs.diagnose_history_doc) reads back out of /debug/history
        values["log_records_per_sec"] = self._rate(
            "log_all", ct(reg, "pio_log_records_total"), dt)
        values["error_log_rate"] = self._rate(
            "log_err", ct(reg, "pio_log_records_total", "level",
                          ("ERROR", "CRITICAL")), dt)
        # sharded runtime (obs/shards.py): skew, exchange fraction and
        # the collective-byte rate of the distributed paths — plus the
        # straggler-window tick the SHARD-STRAGGLER judgment rolls over
        # (fail-soft like every entry; the max-over-programs shape
        # matches the other multi-child gauges above)
        try:
            from predictionio_tpu.obs import shards as _shards

            _shards.OBSERVATORY.history_tick()
        except Exception:
            logger.debug("shard-observatory tick failed", exc_info=True)
        values["shard_imbalance"] = _gauge_max(
            reg, "pio_shard_imbalance")
        values["exchange_frac"] = _gauge_max(
            reg, "pio_shard_exchange_frac")
        values["collective_bytes_per_sec"] = self._rate(
            "coll_bytes", ct(reg, "pio_collective_bytes_total"), dt)
        return values

    def _ratio_rate(self, key: str, num: float | None, den_extra: float | None,
                    dt: float) -> float | None:
        """Interval hit rate: Δhits / (Δhits + Δmisses)."""
        dn = self._rate(key + ":n", num, dt)
        dm = self._rate(key + ":m", den_extra, dt)
        if dn is None or dm is None or dn + dm <= 0:
            return None
        return dn / (dn + dm)

    def _div_rate(self, key: str, num: float | None, den: float | None,
                  dt: float) -> float | None:
        """Interval quotient of two counters: Δnum / Δden (None without
        denominator traffic; may exceed 1 when the numerator answers
        older intervals' work — the quality join rate does when delayed
        feedback lands)."""
        dn = self._rate(key + ":n", num, dt)
        dd = self._rate(key + ":d", den, dt)
        if dn is None or dd is None or dd <= 0:
            return None
        return dn / dd

    # -- the tick -----------------------------------------------------------
    def sample_once(self, t: float | None = None) -> dict[str, float | None]:
        t = time.time() if t is None else t
        # dt from the sample clock itself, so synthetic ticks (tests,
        # the SLO unit suite) get deterministic rates
        dt = (self.interval_s if self._last_sample_t is None
              else t - self._last_sample_t)
        self._last_sample_t = t
        try:
            values = self._collect(max(dt, 1e-9))
        except Exception:  # a broken collector must not kill the thread
            logger.exception("history sample failed")
            return {}
        with self._lock:
            for name, v in values.items():
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = deque(maxlen=self.capacity)
                ring.append((t, v))
        _SAMPLES.inc()
        self._spill(t, values)
        for listener in list(self.listeners):
            try:
                listener(self, t)
            except Exception:
                logger.exception("history listener failed")
        return values

    def _spill(self, t: float, values: dict) -> None:
        path = os.environ.get("PIO_HISTORY_SPILL", "")
        if not path:
            return
        try:
            clean = {k: (None if v is None or not math.isfinite(v) else v)
                     for k, v in values.items()}
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps({"t": round(t, 3), "values": clean})
                        + "\n")
        except OSError:
            from predictionio_tpu.obs.logs import warn_once

            warn_once("history-spill-failed",
                      "history spill to %s failed", path,
                      logger=logger, exc_info=True)

    # -- reads --------------------------------------------------------------
    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(self._rings)

    def points(self, name: str, since: float | None = None
               ) -> list[tuple[float, float | None]]:
        with self._lock:
            ring = self._rings.get(name)
            pts = list(ring) if ring is not None else []
        if since is not None:
            pts = [p for p in pts if p[0] >= since]
        return pts

    def window_values(self, name: str, seconds: float,
                      now_ts: float | None = None) -> list[float]:
        """Non-None values of ``name`` within the trailing window — the
        SLO engine's read path."""
        now_ts = time.time() if now_ts is None else now_ts
        return [v for t, v in self.points(name, since=now_ts - seconds)
                if v is not None]

    def to_json(self, seconds: float | None = None,
                names: list[str] | None = None) -> dict:
        out: dict = {
            "intervalS": self.interval_s,
            "capacity": self.capacity,
            "series": {},
        }
        since = None if seconds is None else time.time() - seconds
        for name in self.series_names():
            if names is not None and name not in names:
                continue
            pts = self.points(name, since=since)
            latest = next((v for _, v in reversed(pts) if v is not None),
                          None)
            out["series"][name] = {
                "latest": latest,
                "points": [[round(t, 3), v] for t, v in pts],
            }
        return out

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-history", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


#: process-global sampler (None until first ensure_started with the
#: feature enabled)
_SAMPLER: HistorySampler | None = None
_SAMPLER_LOCK = threading.Lock()


def ensure_started() -> HistorySampler | None:
    """Create and start the process sampler when history is enabled
    (idempotent; every server mounts /debug/history through
    add_metrics_route, which calls this). Also attaches the SLO engine
    as a tick listener — judgment rides the same clock as observation."""
    global _SAMPLER
    if not history_enabled():
        return None
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            sampler = HistorySampler()
            from predictionio_tpu.obs import slo

            slo.attach(sampler)
            sampler.start()
            _SAMPLER = sampler
        return _SAMPLER


def get_sampler() -> HistorySampler | None:
    return _SAMPLER


def reset() -> None:
    """Tear down the process sampler (tests retuning the interval)."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is not None:
            _SAMPLER.stop()
            _SAMPLER = None
