"""Device-runtime observability: HBM attribution, per-program MFU,
retrace detection.

PR 1 instrumented the host side and PR 5 the request path; the device
itself stayed a black box — nothing said who owned HBM (the dense-A
cache? stager slots? stacked sweep factors? serving-resident models?),
MFU existed only as an offline bench.py calculation, and a silent XLA
retrace burned minutes invisibly. ALX (arxiv 2112.02194) and TurboGR
(arxiv 2605.13433) both treat per-program device-time/HBM accounting as
the prerequisite for TPU tuning campaigns; this module is that layer:

:class:`DeviceArena`
    Named HBM ownership registry. Every subsystem holding device memory
    registers its allocations (``arena(name).register(payload, label)``)
    and frees them when the owner lets go; the live per-arena byte totals
    ride ``pio_device_hbm_bytes{arena=...}`` with per-arena peaks, a
    leak check (``warn_if_leaked``/``assert_empty``) for owner teardown,
    and an ``unattributed`` residual computed against
    ``jax.live_arrays()`` at scrape time (registry collect hook).

:func:`profiled_program`
    Wrapper for the jitted device entry points (dense ALS solves, the
    stacked sweep train, batched top-k, neural train steps). Per call it
    records ``pio_device_dispatch_seconds{program=...}``; per new
    abstract signature it captures a FLOPs estimate once via
    ``lowered.cost_analysis()`` (an analytic ``flops=`` model overrides
    it — bench.py and the live gauge then share ONE accounting); sync'd
    programs publish a live ``pio_device_mfu{program=...}`` gauge
    (window flops / window seconds / device peak, XLA compile seconds
    attributed to the call subtracted).

Retrace detection
    Each program tracks the set of abstract call signatures per *bucket*
    (``bucket=`` callable naming the axes EXPECTED to vary — the serving
    top-k's pow2 batch ladder, a dense train's problem shape). A second
    distinct signature inside one bucket, or a backend compile event
    beyond one-per-signature (jit cache eviction, weak-type flapping),
    counts ``pio_jax_retraces_total{program=...}`` and warns once with
    the differing avals. obs/jax_hooks.py feeds the compile events and
    labels its compile counters with the active program.
"""

from __future__ import annotations

import contextvars
import functools
import logging
import os
import threading
import time

from predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

__all__ = [
    "DeviceArena",
    "DeviceLeakError",
    "arena",
    "arena_bytes",
    "device_bytes",
    "device_peak_flops",
    "hbm_snapshot",
    "observe_program",
    "peak_total_bytes",
    "profiled_program",
    "program_mfu",
    "program_report",
    "refresh_unattributed",
    "reset_program",
    "reset_program_window",
    "shape_bucket",
    "total_retraces",
]

# -- scrape surface ----------------------------------------------------------

HBM_BYTES = REGISTRY.gauge(
    "pio_device_hbm_bytes",
    "Live device memory attributed per named arena (plus the "
    "unattributed residual vs jax.live_arrays, refreshed at scrape)",
    labels=("arena",),
)
HBM_PEAK_BYTES = REGISTRY.gauge(
    "pio_device_hbm_peak_bytes",
    "High-water mark of each arena's attributed device bytes",
    labels=("arena",),
)
DISPATCH_SECONDS = REGISTRY.histogram(
    "pio_device_dispatch_seconds",
    "Host wall seconds per profiled device-program call (sync'd "
    "programs include results-ready; others measure enqueue)",
    labels=("program",),
)
MFU_GAUGE = REGISTRY.gauge(
    "pio_device_mfu",
    "Model FLOPs utilization per profiled program: window flops / "
    "window seconds / device bf16 peak (sync'd programs only)",
    labels=("program",),
)
PROGRAM_FLOPS = REGISTRY.gauge(
    "pio_device_program_flops",
    "FLOPs per dispatch of each profiled program (analytic model when "
    "provided, else lowered.cost_analysis captured once per compile)",
    labels=("program",),
)
RETRACES = REGISTRY.counter(
    "pio_jax_retraces_total",
    "Unexpected re-lowerings of a profiled program: a new abstract "
    "signature inside an existing shape bucket, or a backend compile "
    "beyond one-per-signature",
    labels=("program",),
)
ARENA_LEAKS = REGISTRY.counter(
    "pio_device_arena_leaks_total",
    "Allocations still registered when their arena's owner freed it",
    labels=("arena",),
)


# -- device peak FLOP/s (single source; bench.py imports these) --------------

#: bf16 peak FLOP/s by TPU generation (public numbers; conservative
#: denominator — the ALS solves run in f32). v5e = "TFRT TPU v5 lite".
PEAK_BF16_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v6 lite": 918e12, "v6e": 918e12,
}

_peak_cache: list = []  # [float | None] once probed


def peak_flops_for(device) -> float | None:
    """bf16 peak for one jax device object (None when unrecognized)."""
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in PEAK_BF16_FLOPS.items():
        if tag in kind:
            return peak
    return None


def device_peak_flops() -> float | None:
    """Peak FLOP/s of the default device, probed once per process.
    ``PIO_DEVICE_PEAK_FLOPS`` overrides (unknown device kinds, tests)."""
    env = os.environ.get("PIO_DEVICE_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            logger.warning("ignoring bad PIO_DEVICE_PEAK_FLOPS=%r", env)
    if not _peak_cache:
        try:
            import jax

            _peak_cache.append(peak_flops_for(jax.devices()[0]))
        except Exception:
            _peak_cache.append(None)
    return _peak_cache[0]


# -- HBM arenas --------------------------------------------------------------


class DeviceLeakError(AssertionError):
    """An arena the owner declared empty still holds allocations."""


def device_bytes(payload) -> int:
    """Total bytes of every array leaf in ``payload`` (any pytree of
    objects with ``nbytes``; plain ints pass through as explicit byte
    counts for state whose arrays are awkward to hand over)."""
    if payload is None:
        return 0
    if isinstance(payload, (int, float)):
        return int(payload)
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(payload)
    except Exception:
        leaves = payload if isinstance(payload, (list, tuple)) else [payload]
    total = 0
    for leaf in leaves:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class Allocation:
    """One registered device allocation (free exactly once; idempotent)."""

    __slots__ = ("arena_name", "label", "nbytes", "freed")

    def __init__(self, arena_name: str, label: str, nbytes: int):
        self.arena_name = arena_name
        self.label = label
        self.nbytes = int(nbytes)
        self.freed = False

    def __repr__(self) -> str:  # leak reports show these
        return f"<{self.arena_name}:{self.label or 'alloc'} {self.nbytes}B>"


class DeviceArena:
    """Named set of live device allocations feeding one
    ``pio_device_hbm_bytes`` gauge child."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._live: dict[int, Allocation] = {}
        self._bytes = 0
        self.peak = 0

    def register(self, payload, label: str = "") -> Allocation:
        """Track ``payload`` (pytree of arrays, or an int byte count)
        under this arena until :meth:`free`. Zero-byte payloads are
        tracked too (their free keeps the balance auditable)."""
        alloc = Allocation(self.name, label, device_bytes(payload))
        with self._lock:
            self._live[id(alloc)] = alloc
            self._bytes += alloc.nbytes
            self.peak = max(self.peak, self._bytes)
            # publish under the lock: a set() after release could land
            # out of order with a concurrent mutation's and leave the
            # gauge stale until the next change
            HBM_BYTES.set(self._bytes, arena=self.name)
            HBM_PEAK_BYTES.set(self.peak, arena=self.name)
        _note_total_peak()
        return alloc

    def free(self, alloc: Allocation | None) -> None:
        """Release one allocation (None / double-free are no-ops: tear-
        down paths run from error handlers and must stay idempotent)."""
        if alloc is None or alloc.freed:
            return
        with self._lock:
            if self._live.pop(id(alloc), None) is None:
                return
            alloc.freed = True
            self._bytes -= alloc.nbytes
            HBM_BYTES.set(self._bytes, arena=self.name)

    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def allocations(self) -> list[Allocation]:
        with self._lock:
            return list(self._live.values())

    def warn_if_leaked(self) -> int:
        """Owner-teardown leak check: log + count any allocation still
        registered, returning the leaked byte total. The allocations stay
        registered (they ARE still alive — the gauge must keep telling
        the truth); the counter is the alarm."""
        leaked = self.allocations()
        if not leaked:
            return 0
        total = sum(a.nbytes for a in leaked)
        ARENA_LEAKS.inc(len(leaked), arena=self.name)
        logger.warning(
            "device arena %r: %d allocation(s) (%d bytes) still "
            "registered at owner free: %s",
            self.name, len(leaked), total, leaked[:8])
        return total

    def assert_empty(self) -> None:
        """Raise :class:`DeviceLeakError` listing any live allocations —
        the strict form of :meth:`warn_if_leaked` for tests and explicit
        teardown contracts."""
        leaked = self.allocations()
        if leaked:
            self.warn_if_leaked()
            raise DeviceLeakError(
                f"arena {self.name!r} leaked {len(leaked)} allocation(s): "
                f"{leaked[:8]}")


_arena_lock = threading.Lock()
_ARENAS: dict[str, DeviceArena] = {}

#: Process high-water mark of total device bytes (attributed arenas +
#: the unattributed residual at its last refresh) — bench.py's
#: ``peak_hbm_bytes`` headline field.
_peak_total = 0
_last_unattributed = 0


def arena(name: str) -> DeviceArena:
    """Get-or-create the named arena (module-level convention mirrors
    the metric registry: one object per name, shared by every caller)."""
    with _arena_lock:
        a = _ARENAS.get(name)
        if a is None:
            a = _ARENAS[name] = DeviceArena(name)
        return a


def arena_bytes() -> dict[str, int]:
    with _arena_lock:
        arenas = list(_ARENAS.values())
    return {a.name: a.bytes() for a in arenas}


def _note_total_peak() -> None:
    global _peak_total
    total = sum(arena_bytes().values()) + _last_unattributed
    if total > _peak_total:
        _peak_total = total


def peak_total_bytes() -> int:
    """Process peak of (attributed + last-refreshed unattributed) device
    bytes."""
    return _peak_total


def live_device_bytes() -> int:
    """Total bytes of every live jax array in the process (deleted /
    donated buffers excluded)."""
    try:
        import jax

        total = 0
        for a in jax.live_arrays():
            try:
                if a.is_deleted():
                    continue
                total += int(a.nbytes)
            except Exception:
                continue
        return total
    except Exception:
        return 0


def refresh_unattributed() -> int:
    """Recompute the ``unattributed`` residual: live jax bytes minus the
    attributed arena total, clamped at 0 (an arena whose arrays died
    before their free would otherwise push it negative — the leak
    counter owns that story). Runs as a registry collect hook so every
    scrape/snapshot sees a current figure."""
    global _last_unattributed
    live = live_device_bytes()
    attributed = sum(arena_bytes().values())
    resid = max(live - attributed, 0)
    _last_unattributed = resid
    HBM_BYTES.set(resid, arena="unattributed")
    current_peak = float(
        HBM_PEAK_BYTES.value(arena="unattributed"))
    if resid > current_peak:
        HBM_PEAK_BYTES.set(resid, arena="unattributed")
    _note_total_peak()
    return resid


REGISTRY.add_collect_hook(refresh_unattributed)


def hbm_snapshot() -> dict:
    """One JSON-friendly view of device memory: per-arena live/peak
    bytes, the refreshed unattributed residual, and process totals —
    the dashboard panel and ``pio status`` both render this."""
    resid = refresh_unattributed()
    arenas = {
        name: {"bytes": b, "peak_bytes": arena(name).peak}
        for name, b in sorted(arena_bytes().items())
    }
    return {
        "arenas": arenas,
        "unattributed_bytes": resid,
        "unattributed_peak_bytes": int(
            HBM_PEAK_BYTES.value(arena="unattributed")),
        "live_bytes": resid + sum(a["bytes"] for a in arenas.values()),
        "peak_total_bytes": _peak_total,
    }


# -- per-program accounting --------------------------------------------------


class _ActiveCall:
    """Thread/context-scoped marker while a profiled program executes:
    obs/jax_hooks.py labels compile counters with ``name`` and streams
    compile seconds back here so MFU can subtract them."""

    __slots__ = ("name", "bucket", "compile_s", "compiles")

    def __init__(self, name: str, bucket):
        self.name = name
        self.bucket = bucket
        self.compile_s = 0.0
        self.compiles = 0


_ACTIVE: contextvars.ContextVar[_ActiveCall | None] = contextvars.ContextVar(
    "pio_device_active_program", default=None)


def current_program_name() -> str | None:
    """Name of the profiled program executing on this thread (None
    outside any)."""
    active = _ACTIVE.get()
    return active.name if active is not None else None


def current_dispatch_marker():
    """An object unique to the profiled dispatch executing on this
    thread (None outside any) — the shard observatory keys trace-time
    byte accumulation on it so a retrace restarts the sum instead of
    double-counting (obs/shards.py)."""
    return _ACTIVE.get()


#: Called with ``(program_name, wall_seconds)`` after every profiled
#: dispatch, right beside the program-record observe. The shard
#: observatory (obs/shards.py) registers here; an empty list costs one
#: iteration per dispatch. Listeners must be cheap and never raise —
#: they run on the training/serving hot path (failures are swallowed to
#: a debug log).
_DISPATCH_LISTENERS: list = []


def add_dispatch_listener(fn) -> None:
    """Register a post-dispatch hook (idempotent by identity)."""
    if fn not in _DISPATCH_LISTENERS:
        _DISPATCH_LISTENERS.append(fn)


class _Program:
    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        # bucket key -> list of signatures seen (list, not set: the
        # FIRST signature is the reference shown in retrace warnings)
        self.signatures: dict = {}
        self.compiles: dict = {}  # bucket key -> backend compiles
        self.retraces = 0
        # signature -> cost-analysis FLOPs (per signature, not per
        # program: a second dataset's shapes are a new program body
        # whose FLOPs the first capture says nothing about)
        self.flops_by_sig: dict = {}
        self.calls = 0
        self.seconds = 0.0
        self.flops = 0.0
        self.window_seconds = 0.0  # resettable MFU window
        self.window_flops = 0.0

    def _warn_retrace(self, why: str) -> None:
        # lazy import: logs imports metrics, device imports logs only at
        # warn time, so module import order stays acyclic
        from predictionio_tpu.obs.logs import warn_once

        warn_once(
            f"device-retrace:{self.name}",
            "device program %r retraced: %s (further retraces for "
            "this program counted silently on "
            "pio_jax_retraces_total)", self.name, why, logger=logger)

    def note_signature(self, bucket, sig) -> bool:
        """Record one call's (bucket, signature); returns True when the
        signature is NEW (→ capture a FLOPs estimate). A second distinct
        signature in an existing bucket is a retrace."""
        with self.lock:
            sigs = self.signatures.setdefault(bucket, [])
            if sig in sigs:
                return False
            sigs.append(sig)
            is_retrace = len(sigs) > 1
        if is_retrace:
            RETRACES.inc(program=self.name)
            with self.lock:
                self.retraces += 1
            self._warn_retrace(
                f"bucket {bucket!r} saw a second abstract signature\n"
                f"  first: {sigs[0]}\n  now:   {sig}")
        return True

    def note_compile(self, seconds: float) -> None:
        """One backend compile attributed to this program's active call.
        Compiles beyond one-per-signature in a bucket mean jax re-lowered
        something it had already compiled (cache eviction, weak-type
        flap) — a retrace the signature set alone cannot see."""
        active = _ACTIVE.get()
        bucket = active.bucket if active is not None else None
        if active is not None:
            active.compile_s += seconds
            active.compiles += 1
        with self.lock:
            n = self.compiles.get(bucket, 0) + 1
            self.compiles[bucket] = n
            over = n > len(self.signatures.get(bucket, ()))
        if over:
            RETRACES.inc(program=self.name)
            with self.lock:
                self.retraces += 1
            self._warn_retrace(
                f"bucket {bucket!r}: backend compile #{n} exceeds its "
                "signature count (jit cache eviction or weak-type flap)")

    def observe(self, dt: float, flops: float | None, synced: bool,
                compile_s: float = 0.0) -> None:
        DISPATCH_SECONDS.observe(dt, program=self.name)
        if flops is not None and flops > 0:
            PROGRAM_FLOPS.set(flops, program=self.name)
        with self.lock:
            self.calls += 1
            self.seconds += dt
            if flops:
                self.flops += flops
            if synced and flops:
                # compile seconds are one-time cost, not program rate:
                # leave them in the dispatch histogram, keep them out of
                # the utilization figure
                self.window_seconds += max(dt - compile_s, 1e-9)
                self.window_flops += flops
            ws, wf = self.window_seconds, self.window_flops
        if synced and flops:
            peak = device_peak_flops()
            if peak and ws > 0:
                MFU_GAUGE.set(wf / ws / peak, program=self.name)

    def mfu(self) -> float | None:
        peak = device_peak_flops()
        with self.lock:
            if not peak or self.window_seconds <= 0 \
                    or self.window_flops <= 0:
                return None
            return self.window_flops / self.window_seconds / peak


_program_lock = threading.Lock()
_PROGRAMS: dict[str, _Program] = {}


def _program(name: str) -> _Program:
    with _program_lock:
        p = _PROGRAMS.get(name)
        if p is None:
            p = _PROGRAMS[name] = _Program(name)
        return p


def note_compile(seconds: float) -> str | None:
    """Called by obs/jax_hooks.py per backend compile event; returns the
    active program name (the compile counters' label) or None."""
    name = current_program_name()
    if name is not None:
        _program(name).note_compile(seconds)
    return name


def program_mfu(name: str) -> float | None:
    """Current MFU of a profiled program (None before any sync'd
    observation with a FLOPs estimate, or with no known device peak) —
    bench.py reads its headline MFU here so the gauge and the bench
    figure share one accounting."""
    with _program_lock:
        p = _PROGRAMS.get(name)
    return p.mfu() if p is not None else None


def program_report(name: str) -> dict:
    """Introspection for tests and ``pio status``: per-bucket signature/
    compile counts plus the accounting totals."""
    with _program_lock:
        p = _PROGRAMS.get(name)
    if p is None:
        return {"buckets": {}, "retraces": 0, "calls": 0}
    with p.lock:
        return {
            "buckets": {
                repr(b): {
                    "signatures": len(sigs),
                    "compiles": p.compiles.get(b, 0),
                }
                for b, sigs in p.signatures.items()
            },
            "retraces": p.retraces,
            "calls": p.calls,
            "seconds": round(p.seconds, 6),
            "flops": p.flops,
        }


def program_names() -> list[str]:
    with _program_lock:
        return sorted(_PROGRAMS)


def total_retraces() -> int:
    """Process-lifetime retrace count across every profiled program."""
    return int(RETRACES.total())


def reset_program(name: str) -> None:
    """Drop a program's accounting (tests pair this with the wrapped
    function's ``__wrapped__.clear_cache()`` so compiles-per-bucket
    restart from zero together)."""
    with _program_lock:
        _PROGRAMS.pop(name, None)


def reset_program_window(name: str) -> None:
    """Reset only the MFU window (bench.py: the steady-state section
    measures utilization without the warm-up trains' syncs)."""
    with _program_lock:
        p = _PROGRAMS.get(name)
    if p is not None:
        with p.lock:
            p.window_seconds = 0.0
            p.window_flops = 0.0


def observe_program(name: str, seconds: float, flops: float | None = None,
                    synced: bool = True) -> None:
    """Feed an externally timed dispatch into a program's accounting —
    for callers whose own timing already brackets the sync (bench
    steady-state timers)."""
    _program(name).observe(seconds, flops, synced)


# -- the profiled_program wrapper -------------------------------------------


def _describe(x):
    """Hashable abstract description of one positional argument: arrays
    by dtype/shape (their values never retrace), python scalars by type
    (they trace as weak-typed operands), containers recursively."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("a", str(x.dtype), tuple(x.shape))
    if isinstance(x, (tuple, list)):
        return ("t", tuple(_describe(v) for v in x))
    if isinstance(x, dict):
        return ("d", tuple(sorted(
            (k, _describe(v)) for k, v in x.items())))
    if x is None or isinstance(x, (bool, int, float, str)):
        return ("s", type(x).__name__)
    return ("o", type(x).__name__)


def _describe_kw(x):
    """Keyword arguments are static at every wrap site (keyword-only
    static_argnames), so their VALUES are part of the signature."""
    try:
        hash(x)
        return x
    except TypeError:
        return repr(x)


def _signature(args, kwargs):
    return (
        tuple(_describe(a) for a in args),
        tuple(sorted((k, _describe_kw(v)) for k, v in kwargs.items())),
    )


def shape_bucket(*args) -> tuple:
    """Bucket key from every array leaf's shape in ``args`` — for
    programs whose operand shapes are data-dependent (a dense train's
    correction-cell count varies with the ratings): new data = new
    bucket = expected compile, while a dtype or weak-type flap at
    IDENTICAL shapes still lands in the same bucket and counts as the
    retrace it is."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(args)
    except Exception:
        leaves = list(args)
    return tuple(
        tuple(leaf.shape) for leaf in leaves if hasattr(leaf, "shape"))


def _sync_outputs(out) -> None:
    """Order a results-ready boundary with a tiny readback of the first
    array leaf — the repo's phase-sync idiom (``block_until_ready`` does
    not block through this environment's TPU tunnel; a 4-element fetch
    does — see als_dense._phase_sync)."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "dtype") and getattr(leaf, "size", 0):
                np.asarray(jax.device_get(jnp.ravel(leaf)[:4]))
                return
    except Exception:
        logger.debug("profiled-program sync failed", exc_info=True)


def _cost_analysis_flops(fn, args, kwargs) -> float | None:
    """Best-effort per-dispatch FLOPs from ``fn.lower(...).cost_analysis()``
    (no backend compile — lowering only), captured once per new
    signature. Returns None when the backend has no cost model or the
    function does not expose ``lower`` (non-jit callables)."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        cost = lower(*args, **kwargs).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not cost:
            return None
        fl = float(cost.get("flops", 0.0))
        return fl if fl > 0 else None
    except Exception:
        logger.debug("cost_analysis failed for %r", fn, exc_info=True)
        return None


def profiled_program(name, flops=None, bucket=None, sync: bool = False,
                     estimate: bool = True):
    """Wrap a jitted device entry point with program accounting.

    ``name``: str, or callable(*args, **kwargs) -> str (programs whose
    identity depends on a static arg, e.g. ``als_dense_rank{rank}``).
    ``flops``: callable(*args, **kwargs) -> float — analytic FLOPs per
    dispatch; overrides the cost-analysis capture as the MFU numerator
    (the model bench.py shares, so the two accountings cannot drift).
    ``bucket``: callable -> hashable naming the axes EXPECTED to vary
    (serving batch ladder, problem shape). Default: the full abstract
    signature is its own bucket — safe (no false retraces), and
    compile-beyond-signature detection still fires. A static scalar the
    jit takes POSITIONALLY (e.g. top-k's ``k``) MUST appear in
    ``bucket``: scalar values are not part of the abstract signature,
    and the recompile such a value forces would otherwise read as a
    retrace.
    ``sync``: time to results-ready via a tiny readback (feeds MFU).
    Only set it on seconds-scale dispatches — it costs one host-link
    round trip, which is why the overlapped half-step dispatches stay
    un-synced (their histogram measures enqueue, documented as such).
    ``estimate``: set False to skip the cost-analysis lowering (entry
    points whose re-lowering is expensive relative to their dispatch).
    The capture only happens for ``sync=True`` programs at all — MFU is
    its sole consumer, and paying a re-lowering per new signature on an
    un-synced hot path (the serving top-k's ever-growing batch-shape
    set) would tax exactly the dispatches this module exists to watch.
    """

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            pname = name(*args, **kwargs) if callable(name) else name
            rec = _program(pname)
            bkey = bucket(*args, **kwargs) if bucket is not None else None
            # the bucket key rides inside the signature: python scalars
            # describe by TYPE (traced operands recompile on aval, not
            # value), so a wrap site whose jit takes a static scalar
            # POSITIONALLY must name it in ``bucket`` — the fold-in then
            # keeps one-compile-per-signature accounting truthful
            sig = (_signature(args, kwargs), bkey)
            if bkey is None:
                bkey = sig
            new_sig = rec.note_signature(bkey, sig)
            # sync'd programs only: MFU is the estimate's sole consumer,
            # and the capture costs a re-lowering per new signature —
            # unaffordable on un-synced hot paths like the serving
            # top-k, whose signature set grows with every batch shape
            if new_sig and estimate and sync and flops is None:
                # lower under the program scope: lowering traces the
                # body, and trace-time hooks (the obs/shards.py
                # collective byte ticks) must attribute to this program
                # — the actual dispatch below reuses the trace cache,
                # so this is the only trace those hooks will see.
                # Lowering raises no backend-compile events, so the
                # compile-beyond-signature rule is untouched
                est_token = _ACTIVE.set(_ActiveCall(pname, bkey))
                try:
                    rec.flops_by_sig[sig] = _cost_analysis_flops(
                        fn, args, kwargs)
                finally:
                    _ACTIVE.reset(est_token)
            fl = None
            if flops is not None:
                try:
                    fl = float(flops(*args, **kwargs))
                except Exception:
                    logger.debug("flops model failed for %r", pname,
                                 exc_info=True)
            else:
                fl = rec.flops_by_sig.get(sig)
            active = _ActiveCall(pname, bkey)
            token = _ACTIVE.set(active)
            t0 = time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            finally:
                # reset BEFORE the sync: the tiny readback's own helper
                # ops compile on first use, and attributing those events
                # here would trip the compile-beyond-signature rule
                _ACTIVE.reset(token)
            if sync:
                _sync_outputs(out)
            dt = time.perf_counter() - t0
            rec.observe(dt, fl, synced=sync, compile_s=active.compile_s)
            for listener in _DISPATCH_LISTENERS:
                try:
                    # execute seconds, compile excluded: a first-dispatch
                    # compile would wash out any execute-time fraction a
                    # listener computes (obs/shards.py exchange_frac)
                    listener(pname, max(dt - active.compile_s, 0.0))
                except Exception:
                    logger.debug("dispatch listener failed for %r",
                                 pname, exc_info=True)
            return out

        inner.__wrapped__ = fn
        inner.program_name = name if isinstance(name, str) else None
        return inner

    return wrap
