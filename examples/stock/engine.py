"""Stock backtesting: momentum predictions scored by a portfolio simulator.

The analog of the reference's experimental stock workload
(ref: examples/experimental/scala-stock/src/main/scala/
{BackTestingMetrics,RegressionStrategy}.scala). Two pieces:

* ``MomentumAlgorithm`` — predicts each ticker's next-day return as the
  mean of its last ``window`` daily returns. All days × all tickers are
  scored in ONE jitted pass over the price matrix at train time
  (a [days, tickers] rolling-mean via cumulative sums — no Python loop),
  so predict is a table lookup.
* ``BacktestingEvaluator`` — a custom ``BaseEvaluator`` (the reference's
  ``BacktestingEvaluator`` extends Evaluator the same way): replays the
  per-day predictions as a trading strategy — enter positions whose
  predicted return ≥ ``enter_threshold``, exit at ≤ ``exit_threshold``,
  at most ``max_positions`` concurrent — and reports NAV, total return,
  daily vol, and annualized Sharpe. The daily portfolio loop is a
  ``lax.scan`` over the [days, tickers] decision matrix: positions are a
  mask vector, cash/NAV a carry — the scan replaces the reference's
  mutable ArrayBuffer walk (BackTestingMetrics.scala:100-170).

Training data is ``data/prices.csv`` (``date_idx,ticker,price``). Run
from this directory:

    pio train
    pio eval engine:evaluation
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import Engine, IdentityPreparator, LServing
from predictionio_tpu.core.base import BaseEvaluator, BaseEvaluatorResult
from predictionio_tpu.core.dase import LAlgorithm, LDataSource
from predictionio_tpu.core.evaluation import Evaluation
from predictionio_tpu.core.params import Params


@dataclass(frozen=True)
class StockData:
    tickers: tuple  # (ticker, ...)
    prices: tuple  # row-major [days][tickers] price tuples


@dataclass(frozen=True)
class Query:
    day: int  # date index into the price frame


@dataclass(frozen=True)
class Prediction:
    scores: tuple  # ((ticker, predicted next-day return), ...)


@dataclass(frozen=True)
class DataSourceParams(Params):
    path: str = ""  # defaults to data/prices.csv beside this file
    eval_start: int = 20  # first day queried during evaluation


def _load_prices(path_param: str) -> StockData:
    path = (
        Path(path_param)
        if path_param
        else Path(__file__).parent / "data" / "prices.csv"
    )
    by_day: dict[int, dict[str, float]] = {}
    with open(path) as f:
        for day, ticker, price in csv.reader(f):
            by_day.setdefault(int(day), {})[ticker] = float(price)
    tickers = tuple(sorted(by_day[0]))
    prices = tuple(
        tuple(by_day[d][t] for t in tickers) for d in sorted(by_day)
    )
    return StockData(tickers, prices)


class DataSource(LDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams | None = None):
        self.params = params or DataSourceParams()

    def read_training_local(self) -> StockData:
        return _load_prices(self.params.path)

    def read_eval_local(self):
        """One fold: train on the whole frame, query every day from
        ``eval_start`` on. actual=None — the evaluator recomputes realized
        returns from the price frame itself (ref: BackTestingMetrics
        reads rawData price frames, not per-query actuals); the frame is
        the fold's eval_info."""
        td = self.read_training_local()
        n_days = len(td.prices)
        qa = [
            (Query(day=d), None)
            for d in range(self.params.eval_start, n_days - 1)
        ]
        return [(td, td, qa)]


@dataclass(frozen=True)
class MomentumParams(Params):
    window: int = 10


@dataclass
class MomentumModel:
    tickers: tuple
    scores: np.ndarray  # [days, tickers] predicted next-day returns


@partial(jax.jit, static_argnames=("window",))
def _momentum_scores(prices, window: int):
    """[days, tickers] trailing-mean daily returns: day d's score is the
    mean return over (d-window, d]. Rolling mean via cumsum difference —
    one fused pass, no per-day loop."""
    rets = prices[1:] / prices[:-1] - 1.0  # [days-1, t]
    window = min(window, rets.shape[0])  # short frames: whole-history mean
    csum = jnp.cumsum(rets, axis=0)
    shifted = jnp.concatenate(
        [jnp.zeros((window, rets.shape[1]), rets.dtype), csum[:-window]]
    )
    rolling = (csum - shifted) / window
    # day 0 has no history; early days use the partial mean
    partial_n = jnp.minimum(
        jnp.arange(1, rets.shape[0] + 1), window
    ).astype(rets.dtype)[:, None]
    rolling = jnp.where(
        jnp.arange(rets.shape[0])[:, None] < window,
        csum / partial_n,
        rolling,
    )
    # score for querying day d = trailing stats of returns up to day d
    return jnp.concatenate([jnp.zeros((1, rets.shape[1])), rolling])


class MomentumAlgorithm(LAlgorithm):
    params_class = MomentumParams
    query_class = Query

    def __init__(self, params: MomentumParams | None = None):
        self.params = params or MomentumParams()

    def train_local(self, pd: StockData) -> MomentumModel:
        prices = jnp.asarray(pd.prices, jnp.float32)
        scores = np.asarray(_momentum_scores(prices, self.params.window))
        return MomentumModel(pd.tickers, scores)

    def predict(self, model: MomentumModel, query: Query) -> Prediction:
        d = min(max(query.day, 0), len(model.scores) - 1)
        return Prediction(
            tuple(zip(model.tickers, model.scores[d].tolist()))
        )


class Serving(LServing):
    def __init__(self, params=None):
        pass

    def serve(self, query, predictions):
        return predictions[0]


# ---------------------------------------------------------------------------
# Backtesting evaluator (ref: BackTestingMetrics.scala)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BacktestingParams(Params):
    enter_threshold: float = 0.001
    exit_threshold: float = -0.001
    max_positions: int = 3


@dataclass
class BacktestingResult(BaseEvaluatorResult):
    ret: float = 0.0  # total return over the test span
    vol: float = 0.0  # daily return stdev
    sharpe: float = 0.0  # annualized
    days: int = 0
    nav: tuple = ()  # daily NAV curve

    def to_one_liner(self) -> str:
        return (
            f"ret={self.ret:.4f} vol={self.vol:.4f} "
            f"sharpe={self.sharpe:.2f} days={self.days}"
        )

    def to_json(self):
        return {
            "ret": self.ret,
            "vol": self.vol,
            "sharpe": self.sharpe,
            "days": self.days,
        }

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{d}</td><td>{v:.4f}</td></tr>"
            for d, v in enumerate(self.nav)
        )
        return (
            "<html><body><h1>Backtest</h1>"
            f"<p>{self.to_one_liner()}</p>"
            f"<table><tr><th>day</th><th>NAV</th></tr>{rows}</table>"
            "</body></html>"
        )


@partial(jax.jit, static_argnames=("max_positions",))
def _simulate(enter, exit_, scores, rets, max_positions: int):
    """Daily portfolio walk as a lax.scan.

    enter/exit_: [days, t] decision matrices for each queried day;
    scores: [days, t] predicted returns (entry priority); rets: [days, t]
    NEXT-day realized returns. Carry = current position mask [t].
    Free slots fill best-predicted-score first (the reference sorts its
    candidate list by pValue descending, BackTestingMetrics.scala:88-92).
    Equal-weight NAV: each day's portfolio return is the mean next-day
    return of held positions (ref holds equal dollar positions,
    BackTestingMetrics.scala:120-150)."""
    t = rets.shape[1]

    def step(positions, inp):
        en, ex, sc, ret = inp
        positions = jnp.where(ex > 0, 0.0, positions)
        free = max_positions - positions.sum()
        eligible = (en > 0) & (positions == 0.0)
        # rank eligible candidates by predicted score desc (ties by index):
        # rank_i = 1 + #{eligible j : score_j > score_i, or equal & j < i}
        s = jnp.where(eligible, sc, -jnp.inf)
        idx = jnp.arange(t)
        better = (s[None, :] > s[:, None]) | (
            (s[None, :] == s[:, None]) & (idx[None, :] < idx[:, None])
        )
        rank = 1 + (better & eligible[None, :]).sum(axis=1)
        add = jnp.where(eligible & (rank <= free), 1.0, 0.0)
        positions = jnp.clip(positions + add, 0.0, 1.0)
        held = positions.sum()
        day_ret = jnp.where(
            held > 0, (positions * ret).sum() / jnp.maximum(held, 1.0), 0.0
        )
        return positions, day_ret

    _, daily = jax.lax.scan(
        step, jnp.zeros(t), (enter, exit_, scores, rets)
    )
    return daily


class BacktestingEvaluator(BaseEvaluator):
    def __init__(self, params: BacktestingParams | None = None):
        self.params = params or BacktestingParams()

    def evaluate(self, ctx, evaluation, engine_eval_data_set, params=None):
        p = self.params
        best: BacktestingResult | None = None
        for _engine_params, eval_data_set in engine_eval_data_set:
            for ei, qpas in eval_data_set:  # ei is the StockData fold info
                prices = np.asarray(ei.prices, np.float32)
                rets_all = prices[1:] / prices[:-1] - 1.0
                days = [q.day for q, _pr, _a in qpas]
                scores = np.stack(
                    [
                        np.array([s for _t, s in pr.scores], np.float32)
                        for _q, pr, _a in qpas
                    ]
                )
                enter = scores >= p.enter_threshold
                exit_ = scores <= p.exit_threshold
                rets = rets_all[days]  # day d row = return d -> d+1
                daily = np.asarray(
                    _simulate(
                        jnp.asarray(enter, jnp.float32),
                        jnp.asarray(exit_, jnp.float32),
                        jnp.asarray(scores),
                        jnp.asarray(rets),
                        p.max_positions,
                    )
                )
                nav = np.cumprod(1.0 + daily)
                vol = float(daily.std())
                sharpe = float(
                    daily.mean() / vol * np.sqrt(252) if vol > 0 else 0.0
                )
                result = BacktestingResult(
                    ret=float(nav[-1] - 1.0),
                    vol=vol,
                    sharpe=sharpe,
                    days=len(daily),
                    nav=tuple(float(x) for x in nav),
                )
                if best is None or result.ret > best.ret:
                    best = result
        return best or BacktestingResult()


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"momentum": MomentumAlgorithm},
        serving_class=Serving,
    )


class BacktestingEvaluation(Evaluation):
    """Evaluation binding the custom evaluator (the reference wires its
    BacktestingEvaluator into Workflow.run the same way)."""

    def __init__(self, engine, engine_params_list,
                 backtesting_params: BacktestingParams | None = None):
        super().__init__(engine=engine, engine_params_list=engine_params_list)
        self.backtesting_params = backtesting_params or BacktestingParams()
        self.output_path = None  # no best.json: not a metric sweep

    @property
    def evaluator(self):
        return BacktestingEvaluator(self.backtesting_params)


def evaluation() -> Evaluation:
    """`pio eval engine:evaluation` entry point: a small momentum-window
    sweep scored by the backtest (best total return wins)."""
    eng = engine_factory()
    candidates = [
        eng.engine_params_from_json(
            {"algorithms": [{"name": "momentum", "params": {"window": w}}]}
        )
        for w in (5, 10, 20)
    ]
    return BacktestingEvaluation(eng, candidates)
