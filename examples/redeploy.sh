#!/usr/bin/env bash
# Periodic retrain + hot-swap, the cron pattern of the reference's
# examples/redeploy-script: run `pio train` in the engine directory, then
# tell the live query server to load the new instance without downtime.
#
#   crontab: 0 3 * * *  /path/to/redeploy.sh /path/to/engine 8000
set -euo pipefail
ENGINE_DIR=${1:?usage: redeploy.sh <engine-dir> [port]}
PORT=${2:-8000}

cd "$ENGINE_DIR"
pio train
if curl -fsS "http://127.0.0.1:${PORT}/reload" >/dev/null; then
  echo "redeployed $(date -Is)"
else
  echo "train succeeded but no server answered on :${PORT} (deploy it with: pio deploy --port ${PORT})" >&2
  exit 1
fi
