"""Recommendation engine with a file-reading DataSource.

The analog of the reference's custom-datasource experimental example
(ref: examples/experimental/scala-parallel-recommendation-custom-datasource/
src/main/scala/DataSource.scala): the stock recommendation engine with
ONLY the DataSource swapped — instead of the event store, training data
comes from a ``user::item::rating`` text file (the MovieLens raw format).
Everything else (Preparator, ALS algorithm, Serving) is imported from the
stock template unchanged, which is the example's whole point: DASE
components compose, so replacing one leaves the rest untouched.

Run from this directory::

    pio build && pio train && pio deploy
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

import numpy as np

from predictionio_tpu.core import Engine, PDataSource
from predictionio_tpu.core.params import Params
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.templates.recommendation import (
    ALSAlgorithm,
    Preparator,
    Serving,
    TrainingData,
)


@dataclass(frozen=True)
class DataSourceParams(Params):
    #: path to a ``user::item::rating`` file (ref: DataSource.scala:28
    #: ``sc.textFile(dsp.filepath)`` + the ``split("::")`` match)
    filepath: str = ""


class FileDataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams | None = None):
        self.params = params or DataSourceParams()

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        path = (
            Path(self.params.filepath)
            if self.params.filepath
            else Path(__file__).parent / "data" / "sample_movielens_data.txt"
        )
        users, items, ratings = [], [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                user, item, rating = line.split("::")
                users.append(user)
                items.append(item)
                ratings.append(float(rating))
        return TrainingData(
            users=users,
            items=items,
            ratings=np.asarray(ratings, np.float32),
        )


def engine_factory() -> Engine:
    return Engine(
        data_source_class=FileDataSource,
        preparator_class=Preparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class=Serving,
    )
