"""Linear regression with a preparator-driven fold sweep.

The analog of the reference's regression examples
(ref: examples/experimental/scala-local-regression/Run.scala,
examples/experimental/scala-parallel-regression/Run.scala): ordinary
least squares on a space-separated file (``y x1 x2 ...``), a Preparator
that drops rows with ``index % n == k`` (the reference's fold mechanism,
Run.scala:56-68), and an evaluation that sweeps ``k`` through a
MetricEvaluator with mean-square error — the reference's original demo of
engine-params tuning.

TPU-first notes: where the reference solves OLS with breeze/nak on the
driver JVM, training here builds the normal equations as one jitted
program (``XᵀX`` is a single MXU contraction; the solve is a Cholesky) —
the same shape ALS uses per entity, at whole-dataset scale.

Run from this directory:

    pio train
    pio eval engine:evaluation     # 3-fold MSE sweep, writes best.json
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import Engine, LServing
from predictionio_tpu.core.dase import LAlgorithm, LDataSource, LPreparator
from predictionio_tpu.core.evaluation import Evaluation
from predictionio_tpu.core.metrics import AverageMetric
from predictionio_tpu.core.params import Params


@dataclass(frozen=True)
class TrainingData:
    x: tuple  # row-major feature tuples
    y: tuple


@dataclass(frozen=True)
class Query:
    features: tuple


@dataclass(frozen=True)
class PredictedResult:
    prediction: float


@dataclass(frozen=True)
class DataSourceParams(Params):
    path: str = ""  # defaults to data/lr_data.txt beside this file


class DataSource(LDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams | None = None):
        self.params = params or DataSourceParams()

    def _load(self) -> TrainingData:
        path = (
            Path(self.params.path)
            if self.params.path
            else Path(__file__).parent / "data" / "lr_data.txt"
        )
        xs, ys = [], []
        with open(path) as f:
            for line in f:
                vals = [float(v) for v in line.split()]
                if vals:
                    ys.append(vals[0])
                    xs.append(tuple(vals[1:]))
        return TrainingData(tuple(xs), tuple(ys))

    def read_training_local(self) -> TrainingData:
        return self._load()

    def read_eval_local(self):
        """One fold over the whole file; the fold *structure* comes from
        the Preparator sweep (ref: Run.scala's PreparatorParams demo) —
        queries are the full dataset, training rows are dropped per
        (n, k) by the preparator."""
        td = self._load()
        qa = [(Query(features=x), y) for x, y in zip(td.x, td.y)]
        return [(td, "regression", qa)]


@dataclass(frozen=True)
class PreparatorParams(Params):
    n: int = 0  # 0 → keep everything
    k: int = 0  # drop rows with index % n == k


class Preparator(LPreparator):
    params_class = PreparatorParams

    def __init__(self, params: PreparatorParams | None = None):
        self.params = params or PreparatorParams()

    def prepare_local(self, td: TrainingData) -> TrainingData:
        n, k = self.params.n, self.params.k
        if n <= 0:
            return td
        keep = [i for i in range(len(td.y)) if i % n != k]
        return TrainingData(
            tuple(td.x[i] for i in keep), tuple(td.y[i] for i in keep)
        )


@jax.jit
def _ols(x, y):
    """OLS with intercept via normal equations: one MXU contraction + a
    Cholesky solve (tiny ridge for numerical safety)."""
    xb = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    gram = xb.T @ xb + 1e-8 * jnp.eye(xb.shape[1], dtype=x.dtype)
    rhs = xb.T @ y
    chol = jnp.linalg.cholesky(gram)
    return jax.scipy.linalg.cho_solve((chol, True), rhs)


class OLSAlgorithm(LAlgorithm):
    query_class = Query

    def __init__(self, params=None):
        pass

    def train_local(self, pd: TrainingData) -> np.ndarray:
        x = jnp.asarray(pd.x, jnp.float32)
        y = jnp.asarray(pd.y, jnp.float32)
        return np.asarray(_ols(x, y))  # [features + 1] (last = intercept)

    def predict(self, model: np.ndarray, query: Query) -> PredictedResult:
        v = float(np.dot(model[:-1], np.asarray(query.features)) + model[-1])
        return PredictedResult(prediction=v)


class Serving(LServing):
    def __init__(self, params=None):
        pass

    def serve(self, query, predictions):
        return predictions[0]


class MeanSquareError(AverageMetric):
    """ref: controller.MeanSquareError used by the regression demo."""

    header = "Mean Square Error (negated: higher is better)"

    def calculate_qpa(self, q, p, a) -> float:
        return -((p.prediction - a) ** 2)


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=Preparator,
        algorithm_class_map={"ols": OLSAlgorithm},
        serving_class=Serving,
    )


def evaluation() -> Evaluation:
    """3-fold sweep over PreparatorParams(k) scored by MSE — the
    reference's engine-params tuning demo (Run.scala main)."""
    eng = engine_factory()
    candidates = [
        eng.engine_params_from_json(
            {
                "preparator": {"params": {"n": 3, "k": k}},
                "algorithms": [{"name": "ols", "params": {}}],
            }
        )
        for k in range(3)
    ]
    return Evaluation(
        engine=eng,
        engine_params_list=candidates,
        metric=MeanSquareError(),
    )
