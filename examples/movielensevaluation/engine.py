"""Sliding-window (temporal) evaluation of a recommender.

The analog of the reference's movielens-evaluation experimental example
(ref: examples/experimental/scala-local-movielens-evaluation/src/main/
scala/Evaluation.scala — ``EventsSlidingEvalParams(firstTrainingUntilTime,
evalDuration, evalCount)``): instead of random k-fold splits, each fold
trains on all events BEFORE a cutoff and tests on the events in the
window right AFTER it, then the cutoff slides forward — the honest way to
evaluate a recommender, since production models only ever see the past.

The engine itself is the stock recommendation template (ALS); only the
DataSource changes, adding the temporal ``read_eval``. Metrics report
Precision@K and a baseline-beating rate (fraction of windows where the
model beats recommending the globally-popular items), in the spirit of
the reference's ItemRankDetailedEvaluator baseline comparisons.

Run (after ingesting timestamped ``rate`` events for the app)::

    pio eval engine:evaluation
"""

from __future__ import annotations

import datetime as dt
from dataclasses import dataclass

import numpy as np

from predictionio_tpu.core import Engine, PDataSource
from predictionio_tpu.core.engine import EngineParams
from predictionio_tpu.core.evaluation import Evaluation
from predictionio_tpu.core.metrics import OptionAverageMetric
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.templates.recommendation import (
    ALSAlgorithm,
    AlgorithmParams,
    ActualRating,
    Preparator,
    Query,
    Serving,
    TrainingData,
)
from predictionio_tpu.utils.time import UTC


@dataclass(frozen=True)
class SlidingEvalParams(Params):
    app_name: str = "MyApp1"
    #: ISO date of the first training cutoff (ref: firstTrainingUntilTime)
    first_training_until: str = "1998-02-01"
    eval_duration_days: int = 7
    eval_count: int = 3


class SlidingWindowDataSource(PDataSource):
    """P-flavor DataSource whose eval folds slide through time."""

    params_class = SlidingEvalParams

    def __init__(self, params: SlidingEvalParams | None = None):
        self.params = params or SlidingEvalParams()

    def _events(self, until=None, since=None):
        return PEventStore.find(
            self.params.app_name,
            event_names=["rate"],
            start_time=since,
            until_time=until,
        )

    @staticmethod
    def _training_data(events) -> TrainingData:
        users, items, ratings = [], [], []
        for e in events:
            if e.target_entity_id is None:
                continue
            users.append(e.entity_id)
            items.append(e.target_entity_id)
            ratings.append(float(e.properties.get("rating", float)))
        return TrainingData(
            users=users, items=items,
            ratings=np.asarray(ratings, np.float32),
        )

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return self._training_data(self._events())

    def read_eval(self, ctx: ComputeContext):
        p = self.params
        cutoff = dt.datetime.fromisoformat(p.first_training_until).replace(
            tzinfo=UTC
        )
        window = dt.timedelta(days=p.eval_duration_days)
        folds = []
        for _ in range(p.eval_count):
            td = self._training_data(self._events(until=cutoff))
            test = [
                (
                    Query(user=e.entity_id, num=10),
                    ActualRating(
                        item=e.target_entity_id,
                        rating=float(e.properties.get("rating", float)),
                    ),
                )
                for e in self._events(since=cutoff, until=cutoff + window)
                if e.target_entity_id is not None
            ]
            # a window can only score users the training span has seen
            known = set(td.users)
            test = [(q, a) for q, a in test if q.user in known]
            if td.users and test:
                folds.append((td, f"until={cutoff.date()}", test))
            cutoff += window
        if not folds:
            raise ValueError(
                "no sliding windows contained both training and test events; "
                "check first_training_until / eval_duration_days"
            )
        return folds


class WindowedPrecisionAtK(OptionAverageMetric):
    """Precision@K per sliding window, positives only — the temporal
    counterpart of the recommendation template's PrecisionAtK."""

    def __init__(self, k: int = 10, rating_threshold: float = 4.0):
        self.k = k
        self.rating_threshold = rating_threshold

    @property
    def header(self) -> str:
        return (
            f"Sliding-window PrecisionAtK(k={self.k}, "
            f"threshold={self.rating_threshold})"
        )

    def calculate_qpa(self, q, prediction, actual):
        if actual.rating < self.rating_threshold:
            return None
        top = [s.item for s in prediction.itemScores[: self.k]]
        return 1.0 if actual.item in top else 0.0


def engine_factory() -> Engine:
    return Engine(
        data_source_class=SlidingWindowDataSource,
        preparator_class=Preparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class=Serving,
    )


def evaluation(app_name: str = "MyApp1") -> Evaluation:
    """Two ALS candidates scored across the sliding windows (ref:
    Evaluation.scala's Evaluation1/Evaluation2 objects)."""
    candidates = [
        EngineParams(
            data_source_params=SlidingEvalParams(app_name=app_name),
            algorithms_params=(
                ("als", AlgorithmParams(rank=r, numIterations=8, seed=3)),
            ),
        )
        for r in (4, 8)
    ]
    return Evaluation(
        engine=engine_factory(),
        engine_params_list=candidates,
        metric=WindowedPrecisionAtK(k=10, rating_threshold=4.0),
    )
