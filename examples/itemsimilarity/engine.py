"""Item-to-item similarity engine — the DIMSUM example, redesigned.

The reference's experimental DIMSUM engine
(ref: examples/experimental/scala-parallel-similarproduct-dimsum/src/main/
scala/DIMSUMAlgorithm.scala:69-150) computes thresholded column cosine
similarities of the user x item interaction matrix with Spark's sampled
``RowMatrix.columnSimilarities`` — DIMSUM exists to avoid the all-pairs
shuffle on a cluster. On a TPU the all-pairs product IS the cheap part
(one MXU matmul), so the redesign computes the similarities *exactly*:

    C   = user x item interaction matrix (views, deduplicated)
    Ĉ   = C with L2-normalized columns
    S   = ĈᵀĈ            (exact cosine; chunked over item blocks)
    keep S[i, j] >= threshold, top-k per item

Train-time output is a per-item neighbor table, so serving is a pure
lookup. Events: ``view`` (user → item), read from the event store like
the similarproduct template.

Run from this directory after ingesting view events:

    pio train && pio deploy --port 8000 &
    curl -s -X POST localhost:8000/queries.json -d '{"item": "i1", "num": 4}'
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.core import Engine, FirstServing, IdentityPreparator
from predictionio_tpu.core.dase import LAlgorithm, LDataSource
from predictionio_tpu.data.store.event_stores import PEventStore


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = "MyApp"


@dataclass(frozen=True)
class ViewData:
    user_ids: tuple
    item_ids: tuple
    user_idx: np.ndarray  # [n] int32
    item_idx: np.ndarray  # [n] int32


@dataclass(frozen=True)
class AlgoParams:
    #: minimum cosine to keep a pair (the DIMSUM threshold param)
    threshold: float = 0.1
    #: neighbors retained per item
    top_k: int = 20


@dataclass(frozen=True)
class Query:
    item: str
    num: int = 10


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: tuple = field(default_factory=tuple)


@dataclass(frozen=True)
class SimilarityModel:
    item_ids: tuple  # position -> item string id
    neighbors: dict  # item idx -> tuple[(item idx, cosine), ...] desc


class ViewDataSource(LDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training_local(self) -> ViewData:
        user_ids, item_ids, user_idx, item_idx, _r, _n = (
            PEventStore.interaction_indices(
                self.params.app_name, ["view"], rating_property=None
            )
        )
        return ViewData(tuple(user_ids), tuple(item_ids), user_idx, item_idx)


class CosineSimilarityAlgorithm(LAlgorithm):
    params_class = AlgoParams
    query_class = Query

    def __init__(self, params: AlgoParams):
        self.params = params

    def train_local(self, data: ViewData) -> SimilarityModel:
        import jax.numpy as jnp

        n_users = len(data.user_ids)
        n_items = len(data.item_ids)
        if n_items == 0:
            return SimilarityModel((), {})
        # interaction matrix, deduplicated (same user+item counted once —
        # matching the reference's irDedup, DIMSUMAlgorithm.scala:106-118)
        c = np.zeros((n_users, n_items), np.float32)
        c[data.user_idx, data.item_idx] = 1.0
        norms = np.linalg.norm(c, axis=0)
        norms[norms == 0] = 1.0
        c_hat = jnp.asarray(c / norms)
        # exact all-pairs column cosine. The SCORE matrix is chunked over
        # item blocks (O(chunk x n_items) at a time, with only top-k
        # kept); the dense interaction matrix itself is this example's
        # peak memory — fine into the tens of millions of cells. For
        # production-size catalogs use the similarproduct template, whose
        # factor-based scoring never materializes user x item.
        import jax

        chunk = 2048
        p = self.params
        neighbors: dict[int, tuple] = {}
        for lo in range(0, n_items, chunk):
            hi = min(lo + chunk, n_items)
            # HIGHEST: TPU default-precision f32 dots round through bf16
            # (~1e-3), visibly denting the "exact cosine" this example is
            # about (identical columns must score 1.0)
            block = np.asarray(jnp.matmul(
                c_hat[:, lo:hi].T, c_hat,
                precision=jax.lax.Precision.HIGHEST))  # [b, n_items]
            for bi in range(hi - lo):
                i = lo + bi
                row = block[bi].copy()
                row[i] = -1.0  # drop self-similarity
                keep = np.flatnonzero(row >= p.threshold)
                if len(keep) > p.top_k:
                    keep = keep[np.argsort(-row[keep])[: p.top_k]]
                else:
                    keep = keep[np.argsort(-row[keep])]
                if len(keep):
                    neighbors[i] = tuple(
                        (int(j), float(row[j])) for j in keep
                    )
        return SimilarityModel(data.item_ids, neighbors)

    def predict(self, model: SimilarityModel, query: Query) -> PredictedResult:
        try:
            idx = model.item_ids.index(query.item)
        except ValueError:
            return PredictedResult()
        scored = model.neighbors.get(idx, ())[: query.num]
        return PredictedResult(tuple(
            ItemScore(model.item_ids[j], s) for j, s in scored
        ))


def engine_factory() -> Engine:
    return Engine(
        data_source_class=ViewDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"cosine": CosineSimilarityAlgorithm},
        serving_class=FirstServing,
    )
