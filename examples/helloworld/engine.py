"""Hello-world engine: average temperature per day of week.

The analog of the reference's minimal custom-engine tutorial
(ref: examples/experimental/scala-local-helloworld/HelloWorld.scala):
every DASE component written by hand in one file, no template, no event
store — training data comes from ``data/data.csv``. Run from this
directory:

    pio train
    pio deploy --port 8000 &
    curl -s -X POST localhost:8000/queries.json -d '{"day": "Mon"}'

Even a toy engine inherits the full lifecycle: the trained model is
persisted to the Models store, `pio deploy` serves it with micro-batching,
and /reload hot-swaps after retraining.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from pathlib import Path

from predictionio_tpu.core import Engine, IdentityPreparator, LServing
from predictionio_tpu.core.dase import LAlgorithm, LDataSource


@dataclass(frozen=True)
class MyTrainingData:
    temperatures: tuple  # ((day, temperature), ...)


@dataclass(frozen=True)
class MyQuery:
    day: str


@dataclass(frozen=True)
class MyPredictedResult:
    temperature: float


class MyDataSource(LDataSource):
    def __init__(self, params=None):
        pass

    def read_training_local(self) -> MyTrainingData:
        path = Path(__file__).parent / "data" / "data.csv"
        with open(path) as f:
            rows = tuple(
                (day, float(temp)) for day, temp in csv.reader(f)
            )
        return MyTrainingData(rows)


class MyAlgorithm(LAlgorithm):
    query_class = MyQuery

    def __init__(self, params=None):
        pass

    def train_local(self, pd: MyTrainingData) -> dict:
        sums: dict[str, list[float]] = {}
        for day, temp in pd.temperatures:
            sums.setdefault(day, []).append(temp)
        return {day: sum(v) / len(v) for day, v in sums.items()}

    def predict(self, model: dict, query: MyQuery) -> MyPredictedResult:
        return MyPredictedResult(temperature=model.get(query.day, 0.0))


class MyServing(LServing):
    def __init__(self, params=None):
        pass

    def serve(self, query, predictions):
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        data_source_class=MyDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"algo": MyAlgorithm},
        serving_class=MyServing,
    )
