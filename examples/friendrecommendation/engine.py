"""Friend recommendation by SimRank — dense iterated matmuls on the MXU.

The analog of the reference's experimental SimRank engine
(ref: examples/experimental/scala-parallel-friend-recommendation/src/main/
scala/{DeltaSimRankRDD,SimRankAlgorithm,DataSource}.scala). The reference
propagates per-pair score *deltas* through the graph with RDD joins —
a sparse formulation chosen because dense [n, n] state is expensive on a
JVM cluster. On TPU the opposite holds: SimRank's fixpoint

    S ← C · Wᵀ S W   (off-diagonal),   diag(S) = 1

with W the column-normalized adjacency is two dense [n, n] matmuls per
iteration — exactly the MXU's shape — so the whole computation jits into
one ``lax.fori_loop`` program and a few thousand nodes converge in
milliseconds. Decay C and iteration count mirror the reference's
``DeltaSimRankRDD.decay = 0.8`` and its iteration parameter.

Training data is an edge-list CSV (``data/edges.csv``: ``src,dst`` per
line), matching the reference DataSource's file-based graph loading
(GraphLoader.edgeListFile). Run from this directory:

    pio train
    pio deploy --port 8000 &
    curl -s -X POST localhost:8000/queries.json -d '{"user": "1", "num": 3}'
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import Engine, IdentityPreparator, LServing
from predictionio_tpu.core.dase import LAlgorithm, LDataSource
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap


@dataclass(frozen=True)
class GraphData:
    edges: tuple  # ((src, dst), ...) string ids


@dataclass(frozen=True)
class Query:
    user: str
    num: int = 5


@dataclass(frozen=True)
class FriendScore:
    user: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    friend_scores: tuple  # (FriendScore, ...)


@dataclass(frozen=True)
class DataSourceParams(Params):
    path: str = ""  # defaults to data/edges.csv beside this file


class DataSource(LDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams | None = None):
        self.params = params or DataSourceParams()

    def read_training_local(self) -> GraphData:
        path = (
            Path(self.params.path)
            if self.params.path
            else Path(__file__).parent / "data" / "edges.csv"
        )
        with open(path) as f:
            edges = tuple((s, d) for s, d in csv.reader(f))
        return GraphData(edges)


@dataclass(frozen=True)
class SimRankParams(Params):
    decay: float = 0.8  # ref: DeltaSimRankRDD.decay
    iterations: int = 7


@dataclass
class SimRankModel:
    ids: BiMap  # user id ↔ matrix index
    scores: np.ndarray  # [n, n] SimRank matrix


@partial(jax.jit, static_argnames=("iterations",))
def _simrank(w, decay: float, iterations: int):
    """SimRank fixpoint: S ← C·WᵀSW off-diagonal, 1 on the diagonal.
    ``w`` is the column-normalized adjacency ([n, n], column j sums to 1
    over j's in-neighbors)."""
    n = w.shape[0]
    eye = jnp.eye(n, dtype=w.dtype)

    def step(_, s):
        s = decay * (w.T @ s @ w)
        return s * (1 - eye) + eye

    return jax.lax.fori_loop(0, iterations, step, eye)


class SimRankAlgorithm(LAlgorithm):
    params_class = SimRankParams
    query_class = Query

    def __init__(self, params: SimRankParams | None = None):
        self.params = params or SimRankParams()

    def train_local(self, pd: GraphData) -> SimRankModel:
        nodes = sorted({u for e in pd.edges for u in e})
        ids = BiMap({u: i for i, u in enumerate(nodes)})
        n = len(nodes)
        adj = np.zeros((n, n), np.float32)
        for s, d in pd.edges:
            adj[ids.get(s), ids.get(d)] = 1.0
        in_deg = adj.sum(axis=0, keepdims=True)
        w = adj / np.maximum(in_deg, 1.0)
        scores = np.asarray(
            _simrank(jnp.asarray(w), self.params.decay, self.params.iterations)
        )
        return SimRankModel(ids, scores)

    def predict(self, model: SimRankModel, query: Query) -> PredictedResult:
        idx = model.ids.get(query.user)
        if idx is None:
            return PredictedResult(())
        row = model.scores[idx].copy()
        row[idx] = -np.inf  # never recommend yourself
        top = np.argsort(-row)[: max(query.num, 0)]
        return PredictedResult(
            tuple(
                FriendScore(model.ids.inverse(int(j)), float(row[j]))
                for j in top
                if row[j] > 0
            )
        )


class Serving(LServing):
    def __init__(self, params=None):
        pass

    def serve(self, query, predictions):
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"simrank": SimRankAlgorithm},
        serving_class=Serving,
    )
