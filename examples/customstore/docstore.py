"""Third-party document-store backend — the "bring your own database"
half of the mongo-datasource example.

The reference's experimental engine reads training data out of MongoDB
instead of the built-in event store (ref: examples/experimental/
scala-parallel-recommendation-mongo-datasource/src/main/scala/
DataSource.scala:34-54). Its real lesson is the plugin contract: PIO's
storage registry can load a backend the framework never shipped. This
module is such a backend: a JSON-lines-per-app document store (the
no-dependency stand-in for a document DB), discovered through the
registry's module-path hook (data/storage/registry.py::_backend —
``PIO_STORAGE_SOURCES_<NAME>_TYPE`` set to a module path, DAO classes
found via ``CLASS_PREFIX``; ref: Storage.scala:263-312).

Wire it like any built-in backend::

    export PIO_STORAGE_SOURCES_DOCS_TYPE=examples.customstore.docstore
    export PIO_STORAGE_SOURCES_DOCS_PATH=/var/pio/docstore
    export PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=DOCS

after which `pio app new`, the event server, and engine training all read
and write rating documents through this module — see ``engine.py`` next
to it for the engine side.

Only the Events DAO is implemented (this store holds interaction
documents; metadata/models stay on the default source), exactly like the
reference example keeps metadata in PostgreSQL/Elasticsearch while
ratings live in Mongo.
"""

from __future__ import annotations

import datetime as dt
import json
import threading
from pathlib import Path
from typing import Iterator, Sequence

from predictionio_tpu.data.event import Event, new_event_id
from predictionio_tpu.data.storage import base

#: Registry discovery hook: DAO classes in this module are named
#: ``<CLASS_PREFIX><DaoName>`` (ref: Storage.scala:289-301).
CLASS_PREFIX = "Doc"


class DocClient:
    """One document-store "connection": a directory of JSON-lines
    collections, one file per app/channel."""

    def __init__(self, config: dict | None = None):
        cfg = config or {}
        self.root = Path(cfg.get("PATH", cfg.get("path", "docstore")))
        self.root.mkdir(parents=True, exist_ok=True)
        self.lock = threading.RLock()

    def collection(self, name: str) -> Path:
        return self.root / f"{name}.jsonl"


class DocEvents(base.Events):
    """Events DAO over JSON-lines documents. Append-only writes; reads
    scan the collection — the simplicity is the point (the contract under
    test is the registry plumbing, not storage performance)."""

    def __init__(self, client: DocClient, prefix: str = ""):
        self._c = client
        self._prefix = prefix

    def _coll(self, app_id: int, channel_id: int | None) -> Path:
        name = f"{self._prefix}events_{app_id}"
        if channel_id:
            name += f"_{channel_id}"
        return self._c.collection(name)

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._c.lock:
            self._coll(app_id, channel_id).touch()
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._c.lock:
            path = self._coll(app_id, channel_id)
            existed = path.exists()
            if existed:
                path.unlink()
            return existed

    def close(self) -> None:
        pass

    def _read_all(self, app_id: int, channel_id: int | None) -> list[Event]:
        path = self._coll(app_id, channel_id)
        if not path.exists():
            raise base.StorageError(
                f"Doc store for app {app_id} channel {channel_id} is not "
                "initialized; run `pio app new` first."
            )
        with self._c.lock, open(path) as f:
            return [Event.from_json(json.loads(line)) for line in f if
                    line.strip()]

    def insert(self, event: Event, app_id: int,
               channel_id: int | None = None) -> str:
        eid = event.event_id or new_event_id()
        doc = json.dumps(event.with_id(eid).to_json())
        path = self._coll(app_id, channel_id)
        with self._c.lock:
            if not path.exists():
                raise base.StorageError(
                    f"Doc store for app {app_id} is not initialized; run "
                    "`pio app new` first."
                )
            with open(path, "a") as f:
                f.write(doc + "\n")
        return eid

    def get(self, event_id: str, app_id: int,
            channel_id: int | None = None) -> Event | None:
        for e in self._read_all(app_id, channel_id):
            if e.event_id == event_id:
                return e
        return None

    def delete(self, event_id: str, app_id: int,
               channel_id: int | None = None) -> bool:
        with self._c.lock:
            events = self._read_all(app_id, channel_id)
            kept = [e for e in events if e.event_id != event_id]
            if len(kept) == len(events):
                return False
            with open(self._coll(app_id, channel_id), "w") as f:
                for e in kept:
                    f.write(json.dumps(e.to_json()) + "\n")
            return True

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: dt.datetime | None = None,
        until_time: dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed_: bool = False,
    ) -> Iterator[Event]:
        def ok(e: Event) -> bool:
            if start_time is not None and e.event_time < start_time:
                return False
            if until_time is not None and e.event_time >= until_time:
                return False
            if entity_type is not None and e.entity_type != entity_type:
                return False
            if entity_id is not None and e.entity_id != entity_id:
                return False
            if event_names is not None and e.event not in event_names:
                return False
            if (target_entity_type is not ...
                    and e.target_entity_type != target_entity_type):
                return False
            if (target_entity_id is not ...
                    and e.target_entity_id != target_entity_id):
                return False
            return True

        out = sorted(
            (e for e in self._read_all(app_id, channel_id) if ok(e)),
            key=lambda e: e.event_time,
            reverse=reversed_,
        )
        if limit is not None and limit >= 0:
            out = out[:limit]
        return iter(out)
