"""Recommendation engine over a bring-your-own document store.

The mongo-datasource example analog (ref: examples/experimental/
scala-parallel-recommendation-mongo-datasource/src/main/scala/
DataSource.scala): the reference keeps the recommendation template's
Engine/ALSAlgorithm/Serving untouched and swaps ONLY the DataSource so
training reads rating documents from MongoDB. This example does the
same swap against ``docstore.py`` (the third-party JSON-lines backend
next to this file, loaded through the storage registry's module-path
hook): Preparator, ALSAlgorithm, and Serving are imported verbatim from
``templates/recommendation``; the DataSource below reads raw rating
documents from whatever backend the EVENTDATA repository is wired to.

Run from this directory (after `pio app new docapp` + ingesting rate
events — both of which also go through the custom store)::

    export PIO_STORAGE_SOURCES_DOCS_TYPE=examples.customstore.docstore
    export PIO_STORAGE_SOURCES_DOCS_PATH=$PWD/docstore
    export PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=DOCS
    pio train && pio deploy --port 8000
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.core import Engine
from predictionio_tpu.core.dase import PDataSource
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.store.event_stores import PEventStore
from predictionio_tpu.parallel.mesh import ComputeContext
from predictionio_tpu.templates.recommendation import (
    ALSAlgorithm,
    Preparator,
    Serving,
    TrainingData,
)


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "docapp"


class DocDataSource(PDataSource):
    """Reads rating documents {uid, iid, rating} from the EVENTDATA
    store — which the deployment wires to the third-party docstore
    module (see module docstring). The mapping mirrors the reference's
    mongoRDD.map over BSON fields (DataSource.scala:45-51)."""

    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        users, items, ratings = [], [], []
        for e in PEventStore.find(
            self.params.app_name, event_names=["rate"],
            entity_type="user", target_entity_type="item",
        ):
            users.append(e.entity_id)
            items.append(e.target_entity_id)
            ratings.append(float(e.properties.get("rating")))
        return TrainingData(
            users, items, np.asarray(ratings, np.float32))


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DocDataSource,
        preparator_class=Preparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class=Serving,
    )
