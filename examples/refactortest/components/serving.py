"""Serving module of the refactor-test engine (ref:
examples/experimental/scala-refactor-test/src/main/scala/Serving.scala)."""

from predictionio_tpu.core import FirstServing


class Serving(FirstServing):
    pass
