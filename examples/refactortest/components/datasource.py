"""DataSource module of the refactor-test engine (ref:
examples/experimental/scala-refactor-test/src/main/scala/DataSource.scala:
readTraining emits the integers 0-99; readEval yields one fold whose
queries are those integers and whose actuals are empty)."""

from __future__ import annotations

from dataclasses import dataclass

from predictionio_tpu.core import PDataSource


@dataclass(frozen=True)
class Query:
    q: int


@dataclass(frozen=True)
class PredictedResult:
    p: int


@dataclass(frozen=True)
class TrainingData:
    events: tuple


class DataSource(PDataSource):
    def __init__(self, params=None):
        pass

    def read_training(self, ctx) -> TrainingData:
        return TrainingData(events=tuple(range(100)))

    def read_eval(self, ctx):
        td = self.read_training(ctx)
        qa = [(Query(q=i), None) for i in range(3)]
        return [(td, None, qa)]
