"""Algorithm module of the refactor-test engine (ref:
examples/experimental/scala-refactor-test/src/main/scala/Algorithm.scala:
AlgorithmParams(a) — predict returns q + a)."""

from __future__ import annotations

from dataclasses import dataclass

from predictionio_tpu.core import P2LAlgorithm
from predictionio_tpu.core.params import Params

from components.datasource import PredictedResult, Query, TrainingData


@dataclass(frozen=True)
class AlgorithmParams(Params):
    a: int = 2


class Algorithm(P2LAlgorithm):
    params_class = AlgorithmParams
    query_class = Query

    def __init__(self, params: AlgorithmParams | None = None):
        self.params = params or AlgorithmParams()

    def train(self, ctx, pd: TrainingData):
        return {"n": len(pd.events)}  # vanilla model

    def predict(self, model, query: Query) -> PredictedResult:
        return PredictedResult(p=query.q + self.params.a)
