"""DASE components deliberately spread across modules (see ../engine.py)."""
