"""Vanilla engine whose DASE components live in separate modules.

The analog of the reference's refactor-test experimental example
(ref: examples/experimental/scala-refactor-test/src/main/scala/ — a
vanilla engine split across Engine/DataSource/Algorithm/Serving files in
a ``pio.refactor`` package, existing to prove the workflow machinery
resolves components across namespace boundaries). Here the factory lives
in ``engine.py`` (what the loader imports) while every component is
imported from the ``components`` package beside it — exercising the
engine-dir-on-sys.path loading the same way the reference exercises
jar-on-classpath package resolution.

Run from this directory::

    pio build && pio train
    pio eval engine:evaluation
"""

from __future__ import annotations

from predictionio_tpu.core import Engine, IdentityPreparator
from predictionio_tpu.core.engine import EngineParams
from predictionio_tpu.core.evaluation import Evaluation
from predictionio_tpu.core.metrics import AverageMetric

from components.algorithm import Algorithm, AlgorithmParams
from components.datasource import DataSource
from components.serving import Serving


def engine_factory() -> Engine:
    return Engine(
        data_source_class=DataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"algo": Algorithm},
        serving_class=Serving,
    )


class OffsetMetric(AverageMetric):
    """ref: the VanillaEvaluator's per-query p - q check."""

    header = "mean(prediction - query)"

    def calculate_qpa(self, q, p, a) -> float:
        return float(p.p - q.q)


def evaluation() -> Evaluation:
    return Evaluation(
        engine=engine_factory(),
        engine_params_list=[
            EngineParams(algorithms_params=(("algo", AlgorithmParams(a=a)),))
            for a in (1, 2)
        ],
        metric=OffsetMetric(),
    )
