"""Trim-app: copy a time window of an app's events into a fresh app.

The reference's experimental trim-app
(ref: examples/experimental/scala-parallel-trim-app/src/main/scala/
DataSource.scala:30-56) abuses the engine lifecycle on purpose: the
*DataSource* does the real work — read the source app's events in
[startTime, untilTime), refuse to run if the destination app already has
events, write the window to the destination — and the algorithm/model
are empty. It is the reference's recipe for trimming an app's history
(run trim-app into a new app, then point the engine at it).

Same shape here, over the in-process event store with batched writes.
Run from this directory:

    pio app new TrimmedApp
    pio train    # copies the window src_app -> dst_app

There is nothing to deploy; `pio train` IS the job.
"""

from __future__ import annotations

from dataclasses import dataclass

from predictionio_tpu.core import Engine, FirstServing, IdentityPreparator
from predictionio_tpu.core.dase import LAlgorithm, LDataSource
from predictionio_tpu.data.storage import Storage
from predictionio_tpu.data.store.event_stores import app_name_to_id
from predictionio_tpu.utils.time import parse_datetime


@dataclass(frozen=True)
class TrimParams:
    src_app: str = "MyApp"
    dst_app: str = "TrimmedApp"
    start_time: str | None = None  # ISO-8601, inclusive
    until_time: str | None = None  # ISO-8601, exclusive


@dataclass(frozen=True)
class TrimResult:
    copied: int


@dataclass(frozen=True)
class Query:
    pass


class TrimDataSource(LDataSource):
    params_class = TrimParams

    def __init__(self, params: TrimParams):
        self.params = params

    def read_training_local(self) -> TrimResult:
        p = self.params
        src_id, _ = app_name_to_id(p.src_app)
        dst_id, _ = app_name_to_id(p.dst_app)
        events = Storage.get_events()
        # refuse a non-empty destination, like the reference
        # (DataSource.scala:45-48: "DstApp ... is not empty. Quitting.")
        if next(iter(events.find(app_id=dst_id, limit=1)), None) is not None:
            raise RuntimeError(
                f"destination app {p.dst_app!r} is not empty; quitting"
            )
        window = events.find(
            app_id=src_id,
            start_time=parse_datetime(p.start_time) if p.start_time else None,
            until_time=parse_datetime(p.until_time) if p.until_time else None,
        )
        copied = 0
        batch: list = []
        for e in window:
            batch.append(e)
            if len(batch) >= 500:
                copied += len(events.insert_batch(batch, dst_id))
                batch = []
        if batch:
            copied += len(events.insert_batch(batch, dst_id))
        return TrimResult(copied)


class NoopAlgorithm(LAlgorithm):
    query_class = Query

    def __init__(self, params=None):
        pass

    def train_local(self, data: TrimResult) -> TrimResult:
        return data  # the "model" is the copy report

    def predict(self, model: TrimResult, query: Query) -> TrimResult:
        return model


def engine_factory() -> Engine:
    return Engine(
        data_source_class=TrimDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"noop": NoopAlgorithm},
        serving_class=FirstServing,
    )
